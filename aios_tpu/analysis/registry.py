"""The declared concurrency model of the serving plane.

This file is the single place where a reviewer states WHICH locks exist,
what each one is allowed to shelter, which fields they guard, and which
functions run with a lock already held by their caller (hooks reached
through dynamic dispatch the AST cannot follow). The rules in
:mod:`aios_tpu.analysis.rules` are generic; everything repo-specific
lives here, so adding a lock to the serving plane is a one-line reviewed
registry change — and forgetting to add it means the analyzer simply
does not defend it, which a reviewer can see at a glance.

The same declarations drive the runtime half: ``locks.make_lock(<name>)``
call sites in the declared modules switch to the order-checking
:class:`~aios_tpu.analysis.locks.DebugLock` under ``AIOS_TPU_LOCK_DEBUG=1``
(the lock NAMES here and there must match — ``test_analysis`` checks it).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

# hazard classes rule lock-discipline knows how to spot
HAZARDS = ("dispatch", "readback", "rpc")


@dataclass(frozen=True)
class LockDecl:
    """One declared lock: where it lives and what its body must not do."""

    name: str          # registry id, also the DebugLock name
    module: str        # dotted module
    class_name: str    # owning class (subclasses inherit the discipline)
    attr: str          # attribute the lock is stored under
    forbids: Tuple[str, ...] = HAZARDS

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.module, self.class_name, self.attr)


# -- the lock registry -------------------------------------------------------
# The engine lock's JOB is sheltering the dispatch + donated state swap,
# so it forbids only host-blocking work (D2H readback, RPC) — exactly the
# class of bug PRs 4 and 6 each fixed by hand. Every other serving-plane
# lock is a pure bookkeeping lock: a dispatch or readback under it stalls
# the router/scheduler/scrape threads that share it.

LOCKS: Tuple[LockDecl, ...] = (
    LockDecl("engine", "aios_tpu.engine.engine", "TPUEngine", "_lock",
             forbids=("readback", "rpc")),
    LockDecl("engine_spill", "aios_tpu.engine.engine", "TPUEngine",
             "_spill_lock"),
    LockDecl("prefix_index", "aios_tpu.engine.paged", "_PrefixIndexBase",
             "_lock"),
    LockDecl("host_store", "aios_tpu.engine.paged", "HostPageStore",
             "_lock"),
    LockDecl("batcher", "aios_tpu.engine.batching", "ContinuousBatcher",
             "_lock"),
    LockDecl("batcher_queue", "aios_tpu.engine.batching",
             "ContinuousBatcher", "_qlock"),
    LockDecl("json_masks", "aios_tpu.engine.batching", "ContinuousBatcher",
             "_json_masks_lock"),
    LockDecl("pool", "aios_tpu.serving.pool", "ReplicaPool", "_lock"),
    LockDecl("router", "aios_tpu.serving.router", "Router", "_lock"),
    LockDecl("admission", "aios_tpu.serving.admission",
             "AdmissionController", "_lock"),
    LockDecl("token_bucket", "aios_tpu.serving.admission", "TokenBucket",
             "_lock"),
    LockDecl("recorder", "aios_tpu.obs.flightrec", "FlightRecorder",
             "_lock"),
    LockDecl("slo", "aios_tpu.obs.slo", "SLOEngine", "_lock"),
    LockDecl("model_manager", "aios_tpu.runtime.model_manager",
             "ModelManager", "_lock"),
    LockDecl("faults", "aios_tpu.faults.inject", "FaultPlan", "_lock"),
    LockDecl("failover", "aios_tpu.serving.failover", "FailoverHandle",
             "_lock"),
    LockDecl("devprof", "aios_tpu.obs.devprof", "DevprofLedger", "_lock"),
    # autoscale: pure bookkeeping (hold counters, action journal, the
    # added-engine list) — engine builds and pool mutations run outside
    LockDecl("autoscale", "aios_tpu.serving.autoscale",
             "AutoscaleController", "_lock"),
    # fleet: pure bookkeeping (member table, transition journal, peer
    # set) — announces/scrapes (urllib) and metric/recorder emission
    # for state edges always run outside it
    LockDecl("fleet", "aios_tpu.obs.fleet", "FleetRegistry", "_lock"),
    # handoff: cancel/terminal flags and the live local-handle ref on a
    # disaggregated stream — the transfer RPCs themselves (push, fetch,
    # the handoff stream) always run outside it
    LockDecl("handoff", "aios_tpu.fleet.disagg", "HandoffHandle", "_lock"),
    # quarantine: per-peer breaker bookkeeping (EWMAs, state, probe
    # budget) — the cross-host calls whose outcomes feed it always run
    # outside, and metric/recorder emission for state edges happens
    # after release (no quarantine->recorder lock edge)
    LockDecl("quarantine", "aios_tpu.fleet.breaker", "BreakerBoard",
             "_lock"),
    # drain: the phase flag and the one-shot worker handle — the drain
    # protocol itself (pool drain, kvx pushes, the leaving announce)
    # runs on its worker thread outside the lock
    LockDecl("drain", "aios_tpu.fleet.drain", "DrainCoordinator",
             "_lock"),
    # tsdb: the series map and per-series ring/wheel deques — registry
    # reads (which take metric locks) run before it, metric emission
    # after release; queries copy points under it and aggregate outside
    LockDecl("tsdb", "aios_tpu.obs.tsdb", "Tsdb", "_lock"),
    # incidents: bundle deque, cooldown stamps, id counter — bundle
    # construction (tsdb/recorder/faults/devprof reads) and metric/
    # recorder emission always run outside it
    LockDecl("incidents", "aios_tpu.obs.incidents", "IncidentStore",
             "_lock"),
)


# -- static type hints the AST cannot infer ---------------------------------
# (module, class, field) -> (module, class): lets the one-level call walk
# cross object boundaries (`self.engine.step(...)` under a batcher lock is
# a dispatch; `self.prefix_index.put(...)` under the engine lock acquires
# the index lock).

FIELD_TYPES: Dict[Tuple[str, str, str], Tuple[str, str]] = {
    ("aios_tpu.engine.engine", "TPUEngine", "prefix_index"):
        ("aios_tpu.engine.paged", "_PrefixIndexBase"),
    ("aios_tpu.engine.engine", "TPUEngine", "host_store"):
        ("aios_tpu.engine.paged", "HostPageStore"),
    ("aios_tpu.engine.batching", "ContinuousBatcher", "engine"):
        ("aios_tpu.engine.engine", "TPUEngine"),
    ("aios_tpu.serving.pool", "ReplicaPool", "router"):
        ("aios_tpu.serving.router", "Router"),
    ("aios_tpu.serving.pool", "ReplicaPool", "admission"):
        ("aios_tpu.serving.admission", "AdmissionController"),
    ("aios_tpu.serving.pool", "Replica", "engine"):
        ("aios_tpu.engine.engine", "TPUEngine"),
    ("aios_tpu.serving.pool", "Replica", "batcher"):
        ("aios_tpu.engine.batching", "ContinuousBatcher"),
}

# module-level singletons: bare/dotted name -> (module, class)
GLOBAL_TYPES: Dict[str, Tuple[str, str]] = {
    "RECORDER": ("aios_tpu.obs.flightrec", "FlightRecorder"),
}

# -- caller-held lock contexts ----------------------------------------------
# (module, qualname) -> lock names already held when the function runs.
# These are the dynamic-dispatch seams the AST cannot see through; each
# entry mirrors a docstring contract in the named function.

CONTEXT_FNS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    # PrefixIndex eviction paths run from engine-lock-holding callers
    # (see _PrefixIndexBase._drop docstring), and _drop invokes the
    # engine's spill hook synchronously.
    ("aios_tpu.engine.paged", "_PrefixIndexBase._drop"): ("engine",),
    ("aios_tpu.engine.engine", "TPUEngine._spill_pages"): ("engine",),
    # ring accessor contract: only FlightRecorder.finish calls it, under
    # the recorder lock (the lazy setdefault would race otherwise)
    ("aios_tpu.obs.flightrec", "FlightRecorder._ring"): ("recorder",),
    # journal appends happen inside the state-transition critical
    # sections of _observe/tick (see _journal_append docstring)
    ("aios_tpu.obs.fleet", "FleetRegistry._journal_append"): ("fleet",),
}

# hook attributes whose call target is registered dynamically:
# (module, attr-name called as `self.<attr>(...)`) -> (module, qualname)
HOOK_TARGETS: Dict[Tuple[str, str], Tuple[str, str]] = {
    ("aios_tpu.engine.paged", "spill"):
        ("aios_tpu.engine.engine", "TPUEngine._spill_pages"),
    ("aios_tpu.engine.paged", "reclaimer"):
        ("aios_tpu.engine.paged", "_PrefixIndexBase.reclaim"),
}

# closure-passed locks: (module, qualname, local name) -> lock name
# (the static spill worker receives the spill lock as a parameter)
LOCAL_LOCKS: Dict[Tuple[str, str, str], str] = {
    ("aios_tpu.engine.engine", "TPUEngine._spill_worker", "lock"):
        "engine_spill",
}

# -- hazard call shapes ------------------------------------------------------
# Device dispatch: jit construction/lowering, jitted-handle accessors
# (the engine's per-kind graph caches), and the engine's dispatching
# public surface (what a batcher/pool calls).

DISPATCH_TERMINALS = frozenset({
    "jit", "lower", "device_put", "jump_step", "spec_step",
    "step", "step_async", "step_masked", "prefill",
})
DISPATCH_FN_HANDLE_RE = re.compile(
    r"^_(step|unified|masked_step|prefill|chunk|spec|jump|restore|hist)_fn$"
)

# D2H readback / host-blocking device sync. `np.asarray` is the repo's
# readback idiom (jnp.asarray is H2D and does NOT match).
READBACK_CHAINS = frozenset({("np", "asarray")})
READBACK_TERMINALS = frozenset({
    "block_until_ready", "device_get", "item", "copy_to_host_async",
})

# blocking RPC / host waits: gRPC stubs, channel readiness, future
# results, sleeps, joins. `.get(` is deliberately absent (dict.get).
RPC_TERMINALS = frozenset({
    "sleep", "channel_ready_future", "result", "wait",
})
RPC_CHAIN_MARKER = "stub"  # any chain segment containing this matches


# -- dispatch hygiene (rule jit-warmup) --------------------------------------
# Serving-path modules where a jax.jit call site must be reachable from
# an AOT-warmup registration (the PR 6 "compile counters flat after
# warmup" invariant, statically). ops/ and parallel/ build kernels at
# import/trace time and are exercised by their own tests.

DISPATCH_HYGIENE_MODULES: Tuple[str, ...] = (
    "aios_tpu.engine.engine",
    "aios_tpu.engine.batching",
    # draft-model speculation (spec.DraftModel): its propose/ingest
    # bodies are jitted from engine.py behind compile_draft_spec_fn /
    # compile_draft_ingest_fns, but the module itself is serving-path —
    # a jax.jit added here must be reachable from a warmup registration
    # like everything else on the decode hot path
    "aios_tpu.engine.spec",
)

# a function whose NAME matches counts as a warmup registration root
WARMUP_ROOT_RE = re.compile(r"^(warmup|_compile_aot|compile_\w+)$")


# -- silent-except (rule silent-except) -------------------------------------
# Broad `except Exception` / `except BaseException` / bare `except:`
# handlers in these module prefixes must RECORD the failure — re-raise,
# log it, or land an abort/terminal cause — or carry an
# `# aios: waive(silent-except): <reason>` pragma. Fault paths are the
# least-exercised code in the tree; one that swallows its evidence is an
# observability black hole exactly when the operator needs it most.

SILENT_EXCEPT_PREFIXES: Tuple[str, ...] = (
    "aios_tpu.serving", "aios_tpu.engine",
)

# terminal callee names that count as recording the failure: logging,
# flight-recorder terminal events, gRPC error surfacing, and the
# batcher/pool abort plumbing (which sets abort_reason downstream)
SILENT_EXCEPT_RECORDERS = frozenset({
    "exception", "error", "warning", "critical",
    "finish", "finish_shed", "model_event", "snapshot",
    "abort", "set_details",
    "_abort_all", "_terminate_outstanding", "_finish", "_rec_close",
    "shed", "note_failed_restore",
})

BROAD_EXCEPTION_NAMES = frozenset({"Exception", "BaseException"})


# -- knob/docs drift (rule knob-docs) ---------------------------------------

KNOB_RE = re.compile(r"AIOS_TPU_[A-Z0-9_]+")
CONFIG_DOC = "docs/CONFIG.md"

# metric constructors that must only run inside the instruments catalog
METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
METRIC_PREFIX = "aios_tpu_"
METRIC_CATALOG_MODULES = frozenset({
    "aios_tpu.obs.instruments", "aios_tpu.obs.metrics",
})


@dataclass
class Registry:
    """Bundle of the declarations above; tests construct custom ones to
    drive rule fixtures, production uses :data:`DEFAULT`."""

    locks: Tuple[LockDecl, ...] = LOCKS
    field_types: Dict[Tuple[str, str, str], Tuple[str, str]] = field(
        default_factory=lambda: dict(FIELD_TYPES))
    global_types: Dict[str, Tuple[str, str]] = field(
        default_factory=lambda: dict(GLOBAL_TYPES))
    context_fns: Dict[Tuple[str, str], Tuple[str, ...]] = field(
        default_factory=lambda: dict(CONTEXT_FNS))
    hook_targets: Dict[Tuple[str, str], Tuple[str, str]] = field(
        default_factory=lambda: dict(HOOK_TARGETS))
    local_locks: Dict[Tuple[str, str, str], str] = field(
        default_factory=lambda: dict(LOCAL_LOCKS))
    dispatch_hygiene_modules: Tuple[str, ...] = DISPATCH_HYGIENE_MODULES
    silent_except_prefixes: Tuple[str, ...] = SILENT_EXCEPT_PREFIXES
    silent_except_recorders: frozenset = SILENT_EXCEPT_RECORDERS

    def lock_named(self, name: str) -> Optional[LockDecl]:
        for d in self.locks:
            if d.name == name:
                return d
        return None

    def locks_in_module(self, module: str) -> Tuple[LockDecl, ...]:
        return tuple(d for d in self.locks if d.module == module)


DEFAULT = Registry()
