"""Concurrency & dispatch-discipline analysis for the serving plane.

Static half: an AST rule engine (``python -m aios_tpu.analysis``, also a
tier-1 test) that machine-checks the invariants previous PRs enforced by
reviewer vigilance — no dispatch/readback/blocking-RPC under the
declared locks, an acyclic lock-order graph, ``guarded_by`` field
discipline, jit-behind-warmup dispatch hygiene, and env-knob/metric
catalog drift. Rule catalog and waiver policy: docs/ANALYSIS.md.

Runtime half: :mod:`aios_tpu.analysis.locks` — named, order-checking
debug locks the declared serving-plane locks switch to under
``AIOS_TPU_LOCK_DEBUG=1`` (the test suite runs with it on).

Import note: this package must stay import-light (no jax, no obs) — the
engine imports ``locks.make_lock`` at module import time.
"""

from .core import Finding, ModuleInfo, module_info_for  # noqa: F401
from .locks import (  # noqa: F401
    DebugLock, LockOrderError, debug_enabled, make_lock, watchdog_trips,
)
from .registry import DEFAULT, LOCKS, Registry  # noqa: F401
from .rules import RULE_IDS, Analyzer, run_analysis  # noqa: F401
