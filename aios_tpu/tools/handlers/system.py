"""process.* / service.* / monitor.* / hw.* — system tools.

Reference: tools/src/{process,service,monitor,hw}/ (18 handlers). psutil
backs the read-only paths; systemctl/journalctl paths degrade with a clear
error when the host has no systemd (e.g. containers).
"""

from __future__ import annotations

import os
import signal as signal_mod
import subprocess
import time
from pathlib import Path

import psutil

from . import ToolError, ToolSpec, run_cmd

# ---------------------------------------------------------------------------
# process.*
# ---------------------------------------------------------------------------


def process_list(args: dict) -> dict:
    limit = int(args.get("limit", 50))
    sort_by = args.get("sort_by", "cpu")
    procs = []
    for p in psutil.process_iter(["pid", "name", "username", "cpu_percent",
                                  "memory_info", "status"]):
        try:
            info = p.info
            procs.append(
                {
                    "pid": info["pid"],
                    "name": info["name"],
                    "user": info.get("username"),
                    "cpu_percent": info.get("cpu_percent") or 0.0,
                    "rss_mb": round((info["memory_info"].rss if info.get("memory_info") else 0) / 1e6, 1),
                    "status": info.get("status"),
                }
            )
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            continue
    key = "cpu_percent" if sort_by == "cpu" else "rss_mb"
    procs.sort(key=lambda x: x[key], reverse=True)
    return {"processes": procs[:limit], "total": len(procs)}


def process_spawn(args: dict) -> dict:
    argv = args.get("argv") or args.get("command", "").split()
    if not argv:
        raise ToolError("missing argv/command")
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    return {"pid": proc.pid, "argv": argv}


def process_kill(args: dict) -> dict:
    pid = int(args.get("pid", 0))
    if pid <= 1:
        raise ToolError(f"refusing to kill pid {pid}")
    sig = getattr(signal_mod, args.get("signal", "SIGTERM"), signal_mod.SIGTERM)
    try:
        os.kill(pid, sig)
    except ProcessLookupError as exc:
        raise ToolError(f"no such process {pid}") from exc
    except PermissionError as exc:
        raise ToolError(f"permission denied killing {pid}") from exc
    return {"pid": pid, "signal": int(sig)}


def process_info(args: dict) -> dict:
    pid = int(args.get("pid", 0))
    try:
        p = psutil.Process(pid)
        with p.oneshot():
            return {
                "pid": pid,
                "name": p.name(),
                "status": p.status(),
                "cpu_percent": p.cpu_percent(interval=0.05),
                "rss_mb": round(p.memory_info().rss / 1e6, 1),
                "cmdline": p.cmdline()[:20],
                "create_time": int(p.create_time()),
                "num_threads": p.num_threads(),
            }
    except psutil.NoSuchProcess as exc:
        raise ToolError(f"no such process {pid}") from exc


def process_signal(args: dict) -> dict:
    args = dict(args)
    args.setdefault("signal", "SIGHUP")
    return process_kill(args)


def process_cgroup(args: dict) -> dict:
    pid = int(args.get("pid", os.getpid()))
    path = Path(f"/proc/{pid}/cgroup")
    if not path.exists():
        raise ToolError(f"no cgroup info for pid {pid}")
    return {"pid": pid, "cgroup": path.read_text().strip().splitlines()}


# ---------------------------------------------------------------------------
# service.* — systemd wrappers with graceful degradation
# ---------------------------------------------------------------------------


def _systemctl(*argv: str) -> dict:
    return run_cmd(["systemctl", "--no-pager", *argv], timeout=30)


def service_list(args: dict) -> dict:
    out = _systemctl("list-units", "--type=service", "--all", "--plain",
                     "--no-legend")
    services = []
    for line in out["stdout"].splitlines()[: int(args.get("limit", 100))]:
        parts = line.split(None, 4)
        if len(parts) >= 4:
            services.append(
                {"unit": parts[0], "load": parts[1], "active": parts[2],
                 "sub": parts[3]}
            )
    return {"services": services}


def _service_verb(verb: str):
    def handler(args: dict) -> dict:
        name = args.get("name") or args.get("service")
        if not name:
            raise ToolError("missing service name")
        _systemctl(verb, name)
        return {"service": name, "action": verb}

    return handler


def service_status(args: dict) -> dict:
    name = args.get("name") or args.get("service")
    if not name:
        raise ToolError("missing service name")
    try:
        out = run_cmd(["systemctl", "is-active", name], timeout=10)
        state = out["stdout"].strip()
    except ToolError:
        state = "inactive-or-unknown"
    return {"service": name, "state": state}


# ---------------------------------------------------------------------------
# monitor.*
# ---------------------------------------------------------------------------


def monitor_cpu(args: dict) -> dict:
    return {
        "percent": psutil.cpu_percent(interval=float(args.get("interval", 0.1))),
        "per_core": psutil.cpu_percent(percpu=True),
        "load_avg": list(os.getloadavg()),
        "cores": psutil.cpu_count(),
    }


def monitor_memory(args: dict) -> dict:
    vm = psutil.virtual_memory()
    swap = psutil.swap_memory()
    return {
        "total_mb": round(vm.total / 1e6, 1),
        "used_mb": round(vm.used / 1e6, 1),
        "available_mb": round(vm.available / 1e6, 1),
        "percent": vm.percent,
        "swap_used_mb": round(swap.used / 1e6, 1),
    }


def monitor_disk(args: dict) -> dict:
    parts = []
    for part in psutil.disk_partitions(all=False):
        try:
            usage = psutil.disk_usage(part.mountpoint)
        except OSError:
            continue
        parts.append(
            {
                "mount": part.mountpoint,
                "fstype": part.fstype,
                "total_gb": round(usage.total / 1e9, 2),
                "percent": usage.percent,
            }
        )
    return {"partitions": parts}


def monitor_network(args: dict) -> dict:
    io = psutil.net_io_counters()
    return {
        "bytes_sent": io.bytes_sent,
        "bytes_recv": io.bytes_recv,
        "packets_sent": io.packets_sent,
        "packets_recv": io.packets_recv,
        "errin": io.errin,
        "errout": io.errout,
    }


def monitor_logs(args: dict) -> dict:
    source = args.get("source", "")
    lines = int(args.get("lines", 50))
    if source and Path(source).is_file():
        text = Path(source).read_text(errors="replace").splitlines()[-lines:]
        return {"source": source, "lines": text}
    out = run_cmd(["journalctl", "-n", str(lines), "--no-pager"], timeout=20)
    return {"source": "journalctl", "lines": out["stdout"].splitlines()}


def monitor_ebpf_trace(args: dict) -> dict:
    # the reference shells out to bpftrace; degrade identically when missing
    probe = args.get("probe", "tracepoint:syscalls:sys_enter_execve")
    duration = min(int(args.get("duration", 5)), 30)
    out = run_cmd(
        ["timeout", str(duration), "bpftrace", "-e", f"{probe} {{ printf(\"%s\\n\", comm); }}"],
        timeout=duration + 10,
    )
    return {"probe": probe, "output": out["stdout"].splitlines()[:200]}


def monitor_fs_watch(args: dict) -> dict:
    """Poll-based change snapshot (no inotify dependency): two stats."""
    path = Path(args.get("path", "/tmp"))
    interval = min(float(args.get("interval", 1.0)), 10.0)
    if not path.is_dir():
        raise ToolError(f"{path} is not a directory")

    def snap():
        return {
            str(f): f.stat().st_mtime
            for f in list(path.iterdir())[:500]
            if f.exists()
        }

    before = snap()
    time.sleep(interval)
    after = snap()
    changed = [f for f in after if before.get(f) != after[f]]
    added = [f for f in after if f not in before]
    removed = [f for f in before if f not in after]
    return {"path": str(path), "changed": changed, "added": added,
            "removed": removed}


# ---------------------------------------------------------------------------
# hw.info
# ---------------------------------------------------------------------------


def hw_info(args: dict) -> dict:
    cpu_model = ""
    try:
        for line in Path("/proc/cpuinfo").read_text().splitlines():
            if line.startswith("model name"):
                cpu_model = line.split(":", 1)[1].strip()
                break
    except OSError:
        pass
    info = {
        "cpu_model": cpu_model,
        "cpu_cores": psutil.cpu_count(logical=False) or psutil.cpu_count(),
        "cpu_threads": psutil.cpu_count(),
        "memory_total_mb": round(psutil.virtual_memory().total / 1e6),
        "boot_time": int(psutil.boot_time()),
    }
    # TPU presence (the reference detects GPUs; we detect the TPU chip)
    try:
        import jax

        info["accelerators"] = [str(d) for d in jax.devices()]
        info["accelerator_backend"] = jax.default_backend()
    except Exception:
        info["accelerators"] = []
    return info


TOOLS = {
    "process.list": ToolSpec(process_list, "List processes by cpu/mem",
                             idempotent=True),
    "process.spawn": ToolSpec(process_spawn, "Spawn a detached process"),
    "process.kill": ToolSpec(process_kill, "Send a signal to a process",
                             requires_confirmation=True),
    "process.info": ToolSpec(process_info, "Details for one pid",
                             idempotent=True),
    "process.signal": ToolSpec(process_signal, "Send a specific signal"),
    "process.cgroup": ToolSpec(process_cgroup, "Read a pid's cgroup info",
                               idempotent=True),
    "service.list": ToolSpec(service_list, "List systemd services",
                             idempotent=True),
    "service.start": ToolSpec(_service_verb("start"), "Start a service"),
    "service.stop": ToolSpec(_service_verb("stop"), "Stop a service",
                             requires_confirmation=True),
    "service.restart": ToolSpec(_service_verb("restart"), "Restart a service"),
    "service.status": ToolSpec(service_status, "Service active state",
                               idempotent=True),
    "monitor.cpu": ToolSpec(monitor_cpu, "CPU utilization", idempotent=True),
    "monitor.memory": ToolSpec(monitor_memory, "Memory usage", idempotent=True),
    "monitor.disk": ToolSpec(monitor_disk, "Disk usage by partition",
                             idempotent=True),
    "monitor.network": ToolSpec(monitor_network, "Network IO counters",
                                idempotent=True),
    "monitor.logs": ToolSpec(monitor_logs, "Tail a log file or the journal",
                             idempotent=True),
    "monitor.ebpf_trace": ToolSpec(monitor_ebpf_trace,
                                   "Short bpftrace capture"),
    "monitor.fs_watch": ToolSpec(monitor_fs_watch,
                                 "Watch a directory for changes"),
    "hw.info": ToolSpec(hw_info, "Hardware summary incl. TPU devices",
                        idempotent=True),
}
