"""Device-time attribution: per-graph cost ledger + sampled dispatch timing.

Every observability layer before this one was host-side by construction
(the PR 8 flight recorder stamps *when* a dispatch was submitted, the
PR 9 analyzer proves *who may* dispatch) — none of them could say what a
dispatch COST on the device. Production engines attribute device time
per kernel/graph to drive capacity and regression decisions (RTP-LLM,
PAPERS.md); this module closes that gap for the serving plane:

  * **Per-graph cost ledger** — every AOT-compiled serving graph
    (prefill buckets, decode steps, masked/jump/spec/draft/verify,
    restore, the seq-sharded twins) registers at warmup/attach with the
    static ``compiled.cost_analysis()`` FLOPs + bytes estimates and its
    compile seconds, keyed by the CLOSED :data:`GRAPH_KINDS` enum (the
    same kind strings as ``aios_tpu_engine_xla_compiles_total``); every
    dispatch increments that graph kind's counters.
  * **Sampled device timing** — every Nth dispatch
    (``AIOS_TPU_DEVPROF_SAMPLE``, default 32) the dispatch site times
    completion via a block-until-ready delta; the decode dispatch worker
    samples ONLY when the depth-2 double buffer has slack (no second
    dispatch queued behind it), so the pipeline never stalls for a
    measurement. Samples feed per-graph device-seconds plus derived MFU
    and HBM-bandwidth-utilization gauges against the per-``device_kind``
    peaks in docs/HARDWARE.md (the roofline source of truth); an unknown
    device kind omits the utilization gauges and keeps raw seconds.
  * **Per-request / per-tenant attribution** — sampled device-µs join
    the flight recorder's dispatch events, timelines total estimated
    device-seconds (``Timeline.device_us``), and the batcher bills
    ``aios_tpu_devprof_tenant_device_seconds_total`` at retirement — the
    accounting primitive per-tenant cost and capacity need.
  * **On-demand capture** — ``/debug/profile?secs=N`` (obs/http.py) runs
    a bounded, one-at-a-time ``jax.profiler`` trace into
    ``AIOS_TPU_DEVPROF_DUMP_DIR`` (409 while one is running, hard cap
    :data:`CAPTURE_MAX_SECS`, disabled unless the dump dir is set).

Everything is OFF by default and compiled into the hot paths as the
same near-zero-cost no-op pattern as ``aios_tpu/faults``: the engine
holds ``self._devprof = None`` unless ``AIOS_TPU_DEVPROF`` armed it at
construction, and every hot-path touch is one attribute ``None`` check.
With devprof ON, token streams, dispatch counts, and compile counters
are identical to OFF (tests/test_devprof.py pins it — the PR 6/7/8
invariant, extended).

Timing caveat: a sample measures graph-call start -> result-ready on the
host, which on the TPU backend is device execution plus dispatch/readback
overhead (an upper bound on device busy time) and on the CPU backend is
exact (XLA executes inline). Restore and mid-chunk samples are
submit-side (their scatters are deliberately async) — documented per
kind in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Dict, Optional, Tuple

from ..analysis.locks import make_lock

log = logging.getLogger("aios.obs")

__all__ = [
    "GRAPH_KINDS", "DEVICE_PEAKS", "CAPTURE_MAX_SECS", "DevprofLedger",
    "CaptureBusy", "CaptureDisabled", "enabled", "sample_every",
    "local_device_kind", "ledgers_for", "snapshot_all", "start_capture",
    "capture_status",
]

# The CLOSED enum of serving-graph kinds — one entry per XLA graph
# family the engine compiles (the ``kind`` strings of
# aios_tpu_engine_xla_compiles_total). Ledger call sites must use these
# literals (tests/test_obs_lint.py checks every ``_devprof_note`` call
# site on the AST); :meth:`DevprofLedger.register` rejects anything
# else, so a new graph family is a reviewed enum change, not a stray
# string growing the ``graph`` label set.
GRAPH_KINDS = (
    "step",          # plain/unified decode (the dispatch-worker path)
    "masked",        # grammar-masked 1-step decode
    "prefill",       # whole-prompt prefill buckets
    "seq_prefill",   # sequence-sharded (sp-axis) prefill twins
    "chunk",         # chunked-admission mid/final chunks
    "spec",          # n-gram speculative verify rounds
    "draft_spec",    # fused draft-model propose+verify rounds
    "draft_ingest",  # bulk draft-KV catch-up writes
    "jump",          # grammar jump-ahead multi-token verify
    "mega",          # multi-tick decode megagraph (K ticks per dispatch)
    "restore",       # host-tier KV restore scatters
    "hist",          # prefix-hit history backfill
)

# Published per-chip peaks, keyed by jax ``device_kind``: (dense bf16
# FLOP/s, HBM bytes/s). docs/HARDWARE.md holds the same table and is the
# ROOFLINE SOURCE OF TRUTH — update both together. An unmatched kind
# (CPU backend, future chips) keeps raw device-seconds and omits the
# MFU / HBM-utilization gauges rather than inventing a denominator.
DEVICE_PEAKS: Dict[str, Tuple[float, float]] = {
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}

# /debug/profile hard cap: a profiler trace buffers device events in
# memory and stalls nothing, but an unbounded capture would grow until
# the operator remembers it — 60 s covers any realistic triage window.
CAPTURE_MAX_SECS = 60.0

_DEFAULT_SAMPLE_EVERY = 32


def enabled() -> bool:
    """Whether ``AIOS_TPU_DEVPROF`` arms the ledger (read at ENGINE
    CONSTRUCTION — arming is a per-engine decision, like the pipeline
    knob, so a live engine never grows instrumentation mid-serving)."""
    return os.environ.get("AIOS_TPU_DEVPROF", "").lower() in (
        "1", "on", "true", "yes"
    )


def sample_every() -> int:
    """``AIOS_TPU_DEVPROF_SAMPLE``: time every Nth dispatch (default
    32, floor 1 = every dispatch; the lenient-env convention)."""
    raw = os.environ.get("AIOS_TPU_DEVPROF_SAMPLE", "").strip()
    if not raw:
        return _DEFAULT_SAMPLE_EVERY
    try:
        return max(int(raw), 1)
    except ValueError:
        log.warning(
            "AIOS_TPU_DEVPROF_SAMPLE=%r ignored (expected a positive "
            "integer)", raw,
        )
        return _DEFAULT_SAMPLE_EVERY


def local_device_kind() -> str:
    """The jax ``device_kind`` of device 0, or "" when no backend is
    reachable (devprof then keeps raw seconds, no roofline)."""
    try:
        import jax

        return str(getattr(jax.devices()[0], "device_kind", ""))
    except Exception as exc:  # noqa: BLE001 - obs must not break loading
        log.warning("devprof: no jax backend for device_kind (%s)", exc)
        return ""


def resolve_peaks(device_kind: str) -> Optional[Tuple[float, float]]:
    """(peak FLOP/s, peak HBM bytes/s) for a device kind, or None when
    the kind is not in the table (utilization gauges are then omitted)."""
    if not device_kind:
        return None
    hit = DEVICE_PEAKS.get(device_kind)
    if hit is not None:
        return hit
    # lenient prefix match: libtpu has shipped kinds like
    # "TPU v5 lite" vs "TPU v5litepod" across versions
    for name, peaks in DEVICE_PEAKS.items():
        if device_kind.startswith(name):
            return peaks
    return None


def _cost_of(compiled) -> Optional[Tuple[float, float]]:
    """(flops, bytes) per dispatch from an AOT-compiled executable's
    static cost analysis; None when the backend provides nothing usable
    (the ledger then keeps dispatch counts and timing, no roofline)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - optional metadata, backend-dependent
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    byt = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and byt <= 0.0:
        return None
    return (flops, byt)


class _GraphStat:
    """Per-graph-kind accumulators. ``sampled_*`` sum over the sampled
    dispatches only; the estimated total device time extrapolates their
    mean over every dispatch."""

    __slots__ = (
        "dispatches", "est_flops", "est_bytes", "compiles",
        "compile_seconds", "samples", "sampled_seconds", "sampled_flops",
        "sampled_bytes",
    )

    def __init__(self) -> None:
        self.dispatches = 0
        self.est_flops = 0.0
        self.est_bytes = 0.0
        self.compiles = 0
        self.compile_seconds = 0.0
        self.samples = 0
        self.sampled_seconds = 0.0
        self.sampled_flops = 0.0
        self.sampled_bytes = 0.0


# per-model WeakSets of live ledgers (one per replica engine): the
# scrape gauges and /debug/devprof SUM over them (the
# aios_tpu_prefix_host_* aggregation lesson — set_function is
# last-writer-wins across replicas). Plain lock: registration happens at
# engine construction / debug reads only, never on a dispatch path.
_LEDGERS: Dict[str, "weakref.WeakSet[DevprofLedger]"] = {}
_reg_lock = threading.Lock()


def ledgers_for(model: str) -> "weakref.WeakSet[DevprofLedger]":
    with _reg_lock:
        return _LEDGERS.setdefault(model, weakref.WeakSet())


class DevprofLedger:
    """One engine's device-time ledger: per-graph dispatch counters,
    static cost estimates, and sampled completion timings. All methods
    are O(1) dict work under the ledger's own lock — never a dispatch,
    readback, or RPC (the analyzer's devprof lock declaration)."""

    def __init__(self, model: str, device_kind: Optional[str] = None,
                 sample_n: Optional[int] = None) -> None:
        self.model = model
        self.device_kind = (
            device_kind if device_kind is not None else local_device_kind()
        )
        self.peaks = resolve_peaks(self.device_kind)
        self.sample_n = sample_n if sample_n is not None else sample_every()
        self._lock = make_lock("devprof")
        self._graphs: Dict[str, _GraphStat] = {}  #: guarded_by _lock
        # (kind, graph-store key) -> (flops, bytes) per dispatch
        self._costs: Dict[Tuple[str, object], Tuple[float, float]] = {}  #: guarded_by _lock
        self._backlog = 0  #: guarded_by _lock
        self._last: Optional[Tuple[str, float]] = None  #: guarded_by _lock
        ledgers_for(model).add(self)

    # -- registration (warmup / attach) ------------------------------------

    def register(self, kind: str, key, compiled, compile_s: float) -> None:
        """Record one AOT-compiled graph: its compile time and the
        static cost estimate the dispatch counters will charge per
        dispatch. ``kind`` must be a :data:`GRAPH_KINDS` member."""
        if kind not in GRAPH_KINDS:
            raise ValueError(
                f"unknown devprof graph kind {kind!r} (closed enum "
                f"GRAPH_KINDS — extend it with review)"
            )
        cost = _cost_of(compiled) if compiled is not None else None
        with self._lock:
            g = self._graphs.setdefault(kind, _GraphStat())
            g.compiles += 1
            g.compile_seconds += float(compile_s)
            if cost is not None:
                self._costs[(kind, key)] = cost

    # -- hot path ----------------------------------------------------------

    def note(self, kind: str, key=None) -> bool:
        """Count one dispatch of ``kind``; True when this dispatch is
        due a timing sample (the 1st, then every Nth)."""
        with self._lock:
            g = self._graphs.setdefault(kind, _GraphStat())
            g.dispatches += 1
            cost = self._costs.get((kind, key))
            if cost is not None:
                g.est_flops += cost[0]
                g.est_bytes += cost[1]
            return (g.dispatches - 1) % self.sample_n == 0

    def sample(self, kind: str, key, secs: float) -> None:
        """Land one completion-timing sample for ``kind``."""
        with self._lock:
            g = self._graphs.setdefault(kind, _GraphStat())
            g.samples += 1
            g.sampled_seconds += secs
            cost = self._costs.get((kind, key))
            if cost is not None:
                g.sampled_flops += cost[0]
                g.sampled_bytes += cost[1]
            self._last = (kind, secs)

    def take_last_sample(self) -> Optional[Tuple[str, float]]:
        """Pop the most recent (kind, seconds) sample — the batcher
        joins it onto the flight-recorder event of the dispatch it just
        issued (all dispatches of one batcher are scheduler-thread
        sequential, so last-sample is that dispatch's or None)."""
        with self._lock:
            last, self._last = self._last, None
            return last

    # dispatch-worker backlog (the depth-2 double buffer): the worker
    # samples only when nothing is queued behind it, so a measurement
    # never delays the next dispatch's submission.

    def enqueue(self) -> None:
        with self._lock:
            self._backlog += 1

    def dequeue(self) -> None:
        with self._lock:
            self._backlog = max(self._backlog - 1, 0)

    def queue_depth(self) -> int:
        with self._lock:
            return self._backlog

    # -- reads -------------------------------------------------------------

    def mean_s(self, kind: str) -> Optional[float]:
        """Mean sampled device-seconds per dispatch of ``kind`` (None
        before the first sample) — the per-request attribution rate."""
        with self._lock:
            g = self._graphs.get(kind)
            if g is None or not g.samples:
                return None
            return g.sampled_seconds / g.samples

    def totals(self, kind: str) -> Tuple[float, float, float, float, float,
                                         float, float]:
        """(dispatches, est_flops, est_bytes, samples, sampled_seconds,
        sampled_flops, sampled_bytes) for gauge aggregation across
        replica ledgers."""
        with self._lock:
            g = self._graphs.get(kind)
            if g is None:
                return (0.0,) * 7
            return (
                float(g.dispatches), g.est_flops, g.est_bytes,
                float(g.samples), g.sampled_seconds, g.sampled_flops,
                g.sampled_bytes,
            )

    def device_seconds(self, kind: str) -> float:
        """Estimated total device-busy seconds for ``kind``: mean
        sampled completion time extrapolated over every dispatch."""
        with self._lock:
            g = self._graphs.get(kind)
            if g is None or not g.samples:
                return 0.0
            return g.sampled_seconds / g.samples * g.dispatches

    def snapshot(self) -> dict:
        """The ledger as JSON-shaped dict (bench_devprof /
        /debug/devprof): one entry per graph kind that dispatched or
        compiled, with utilization only where the roofline is known."""
        with self._lock:
            graphs = {k: g for k, g in self._graphs.items()
                      if g.dispatches or g.compiles}
            out: dict = {
                "model": self.model,
                "device_kind": self.device_kind,
                "sample_every": self.sample_n,
                "graphs": {},
            }
            for kind in GRAPH_KINDS:
                g = graphs.get(kind)
                if g is None:
                    continue
                entry: dict = {
                    "dispatches": g.dispatches,
                    "compiles": g.compiles,
                    "compile_seconds": round(g.compile_seconds, 4),
                    "est_flops": g.est_flops,
                    "est_bytes": g.est_bytes,
                    "samples": g.samples,
                    "sampled_seconds": round(g.sampled_seconds, 6),
                }
                if g.samples:
                    per = g.sampled_seconds / g.samples
                    entry["device_seconds_per_dispatch"] = round(per, 6)
                    entry["device_seconds"] = round(per * g.dispatches, 4)
                if self.peaks is not None and g.sampled_seconds > 0:
                    # 4 significant digits, NOT round(x, 4): a CPU-run
                    # ratio against a TPU roofline is ~1e-10 and a fixed
                    # decimal rounding would zero it out of the JSON
                    pf, pb = self.peaks
                    if g.sampled_flops:
                        entry["mfu"] = float(
                            f"{g.sampled_flops / g.sampled_seconds / pf:.4g}"
                        )
                    if g.sampled_bytes:
                        entry["hbm_bw_util"] = float(
                            f"{g.sampled_bytes / g.sampled_seconds / pb:.4g}"
                        )
                out["graphs"][kind] = entry
            return out


def snapshot_all(model: str = "") -> dict:
    """Every live ledger's snapshot, grouped per model (the
    /debug/devprof payload; replica ledgers list separately — the
    metric gauges do the summing)."""
    with _reg_lock:
        items = {
            m: list(s) for m, s in _LEDGERS.items()
            if (not model or m == model)
        }
    return {
        "capture": capture_status(),
        "models": {
            m: [led.snapshot() for led in leds]
            for m, leds in items.items() if leds
        },
    }


# -- on-demand profiler capture (/debug/profile) ----------------------------

class CaptureBusy(RuntimeError):
    """A capture is already running (HTTP 409)."""


class CaptureDisabled(RuntimeError):
    """AIOS_TPU_DEVPROF_DUMP_DIR is not set (HTTP 403)."""


_capture_lock = threading.Lock()  # capture start/stop only, never hot-path
_capture = {"busy": False, "path": "", "started": 0.0, "secs": 0.0}


def capture_status() -> dict:
    with _capture_lock:
        return dict(_capture)


def start_capture(secs: float) -> dict:
    """Start a bounded ``jax.profiler`` trace into
    ``AIOS_TPU_DEVPROF_DUMP_DIR`` on a daemon thread; one at a time.
    Returns {path, secs}; raises :class:`CaptureDisabled` /
    :class:`CaptureBusy`. ``secs`` clamps to (0, CAPTURE_MAX_SECS]."""
    dump_dir = os.environ.get("AIOS_TPU_DEVPROF_DUMP_DIR", "").strip()
    if not dump_dir:
        raise CaptureDisabled(
            "profiler capture disabled: set AIOS_TPU_DEVPROF_DUMP_DIR"
        )
    secs = min(max(float(secs), 0.05), CAPTURE_MAX_SECS)
    with _capture_lock:
        if _capture["busy"]:
            raise CaptureBusy(
                f"capture already running ({_capture['path']}, "
                f"{_capture['secs']:g}s)"
            )
        path = os.path.join(dump_dir, f"devprof-{int(time.time())}")
        _capture.update(
            busy=True, path=path, started=time.time(), secs=secs
        )

    def run() -> None:
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            with jax.profiler.trace(path):
                time.sleep(secs)
            log.warning("devprof capture (%.2fs) -> %s", secs, path)
        except Exception:  # noqa: BLE001 - capture must never crash serving
            log.exception("devprof capture failed")
        finally:
            with _capture_lock:
                _capture["busy"] = False

    threading.Thread(
        target=run, name="devprof-capture", daemon=True
    ).start()
    return {"profiling": True, "path": path, "secs": secs}
