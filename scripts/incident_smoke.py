#!/usr/bin/env python3
"""Incident-bundle smoke: two REAL processes, a seeded fault storm, and
a deterministic incident verdict (the preflight.sh gate 9;
docs/TESTING.md, docs/RUNBOOK.md §12).

One round:

  1. spawn worker A (scripts/fleet_worker.py — fleet + tsdb + incidents
     armed) and worker B seeded with A's metrics endpoint AND a seeded
     fault schedule (``AIOS_TPU_FAULTS=seed=7;pool.scheduler_crash=
     nth:4``) — membership converges through announce gossip;
  2. drive a request wave at B over gRPC until the seeded crash fires;
     the injector's fired-fault hook must freeze an incident bundle with
     cause ``fault`` on B;
  3. assert the bundle carries the fired-fault journal evidence
     (point/mode/hit) AND a non-empty tsdb window (the ring was sampling
     while the wave ran);
  4. assert ``GET /debug/tsdb/fleet`` on A federates tsdb series from
     BOTH hosts, and ``fleetctl history`` against A exits 0;
  5. normalize the fault-cause bundles (cause, model, trigger fields,
     fired-fault tail) into the round verdict.

The whole round runs TWICE; the verdicts must be identical (the seeded
schedule makes the crash — and therefore the incident — replayable).
Human progress goes to stderr; ONE JSON verdict line goes to stdout.
Exit 0 on pass.

FLEET_SMOKE_TIME_SCALE stretches every window and timeout on slow
containers, same as the other fleet smokes.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

SCALE = float(os.environ.get("FLEET_SMOKE_TIME_SCALE", "1") or 1)
INTERVAL = 0.3 * SCALE
MODEL = "fleet-smoke"  # the one model fleet_worker.py loads
FAULT_SPEC = "seed=7;pool.scheduler_crash=nth:4"


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def worker_env(host_id: str, peers: str = "", faults: str = "") -> dict:
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
        "AIOS_TPU_FLEET": "1",
        "AIOS_TPU_FLEET_HOST": host_id,
        "AIOS_TPU_FLEET_PEERS": peers,
        "AIOS_TPU_FLEET_INTERVAL_SECS": str(INTERVAL),
        # the observability plane under test: the ring samples fast so
        # the bundle's window is non-empty within a short wave, and the
        # incident builder's aftermath wait stays short
        "AIOS_TPU_TSDB": "1",
        "AIOS_TPU_TSDB_STEP_SECS": "0.2",
        "AIOS_TPU_INCIDENT_WINDOW_SECS": "1",
        "AIOS_TPU_INCIDENT_COOLDOWN_SECS": "0",
        "AIOS_TPU_FAULTS": faults,
    }


def spawn_worker(host_id: str, peers: str = "", faults: str = "") -> tuple:
    """-> (Popen, grpc_port, metrics_port); waits for the ready line."""
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_worker.py")],
        env=worker_env(host_id, peers, faults), cwd=REPO,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    deadline = time.monotonic() + 180 * SCALE
    while True:
        line = p.stdout.readline()
        if line.startswith("FLEET_WORKER_READY "):
            ports = json.loads(line.split(" ", 1)[1])
            return p, ports["grpc_port"], ports["metrics_port"]
        if not line and p.poll() is not None:
            raise RuntimeError(f"worker {host_id} died before ready")
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError(f"worker {host_id} never became ready")


def fetch_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode("utf-8"))


def poll(fn, what: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1 * SCALE)
    raise RuntimeError(f"timed out waiting for {what}")


def request_wave(grpc_port: int, tag: str, n: int = 6) -> None:
    """Enough scheduler ticks to walk the seeded nth:4 crash trigger
    past its firing point (the pool respawns and keeps serving)."""
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2

    for i in range(n):
        channel = rpc.insecure_channel(f"127.0.0.1:{grpc_port}")
        try:
            services.AIRuntimeStub(channel).Infer(
                runtime_pb2.InferRequest(
                    model=MODEL, prompt=f"storm {tag} {i}",
                    max_tokens=8, temperature=5e-5,
                    task_id=f"incident-smoke-{tag}-{i}",
                ),
                timeout=120,
            )
        finally:
            channel.close()


def norm_bundle(bundle: dict) -> dict:
    """A bundle modulo timestamps/ids/window content: the trigger
    identity and the fired-fault evidence must replay exactly."""
    return {
        "model": bundle["model"],
        "cause": bundle["cause"],
        "fields": bundle["fields"],
        "faults": [
            {k: e.get(k) for k in ("point", "mode", "hit", "model")}
            for e in bundle["faults"]
            if e.get("point") == "pool.scheduler_crash"
        ],
    }


def run_round(tag: str) -> dict:
    pa, _grpc_a, metrics_a = spawn_worker("hostA")
    pb = None
    try:
        pb, grpc_b, metrics_b = spawn_worker(
            "hostB", peers=f"127.0.0.1:{metrics_a}", faults=FAULT_SPEC,
        )
        log(f"[{tag}] workers up: A metrics={metrics_a}, "
            f"B grpc={grpc_b} metrics={metrics_b} faults={FAULT_SPEC!r}")

        def both_up():
            members = fetch_json(metrics_a, "/fleet/members")["members"]
            ups = {m["host"] for m in members if m["state"] == "up"}
            return ups == {"hostA", "hostB"}

        poll(both_up, "both members up on A", 30 * SCALE)
        log(f"[{tag}] membership converged")

        request_wave(grpc_b, tag)

        def fault_incident():
            incs = fetch_json(metrics_b, "/debug/incidents")["incidents"]
            return [m for m in incs if m["cause"] == "fault"]

        metas = poll(fault_incident, "a fault-cause incident on B",
                     30 * SCALE)
        bundles = [
            fetch_json(metrics_b, f"/debug/incidents?id={m['id']}")
            for m in metas
        ]
        log(f"[{tag}] {len(bundles)} fault incident(s) frozen on B")

        # the bundle holds the cross-layer evidence, not just the label:
        # the fired-fault journal entry AND a sampled tsdb window
        assert any(
            e.get("point") == "pool.scheduler_crash" and e.get("hit") == 4
            for b in bundles for e in b["faults"]
        ), "no bundle carries the fired pool.scheduler_crash journal entry"
        assert any(
            b["tsdb"]["armed"] and b["tsdb"]["series"] for b in bundles
        ), "no bundle froze a non-empty tsdb window"
        log(f"[{tag}] bundle carries fault journal + tsdb window")

        # the crash-respawn edge must be visible in a frozen window: the
        # scheduler crash increments the restarts counter, the ring
        # samples it as a delta, and SOME bundle's window (the fault
        # trigger's aftermath, or the crash_respawn snapshot's own
        # incident) holds a positive point for it
        def respawn_edge_frozen():
            metas = fetch_json(metrics_b, "/debug/incidents")["incidents"]
            for m in metas:
                b = fetch_json(metrics_b,
                               f"/debug/incidents?id={m['id']}")
                for s in b["tsdb"]["series"]:
                    if (s["name"] == "aios_tpu_serving_replica_"
                                     "restarts_total"
                            and sum(v for _, v in s["points"]) > 0):
                        return True
            return False

        poll(respawn_edge_frozen,
             "the crash-respawn edge in a frozen tsdb window", 30 * SCALE)
        log(f"[{tag}] a frozen window shows the crash-respawn edge")

        def federated_tsdb():
            got = fetch_json(
                metrics_a,
                "/debug/tsdb/fleet?name=aios_tpu_tsdb_sample_passes_total"
                "&verb=raw&window=60",
            )
            hosts = {
                h for h, payload in got.get("hosts", {}).items()
                if payload.get("series")
            }
            return {"hostA", "hostB"} <= hosts

        poll(federated_tsdb, "tsdb series from both hosts on A",
             15 * SCALE)
        log(f"[{tag}] /debug/tsdb/fleet federates both hosts")

        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleetctl.py"),
             "history", "aios_tpu_tsdb_sample_passes_total",
             "--target", f"127.0.0.1:{metrics_a}"],
            cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode
        assert rc == 0, f"fleetctl history exited {rc} with live series"
        log(f"[{tag}] fleetctl history: 0")

        return {
            "bundles": sorted(
                (norm_bundle(b) for b in bundles),
                key=lambda b: json.dumps(b, sort_keys=True),
            ),
        }
    finally:
        for p in (pa, pb):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def main() -> int:
    rounds = [run_round("round1"), run_round("round2")]
    identical = rounds[0] == rounds[1]
    has_fault = any(
        b["cause"] == "fault"
        and b["fields"].get("point") == "pool.scheduler_crash"
        for b in rounds[0]["bundles"]
    )
    verdict = {
        "smoke": "incidents",
        "fault_spec": FAULT_SPEC,
        "bundles": rounds[0]["bundles"],
        "identical": identical,
        "fault_incident": has_fault,
        "pass": identical and has_fault,
    }
    print(json.dumps(verdict, sort_keys=True))
    if not identical:
        log("FAIL: incident verdicts diverged across seeded runs:")
        log(f"  round1: {rounds[0]}")
        log(f"  round2: {rounds[1]}")
    if not has_fault:
        log(f"FAIL: no fault-cause incident for the seeded crash: "
            f"{rounds[0]['bundles']}")
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
