"""Mixture-of-experts: HF parity, dispatch/dense equivalence, EP sharding.

The reference's only MoE access is the cloud qwen3:30b endpoint behind the
api-gateway (api-gateway/src/main.rs:70-88); serving MoE models locally
(Qwen3-30B-A3B / Mixtral class) is a TPU-build extension. Ground truth is
transformers' Mixtral/Qwen3-MoE implementations on CPU fp32, same pattern
as test_model_parity.py.
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from aios_tpu.engine import model as M
from aios_tpu.engine import moe as moe_mod
from aios_tpu.engine import weights as W
from aios_tpu.engine.config import (
    MIXTRAL_8X7B,
    QWEN3_30B_A3B,
    TINY_MOE,
    from_gguf_metadata,
    from_hf_config,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow

ATOL = 2e-4
RTOL = 2e-4


def _hf_logits(hf_model, tokens):
    with torch.no_grad():
        out = hf_model(torch.tensor(tokens, dtype=torch.long))
    return out.logits.float().numpy()


def _tokens(cfg, batch=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)


@pytest.fixture(scope="module")
def mixtral_pair():
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=None,
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    hf = MixtralForCausalLM(hf_cfg).eval()
    cfg = from_hf_config(hf_cfg.to_dict(), name="tiny-mixtral-test")
    return hf, cfg


@pytest.fixture(scope="module")
def qwen3_moe_pair():
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    hf_cfg = Qwen3MoeConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        moe_intermediate_size=32,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=2,
        head_dim=8,
        num_experts=8,
        num_experts_per_tok=3,
        norm_topk_prob=True,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(8)
    hf = Qwen3MoeForCausalLM(hf_cfg).eval()
    cfg = from_hf_config(hf_cfg.to_dict(), name="tiny-qwen3moe-test")
    return hf, cfg


def test_mixtral_config_mapping(mixtral_pair):
    _, cfg = mixtral_pair
    assert cfg.moe and cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
    assert cfg.expert_dim == 96  # mixtral experts use intermediate_size
    assert cfg.norm_topk_prob  # mixtral always renormalizes top-k


def test_qwen3_moe_config_mapping(qwen3_moe_pair):
    _, cfg = qwen3_moe_pair
    assert cfg.moe and cfg.num_experts == 8 and cfg.num_experts_per_tok == 3
    assert cfg.expert_dim == 32  # qwen3-moe has a separate expert width
    assert cfg.qk_norm


def test_mixtral_logits_parity(mixtral_pair):
    hf, cfg = mixtral_pair
    tokens = _tokens(cfg)
    params = W.params_from_hf_state_dict(hf.state_dict(), cfg)
    np.testing.assert_allclose(
        np.asarray(M.forward_full(params, cfg, tokens, kernels=False)),
        _hf_logits(hf, tokens),
        atol=ATOL,
        rtol=RTOL,
    )


def test_qwen3_moe_logits_parity(qwen3_moe_pair):
    hf, cfg = qwen3_moe_pair
    tokens = _tokens(cfg, seed=4)
    params = W.params_from_hf_state_dict(hf.state_dict(), cfg)
    np.testing.assert_allclose(
        np.asarray(M.forward_full(params, cfg, tokens, kernels=False)),
        _hf_logits(hf, tokens),
        atol=ATOL,
        rtol=RTOL,
    )


# ---------------------------------------------------------------------------
# dense vs dispatch
# ---------------------------------------------------------------------------


def _layer0(params):
    return {k: v[0] for k, v in params["layers"].items()}


def test_dispatch_matches_dense_at_full_capacity():
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = _layer0(params)
    h = jax.random.normal(jax.random.PRNGKey(2), (2, 16, cfg.hidden_size))
    dense, aux_d = moe_mod.moe_ffn_dense(h, lp, cfg)
    N = h.shape[0] * h.shape[1]
    disp, aux_p = moe_mod.moe_ffn_dispatch(
        h, lp, cfg, capacity=N * cfg.num_experts_per_tok
    )
    np.testing.assert_allclose(dense, disp, atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(aux_d, aux_p, atol=1e-6, rtol=1e-6)


def test_dispatch_drops_only_overflow_tokens():
    """With capacity 8 on a 4-expert/top-2 router over 32 tokens, some
    picks overflow; output must stay finite and within the span of the
    dense result (dropped picks zero one expert's contribution)."""
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    lp = _layer0(params)
    h = jax.random.normal(jax.random.PRNGKey(3), (2, 16, cfg.hidden_size))
    out, aux = moe_mod.moe_ffn_dispatch(h, lp, cfg, capacity=8)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(float(aux))


def test_gather_matches_dense():
    """The gathered-expert path (stream only routed experts' weights) is
    exact — identical math to dense, reordered."""
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = _layer0(params)
    h = jax.random.normal(jax.random.PRNGKey(5), (2, 3, cfg.hidden_size))
    dense, aux_d = moe_mod.moe_ffn_dense(h, lp, cfg)
    gath, aux_g = moe_mod.moe_ffn_gather(h, lp, cfg)
    np.testing.assert_allclose(gath, dense, atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(aux_g, aux_d, atol=1e-6, rtol=1e-6)


def test_gather_matches_dense_quantized():
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    h = jax.random.normal(jax.random.PRNGKey(6), (1, 2, cfg.hidden_size))
    for fuse in (True, False):
        qp = M.quantize_params(params, fuse=fuse)
        lp = {
            k: (v[0] if not isinstance(v, dict)
                else {"q": v["q"][0], "s": v["s"][0]})
            for k, v in qp["layers"].items()
        }
        dense, _ = moe_mod.moe_ffn_dense(h, lp, cfg)
        gath, _ = moe_mod.moe_ffn_gather(h, lp, cfg)
        np.testing.assert_allclose(gath, dense, atol=1e-6, rtol=1e-6)


def test_engine_auto_selects_gather_only_when_sparse(monkeypatch):
    """AIOS_TPU_MOE_GATHER=1 + slots*k < X -> gathered decode (streams only
    routed experts); otherwise dense (chip-measured default: dense wins at
    small expert sizes). Sharded engines never gather (ep psum instead)."""
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    monkeypatch.setenv("AIOS_TPU_MOE_GATHER", "1")
    cfg = TINY_MOE  # X=4, k=2
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    e1 = TPUEngine(cfg, params, num_slots=1, max_context=64,
                   cache_dtype=jnp.float32)
    assert e1._moe_impl == "gather"
    out_gather = e1.generate([1, 2, 3, 4, 5], max_new_tokens=16,
                             temperature=0.0)
    e1.close()
    e2 = TPUEngine(cfg, params, num_slots=4, max_context=64,
                   cache_dtype=jnp.float32)
    assert e2._moe_impl is None  # 4*2 >= 4 experts: dense
    out_dense = e2.generate([1, 2, 3, 4, 5], max_new_tokens=16,
                            temperature=0.0)
    e2.close()
    assert out_gather == out_dense
    e3 = TPUEngine(cfg, params, num_slots=2, max_context=64,
                   cache_dtype=jnp.float32,
                   shardings=ShardingPlan(build_mesh(8, dp=2, ep=2, tp=2)))
    assert e3._moe_impl is None
    e3.close()


def test_verify_gather_gating(monkeypatch):
    """Verify feeds K+1 tokens per slot, so spec rounds fall back to dense
    when S*(K+1)*k reaches the expert count; decode keeps gathering."""
    from aios_tpu.engine.engine import TPUEngine

    monkeypatch.setenv("AIOS_TPU_MOE_GATHER", "1")
    cfg = TINY_MOE  # X=4, k=2
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    seen = {}
    real_verify = M.verify_step

    def spy(*args, **kw):
        seen["verify_moe_impl"] = kw.get("moe_impl")
        return real_verify(*args, **kw)

    monkeypatch.setattr(M, "verify_step", spy)
    eng = TPUEngine(cfg, params, num_slots=1, max_context=64,
                    cache_dtype=jnp.float32)
    assert eng._moe_impl == "gather"
    eng.prefill(0, [1, 2, 3, 4], temperature=0.0)
    eng.spec_step(1, draft_len=3)  # 1*(3+1)*2 = 8 >= 4 experts -> dense
    eng.close()
    assert seen["verify_moe_impl"] is None


def test_env_var_overrides_engine_gather(monkeypatch):
    """AIOS_TPU_MOE_IMPL is the operator's escape hatch: it beats the
    engine's static 'gather' choice at trace time."""
    from aios_tpu.engine import moe as moe_mod_check  # noqa: F401

    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    h = jax.random.normal(jax.random.PRNGKey(7), (1, 1, cfg.hidden_size))
    lp = _layer0(params)
    called = {}
    real = moe_mod.moe_ffn_dense

    def spy(*a, **k):
        called["dense"] = True
        return real(*a, **k)

    monkeypatch.setattr(moe_mod, "moe_ffn_dense", spy)
    monkeypatch.setenv("AIOS_TPU_MOE_IMPL", "dense")
    M._mlp(h, {**lp, "ffn_norm": lp["ffn_norm"]}, cfg, moe_impl="gather")
    assert called.get("dense")


def test_spec_decode_under_gather(monkeypatch):
    from aios_tpu.engine.engine import TPUEngine

    monkeypatch.setenv("AIOS_TPU_MOE_GATHER", "1")
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = TPUEngine(cfg, params, num_slots=1, max_context=64,
                    cache_dtype=jnp.float32)
    assert eng._moe_impl == "gather"
    ref = eng.generate([1, 2, 3, 4] * 3, max_new_tokens=16, temperature=0.0)
    eng.release(0)
    first = eng.prefill(0, [1, 2, 3, 4] * 3, temperature=0.0)
    got = [first]
    while len(got) < 16:
        toks, counts = eng.spec_step(1, draft_len=3)
        got.extend(toks[0, 0, : int(counts[0, 0])].tolist())
    eng.close()
    assert got[:16] == ref


def test_load_balance_aux_perfectly_balanced():
    """Uniform router probs + uniform assignment -> aux == 1.0."""
    probs = jnp.full((8, 4), 0.25)
    idx = jnp.tile(jnp.asarray([[0, 1], [2, 3]], jnp.int32), (4, 1))
    aux = moe_mod.load_balance_aux(probs, idx, 4)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-6)


def test_serving_forward_never_auto_dispatches(monkeypatch):
    """The serving forward (no with_aux) must stay on the exact dense path
    even at >=1024 tokens — auto-dispatch is training-only (it drops
    overflow picks and would skew prefill logits)."""
    monkeypatch.delenv("AIOS_TPU_MOE_IMPL", raising=False)
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = _tokens(cfg, batch=1, seq=1024, seed=21)
    auto = np.asarray(M.forward_full(params, cfg, tokens, kernels=False))
    monkeypatch.setenv("AIOS_TPU_MOE_IMPL", "dense")
    dense = np.asarray(M.forward_full(params, cfg, tokens, kernels=False))
    np.testing.assert_array_equal(auto, dense)


def test_pp_train_step_moe_aux(cpu_devices):
    """Pipeline-parallel training must fold the MoE aux in (same contract
    as the GSPMD step) — bubble ticks' garbage-activation aux excluded."""
    from aios_tpu.engine.train import make_optimizer
    from aios_tpu.parallel.pipeline import (
        build_pp_mesh,
        make_pp_train_step,
        shard_pp_params,
    )

    cfg = TINY_MOE
    mesh = build_pp_mesh(pp=2, dp=2)
    params = shard_pp_params(
        M.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32), mesh
    )
    pp_init, pp_step = make_pp_train_step(
        cfg, mesh, num_microbatches=2,
        optimizer=make_optimizer(warmup_steps=1, total_steps=10),
    )
    state = pp_init(params)
    B = 2 * 2 * 2  # MB * dp * rows
    batch = {
        "tokens": jnp.asarray(_tokens(cfg, batch=B, seq=16, seed=17)),
        "loss_mask": jnp.ones((B, 16), jnp.float32),
    }
    state, metrics = jax.jit(pp_step)(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # the load-balance term is X*sum(f*P) >= 1 for any real routing; a
    # bubble-polluted or missing aux would show up as 0 or garbage
    assert 0.9 < float(metrics["moe_aux"]) < 4.0


def test_runtime_resolves_moe_presets_exactly():
    from aios_tpu.runtime.model_manager import ModelManager

    cfg = ModelManager._resolve_preset("qwen3-30b-a3b")
    assert cfg.moe and cfg.num_experts == 128
    assert ModelManager._resolve_preset("qwen3-14b").moe is False
    assert ModelManager._resolve_preset("tiny-moe").moe


def test_forward_full_with_aux():
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = _tokens(cfg, seed=6)
    logits, aux = M.forward_full(
        params, cfg, tokens, kernels=False, with_aux=True
    )
    base = M.forward_full(params, cfg, tokens, kernels=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(base))
    assert 0.9 < float(aux) < 4.0  # X * sum(f*P) >= 1, small for random


# ---------------------------------------------------------------------------
# decode + quantized serving
# ---------------------------------------------------------------------------


def test_moe_decode_matches_forward():
    """Teacher-forced decode_step logits equal forward_full's rows."""
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    seq = _tokens(cfg, batch=1, seq=8, seed=11)[0]
    full = np.asarray(
        M.forward_full(params, cfg, seq[None, :], kernels=False)
    )[0]
    k, v = M.init_kv_cache(cfg, 1, 16, jnp.float32)
    for t in range(len(seq)):
        logits, k, v = M.decode_step(
            params,
            cfg,
            jnp.asarray(seq[t : t + 1]),
            jnp.asarray([t], jnp.int32),
            k,
            v,
            kernels=False,
        )
        np.testing.assert_allclose(
            np.asarray(logits)[0], full[t], atol=1e-4, rtol=1e-4
        )


def test_moe_quantized_decode_close():
    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.ones((4,), jnp.int32)
    zeros = jnp.zeros((4,), jnp.int32)
    ref, _, _ = M.decode_step(
        params, cfg, toks, zeros, *M.init_kv_cache(cfg, 4, 16, jnp.float32),
        kernels=False,
    )
    for fuse in (True, False):
        qp = M.quantize_params(params, fuse=fuse)
        assert ("we_gateup" in qp["layers"]) == fuse
        assert isinstance(qp["layers"]["we_down"], dict)
        assert not isinstance(qp["layers"]["w_router"], dict)  # router bf16
        got, _, _ = M.decode_step(
            qp, cfg, toks, zeros, *M.init_kv_cache(cfg, 4, 16, jnp.float32),
            kernels=False,
        )
        assert np.argmax(np.asarray(got), -1).tolist() == np.argmax(
            np.asarray(ref), -1
        ).tolist()
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=0.05, rtol=0.05
        )


def test_init_quantized_params_moe_shapes():
    cfg = TINY_MOE
    qp = M.init_quantized_params(cfg, jax.random.PRNGKey(1))
    X, E, Fm = cfg.num_experts, cfg.hidden_size, cfg.expert_dim
    L = cfg.num_layers
    assert qp["layers"]["we_gateup"]["q"].shape == (L, X, E, 2 * Fm)
    assert qp["layers"]["we_gateup"]["s"].shape == (L, X, 1, 2 * Fm)
    assert qp["layers"]["we_down"]["q"].shape == (L, X, Fm, E)
    assert qp["layers"]["w_router"].shape == (L, E, X)


def test_moe_paged_decode_matches_dense_cache():
    """MoE flows through the paged KV pool unchanged (the FFN is
    orthogonal to the cache layout)."""
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = [1, 2, 3, 4, 5]
    dense = TPUEngine(cfg, params, num_slots=2, max_context=128,
                      cache_dtype=jnp.float32)
    ref = dense.generate(prompt, max_new_tokens=24, temperature=0.0)
    dense.close()
    paged = TPUEngine(cfg, params, num_slots=2, max_context=128,
                      cache_dtype=jnp.float32,
                      paged_pool_rows=256, page_size=32)
    got = paged.generate(prompt, max_new_tokens=24, temperature=0.0)
    paged.close()
    assert got == ref


# ---------------------------------------------------------------------------
# expert parallelism on the virtual mesh
# ---------------------------------------------------------------------------


def test_ep_sharded_train_step(cpu_devices):
    from aios_tpu.engine.train import make_optimizer, make_train_step
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    cfg = TINY_MOE
    mesh = build_mesh(8, dp=2, ep=2, tp=2)
    plan = ShardingPlan(mesh)
    plan.validate(cfg, num_slots=4)
    params = plan.put_params(
        M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    )
    init_state, train_step = make_train_step(
        cfg, mesh, optimizer=make_optimizer(warmup_steps=1, total_steps=10)
    )
    state = init_state(params)
    batch = {
        "tokens": jnp.asarray(_tokens(cfg, batch=4, seq=16, seed=13)),
        "loss_mask": jnp.ones((4, 16), jnp.float32),
    }
    state, metrics = jax.jit(train_step)(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["moe_aux"]))
    assert int(state["step"]) == 1


def test_ep_sharded_engine_decode_matches_single_device(cpu_devices):
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    cfg = TINY_MOE
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    plan = ShardingPlan(build_mesh(8, dp=2, ep=2, tp=2))
    eng = TPUEngine(
        cfg, params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, shardings=plan,
    )
    ref = TPUEngine(cfg, params, num_slots=4, max_context=64,
                    cache_dtype=jnp.float32)
    try:
        first = eng.prefill(0, [1, 2, 3, 4], temperature=0.0)
        toks = eng.step(3)
        f0 = ref.prefill(0, [1, 2, 3, 4], temperature=0.0)
        t0 = ref.step(3)
        assert first == f0
        assert toks.tolist() == t0.tolist()
    finally:
        eng.close()
        ref.close()


@pytest.mark.parametrize("seq_parallel", ["ring", "ulysses"])
def test_moe_train_composes_with_sequence_parallel(cpu_devices, seq_parallel):
    """MoE (ep) x sequence parallelism (sp) x TP in one train step: the
    expert FFN is orthogonal to the attention sharding, so ring/Ulysses
    and the ep psum compose on the same mesh."""
    from aios_tpu.engine.train import make_optimizer, make_train_step
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    cfg = TINY_MOE  # 4 heads, 2 kv heads: ulysses sp=2 divides both
    mesh = build_mesh(8, dp=1, sp=2, ep=2, tp=2)
    plan = ShardingPlan(mesh)
    plan.validate(cfg, num_slots=2)
    params = plan.put_params(
        M.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    )
    init_state, train_step = make_train_step(
        cfg, mesh, optimizer=make_optimizer(warmup_steps=1, total_steps=10),
        seq_parallel=seq_parallel,
    )
    state = init_state(params)
    batch = {
        "tokens": jnp.asarray(_tokens(cfg, batch=2, seq=32, seed=31)),
        "loss_mask": jnp.ones((2, 32), jnp.float32),
    }
    state, metrics = jax.jit(train_step)(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.9 < float(metrics["moe_aux"]) < 4.0


def test_ep_requires_moe_config():
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    plan = ShardingPlan(build_mesh(8, dp=2, ep=2, tp=2))
    with pytest.raises(AssertionError):
        plan.validate(TINY_TEST, num_slots=4)


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------


def test_moe_preset_param_counts():
    total = QWEN3_30B_A3B.num_params()
    active = QWEN3_30B_A3B.active_params()
    assert 29e9 < total < 32e9, total
    assert 2.5e9 < active < 4e9, active
    assert 45e9 < MIXTRAL_8X7B.num_params() < 48e9


def test_moe_config_from_gguf_metadata():
    md = {
        "general.architecture": "qwen3moe",
        "general.name": "Qwen3 30B A3B",
        "qwen3moe.block_count": 48,
        "qwen3moe.embedding_length": 2048,
        "qwen3moe.feed_forward_length": 6144,
        "qwen3moe.expert_feed_forward_length": 768,
        "qwen3moe.expert_count": 128,
        "qwen3moe.expert_used_count": 8,
        "qwen3moe.attention.head_count": 32,
        "qwen3moe.attention.head_count_kv": 4,
        "qwen3moe.attention.key_length": 128,
        "qwen3moe.context_length": 32768,
        "qwen3moe.vocab_size": 151936,
    }
    cfg = from_gguf_metadata(md)
    assert cfg.moe and cfg.num_experts == 128 and cfg.num_experts_per_tok == 8
    assert cfg.expert_dim == 768
    assert cfg.qk_norm  # qwen3* arch
    assert cfg.head_dim == 128
