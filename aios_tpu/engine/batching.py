"""Continuous batching: many concurrent requests over one decode graph.

The reference serializes requests per model into llama-server's HTTP queue
and caps concurrent AI work at 3 (autonomy.rs Semaphore(3), SURVEY.md
section 2.4); here the 8+ agents' requests land in ONE batched decode step —
the scheduler assigns each request a cache slot, prefills it, and every
decode dispatch advances all active slots together. Tokens stream to each
caller through a per-request queue as dispatches complete.

Scheduling policy (single background thread, dispatch-level granularity):
  * admit waiting requests whenever slots are free (prefill immediately);
  * decode in chunks of `chunk_steps` tokens per dispatch (amortizes
    host<->device round trips); a smaller chunk is used when requests are
    waiting so admission latency stays low;
  * requests retire on EOS/stop token, max_tokens, or a full cache slot.
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.locks import make_lock
from .engine import (
    JUMP_BUCKETS, MEGA_STOP_SLOTS, ChunkedPrefill, PendingDecode,
    TPUEngine, _env_flag,
)
from .paged import PoolExhausted
from .sampling import GREEDY_EPS
from .spec import SPEC_PROPOSERS
from .. import faults
from ..obs import instruments as obs
from ..obs import flightrec

log = logging.getLogger("aios.batcher")

_END = object()

# Live batchers per model name: replica batchers share the (model,) label
# on the aios_tpu_engine_dispatch_inflight_total gauge, and set_function
# is last-writer-wins — so the scrape callback sums over this set instead
# of reporting whichever replica registered last (the same aggregation
# pattern as engine._HOST_STORES_BY_MODEL). Dead batchers drop out when
# collected; a shut-down batcher reports 0 (its pending is dropped).
_BATCHERS_BY_MODEL: Dict[str, object] = {}

# Queued requests gain +1 effective priority per this many seconds
# waiting, bounding starvation under sustained higher-priority traffic
# (a priority-0 request outranks a fresh strategic (3) after ~15 s).
PRIORITY_AGING_SECS = 5.0

# How long an EWMA-collapse keeps a proposer suspended before probe
# dispatches re-measure (the workload may have turned repetitive again).
# Default only — AIOS_TPU_SPEC_REPROBE_SECS / ModelConfig.spec_reprobe_secs
# / boot [models] spec_reprobe_secs override per deployment.
SPEC_REPROBE_SECS = 10.0

# EWMA smoothing for the per-dispatch draft-acceptance ratio.
SPEC_EWMA_ALPHA = 0.3

# Probe dispatches granted after a reprobe window expires: their ratios
# accumulate into a fresh cumulative average and the floor only re-judges
# once the budget is consumed — one unlucky probe dispatch (a single
# non-repetitive request in an otherwise healthy stream) can no longer
# re-disable speculation instantly on a zeroed EWMA. Deliberately NOT
# applied to a cold-started batcher: shutting speculation off fast on
# first evidence is the long-standing (and tested) cold-start behavior,
# and a wrong first verdict there costs one reprobe window, not a flap
# cycle.
SPEC_PROBE_DISPATCHES = 3

# retry-after hint for a retryable crash abort that reached the client
# (the pool's failover budget was exhausted, or there was no pool)
DEFAULT_RETRY_AFTER_MS = 1000


@dataclass
class Request:
    prompt_ids: List[int]
    max_tokens: int = 256
    temperature: float = 0.7
    top_p: float = 0.95
    stop_ids: Tuple[int, ...] = ()
    request_id: str = ""
    # grammar-constrained decoding: output restricted to one JSON object
    # (the reference's non-streaming response_format=json_object behavior,
    # inference.rs:114-122, realized with logit masks instead of GBNF)
    json_mode: bool = False
    # structured outputs: output restricted to the exact SHAPE of this
    # schema (engine/jsonschema.py subset — known/required keys, enums,
    # typed scalars, nested/any subtrees). Wins over json_mode when both
    # are set (it is the stricter guarantee).
    json_schema: Optional[dict] = None
    # admission priority: higher admits first when slots are contended
    # (FIFO within a priority level — no wire field; the runtime derives
    # it from the request's intelligence level so strategic reasoning
    # doesn't queue behind bulk operational traffic)
    priority: int = 0
    # flight-recorder timeline (obs/flightrec.py) riding the request
    # through admission -> routing -> scheduling; opened by the runtime
    # service (with tenant + trace context), the pool, or the batcher —
    # whoever sees the request first. None when recording is disabled.
    rec: object = field(default=None, repr=False, compare=False)
    # transparent-failover controller (serving/failover.py), set by the
    # pool: when this request dies with a retryable abort, the controller
    # claims the terminal event and resumes the stream on a surviving
    # replica instead of surfacing a truncation. None = no failover.
    failover: object = field(default=None, repr=False, compare=False)


@dataclass
class _Live:
    req: Request
    slot: int
    produced: int = 0
    out_q: "queue.Queue" = field(default_factory=queue.Queue)
    first_token_at: float = 0.0
    submitted_at: float = 0.0
    done: bool = False
    admitted_at: float = 0.0  # first slot assignment (queue-wait boundary)
    cancelled: bool = False  # set by RequestHandle.cancel(); reaped by _tick
    # non-empty when the request was ABORTED (scheduler failure, model
    # unload) rather than finished/cancelled — consumers must not present
    # the truncated output as a normal completion
    abort_reason: str = ""
    constraint: object = None  # jsonmode.JsonConstraint when json_mode


@dataclass
class _PendingTick:
    """One pipelined decode dispatch in flight: the engine's pending
    handle plus the live map snapshotted AT DISPATCH TIME — its tokens
    belong to the requests that were live then (requests retired since
    have ``done`` set and their columns are dropped at consume).
    ``evs`` holds the flight-recorder event dicts recorded at submit so
    a devprof timing sample (known only when the worker finishes) can
    join them at consume time — all on the scheduler thread, and
    readers only copy FINISHED timelines, so the late join races
    nothing."""

    pending: PendingDecode
    lives: Dict[int, "_Live"]
    evs: tuple = ()


class RequestHandle:
    """Caller-side view of an in-flight request (blocking token iterator)."""

    def __init__(self, live: _Live, batcher: "ContinuousBatcher"):
        self._live = live
        self._batcher = batcher

    def __iter__(self):
        while True:
            item = self._live.out_q.get()
            if item is _END:
                return
            yield item

    def tokens(self) -> List[int]:
        return list(self)

    def cancel(self) -> None:
        """Abort this request: its slot (and KV pages) free at the
        scheduler's next boundary and the token iterator ends. The llama.cpp
        parity point — llama-server aborts decode when the HTTP client
        disconnects — wired to gRPC disconnect by the runtime service.
        Idempotent; a no-op after completion."""
        self._live.cancelled = True
        self._batcher._wake.set()

    @property
    def aborted(self) -> bool:
        """True when the stream ended by ABORT (scheduler failure, model
        unload) — the collected tokens are a truncation, not a
        completion; serving layers map this to an error status."""
        return bool(self._live.abort_reason)

    @property
    def abort_reason(self) -> str:
        return self._live.abort_reason

    @property
    def retry_after_ms(self) -> int:
        """Backoff hint for a RETRYABLE abort (0 when not aborted, or
        when retrying cannot help — e.g. the prompt exceeds the pool).
        The runtime service forwards it as ``retry-after-ms`` trailing
        metadata, the same convention as admission sheds."""
        reason = self._live.abort_reason
        if not reason:
            return 0
        if flightrec.abort_cause(reason) in flightrec.RETRYABLE_ABORT_CAUSES:
            return DEFAULT_RETRY_AFTER_MS
        return 0

    @property
    def ttft_ms(self) -> float:
        if not self._live.first_token_at:
            return 0.0
        return (self._live.first_token_at - self._live.submitted_at) * 1000.0


class ContinuousBatcher:
    """Background scheduler marrying a request queue to engine slots."""

    def __init__(
        self,
        engine: TPUEngine,
        chunk_steps: int = 16,  # ~70ms/dispatch TinyLlama, ~300ms Mistral on
        # v5e; bigger chunks amortize dispatch overhead (+15% measured), and
        # the admit_chunk_steps fallback keeps admission latency low
        admit_chunk_steps: int = 2,
        prefill_chunk: Optional[int] = None,  # None -> the engine's default
        speculative: bool = False,  # n-gram speculative decode dispatches
        spec_draft_len: int = 7,
        spec_ngram: int = 3,
        tokenizer=None,  # enables json_mode requests (mask table source)
        pipeline: Optional[bool] = None,  # depth-2 pipelined decode loop
        jump_ahead: Optional[bool] = None,  # grammar jump-ahead decoding
        spec_min_accept: Optional[float] = None,  # spec auto-disable floor
        spec_reprobe_secs: Optional[float] = None,  # reprobe window
    ) -> None:
        self.engine = engine
        # Pipelined decode (AIOS_TPU_DECODE_PIPELINE /
        # ModelConfig.decode_pipeline): dispatch N+1 is enqueued BEFORE
        # dispatch N's tokens are consumed, so the host's emit/detokenize/
        # retire phase overlaps device execution instead of idling it — a
        # depth-2 double buffer over the plain decode path, with explicit
        # flushes at grammar-constrained ticks, pool-pressure evictions,
        # and idle boundaries (_flush_pending). Greedy token streams are
        # identical to the unpipelined loop (per-dispatch length
        # snapshots anchor out-of-cache retirement to the dispatch that
        # produced each token); sampled streams are identical for
        # batches admitted together (<= slots) — under queue pressure a
        # freed slot re-admits one dispatch later than the sync loop
        # would, shifting the shared key-split sequence.
        if pipeline is None:
            pipeline = _env_flag("AIOS_TPU_DECODE_PIPELINE")
        if pipeline is None:
            pipeline = bool(getattr(engine.cfg, "decode_pipeline", False))
        self.pipeline = bool(pipeline)
        self._pending: Optional[_PendingTick] = None
        self.flushes = 0
        # host-gap accounting: wall time between consecutive decode
        # dispatches spent on the host (the device-idle window the
        # pipeline exists to close); bench_dispatch reads the totals
        self.decode_dispatches = 0
        self.host_gap_seconds = 0.0
        self._gap_mark: Optional[float] = None
        self._gap_wait = 0.0  # time blocked in consume-wait since the mark
        self._mask_base = None  # cached all-zeros [slots, vocab] device mask
        self.tokenizer = tokenizer
        self._json_masks = None  # lazy jsonmode.JsonMaskCache
        self._json_masks_lock = make_lock("json_masks")
        self._token_table = None  # shared token->bytes table
        self._byte_matrix = None  # shared (mat, lens) across mask caches
        from collections import OrderedDict

        self._schema_caches: "OrderedDict[str, object]" = OrderedDict()
        self.chunk_steps = chunk_steps
        self.admit_chunk_steps = admit_chunk_steps
        # Speculative dispatches (engine.spec_step) emit 1..draft_len+1
        # tokens per slot per round — greedy requests decode the identical
        # sequence in fewer dispatches (engine/spec.py); sampling requests
        # transparently take their usual one token per round.
        if speculative and not getattr(engine, "spec_supported", True):
            log.warning(
                "speculative decoding disabled: unsupported on this "
                "engine config (dp-replicated page pool)"
            )
            speculative = False
        self.speculative = speculative
        self.spec_draft_len = spec_draft_len
        self.spec_ngram = spec_ngram
        # Spec auto-disable (AIOS_TPU_SPEC_MIN_ACCEPT /
        # ModelConfig.spec_min_accept): when the EWMA draft-acceptance
        # ratio of this batcher's spec dispatches collapses below the
        # floor, speculation suspends — decode falls back to the
        # plain/pipelined path, whose per-dispatch cost the failed
        # drafts were inflating — and one probe dispatch re-measures
        # after SPEC_REPROBE_SECS. 0 = never auto-disable.
        if spec_min_accept is None:
            raw = os.environ.get("AIOS_TPU_SPEC_MIN_ACCEPT", "").strip()
            if raw:
                try:
                    spec_min_accept = float(raw)
                    if not 0.0 <= spec_min_accept <= 1.0:
                        raise ValueError("must be in [0, 1]")
                except ValueError as exc:
                    log.warning(
                        "AIOS_TPU_SPEC_MIN_ACCEPT=%r ignored (%s)", raw, exc
                    )
                    spec_min_accept = None
        if spec_min_accept is None:
            spec_min_accept = float(
                getattr(engine.cfg, "spec_min_accept", 0.0)
            )
        self.spec_min_accept = spec_min_accept
        # Reprobe window after an auto-disable (AIOS_TPU_SPEC_REPROBE_SECS
        # / ModelConfig.spec_reprobe_secs): how long a collapsed proposer
        # stays suspended before its probe dispatches re-measure.
        if spec_reprobe_secs is None:
            raw = os.environ.get("AIOS_TPU_SPEC_REPROBE_SECS", "").strip()
            if raw:
                try:
                    spec_reprobe_secs = float(raw)
                    if spec_reprobe_secs <= 0:
                        raise ValueError("must be > 0")
                except ValueError as exc:
                    log.warning(
                        "AIOS_TPU_SPEC_REPROBE_SECS=%r ignored (%s)",
                        raw, exc,
                    )
                    spec_reprobe_secs = None
        if spec_reprobe_secs is None:
            spec_reprobe_secs = float(
                getattr(engine.cfg, "spec_reprobe_secs", SPEC_REPROBE_SECS)
                or SPEC_REPROBE_SECS
            )
        self.spec_reprobe_secs = spec_reprobe_secs
        # Proposer ladder: draft-model speculation when the engine carries
        # a draft, prompt-lookup n-gram always (the floor of the ladder).
        # The constrained tick's FSM jump-ahead outranks both — it owns
        # the tick whenever a constrained slot has a forced run — so the
        # full preference order is jump-ahead -> draft -> ngram. Each
        # proposer keeps its OWN acceptance EWMA and suspension window,
        # so an auto-disable falls one rung (draft -> ngram -> off)
        # instead of turning speculation off all-or-nothing.
        self.spec_proposers: Tuple[str, ...] = (
            ("draft", "ngram") if engine.draft is not None else ("ngram",)
        )
        self.spec_ewma: Dict[str, Optional[float]] = {
            p: None for p in self.spec_proposers
        }
        self._spec_off_until: Dict[str, float] = {
            p: 0.0 for p in self.spec_proposers
        }
        # post-reprobe probe budget per proposer (SPEC_PROBE_DISPATCHES)
        self._spec_probe_left: Dict[str, int] = {
            p: 0 for p in self.spec_proposers
        }
        self._spec_probe_seen: Dict[str, int] = {
            p: 0 for p in self.spec_proposers
        }
        self.spec_autodisables = 0
        # Degrade switches (serving/autoscale.py ladder): the SLO-burn
        # controller flips these to shed OPTIONAL work under sustained
        # burn — speculation first (failed drafts inflate per-dispatch
        # cost), then grammar jump-ahead. Both paths are token-identical
        # on/off by construction, so a mid-stream flip never perturbs a
        # greedy stream; plain bool stores, safe to flip cross-thread.
        self.degrade_spec = False
        self.degrade_jump = False
        # Grammar jump-ahead (AIOS_TPU_JUMP_AHEAD /
        # ModelConfig.jump_ahead, default ON): chains of grammar-FORCED
        # tokens (singleton masks — schema key literals, ':', ',',
        # closers) emit host-side and append their KV in ONE multi-token
        # verify dispatch instead of one masked dispatch each. Greedy
        # streams are token-identical to the per-step path (forced
        # tokens of sampled streams too; the sampled remainder draws a
        # shifted key chain, the unified_step caveat). Unsupported —
        # like speculative verify — on a dp-replicated page pool.
        if jump_ahead is None:
            jump_ahead = _env_flag("AIOS_TPU_JUMP_AHEAD")
        if jump_ahead is None:
            jump_ahead = bool(getattr(engine.cfg, "jump_ahead", True))
        self.jump_ahead = bool(jump_ahead) and getattr(
            engine, "spec_supported", True
        )
        self.jump_max = JUMP_BUCKETS[-1]
        # prompts longer than this admit incrementally (one cache-writing
        # chunk per scheduler pass) so a long admission never stalls decode
        # for the active slots; 0 disables. Defaults to the engine's
        # prefill_chunk_default — the same size warmup pre-compiles — and
        # falls back to monolithic prefill when the engine's bucket grid
        # can't honour the chunk size.
        if prefill_chunk is None:
            prefill_chunk = engine.prefill_chunk_default
        self.prefill_chunk: Optional[int] = prefill_chunk or None
        if self.prefill_chunk is not None and (
            self.prefill_chunk not in engine.buckets
            or engine.max_context % self.prefill_chunk
        ):
            self.prefill_chunk = None
        if self.prefill_chunk is not None and getattr(
            engine, "pool_replicas", 1
        ) > 1:
            log.warning(
                "chunked admission disabled: unsupported on a "
                "dp-replicated page pool (whole-prompt prefill instead)"
            )
            self.prefill_chunk = None
        # paged engines can run out of physical KV pages mid-stream; the
        # policy is to retire the LONGEST request (it has produced the most
        # and frees the most pages) and retry — counted for observability
        self.pool_evictions = 0
        self.cancellations = 0
        self._closed = False  # set by shutdown(); submit() refuses after
        self._waiting: "deque[_Live]" = deque()  #: guarded_by _qlock
        self._qlock = make_lock("batcher_queue")
        self._prefilling: Optional[Tuple[_Live, ChunkedPrefill]] = None
        self._prefill_chunks = 0  # chunks of the in-flight admission
        self._reserved_slot = -1  # slot mid-chunked-prefill (not yet active)
        self._live: Dict[int, _Live] = {}  #: guarded_by _lock
        self._wake = threading.Event()
        self._stop = False
        self._ids = itertools.count()
        self._lock = make_lock("batcher")
        self.completed = 0
        self.last_error: Optional[BaseException] = None
        # If the engine went through its warmup gate, make sure OUR dispatch
        # sizes are compiled too (warmup's defaults cover the default sizes;
        # a non-default chunk_steps would otherwise compile for seconds on
        # the scheduler thread at first dispatch, stalling live requests).
        # AOT — compile_step_fn lowers without dispatching, so attaching a
        # batcher never perturbs engine state. Largest size first keeps
        # unified_step engines on ONE dynamic-n graph. A never-warmed
        # engine (tests, lazy callers) is left lazy.
        if engine._step_fns:
            for n in sorted(
                {self.admit_chunk_steps, self.chunk_steps}, reverse=True
            ):
                engine.compile_step_fn(n)
            if self.speculative:
                for n in {self.admit_chunk_steps, self.chunk_steps}:
                    engine.compile_spec_fn(
                        n, self.spec_draft_len, self.spec_ngram
                    )
                    # the draft proposer's fused graphs for the same round
                    # sizes (no-ops without a draft model), so the ladder
                    # never compiles mid-serving whichever rung serves
                    engine.compile_draft_spec_fn(n, self.spec_draft_len)
                if engine.draft is not None:
                    engine.compile_draft_ingest_fns()
            if engine.mega_ticks:
                # the megagraph windows this batcher can dispatch: each
                # step size capped by the armed K, bucketed to its power
                # of two (warmup already covered 1..mega_bucket(K), so
                # these are no-ops unless the batcher's sizes diverge)
                for n in {self.admit_chunk_steps, self.chunk_steps}:
                    engine.compile_mega_fn(
                        engine.mega_bucket(min(n, engine.mega_ticks))
                    )
            if self.jump_ahead and "masked" in engine._step_fns:
                # constrained serving was declared at warmup (the masked
                # graph is the same signal json-mode deployments use):
                # make sure every run-length bucket the constrained tick
                # can dispatch is compiled too (no-ops when warmup's
                # jump_sizes already covered them). Deployments that
                # never warmed the masked step keep the lazy behavior —
                # their first constrained request compiles both, visibly.
                for k in JUMP_BUCKETS:
                    engine.compile_jump_fn(k)
        # Metric children resolved ONCE (labels() is a locked dict lookup
        # — fine per request, too slow per decoded token); the queue-depth
        # gauge pulls live state at scrape time through a weakref so a
        # shut-down batcher can be collected.
        import weakref

        model_name = engine.cfg.name
        self._obs_tokens = obs.ENGINE_TOKENS.labels(model=model_name)
        self._obs_ttft = obs.ENGINE_TTFT.labels(model=model_name)
        self._obs_completed = obs.ENGINE_REQUESTS_COMPLETED.labels(
            model=model_name
        )
        self._obs_cancelled = obs.ENGINE_REQUESTS_CANCELLED.labels(
            model=model_name
        )
        self._obs_evictions = obs.ENGINE_POOL_EVICTIONS.labels(
            model=model_name
        )
        self._obs_tps = obs.ENGINE_TOKENS_PER_SECOND.labels(model=model_name)
        self._obs_gap = obs.ENGINE_DISPATCH_HOST_GAP.labels(model=model_name)
        _ref = weakref.ref(self)
        obs.ENGINE_QUEUE_DEPTH.labels(model=model_name).set_function(
            lambda: (lambda b: float(b.queue_depth()) if b is not None
                     else 0.0)(_ref())
        )
        peers = _BATCHERS_BY_MODEL.setdefault(model_name, weakref.WeakSet())
        peers.add(self)
        obs.ENGINE_DISPATCH_INFLIGHT.labels(model=model_name).set_function(
            lambda: float(sum(1 for b in peers if b._pending is not None))
        )

        def _acceptance(proposer):
            def read() -> float:
                vals = [
                    b.spec_ewma.get(proposer) for b in peers
                    if b.spec_ewma.get(proposer) is not None
                ]
                return float(sum(vals) / len(vals)) if vals else 0.0

            return read

        for p in SPEC_PROPOSERS:
            obs.SPEC_ACCEPTANCE.labels(
                model=model_name, proposer=p
            ).set_function(_acceptance(p))
        # tokens/sec gauge state: emitted tokens over a ~1 s window,
        # refreshed from the scheduler loop (decays to 0 when idle).
        # last_tps additionally keeps the most recent NON-ZERO rate so the
        # serving layer's deadline estimates survive idle gaps (the gauge
        # honestly decays to 0; feasibility math wants "how fast does this
        # replica decode when it decodes").
        self._rate_tokens = 0
        self._rate_t0 = time.monotonic()
        self.last_tps = 0.0
        # optional serving-layer hook: a Histogram child observed with the
        # submit->slot-assignment wait of each admitted request
        # (ReplicaPool sets it; None keeps the engine layer obs-free)
        self.queue_wait_obs = None
        self._thread = threading.Thread(
            target=self._run, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    # -- public API ---------------------------------------------------------

    def _token_bytes(self):
        """Shared token->bytes table (built once; caller holds the lock)."""
        if self._token_table is None:
            from . import jsonmode

            if self.tokenizer is None:
                raise ValueError(
                    "json_mode/json_schema requires the batcher to know "
                    "the tokenizer"
                )
            self._token_table = jsonmode.token_bytes_table(
                self.tokenizer, self.engine.cfg.vocab_size
            )
        return self._token_table

    def _json_mask_cache(self):
        """Lazily build the per-model mask cache (one vocab walk; locked —
        concurrent first json_mode submits from the gRPC pool must share
        ONE cache, not each walk the vocab)."""
        with self._json_masks_lock:
            if self._json_masks is None:
                from . import jsonmode

                # compact=True: generation never emits structural
                # whitespace (canonical compact JSON, still valid), so
                # grammar-forced positions are SINGLETON states that
                # jump-ahead collapses into multi-token runs — and the
                # budget closing walk can't dither on whitespace
                self._json_masks = jsonmode.JsonMaskCache(
                    self._token_bytes(),
                    getattr(self.tokenizer, "eos_id", None),
                    compact=True,
                )
            return self._json_masks

    def _schema_mask_cache(self, schema: dict):
        """Per-(model, schema) mask cache; compiled once, shared by every
        request carrying the same schema (the autonomy loop resends its
        tool_calls schema on every reasoning round). LRU-bounded — the
        schema string is CLIENT input, and every cache pins per-state mask
        rows — with the vocab byte matrix built once and shared."""
        from . import jsonschema

        key = jsonschema.schema_cache_key(schema)
        with self._json_masks_lock:
            cache = self._schema_caches.get(key)
            if cache is not None:
                self._schema_caches.move_to_end(key)
                return cache
            table = self._token_bytes()
            if self._byte_matrix is None:
                base = self._json_masks
                if base is not None:
                    self._byte_matrix = (base._byte_mat, base._byte_lens)
            cache = jsonschema.SchemaMaskCache(
                table,
                getattr(self.tokenizer, "eos_id", None),
                schema,
                byte_matrix=self._byte_matrix,
                compact=True,  # same rationale as the json_mode cache
            )
            if self._byte_matrix is None:
                self._byte_matrix = (cache._byte_mat, cache._byte_lens)
            if cache.start_token_id is None:
                raise ValueError(
                    "json_schema root must be an object, array, or "
                    "any (scalar roots have no forced opener; wrap "
                    "them in an object)"
                )
            while len(self._schema_caches) >= 16:
                self._schema_caches.popitem(last=False)
            self._schema_caches[key] = cache
            return cache

    def queue_depth(self) -> int:
        """Requests waiting for a slot (admission backlog) — the slot-
        starvation signal the proactive generator watches."""
        with self._qlock:
            return len(self._waiting) + (
                1 if self._prefilling is not None else 0
            )

    def outstanding_tokens(self) -> int:
        """Work queued on this batcher, in tokens: waiting requests count
        prompt + budget (prefill is still ahead of them), live requests
        their remaining budget. Budgets are CAPPED at what the cache can
        actually hold — a max_tokens=50k request on an 8k context retires
        at the cache end, and counting the phantom 42k would make the
        serving layer's deadline estimates shed feasible requests. The
        router's least-loaded score and the admission layer's
        deadline-feasibility estimate both read this."""
        cap = self.engine.max_context
        with self._qlock:
            waiting = list(self._waiting)
            if self._prefilling is not None:
                waiting.append(self._prefilling[0])
        total = 0
        for l in waiting:
            # prompts truncate to the last cap-1 ids at admission — count
            # what will actually prefill, not the client's raw length —
            # and decode retires at the cache end, so the budget term is
            # bounded by the room left AFTER that prompt
            p = min(len(l.req.prompt_ids), cap - 1)
            total += p + max(min(l.req.max_tokens, cap - p), 0)
        with self._lock:
            total += sum(
                max(
                    min(
                        l.req.max_tokens - l.produced,
                        cap - self.engine.slot_length(l.slot),
                    ),
                    0,
                )
                for l in self._live.values()
            )
        return total

    def tokens_per_second(self) -> float:
        """Most recent non-zero observed decode rate (tokens/sec across
        all slots); 0.0 until the first measured window."""
        return self.last_tps

    def submit(self, req: Request) -> RequestHandle:
        if not req.prompt_ids:
            # fail fast on the caller's thread — an exception on the
            # scheduler thread would strand every waiter
            raise ValueError("empty prompt")
        if not req.request_id:
            req.request_id = f"req-{next(self._ids)}"
        if req.rec is None:
            # direct batcher callers (tests, bench) still get a timeline;
            # serving-path requests arrive with one already opened
            req.rec = flightrec.RECORDER.begin(
                self.engine.cfg.name, req.request_id,
                prompt_tokens=len(req.prompt_ids), priority=req.priority,
            )
        elif not req.rec.request_id:
            req.rec.request_id = req.request_id  # auto-assigned id above
        live = _Live(req=req, slot=-1, submitted_at=time.monotonic())
        if req.json_schema is not None:
            from . import jsonmode

            # built on the CALLER's thread (fail fast + keep the vocab
            # walk / schema compile off the scheduler thread)
            cache = self._schema_mask_cache(req.json_schema)
            min_bytes = cache._distance(cache.start())
            max_tok_bytes = cache._byte_mat.shape[1]
            if req.max_tokens * max_tok_bytes < min_bytes:
                # even all-longest tokens cannot carry the schema's minimal
                # completion: the output could only truncate
                raise ValueError(
                    f"max_tokens={req.max_tokens} cannot fit the schema's "
                    f"minimal completion ({min_bytes} bytes)"
                )
            live.constraint = jsonmode.JsonConstraint(cache)
        elif req.json_mode:
            from . import jsonmode

            live.constraint = jsonmode.JsonConstraint(self._json_mask_cache())
        with self._qlock:
            if self._closed:
                # shutdown() already drained the queue; an enqueue now
                # would never be scheduled NOR terminated — its consumer
                # would block forever (the UnloadModel/submit race)
                raise RuntimeError("batcher is shut down")
            self._waiting.append(live)
        self._wake.set()
        return RequestHandle(live, self)

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return self.submit(Request(prompt_ids=list(prompt_ids), **kw)).tokens()

    def shutdown(self) -> None:
        with self._qlock:
            self._closed = True  # new submits refuse from here on
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # a long dispatch (large-model prefill) can hold _tick past
            # 10 s; the loop exits right after it sees _stop, so wait
            # more before touching shared state
            log.warning("batcher scheduler still in a dispatch; waiting")
            self._thread.join(timeout=60)
        if self._thread.is_alive():
            # wedged dispatch (e.g. a dead TPU tunnel): releasing slots
            # under a thread that may still write them risks use-after-
            # free — leave the state alone and surface the condition
            log.error(
                "batcher scheduler did not stop after 70s; outstanding "
                "requests are NOT terminated (wedged dispatch?)"
            )
            return
        # the pushed throughput gauge would otherwise freeze at its last
        # measured rate (ghost tok/s for an unloaded model); zeroed AFTER
        # the join so a final in-flight _tick can't overwrite it. The pull
        # gauges (queue depth, occupancy) decay through their weakrefs.
        self._obs_tps.set(0.0)
        # terminate every outstanding request AFTER the scheduler stopped:
        # nothing will ever deliver their end-of-stream once the thread is
        # gone, so a consumer blocked in out_q.get() — e.g. a StreamInfer
        # handler whose model is being UnloadModel'ed mid-stream — would
        # hang forever
        self._terminate_outstanding("model unloading")

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._live)

    # -- scheduler loop -----------------------------------------------------

    def _advance_prefill(self) -> None:
        """Run ONE chunk of the in-flight chunked prefill (if any); decode
        dispatches for the active slots happen between calls."""
        if self._prefilling is None:
            return
        live, pc = self._prefilling
        t0 = time.monotonic()
        pos0 = pc.pos
        reused0 = getattr(self.engine, "prefix_rows_reused", 0)
        restored0 = getattr(self.engine, "prefix_rows_restored", 0)
        while True:
            try:
                first = pc.step()
                break
            except PoolExhausted as e:
                # mid-admission exhaustion: free pages and retry the SAME
                # chunk NOW — deferring to the next tick would let _admit()
                # hand the freed pages to a new request and force another
                # eviction. With nobody left to evict the admission itself
                # is the victim (its partial pages release); when only
                # HIGHER-priority streams hold the pool, keep the partial
                # admission and retry next tick (they drain eventually).
                outcome = self._evict_longest(
                    e.replica, requester_priority=live.req.priority
                )
                if outcome == "blocked":
                    return
                if outcome == "empty":
                    self._prefilling = None
                    self._reserved_slot = -1
                    live.done = True
                    live.abort_reason = "evicted: KV pool exhausted"
                    self.engine.release(live.slot)
                    self._rec_close(live)
                    live.out_q.put(_END)
                    return
        self._prefill_chunks += 1
        # tokens = rows actually consumed this chunk (the FINAL chunk is
        # usually partial — recording the nominal chunk size would
        # overstate the prompt in every chunked timeline)
        self._rec_prefill(
            live, pc.pos - pos0, t0, reused0, restored0,
            chunk=self._prefill_chunks,
        )
        if first is not None:
            self._prefilling = None
            self._reserved_slot = -1
            if live.constraint is not None:
                first = self._constrained_first(live, first)
            live.first_token_at = time.monotonic()
            self._obs_ttft.observe(live.first_token_at - live.submitted_at)
            with self._lock:
                self._live[live.slot] = live
            self._emit(live, first)

    def _admit(self) -> None:
        while True:
            free = [
                s for s in self.engine.free_slots() if s != self._reserved_slot
            ]
            if not free:
                return
            with self._qlock:
                if not self._waiting:
                    return
                # highest EFFECTIVE priority admits first: queue age adds
                # +1 level per AGING_SECS, so sustained high-priority
                # traffic cannot starve a waiting request forever, and
                # within a level the continuous boost makes the oldest
                # strictly maximal (FIFO holds)
                now = time.monotonic()
                live = max(
                    self._waiting,
                    key=lambda l: l.req.priority
                    + (now - l.submitted_at) / PRIORITY_AGING_SECS,
                )
                self._waiting.remove(live)
            if not live.admitted_at:
                # first slot assignment ends the queue wait (requeues —
                # pool-exhaustion retries, chunked-admission turns — keep
                # their original boundary)
                live.admitted_at = time.monotonic()
                if self.queue_wait_obs is not None:
                    self.queue_wait_obs.observe(
                        live.admitted_at - live.submitted_at
                    )
                rec = live.req.rec
                if rec is not None:
                    wait_ms = (
                        live.admitted_at - live.submitted_at
                    ) * 1000.0
                    rec.queue_wait_ms = wait_ms
                    rec.event("queue", wait_ms=round(wait_ms, 3))
            alloc = self.engine.allocator
            if alloc is not None and alloc.replicas > 1:
                # dp-partitioned pool: admit onto the replica with the
                # most free pages — picking a starved replica would evict
                # a live stream while another replica sits idle
                slot = max(free, key=alloc.free_pages_for)
            else:
                slot = free[0]
            live.slot = slot
            ids = live.req.prompt_ids
            need_rows = min(len(ids), self.engine.max_context - 1)
            window = self.engine.cfg.sliding_window
            if (
                alloc is not None
                and window is not None
                and self.prefill_chunk is not None
            ):
                # chunked admission on windowed models trims as it goes —
                # peak residency is window + one in-flight chunk (plus a
                # page of straddle), not the whole prompt
                need_rows = min(
                    need_rows, window + self.prefill_chunk + alloc.page_size
                )
            elif (
                alloc is not None
                and getattr(self.engine, "kv_compress_armed", False)
                and self.prefill_chunk is not None
            ):
                # window+sink KV compression prunes mid-admission the
                # same way: a prompt longer than the pool still admits,
                # peaking at sink + window + one in-flight chunk (plus a
                # page of straddle per boundary) — this is what opens
                # long-document prompts beyond the per-slot pool share
                comp_rows = (
                    self.engine.kv_sink_pages + self.engine.kv_window_pages
                ) * alloc.page_size
                need_rows = min(
                    need_rows,
                    max(self.engine.kv_compress_after, comp_rows)
                    + self.prefill_chunk + 2 * alloc.page_size,
                )
            if alloc is not None and alloc.blocks_for(
                need_rows
            ) > alloc.capacity_blocks():
                # the prompt can NEVER fit the pool — fail it up front;
                # evicting live requests one per tick would truncate every
                # co-resident stream before reaching the same conclusion
                log.warning(
                    "request %s prompt (%d tokens) exceeds the whole KV "
                    "page pool; failing it", live.req.request_id, len(ids),
                )
                live.done = True
                live.abort_reason = "prompt exceeds the KV page pool"
                self._rec_close(live)
                live.out_q.put(_END)
                continue
            chunked = self.prefill_chunk is not None and len(ids) > self.prefill_chunk
            if chunked:
                if self._prefilling is not None:
                    # one incremental admission at a time; FIFO order holds
                    with self._qlock:
                        self._waiting.appendleft(live)
                    return
                self._prefilling = (
                    live,
                    self.engine.start_chunked_prefill(
                        slot,
                        ids,
                        temperature=live.req.temperature,
                        top_p=live.req.top_p,
                        chunk=self.prefill_chunk,
                    ),
                )
                self._prefill_chunks = 0
                self._reserved_slot = slot
                continue
            t0 = time.monotonic()
            reused0 = getattr(self.engine, "prefix_rows_reused", 0)
            restored0 = getattr(self.engine, "prefix_rows_restored", 0)
            try:
                first = self.engine.prefill(
                    slot,
                    ids,
                    temperature=live.req.temperature,
                    top_p=live.req.top_p,
                )
            except PoolExhausted as e:
                with self._qlock:
                    self._waiting.appendleft(live)  # keep FIFO order
                outcome = self._evict_longest(
                    e.replica, requester_priority=live.req.priority
                )
                if outcome == "empty":
                    # nothing to evict: the prompt is bigger than the whole
                    # pool — fail just this request, not the scheduler
                    with self._qlock:
                        self._waiting.popleft()
                    live.done = True
                    live.abort_reason = "prompt exceeds the KV page pool"
                    self._rec_close(live)
                    live.out_q.put(_END)
                # "blocked": the pool is held by strictly higher-priority
                # streams — the admission stays queued and retries as they
                # drain; "evicted": retry next pass with the freed pages
                return
            self._rec_prefill(live, len(ids), t0, reused0, restored0)
            if live.constraint is not None:
                first = self._constrained_first(live, first)
            live.first_token_at = time.monotonic()
            self._obs_ttft.observe(live.first_token_at - live.submitted_at)
            with self._lock:
                self._live[slot] = live
            self._emit(live, first)

    def _constrained_first(self, live: _Live, first: int) -> int:
        """Grammar-constrained requests overwrite the unmasked first token
        sampled by prefill with the grammar's forced opener ('{')."""
        cache = live.constraint.cache
        forced = cache.start_token_id
        if forced is None:  # no "{" token in vocab: fail open, unconstrained
            log.warning("json_mode: vocab has no '{' token; unconstrained")
            live.constraint = None
            return first
        self.engine.force_pending_token(live.slot, forced)
        live.constraint.advance(forced)
        return forced

    def _emit(self, live: _Live, token: int,
              slot_len: Optional[int] = None) -> None:
        if live.cancelled:
            return  # reaped (slot freed) at the next tick boundary
        live.produced += 1
        self._obs_tokens.inc()
        self._rate_tokens += 1
        live.out_q.put(token)
        hit_stop = token in live.req.stop_ids
        out_of_budget = live.produced >= live.req.max_tokens
        # pipelined consumes pass the slot length AS OF the dispatch that
        # produced this token — the engine's live length already includes
        # the in-flight next dispatch, and reading it would retire
        # requests one dispatch early (diverging from the sync loop)
        if slot_len is None:
            slot_len = self.engine.slot_length(live.slot)
        out_of_cache = slot_len >= self.engine.max_context - 1
        if hit_stop or out_of_budget or out_of_cache:
            self._finish(live)

    # -- pipelined decode (depth-2 double buffer) ---------------------------

    def _consume(self, tick: _PendingTick) -> None:
        """Emit one finished dispatch's tokens to whoever is still live.

        A PoolExhausted surfacing from the dispatch worker (the ensure()
        failed; engine state untouched) retires a victim here instead —
        the batch retries on a later dispatch, exactly like the sync
        loop's dispatch-site handling."""
        t0 = time.monotonic()
        try:
            tokens = tick.pending.wait()
        except PoolExhausted as e:
            self._gap_wait += time.monotonic() - t0
            # the depth-2 buffer already issued the NEXT dispatch against
            # the same exhausted pool — collect its (identical) failure
            # BEFORE evicting, or the eviction path would flush it, see a
            # second PoolExhausted, and retire a second victim for ONE
            # pressure event
            nxt, self._pending = self._pending, None
            if nxt is not None:
                try:
                    nxt.pending.wait()
                except PoolExhausted:
                    pass  # state untouched; the post-evict tick retries
                else:
                    self._pending = nxt  # it ran after all: deliver it
            self._evict_longest(e.replica)
            return
        self._gap_wait += time.monotonic() - t0
        dev = tick.pending.device_s
        if dev is not None:
            # late devprof join: the sampled device-µs of the dispatch
            # the worker just finished, onto the events recorded at its
            # submit (scheduler-thread-only mutation of LIVE timelines —
            # readers copy finished rings, never these)
            for ev in tick.evs:
                ev["dev_us"] = round(dev * 1e6, 1)
        lengths = tick.pending.lengths
        if getattr(lengths, "ndim", 1) == 2:
            # megagraph dispatch: per-tick length snapshots [k, S] and
            # k REAL ticks of tokens — each row retires against the
            # lengths AS OF its own tick (a context-cap finish must fire
            # on the tick that hit the cap, not the window's last), and
            # the flight-recorder events' n joins late with the real k
            # (never the requested K when the device loop exited early)
            for ev in tick.evs:
                ev["n"] = tick.pending.ticks
            for row, lrow in zip(tokens, lengths):
                for slot, live in tick.lives.items():
                    if live.done:
                        continue
                    self._emit(
                        live, int(row[slot]), slot_len=int(lrow[slot])
                    )
            return
        for row in tokens:
            for slot, live in tick.lives.items():
                if live.done:
                    continue
                self._emit(live, int(row[slot]), slot_len=int(lengths[slot]))

    def _mega_operands(
        self, slots: Dict[int, "_Live"]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Device operands for one megagraph window: per-slot stop ids
        ``[S, MEGA_STOP_SLOTS]`` (pad -1 — BEST-EFFORT, the device
        checks only the first MEGA_STOP_SLOTS ids while ``_emit`` stays
        authoritative over the full set) and remaining token budgets
        ``[S]`` (0 for slots with no live request, so an empty column
        can never hold the device loop open)."""
        eng = self.engine
        stops = np.full((eng.num_slots, MEGA_STOP_SLOTS), -1, np.int32)
        budgets = np.zeros((eng.num_slots,), np.int32)
        for slot, live in slots.items():
            if live.done:
                continue
            ids = tuple(live.req.stop_ids)[:MEGA_STOP_SLOTS]
            if ids:
                stops[slot, : len(ids)] = ids
            budgets[slot] = max(live.req.max_tokens - live.produced, 0)
        return stops, budgets

    def _flush_pending(self, cause: str) -> None:
        """Consume the in-flight pipelined dispatch NOW. Called whenever
        the next dispatch cannot be issued ahead of consumption:
        grammar-constrained ticks (the mask depends on every emitted
        token), speculative ticks, pool-pressure evictions (a victim's
        already-produced tokens must land before its stream aborts), and
        idle boundaries. No-op when nothing is pending."""
        tick = self._pending
        if tick is None:
            return
        self._pending = None
        self.flushes += 1
        # labels() resolves per FLUSH, not per token — the locked lookup
        # is fine at this rate (unlike the per-token children above)
        obs.ENGINE_DISPATCH_FLUSHES.labels(
            model=self.engine.cfg.name, cause=cause
        ).inc()
        self._consume(tick)

    def _note_dispatch(self) -> Optional[float]:
        """Record and return the host gap since the previous decode
        dispatch (the window the device idles in the sync loop; the
        pipeline's whole point is to hide it) — None for the first
        dispatch after an idle boundary. Call immediately BEFORE
        dispatching; the dispatch site stamps ``_gap_mark`` when the
        engine call returns.
        Time the pipelined tick spent BLOCKED waiting on the previous
        dispatch's tokens (``_gap_wait``) is subtracted — that's device
        time, and counting it would make the pipelined gap read as if
        the host were busier than the sync loop's."""
        act = faults.point("dispatch.delay", self.engine.cfg.name)
        if act is not None and act.delay_s > 0:
            # injected host stall: lands in the host-gap accounting like
            # any real slow-host phase would (docs/FAULTS.md)
            time.sleep(act.delay_s)
        gap = None
        if self._gap_mark is not None:
            gap = time.monotonic() - self._gap_mark - self._gap_wait
            gap = max(gap, 0.0)
            self.host_gap_seconds += gap
            self.decode_dispatches += 1
            self._obs_gap.observe(gap)
        self._gap_wait = 0.0
        return gap

    # -- flight-recorder hooks (obs/flightrec.py) ---------------------------
    # One event per DISPATCH per live request — never per token — and
    # every call is a no-op when the request carries no timeline, so the
    # recorder can be disabled without touching a single dispatch.

    def _rec_dispatch(self, lives, kind: str, n: int,
                      gap: Optional[float] = None,
                      dur_s: Optional[float] = None,
                      graph: str = "step", join_sample: bool = True,
                      **extra) -> list:
        """Record one dispatch on every live timeline. ``graph`` names
        the devprof GRAPH_KINDS entry this dispatch ran as: when devprof
        is armed, a fresh timing sample of that kind joins the event as
        ``dev_us`` (``join_sample=False`` for pipelined submits — their
        sample lands at consume, see _consume) and the ledger's mean
        device time, split by occupancy, accrues on each timeline's
        estimated device_us. Returns the recorded event dicts."""
        occ = len(lives)
        fields = dict(n=n, occ=occ, **extra)
        if gap is not None:
            fields["gap_ms"] = round(gap * 1e3, 3)
        if dur_s is not None:
            fields["dur_ms"] = round(dur_s * 1e3, 3)
        est = None
        if self.engine._devprof is not None:
            if join_sample:
                s = self.engine.devprof_take_sample()
                if s is not None and s[0] == graph:
                    fields["dev_us"] = round(s[1] * 1e6, 1)
            est = self.engine.devprof_est_s(graph)
        evs = []
        for live in lives:
            rec = live.req.rec
            if rec is not None and not live.done:
                ev = rec.event(kind, **fields)
                if ev is not None:
                    evs.append(ev)
                if est:
                    rec.device_us += est * 1e6 / max(occ, 1)
        return evs

    def _rec_prefill(self, live: _Live, tokens: int, t0: float,
                     reused0: float, restored0: float,
                     chunk: Optional[int] = None) -> None:
        # pop the engine's sample FIRST (even when this request carries
        # no timeline) so a prefill-kind sample can never linger and
        # mis-join a later dispatch's event
        sample = None
        if self.engine._devprof is not None:
            sample = self.engine.devprof_take_sample()
        rec = live.req.rec
        if rec is None:
            return
        dur_s = time.monotonic() - t0
        fields = dict(
            tokens=tokens,
            dur_ms=round(dur_s * 1e3, 3),
        )
        if sample is not None and sample[0] in (
            "prefill", "chunk", "seq_prefill"
        ):
            fields["dev_us"] = round(sample[1] * 1e6, 1)
        if self.engine._devprof is not None:
            # prefill is request-exclusive and the engine call blocked
            # through completion: bill the measured wall time (an upper
            # bound on device time, exact on the CPU backend)
            rec.device_us += dur_s * 1e6
        cached = getattr(self.engine, "prefix_rows_reused", 0) - reused0
        restored = (
            getattr(self.engine, "prefix_rows_restored", 0) - restored0
        )
        if cached:
            fields["cached_rows"] = int(cached)
        if restored:
            fields["restored_rows"] = int(restored)
        if chunk is not None:
            fields["chunk"] = chunk
        rec.event("prefill", **fields)

    def _rec_close(self, live: _Live) -> None:
        """Finalize the request's timeline into the recorder ring —
        called on EVERY end-of-life path, right before the consumer's
        end-of-stream lands. Accounting is CUMULATIVE over the timeline
        (one client request may span several batcher attempts under
        transparent failover): tokens accumulate, TTFT anchors to the
        timeline's origin (failover delay counts against it — the SLO
        contract), TPOT spreads the post-first-token wall time over
        every token the client actually received."""
        rec = live.req.rec
        if rec is None:
            return
        rec.tokens_out += live.produced
        if live.first_token_at and not rec.ttft_ms:
            rec.ttft_ms = (live.first_token_at - rec.t0) * 1000.0
        if rec.ttft_ms and rec.tokens_out > 1:
            rec.tpot_ms = (
                ((time.monotonic() - rec.t0) * 1000.0 - rec.ttft_ms)
                / (rec.tokens_out - 1)
            )
        if live.abort_reason:
            fo = live.req.failover
            if fo is not None and fo.claims(live.abort_reason):
                # the failover controller owns this request's terminal
                # event: it either resumes the stream on a surviving
                # replica (the SAME timeline keeps accumulating) or
                # finishes it aborted once the retry budget exhausts —
                # finishing here would freeze the record mid-recovery
                # and ding SLO availability for a request the client
                # may yet see complete
                return
        # terminal from here on: bill the tenant's estimated device
        # seconds ONCE (finish() freezes the timeline below; a
        # failover-resumed request reaches this point only on its final
        # attempt, with device_us accumulated across every attempt)
        if (
            self.engine._devprof is not None
            and rec.device_us
            and not rec.finished_at
        ):
            obs.DEVPROF_TENANT_SECONDS.labels(tenant=rec.tenant).inc(
                rec.device_us / 1e6
            )
        if live.abort_reason:
            flightrec.RECORDER.finish(
                rec, "aborted", abort_reason=live.abort_reason
            )
        elif live.cancelled:
            flightrec.RECORDER.finish(rec, "cancelled")
        else:
            flightrec.RECORDER.finish(rec, "retired")

    def _finish(self, live: _Live, *, was_cancelled: bool = False,
                abort_reason: str = "") -> None:
        live.done = True
        if abort_reason:
            # the stream is a truncation, not a completion: consumers see
            # handle.aborted and surface an error/resubmit condition
            # instead of presenting the cut-short text as a normal answer
            live.abort_reason = abort_reason
        with self._lock:
            self._live.pop(live.slot, None)
        self.engine.release(live.slot)
        if was_cancelled:
            self.cancellations += 1
            self._obs_cancelled.inc()
        else:
            self.completed += 1
            self._obs_completed.inc()
        self._rec_close(live)
        # _END goes last: when a consumer unblocks, all scheduler-side state
        # (slot freed, counters bumped) is already final
        live.out_q.put(_END)

    def _reap_cancelled(self) -> None:
        """Free every cancelled request before admission/decode: queued ones
        drop out of the wait list, a cancelled chunked admission releases
        its reserved slot mid-prefill, and live slots release their cache
        (the pages a disconnected agent was pinning)."""
        with self._qlock:
            still = deque()
            dropped: List[_Live] = []
            for live in self._waiting:
                (dropped if live.cancelled else still).append(live)
            if dropped:
                self._waiting = still
        for live in dropped:
            live.done = True
            self.cancellations += 1
            self._obs_cancelled.inc()
            self._rec_close(live)
            live.out_q.put(_END)
        if self._prefilling is not None and self._prefilling[0].cancelled:
            live = self._prefilling[0]
            self._prefilling = None
            self._reserved_slot = -1
            self._finish(live, was_cancelled=True)
        with self._lock:
            cancelled = [l for l in self._live.values() if l.cancelled]
        for live in cancelled:
            self._finish(live, was_cancelled=True)

    def _evict_longest(
        self, replica: Optional[int] = None,
        requester_priority: Optional[int] = None,
    ) -> str:
        """Retire the lowest-priority live request, longest first within a
        priority level (frees the most pages), so a pool-exhausted
        dispatch can make progress without sacrificing strategic work to
        keep bulk traffic alive. ``replica`` restricts the hunt to the
        starved replica of a dp-partitioned pool — evicting elsewhere
        frees nothing useful. ``requester_priority`` (admission paths)
        refuses to evict a victim that STRICTLY outranks the requester —
        the admission waits instead.

        Returns "evicted", "empty" (nothing live to evict), or "blocked"
        (only higher-priority victims exist)."""
        # land the in-flight pipelined tokens first: the victim keeps what
        # it already produced (matching the sync loop), and a retirement
        # during the flush may itself free the pages this hunt is after
        self._flush_pending("evict")
        alloc = self.engine.allocator
        with self._lock:
            candidates = [
                l for l in self._live.values()
                if replica is None or alloc.replica_of(l.slot) == replica
            ]
            if not candidates:
                return "empty"
            victim = min(
                candidates,
                key=lambda l: (
                    l.req.priority, -self.engine.slot_length(l.slot)
                ),
            )
        if (
            requester_priority is not None
            and victim.req.priority > requester_priority
        ):
            return "blocked"
        log.warning(
            "KV page pool exhausted; retiring lowest-priority longest "
            "request %s (priority %d, %d rows) to free pages",
            victim.req.request_id,
            victim.req.priority,
            self.engine.slot_length(victim.slot),
        )
        self.pool_evictions += 1
        self._obs_evictions.inc()
        # the victim's stream is a truncation: mark it aborted so the
        # serving layer returns an error/resubmittable status instead of
        # a silently short normal completion
        self._finish(victim, abort_reason="evicted: KV pool exhausted")
        return "evicted"

    def _abort_all(self, exc: BaseException) -> None:
        """A scheduler-thread failure must surface, not strand callers: every
        live / mid-prefill / queued request is terminated (its iterator ends)
        and the error is kept for inspection."""
        self.last_error = exc
        log.exception("continuous batcher scheduler failed; aborting requests")
        self._terminate_outstanding(f"scheduler failed: {exc!r}"[:200])

    def _terminate_outstanding(self, reason: str) -> None:
        """End every live / mid-prefill / queued request (slot released,
        iterator ends with its abort_reason set, so the serving layer
        reports an error instead of presenting the truncation as a normal
        completion). Called on scheduler failure and on shutdown — any
        path after which no scheduler pass will run again."""
        victims: List[_Live] = []
        # an in-flight pipelined dispatch dies with the scheduler: its
        # tokens would extend streams that are being aborted as
        # truncations anyway, so drop, don't emit
        self._pending = None
        if self._prefilling is not None:
            victims.append(self._prefilling[0])
            self._prefilling = None
            self._reserved_slot = -1
        with self._lock:
            victims.extend(self._live.values())
            self._live.clear()
        with self._qlock:
            victims.extend(self._waiting)
            self._waiting.clear()
        for live in victims:
            live.done = True
            live.abort_reason = reason
            if live.slot >= 0:
                try:
                    self.engine.release(live.slot)
                # aios: waive(silent-except): best-effort slot release during teardown — the abort itself is recorded via live.abort_reason on the very next line
                except Exception:  # noqa: BLE001
                    pass
            self._rec_close(live)
            live.out_q.put(_END)

    def _run(self) -> None:
        while not self._stop:
            try:
                self._tick()
            except Exception as exc:  # noqa: BLE001
                self._abort_all(exc)

    # -- speculative auto-disable (per-proposer EWMA acceptance floor) ------

    def _spec_proposer(self, greedy_live: bool = True) -> Optional[str]:
        """Which proposer the next decode tick should dispatch with, or
        None when every rung of the ladder is suspended. Each proposer
        keeps its own EWMA and suspension window, so a collapsed draft
        model falls back to n-gram (not to nothing) and a collapsed
        n-gram still leaves the draft serving. An expired window grants
        the proposer SPEC_PROBE_DISPATCHES probe dispatches on a fresh
        cumulative average before the floor re-judges (a zeroed EWMA let
        one bad probe re-disable instantly). ``greedy_live=False`` skips
        the draft rung: with no greedy slot live the draft's K propose
        steps are pure overhead AND produce no measurable acceptance, so
        the tick falls through to n-gram, whose zero-acceptance EWMA
        suspends speculation properly."""
        now = time.monotonic()
        for p in self.spec_proposers:
            if p == "draft" and not greedy_live:
                continue
            off = self._spec_off_until[p]
            if off:
                if now < off:
                    continue
                self._spec_off_until[p] = 0.0
                self.spec_ewma[p] = None
                self._spec_probe_left[p] = SPEC_PROBE_DISPATCHES
                self._spec_probe_seen[p] = 0
            return p
        return None

    def _spec_active(self) -> bool:
        """Whether the next decode tick may dispatch speculatively at
        all (any rung of the proposer ladder available)."""
        if self.degrade_spec:
            return False
        return self._spec_proposer() is not None

    def _spec_measure(self, proposer: str, counts,
                      consumed: Dict[int, int], proposed=None) -> None:
        """Fold one spec dispatch's acceptance into ``proposer``'s EWMA
        and suspend THAT proposer when it collapses below the floor.
        ``counts`` is the dispatch's [rounds, num_slots] emitted-token
        matrix; ``consumed`` maps slot -> rounds whose tokens were
        actually EMITTED (each emits 1 + accepted-drafts). Rounds past a
        request's mid-dispatch retirement are excluded — their drafts
        score a continuation that is never served, and folding them in
        would suspend speculation on workloads whose served tokens
        accept perfectly well. ``proposed`` (draft proposer) is the
        [rounds, num_slots] offered-token matrix: the denominator counts
        only real proposals, so sampled-heavy batches don't read as
        rejection — the n-gram proposer keeps its historical
        every-round denominator."""
        if proposed is None:
            possible = sum(consumed.values()) * self.spec_draft_len
        else:
            possible = sum(
                float(proposed[:r, s].sum()) for s, r in consumed.items()
            )
        if not possible:
            return
        accepted = sum(
            float(counts[:r, s].sum()) - r for s, r in consumed.items()
        )
        ratio = max(accepted, 0.0) / possible
        prev = self.spec_ewma[proposer]
        if prev is None:
            self.spec_ewma[proposer] = ratio
            self._spec_probe_seen[proposer] = 1
        elif self._spec_probe_left[proposer] > 0:
            # probe phase: cumulative average over the probe budget (an
            # EWMA seeded from one sample would weight it like a whole
            # collapsed history)
            n = self._spec_probe_seen[proposer]
            self.spec_ewma[proposer] = (prev * n + ratio) / (n + 1)
            self._spec_probe_seen[proposer] = n + 1
        else:
            self.spec_ewma[proposer] = (
                (1 - SPEC_EWMA_ALPHA) * prev + SPEC_EWMA_ALPHA * ratio
            )
        if self._spec_probe_left[proposer] > 0:
            self._spec_probe_left[proposer] -= 1
            if self._spec_probe_left[proposer] > 0:
                return  # verdict deferred until the probe budget drains
        if (
            self.spec_min_accept > 0
            and self.spec_ewma[proposer] < self.spec_min_accept
        ):
            self._spec_off_until[proposer] = (
                time.monotonic() + self.spec_reprobe_secs
            )
            self.spec_autodisables += 1
            log.info(
                "%s: %s speculation suspended (EWMA acceptance %.3f < "
                "floor %.3f); re-probing in %.0fs",
                self.engine.cfg.name, proposer, self.spec_ewma[proposer],
                self.spec_min_accept, self.spec_reprobe_secs,
            )

    # -- grammar jump-ahead (compressed-FSM run collapse) -------------------

    def _jump_tick(self, constrained) -> bool:
        """Collapse chains of grammar-FORCED tokens into one multi-token
        dispatch (engine.jump_step) instead of one masked dispatch each.

        Each constrained slot's automaton is probed for a forced run —
        states whose effective mask admits exactly ONE token (schema key
        literals, '":', '",', closing braces; see
        JsonConstraint.forced_run). Runs of >= 2 tokens pay for a jump:
        the dispatch appends their K/V through the verify machinery with
        acceptance pinned to all-accept, and the tokens emit host-side
        (they ARE the only tokens any sampler could produce, so streams
        are identical to the per-step path). Slots without a run — and
        unconstrained co-residents — do not advance this dispatch; the
        next tick serves them with the usual masked step, so a mixed
        batch pays at most one extra tick per run while the run itself
        collapses from len(run) dispatches to one.

        Returns True when a jump dispatch was issued (the tick is done).
        """
        runs: Dict[int, List[int]] = {}
        for s_, live in constrained:
            c = live.constraint
            if c is None or getattr(c, "failed", False):
                continue
            rem = live.req.max_tokens - live.produced
            # the verify-write contract: post-run length <= C-2
            room = self.engine.max_context - 2 - self.engine.slot_length(s_)
            cap = min(self.jump_max, rem, room)
            if cap < 2:
                continue
            run = c.forced_run(cap, remaining=rem,
                               stop_ids=live.req.stop_ids)
            if len(run) >= 2:
                runs[s_] = run
        if not runs:
            return False
        k = max(len(r) for r in runs.values())
        forced = np.zeros((self.engine.num_slots, k), np.int32)
        counts = np.zeros((self.engine.num_slots,), np.int32)
        for s_, run in runs.items():
            forced[s_, : len(run)] = run
            counts[s_] = len(run)
        try:
            gap = self._note_dispatch()
            t0 = time.monotonic()
            self.engine.jump_step(forced, counts)
            self._gap_mark = time.monotonic()
        except PoolExhausted as e:
            self._evict_longest(e.replica)  # retry next tick
            return True
        dur_ms = round((self._gap_mark - t0) * 1e3, 3)
        dev_us = None
        est_us = 0.0
        if self.engine._devprof is not None:
            s = self.engine.devprof_take_sample()
            if s is not None and s[0] == "jump":
                dev_us = round(s[1] * 1e6, 1)
            est = self.engine.devprof_est_s("jump")
            if est:
                est_us = est * 1e6 / max(len(runs), 1)
        by_slot = dict(constrained)
        for s_ in sorted(runs):
            live = by_slot[s_]
            if live.done:
                continue
            rec = live.req.rec
            if rec is not None:
                rec.event(
                    "jump", k=len(runs[s_]), occ=len(runs),
                    dur_ms=dur_ms,
                    **({"gap_ms": round(gap * 1e3, 3)}
                       if gap is not None else {}),
                    **({"dev_us": dev_us}
                       if dev_us is not None else {}),
                )
                rec.device_us += est_us
            for tok in runs[s_]:
                live.constraint.advance(tok)
                self._emit(live, tok)
                if live.done:
                    break
        return True

    def _tick(self) -> None:
        now = time.monotonic()
        if now - self._rate_t0 >= 1.0:
            rate = self._rate_tokens / (now - self._rate_t0)
            self._obs_tps.set(rate)
            if rate > 0:
                # remember the decoding-time rate across idle windows (the
                # gauge decays to 0; deadline feasibility must not)
                self.last_tps = rate
            self._rate_tokens = 0
            self._rate_t0 = now
        if self._pending is not None:
            # ordering fence: the pipelined dispatch handed to the worker
            # last tick must HOLD the engine lock before this tick issues
            # any engine call (slot releases, admissions, chunk writes) —
            # those must land after it, or the slot set it was issued
            # against could change under it
            self._pending.pending.wait_started()
        self._reap_cancelled()
        self._advance_prefill()
        self._admit()
        with self._lock:
            slots = {s: l for s, l in self._live.items()}
        if slots:
            # chaos: a scheduler crash mid-decode — the exception rides
            # the real _run -> _abort_all -> respawn path, gated on live
            # slots so idle wake-loop ticks don't consume trigger hits
            # (an nth:N schedule then counts DECODE ticks, which is what
            # a deterministic crash drill wants to aim at)
            act = faults.point("pool.scheduler_crash", self.engine.cfg.name)
            if act is not None:
                raise faults.InjectedFault(
                    f"injected scheduler crash ({act.mode}, hit {act.hit})"
                )
        if not slots:
            # nothing live NOW: land whatever the last pipelined dispatch
            # produced (its requests retired mid-consume, so this usually
            # just drops garbage columns) before going idle
            self._flush_pending("idle")
            self._gap_mark = None
            if self._prefilling is not None:
                return  # nothing to decode; keep chunking
            self._wake.wait(timeout=0.05)
            self._wake.clear()
            return
        constrained = [
            (s_, l) for s_, l in slots.items() if l.constraint is not None
        ]
        if constrained:
            # grammar masks change per emitted token, so constrained slots
            # ride 1-step dispatches — and the mask for the NEXT step
            # depends on every token emitted so far, so the pipeline
            # drains first. Rows are cached DEVICE-resident per automaton
            # state and scattered into a cached all-zeros [S, V] base, so
            # unconstrained co-resident slots cost nothing (no per-slot
            # row stack, no per-step PCIe traffic).
            self._flush_pending("constrained")
            if self.jump_ahead and not self.degrade_jump \
                    and self._jump_tick(constrained):
                return
            import jax.numpy as jnp

            by_slot = dict(constrained)
            idx = sorted(by_slot)
            rows = [
                by_slot[s_].constraint.device_mask(
                    remaining=by_slot[s_].req.max_tokens
                    - by_slot[s_].produced
                )
                for s_ in idx
            ]
            if len(idx) == self.engine.num_slots:
                mask = jnp.stack(rows)
            else:
                base = self._mask_base
                if base is None:
                    base = self._mask_base = jnp.zeros(
                        (self.engine.num_slots, self.engine.cfg.vocab_size),
                        jnp.float32,
                    )
                mask = base.at[jnp.asarray(idx, jnp.int32)].set(
                    jnp.stack(rows)
                )
            try:
                gap = self._note_dispatch()
                t0 = time.monotonic()
                tokens = self.engine.step_masked(mask)
                self._gap_mark = time.monotonic()
            except PoolExhausted as e:
                self._evict_longest(e.replica)
                return
            self._rec_dispatch(
                slots.values(), "decode", 1, gap,
                self._gap_mark - t0, graph="masked", constrained=True,
            )
            for slot, live in list(slots.items()):
                if live.done:
                    continue
                tok = int(tokens[0, slot])
                if live.constraint is not None:
                    live.constraint.advance(tok)
                self._emit(live, tok)
            return
        # keep admission latency low when someone is waiting (constrained
        # ticks above ignore chunking — they are always 1 step). n is
        # always one of exactly TWO values — each step size is its own XLA
        # graph, so clamping n to a data-dependent remaining-budget (as an
        # earlier version did) triggers fresh multi-second compiles on this
        # thread mid-serving; overshooting a request's max_tokens just
        # produces ignored tokens, which costs microseconds instead
        with self._qlock:
            anyone_waiting = bool(self._waiting) or self._prefilling is not None
        n = self.admit_chunk_steps if anyone_waiting else self.chunk_steps
        proposer = None
        if self.speculative and not self.degrade_spec:
            # the draft rung needs a greedy slot to propose for; without
            # one it falls through to n-gram (see _spec_proposer)
            greedy_live = any(
                l.req.temperature < GREEDY_EPS for l in slots.values()
            )
            proposer = self._spec_proposer(greedy_live)
        if proposer is not None:
            # [n, S, K+1] tokens, [n, S] counts — emit each round's accepted
            # run in order; _emit retires requests mid-dispatch as usual.
            # Speculative dispatches consume their own output synchronously
            # (acceptance counts gate the emit), so they never pipeline;
            # drain any pending plain dispatch first.
            self._flush_pending("spec")
            proposed = None
            try:
                gap = self._note_dispatch()
                t0 = time.monotonic()
                if proposer == "draft":
                    tokens, counts, proposed = self.engine.spec_step_draft(
                        n, draft_len=self.spec_draft_len
                    )
                else:
                    tokens, counts = self.engine.spec_step(
                        n, draft_len=self.spec_draft_len,
                        ngram=self.spec_ngram,
                    )
                self._gap_mark = time.monotonic()
            except PoolExhausted as e:
                self._evict_longest(e.replica)  # retry next tick
                return
            dur_ms = round((self._gap_mark - t0) * 1e3, 3)
            graph = "draft_spec" if proposer == "draft" else "spec"
            dev_us = None
            est_us = 0.0
            if self.engine._devprof is not None:
                s = self.engine.devprof_take_sample()
                if s is not None and s[0] == graph:
                    dev_us = round(s[1] * 1e6, 1)
                est = self.engine.devprof_est_s(graph)
                if est:
                    est_us = est * 1e6 / max(len(slots), 1)
            consumed: Dict[int, int] = {}
            for r in range(tokens.shape[0]):
                for slot, live in list(slots.items()):
                    if live.done:
                        continue
                    consumed[slot] = r + 1  # this round's tokens are served
                    for j in range(int(counts[r, slot])):
                        self._emit(live, int(tokens[r, slot, j]))
                        if live.done:
                            break
            for slot, live in slots.items():
                rounds = consumed.get(slot)
                rec = live.req.rec
                if rec is not None and rounds:
                    # emitted = rounds + accepted drafts for this slot's
                    # SERVED rounds (the _spec_measure accounting)
                    rec.event(
                        "spec", rounds=rounds, proposer=proposer,
                        emitted=int(counts[:rounds, slot].sum()),
                        draft_len=self.spec_draft_len, dur_ms=dur_ms,
                        **({"gap_ms": round(gap * 1e3, 3)}
                           if gap is not None else {}),
                        **({"dev_us": dev_us}
                           if dev_us is not None else {}),
                    )
                    rec.device_us += est_us
            self._spec_measure(proposer, counts, consumed, proposed)
            return
        if self.engine.mega_ticks:
            # device-resident multi-tick window: ONE megagraph dispatch
            # runs up to min(n, mega_ticks) decode ticks with sampling,
            # stop/budget/cap checks on device and early exit the moment
            # no live slot needs another tick — the host round-trip
            # (readback, emit, recorder) amortizes over the k real
            # ticks. Constrained and speculative batches never reach
            # here (their branches above return first): a constrained
            # tick's mask depends on every emitted token, so "a
            # constrained tick is due" is realized as routing, not as a
            # device predicate. The window size equals the plain loop's
            # dispatch size, so the key fanout (split(key, K+1)) — and
            # with it every sampled stream — matches the off arm
            # key-for-key.
            window = min(n, self.engine.mega_ticks)
            cap = self.engine.max_context - 1
            stuck = [
                live for slot, live in slots.items()
                if not live.done and self.engine.slot_length(slot) >= cap
            ]
            if stuck:
                # a slot already AT the context cap can never run a
                # device tick (the loop's live predicate excludes it) —
                # finish it here or a 0-tick dispatch would emit nothing
                # and the scheduler would spin on it forever
                for live in stuck:
                    self._finish(live)
                slots = {s: l for s, l in slots.items() if not l.done}
                if not slots:
                    return
            stops, budgets = self._mega_operands(slots)
            if self.pipeline:
                prev = self._pending
                gap = self._note_dispatch()
                handle = self.engine.mega_step_async(window, stops, budgets)
                self._gap_mark = time.monotonic()
                # recorded with the REQUESTED window; _consume late-joins
                # the real k (early exit) onto these events
                evs = self._rec_dispatch(
                    slots.values(), "decode", window, gap, pipelined=True,
                    join_sample=False, graph="mega",
                )
                self._pending = _PendingTick(handle, slots, tuple(evs))
                if prev is not None:
                    self._consume(prev)
                return
            try:
                gap = self._note_dispatch()
                t0 = time.monotonic()
                tokens, lengths, k = self.engine.mega_step(
                    window, stops, budgets
                )
                self._gap_mark = time.monotonic()
            except PoolExhausted as e:
                self._evict_longest(e.replica)
                return
            # k REAL ticks — never the requested window when the device
            # loop exited early (the SLO/TPOT accounting contract)
            self._rec_dispatch(
                slots.values(), "decode", k, gap, self._gap_mark - t0,
                graph="mega",
            )
            for row, lrow in zip(tokens, lengths):
                for slot, live in list(slots.items()):
                    if live.done:
                        continue
                    self._emit(
                        live, int(row[slot]), slot_len=int(lrow[slot])
                    )
            return
        if self.pipeline:
            # depth-2 double buffer: hand dispatch N+1 to the engine's
            # dispatch worker, THEN consume dispatch N — the host's
            # emit/retire phase runs while N+1 executes (the worker holds
            # the blocking graph call + readback, so this overlaps even
            # on the CPU backend, where XLA executes inline in the
            # dispatching thread). Tokens stream identically to the sync
            # loop: each dispatch's live map and post-dispatch lengths
            # are snapshotted, so late retirements drop exactly the
            # columns the sync loop would never have dispatched. A
            # PoolExhausted surfaces at consume time (_consume evicts).
            prev = self._pending
            gap = self._note_dispatch()
            handle = self.engine.step_async(n)
            self._gap_mark = time.monotonic()
            # the worker's timing sample (if this dispatch drew one)
            # joins these events at consume time — see _consume
            evs = self._rec_dispatch(
                slots.values(), "decode", n, gap, pipelined=True,
                join_sample=False,
            )
            self._pending = _PendingTick(handle, slots, tuple(evs))
            if prev is not None:
                self._consume(prev)
            return
        try:
            gap = self._note_dispatch()
            t0 = time.monotonic()
            tokens = self.engine.step(n)  # [n, num_slots]
            self._gap_mark = time.monotonic()
        except PoolExhausted as e:
            # retire the longest request and retry on the next tick; the
            # failed ensure() left all engine state untouched
            self._evict_longest(e.replica)
            return
        self._rec_dispatch(
            slots.values(), "decode", n, gap, self._gap_mark - t0
        )
        for step_row in tokens:
            for slot, live in list(slots.items()):
                if live.done:
                    continue
                self._emit(live, int(step_row[slot]))
