"""Prompt context assembly with token budgeting.

Reference parity (agent-core/src/context.rs:46-122): merges a system prompt
with relevance-scored chunks under a token budget using the 4-chars-per-token
estimate (context.rs:64-66,119-122). The memory service's AssembleContext is
the cross-process variant; this one builds prompts inside the orchestrator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

CHARS_PER_TOKEN = 4


def estimate_tokens(text: str) -> int:
    return max(1, len(text) // CHARS_PER_TOKEN)


@dataclass
class ContextChunk:
    source: str
    content: str
    relevance: float = 0.5


@dataclass
class ContextAssembler:
    system_prompt: str = ""
    max_tokens: int = 2048
    chunks: List[ContextChunk] = field(default_factory=list)

    def add(self, source: str, content: str, relevance: float = 0.5) -> None:
        self.chunks.append(ContextChunk(source, content, relevance))

    def assemble(self, task_description: str = "") -> str:
        """Highest-relevance chunks first until the budget is spent."""
        budget = self.max_tokens
        parts: List[str] = []
        if self.system_prompt:
            parts.append(self.system_prompt)
            budget -= estimate_tokens(self.system_prompt)
        if task_description:
            line = f"Task: {task_description}"
            parts.append(line)
            budget -= estimate_tokens(line)
        for chunk in sorted(self.chunks, key=lambda c: -c.relevance):
            cost = estimate_tokens(chunk.content) + 2
            if cost > budget:
                continue
            parts.append(f"[{chunk.source}] {chunk.content}")
            budget -= cost
        return "\n\n".join(parts)

    def total_tokens(self) -> int:
        return estimate_tokens(self.assemble())
