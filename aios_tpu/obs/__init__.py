"""Unified observability substrate: metrics + tracing for every layer.

The serving literature (SGLang, RTP-LLM — PAPERS.md) treats first-class
runtime metrics as the prerequisite for scheduling/batching work; this
package is that substrate for the aiOS-TPU stack:

  * ``obs.metrics``     — thread-safe Prometheus-style registry
                          (Counter / Gauge / Histogram with labels, text
                          exposition, process-wide default registry);
  * ``obs.instruments`` — the ONE catalog of every metric the stack
                          registers (docs/OBSERVABILITY.md mirrors it);
  * ``obs.tracing``     — span-based tracing with W3C ``traceparent``
                          context propagation (goal -> task -> agent ->
                          RPC -> decode hierarchy);
  * ``obs.interceptors``— gRPC client/server interceptors wiring every
                          RPC into rpc_{requests,errors,latency} metrics
                          and the span tree (installed by aios_tpu.rpc);
  * ``obs.flightrec``   — the serving-plane flight recorder: one bounded
                          structured timeline per request (admission ->
                          route -> queue -> prefill -> decode ticks ->
                          retirement), Chrome-trace export, anomaly
                          snapshots;
  * ``obs.slo``         — windowed TTFT/TPOT/availability objectives per
                          model computed from the recorder, exported as
                          the ``aios_tpu_slo_*`` family and folded into
                          every /healthz;
  * ``obs.http``        — stdlib /metrics + /healthz + /debug/* endpoint
                          each service's serve() can start;
  * ``obs.fleet``       — the fleet telemetry plane: membership
                          heartbeats with suspect/dead failure
                          detection, /metrics/fleet federation, and
                          cross-process trace stitching (the placement/
                          failover signal the multi-host data plane
                          routes on);
  * ``obs.tsdb``        — the black-box time-series ring: a background
                          sampler over every registered instrument (raw
                          ring cascading into a downsampled wheel),
                          queried at /debug/tsdb with the closed-verb
                          expression form and federated fleet-wide
                          (armed by AIOS_TPU_TSDB, None-check off);
  * ``obs.incidents``   — incident bundles: every anomaly trigger
                          (snapshot, SLO breach, autoscale action,
                          breaker open, crash-respawn, fired fault)
                          freezes the tsdb window + flightrec snapshot +
                          fault journal + devprof + lock-watchdog state
                          into a bounded store at /debug/incidents.

No third-party dependencies: prometheus_client is not in the image, so
the registry is self-contained stdlib code.
"""

from __future__ import annotations

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from .tracing import (  # noqa: F401
    Span,
    current_span,
    current_traceparent,
    parse_traceparent,
    recent_spans,
    start_span,
)
from .http import start_metrics_server, maybe_start_metrics_server  # noqa: F401
from . import flightrec  # noqa: F401
from . import slo  # noqa: F401 - registers the recorder's SLO listener
from . import fleet  # noqa: F401 - fleet membership/federation plane
from . import tsdb  # noqa: F401 - black-box time-series ring
from . import incidents  # noqa: F401 - anomaly incident bundles
from .flightrec import RECORDER, FlightRecorder, Timeline  # noqa: F401

# Wire the previously-dormant span-exporter hook: finished spans fold
# into the matching request timeline by trace id (a deployment's own
# set_exporter call, made before or after import, wins — install only
# claims the hook when it is free).
flightrec.install_span_export()
