"""Device-resident multi-tick decode megagraph (ISSUE 19).

Guarantees under test:
  * token identity: with ``mega_ticks`` armed the batcher runs up to K
    decode ticks per dispatch inside one ``lax.while_loop`` — sampling,
    stop detection, budget/context-cap checks on device — and every
    stream (greedy, sampled, schema-constrained; pipelined and sync) is
    byte-identical to the K=1 loop. The megagraph's key fanout
    (``split(key, K+1)``) matches the per-size scan graph of the same
    window, so sampled streams match key-for-key.
  * early exit: the loop returns after k <= K REAL ticks the moment no
    live slot needs another tick (EOS/stop hit, budget exhausted,
    context cap) or the ``pool.megatick_abort`` fault caps the window;
    ``engine.mega_tick_total`` records k, never K.
  * no compile after warmup: warmup AOT-builds every power-of-two
    megagraph bucket, so a mega-armed serving sweep moves the compile
    counters by exactly zero.
  * shard_map twin: a dp/tp-sharded plan with the shard_mapped ragged
    decode attention runs the SAME megagraph (``_decode_body`` composes
    ``_attn_impl``) — no silent fallback, identical tokens.
  * failover: a replica crash mid-megadispatch resumes the stream from
    the tokens already emitted, token-identical to a fault-free run.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu import faults
from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def params():
    return M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)


# distinct model name: the eviction test below ABORTS a request, which
# freezes a flight-recorder anomaly snapshot and claims the global
# per-(model, cause) SNAPSHOT_COOLDOWN — under TINY_TEST.name that
# cooldown would swallow test_obs_flightrec's own abort snapshot when
# this module runs within 30s of it
MEGA_TEST = TINY_TEST.scaled(name="mega-test")


def make_engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_context", 128)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(MEGA_TEST, params, **kw)


def run_batch(params, mega, reqs, *, pipeline=True, engine_kw=None,
              batcher_kw=None, tokenizer=None, warm=True):
    """One engine+batcher lifecycle with ``mega_ticks=mega``. The
    batcher's dispatch window (chunk_steps=8 == admit_chunk_steps=8)
    equals the armed K, so the mega arm's key fanout matches the off
    arm's scan graph and sampled streams can be compared byte-for-byte."""
    ekw = dict(engine_kw or {})
    ekw["mega_ticks"] = mega
    eng = make_engine(params, **ekw)
    if warm:
        eng.warmup(step_sizes=(8,), prefill_chunk=32,
                   masked_step=tokenizer is not None)
    kw = dict(chunk_steps=8, admit_chunk_steps=8, pipeline=pipeline,
              tokenizer=tokenizer)
    kw.update(batcher_kw or {})
    b = ContinuousBatcher(eng, **kw)
    try:
        handles = [b.submit(Request(**r)) for r in reqs]
        outs = [h.tokens() for h in handles]
        stats = dict(eng.stats())
        stats["flushes"] = b.flushes
        stats["dispatches"] = b.decode_dispatches
        stats["evictions"] = b.pool_evictions
        stats["aborted"] = [h.abort_reason for h in handles]
        return outs, stats
    finally:
        b.shutdown()
        eng.close()


@pytest.mark.parametrize("pipeline", [False, True])
def test_mega_token_identical_greedy(params, pipeline):
    """Greedy streams with staggered retirement boundaries and a
    mid-window stop token: mega_ticks=8 == mega_ticks=0, byte for byte,
    in both the sync and the pipelined loop."""
    reqs = [
        dict(prompt_ids=[3 + i, 17, 91, 4 + i], max_tokens=18 + 5 * i,
             temperature=0.0)
        for i in range(4)
    ]
    off, _ = run_batch(params, 0, reqs, pipeline=pipeline)
    # make one request stop early on a token the free run actually emits
    reqs[1]["stop_ids"] = (off[1][4],)
    off, _ = run_batch(params, 0, reqs, pipeline=pipeline)
    on, s_on = run_batch(params, 8, reqs, pipeline=pipeline)
    assert on == off
    assert len(off[1]) <= 5 + 1  # the stop actually fired
    assert s_on["mega_dispatches"] > 0  # the megagraph actually served


@pytest.mark.parametrize("pipeline", [False, True])
def test_mega_token_identical_sampled(params, pipeline):
    """temperature > 0 with the fixed engine seed: the megagraph's
    split(key, K+1) fanout is the scan graph's, so sampled streams match
    token-for-token."""
    reqs = [
        dict(prompt_ids=[7 + i, 2, 55], max_tokens=21 + 4 * i,
             temperature=0.85, top_p=0.9)
        for i in range(4)
    ]
    off, _ = run_batch(params, 0, reqs, pipeline=pipeline)
    on, s_on = run_batch(params, 8, reqs, pipeline=pipeline)
    assert on == off
    assert any(len(set(t)) > 1 for t in on)  # actually sampled something
    assert s_on["mega_dispatches"] > 0


def test_mega_token_identical_constrained(params):
    """A schema-constrained stream and its co-resident plain stream:
    constrained ticks route through the masked/jump path in BOTH arms
    (the mask depends on every emitted token), so arming mega must
    change nothing — and the plain slot's megagraph ticks must not
    perturb the constrained slot either."""
    tok = ByteTokenizer()
    reqs = [
        dict(prompt_ids=tok.encode("emit json"), max_tokens=40,
             temperature=0.9, top_p=0.95, stop_ids=(tok.eos_id,),
             json_mode=True),
        dict(prompt_ids=tok.encode("plain"), max_tokens=24,
             temperature=0.0),
    ]
    off, _ = run_batch(params, 0, reqs, tokenizer=tok)
    on, _ = run_batch(params, 8, reqs, tokenizer=tok)
    assert on == off
    parsed = json.loads(tok.decode(on[0]))
    assert isinstance(parsed, dict)  # the constraint really constrained


def test_mega_early_exit_on_budget_and_eos(params):
    """A window whose every live slot retires mid-window (token budget,
    then an EOS hit) makes the device loop exit after k < K real ticks:
    mega_tick_total records k, never the requested window."""
    reqs = [dict(prompt_ids=[9, 8, 7], max_tokens=3, temperature=0.0)]
    outs, stats = run_batch(params, 8, reqs, pipeline=False)
    assert len(outs[0]) == 3
    assert stats["mega_dispatches"] >= 1
    # prefill emits token 1; the window needed 2 more ticks of its 8
    assert stats["mega_ticks"] < stats["mega_dispatches"] * 8
    assert stats["mega_ticks"] == stats["decode_steps"]

    # EOS mid-window: the device stop check (first MEGA_STOP_SLOTS stop
    # ids) exits the loop on the tick that produced the stop token
    free, _ = run_batch(
        params, 0, [dict(prompt_ids=[5, 6, 7], max_tokens=32,
                         temperature=0.0)], pipeline=False)
    stop = free[0][4]
    reqs = [dict(prompt_ids=[5, 6, 7], max_tokens=32, temperature=0.0,
                 stop_ids=(stop,))]
    off, _ = run_batch(params, 0, reqs, pipeline=False)
    on, s_on = run_batch(params, 8, reqs, pipeline=False)
    assert on == off and on[0][-1] == stop
    assert s_on["mega_ticks"] < s_on["mega_dispatches"] * 8


def test_megatick_abort_fault_forces_early_exit(params):
    """The ``pool.megatick_abort`` catalog point caps the device loop's
    abort_after operand mid-window: the dispatch returns early with
    k < K, the batcher retires/streams off the k real ticks, and the
    streams stay byte-identical to the unfaulted run (the remaining
    ticks simply run in later dispatches)."""
    reqs = [
        dict(prompt_ids=[3 + i, 17, 91], max_tokens=20, temperature=0.0)
        for i in range(3)
    ]
    clean, _ = run_batch(params, 8, reqs)
    plan = faults.activate("seed=5;pool.megatick_abort=nth:1,ticks=2")
    try:
        out, stats = run_batch(params, 8, reqs)
    finally:
        faults.deactivate()
    assert out == clean
    fired = [e for e in plan.journal() if e["point"] == "pool.megatick_abort"]
    assert fired, "the abort point never fired"
    # the capped dispatch ran fewer ticks than its window
    assert stats["mega_ticks"] < stats["mega_dispatches"] * 8


def test_mega_eviction_mid_window_recovers(params):
    """Pool exhaustion surfacing from a megadispatch: the eviction path
    consumes the in-flight window first (the victim keeps every token it
    produced), the survivor completes, and the engine stays coherent."""
    reqs = [
        dict(prompt_ids=list(range(1, 31)), max_tokens=50, temperature=0.0,
             priority=1),
        dict(prompt_ids=list(range(40, 70)), max_tokens=80, temperature=0.0),
    ]
    outs, stats = run_batch(
        params, 8, reqs,
        engine_kw=dict(num_slots=2, paged_pool_rows=128, page_size=32,
                       prefix_cache=False),
    )
    assert stats["evictions"] >= 1
    aborted = [r for r in stats["aborted"] if r]
    assert aborted and "evicted" in aborted[0]
    survivor = [o for o, r in zip(outs, stats["aborted"]) if not r]
    assert survivor and len(survivor[0]) > 0
    assert stats["mega_dispatches"] > 0


def test_mega_no_compile_after_warmup_sweep(params):
    """warmup AOT-builds every power-of-two megagraph bucket up to the
    armed K, and attaching a batcher compiles its window buckets without
    dispatching — a mega-armed serving wave moves the compile counters
    by exactly zero."""
    eng = make_engine(params, mega_ticks=8)
    try:
        eng.warmup(step_sizes=(2, 8), prefill_chunk=32)
        before = eng.stats()["xla_compiles"]
        b = ContinuousBatcher(eng, chunk_steps=8, admit_chunk_steps=2,
                              pipeline=True)
        try:
            assert eng.stats()["xla_compiles"] == before  # attach is AOT
            hs = [
                b.submit(Request(prompt_ids=[3 + i, 4, 5],
                                 max_tokens=12 + i, temperature=0.0))
                for i in range(4)
            ]
            for h in hs:
                h.tokens()
        finally:
            b.shutdown()
        assert eng.mega_dispatches > 0
        # every window size the wave dispatched (admit window 2 AND the
        # full window 8, plus any early-exited k) hit a warmed bucket
        assert eng.stats()["xla_compiles"] == before, (
            "a megagraph bucket compiled mid-serving"
        )
    finally:
        eng.close()


@pytest.mark.slow
def test_mega_shard_map_twin_identity(params, cpu_devices):
    """A dp/tp plan with the shard_mapped ragged decode attention armed:
    the megagraph composes ``_attn_impl`` inside ``_decode_body``, so
    the sharded engine serves K-tick windows with NO silent fallback and
    tokens identical to the unsharded megagraph run."""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    reqs = [
        dict(prompt_ids=[3 + i, 17, 91, 4 + i], max_tokens=16 + 3 * i,
             temperature=0.0)
        for i in range(2)
    ]
    plain, _ = run_batch(params, 8, reqs, pipeline=False)
    plan = ShardingPlan(build_mesh(4, dp=2))
    # sharded engines serve lazily (the repo-wide convention: AOT
    # executables pin input shardings, and the post-prefill state's
    # sharding differs from the steady-state one — the SAME limitation
    # the plain step graphs have)
    sharded, stats = run_batch(
        params, 8, reqs, pipeline=False, warm=False,
        engine_kw=dict(shardings=plan, sharded_attention=True),
    )
    assert sharded == plain
    assert stats["mega_dispatches"] > 0


def test_mega_failover_mid_megadispatch_resumes(params):
    """A replica crash injected while megadispatches serve a 2-replica
    pool: failover resumes every stream from the tokens already emitted,
    token-identical to a fault-free run."""
    from aios_tpu.serving import ReplicaPool, ServingConfig

    name = "mega-failover-test"
    cfg = TINY_TEST.scaled(name=name, max_context=128)

    def build():
        engines = [
            TPUEngine(cfg, params, num_slots=4, max_context=128,
                      cache_dtype=jnp.float32, mega_ticks=8)
            for _ in range(2)
        ]
        return ReplicaPool(
            name, engines,
            lambda e: ContinuousBatcher(e, chunk_steps=8,
                                        admit_chunk_steps=8),
            ServingConfig(replicas=2, failover_retries=2),
        )

    def wave(pool, tag):
        handles = [
            pool.submit(Request(prompt_ids=[3 + i, 7, 11], max_tokens=24,
                                temperature=0.0,
                                request_id=f"{tag}-{i}"))
            for i in range(4)
        ]
        streams = {}
        threads = []
        for i, h in enumerate(handles):
            t = threading.Thread(
                target=lambda i=i, h=h: streams.__setitem__(i, h.tokens()),
                daemon=True,
            )
            t.start()
            threads.append(t)
        stuck = 0
        for t in threads:
            t.join(timeout=120)
            stuck += int(t.is_alive())
        return [streams.get(i) for i in range(4)], handles, stuck

    pool = build()
    try:
        ref, _, stuck = wave(pool, "ref")
        assert stuck == 0 and all(len(s) == 24 for s in ref)
        faults.activate("seed=2;pool.scheduler_crash=nth:4")
        try:
            out, handles, stuck = wave(pool, "crash")
        finally:
            faults.deactivate()
        assert stuck == 0, "a request leaked through the crash"
        assert out == ref, "failover streams must be token-identical"
        assert not any(h.aborted for h in handles)
        assert pool.restarts == 1
    finally:
        pool.shutdown()
