"""On-device token sampling: temperature, top-k, top-p, greedy.

Runs inside the jitted decode step (no host round-trip per token), vectorized
over slots with *per-slot* sampling parameters — different agents' requests in
the same continuous batch can use different temperatures (the reference's
per-request `temperature` field, runtime.proto InferRequest).

Replaces llama-server's sampler chain for the parameters the reference
actually exposes (temperature; plus top-k/top-p which llama-server applies
with its defaults — inference.rs:103-112 sends temperature only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

GREEDY_EPS = 1e-4  # temperatures below this mean argmax


def top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the nucleus. logits [B, V], top_p [B] in (0, 1]."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens while the cumulative mass *before* them is < top_p
    keep_sorted = (cumulative - sorted_probs) < top_p[:, None]
    # threshold = smallest logit still kept
    kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
    threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def top_k_filter(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask logits below the k-th largest. top_k [B] int32 (0 = disabled)."""
    V = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    k = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))
    threshold = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def sample(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B], 1.0 disables
    top_k: jnp.ndarray | None = None,  # [B] int32, 0 disables
) -> jnp.ndarray:
    """Sample one token per row; temperature < GREEDY_EPS rows take argmax."""
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, GREEDY_EPS)[:, None]
    scaled = logits / temp
    if top_k is not None:
        scaled = top_k_filter(scaled, top_k)
    scaled = top_p_filter(scaled, top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1)

    return jnp.where(temperature < GREEDY_EPS, greedy, sampled).astype(jnp.int32)
