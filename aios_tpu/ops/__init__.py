"""Pallas TPU kernels for the hot ops of the decode/prefill path.

The reference has no in-tree kernels at all — it shells out to llama.cpp
(SURVEY.md section 2.3, runtime/src/model_manager.rs:187-204). Here the hot
loops are owned by this package:

  * ``flash_attention`` — blockwise causal attention for prefill/training.
    Never materializes the [T, S] score matrix, which is what makes 8k+
    contexts fit in a single chip's HBM (a naive prefill at T=8192 would
    allocate ~8.6 GB of fp32 scores per layer).
  * ``decode_attention`` — ragged batched-decode attention over the slot KV
    cache. Manually DMAs only the valid rows [0, length] of each slot from
    HBM (double-buffered), so short sequences don't pay full-context
    bandwidth.
  * ``quantized_matmul`` — int8-weight x bf16-activation matmul with
    per-output-channel scales; weights stream from HBM as int8 (half the
    bytes of bf16), dequantized in VMEM right before hitting the MXU.

Every kernel has a pure-jnp reference implementation used (a) as the CPU
fallback so the whole framework runs anywhere, and (b) as the ground truth
for numeric parity tests (kernels additionally run under
``pltpu.force_tpu_interpret_mode`` on CPU in tests).
"""

from __future__ import annotations

import os

import jax

from .decode_attention import (
    decode_attention,
    decode_attention_int8,
    decode_attention_int8_reference,
    decode_attention_reference,
)
from .flash_attention import flash_attention, flash_attention_reference
from .paged_attention import (
    gather_pages,
    paged_decode_attention,
    paged_decode_attention_int8,
    paged_decode_attention_int8_reference,
    paged_decode_attention_reference,
)
from .int4_matmul import (
    dequantize_int4,
    int4_matmul,
    int4_matmul_reference,
    quantize_int4,
)
from .quantized_matmul import (
    dequantize,
    quantize_int8,
    quantized_matmul,
    quantized_matmul_reference,
)
from .verify_attention import (
    multiquery_decode_attention,
    multiquery_decode_attention_int8,
    multiquery_decode_attention_int8_reference,
    multiquery_decode_attention_reference,
)

__all__ = [
    "flash_attention",
    "flash_attention_reference",
    "decode_attention",
    "decode_attention_int8",
    "decode_attention_int8_reference",
    "decode_attention_reference",
    "paged_decode_attention",
    "paged_decode_attention_int8",
    "paged_decode_attention_int8_reference",
    "paged_decode_attention_reference",
    "gather_pages",
    "multiquery_decode_attention",
    "multiquery_decode_attention_int8",
    "multiquery_decode_attention_int8_reference",
    "multiquery_decode_attention_reference",
    "quantize_int8",
    "dequantize",
    "quantized_matmul",
    "quantized_matmul_reference",
    "quantize_int4",
    "dequantize_int4",
    "int4_matmul",
    "int4_matmul_reference",
    "use_pallas",
]


_BACKEND_IS_TPU: bool | None = None


def use_pallas() -> bool:
    """True when the Pallas kernel path should be used.

    On TPU backends the kernels are the default; ``AIOS_TPU_NO_PALLAS=1``
    forces the jnp reference path (debugging / A-B benchmarking). Non-TPU
    backends always take the reference path — the kernels are Mosaic-only.

    The backend probe is cached only on success: a transient init failure
    (e.g. the tunnelled TPU backend coming up late) must not pin the slow
    path for the process lifetime.
    """
    if os.environ.get("AIOS_TPU_NO_PALLAS", "").lower() in ("1", "true"):
        return False
    global _BACKEND_IS_TPU
    if _BACKEND_IS_TPU is None:
        try:
            _BACKEND_IS_TPU = jax.default_backend() == "tpu"
        except Exception:
            return False  # retry on the next call
    return _BACKEND_IS_TPU
