#!/usr/bin/env bash
# Launch the full aiOS-TPU stack via the boot supervisor (foreground).
#
# TPU-native equivalent of /root/reference/scripts/run-qemu.sh: the reference
# boots its ISO in QEMU; here the five services boot as supervised host
# processes on the TPU VM (aios_tpu/boot/supervisor.py — topo order, health
# gates, restart caps).
#
# Usage: scripts/run-aios.sh [--data-dir DIR] [--model-dir DIR] [--cpu]
set -euo pipefail

REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"

while [[ $# -gt 0 ]]; do
  case "$1" in
    --data-dir) export AIOS_DATA_DIR="$2"; shift 2 ;;
    --model-dir) export AIOS_MODEL_DIR="$2"; shift 2 ;;
    --cpu) export JAX_PLATFORMS=cpu; shift ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done

cd "$REPO_DIR"
exec "${PYTHON:-python3}" -m aios_tpu.boot.supervisor
