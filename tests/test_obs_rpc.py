"""Tracing + RPC interceptors: spans nest, traceparent crosses the wire,
and every RPC lands in the rpc_{requests,errors,latency} instruments.

The integration test at the bottom is the acceptance path: ONE StreamInfer
through the real RuntimeService produces a server span with the caller's
trace id, a TTFT observation, tokens/sec + occupancy gauges, and all of it
in the /metrics text exposition.
"""

import json
import urllib.request

import grpc
import pytest

from aios_tpu import rpc, services
from aios_tpu.obs import tracing
from aios_tpu.obs.http import start_metrics_server
from aios_tpu.obs.metrics import REGISTRY
from aios_tpu.proto_gen import common_pb2, runtime_pb2

SVC = "aios.runtime.AIRuntime"


def _sample(name, **labels):
    return REGISTRY.sample(name, labels)


# -- tracing units ---------------------------------------------------------


def test_span_nesting_same_trace():
    with tracing.start_span("outer") as outer:
        with tracing.start_span("inner") as inner:
            assert tracing.current_span() is inner
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        assert tracing.current_span() is outer
    assert tracing.current_span() is None
    assert outer.end >= outer.start


def test_traceparent_roundtrip():
    with tracing.start_span("root") as span:
        tp = tracing.current_traceparent()
    assert tracing.parse_traceparent(tp) == (span.trace_id, span.span_id)
    assert tracing.parse_traceparent("garbage") is None
    assert tracing.parse_traceparent("") is None


def test_continue_span_adopts_remote_identity():
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with tracing.continue_span(tp, "server-side") as span:
        assert span.trace_id == "ab" * 16
        assert span.parent_id == "cd" * 8
    with tracing.continue_span("malformed", "fresh") as span:
        assert span.parent_id == ""  # fresh root, not a crash


def test_error_span_marked():
    with pytest.raises(RuntimeError):
        with tracing.start_span("boom"):
            raise RuntimeError("x")
    s = tracing.recent_spans("boom")[-1]
    assert s.status == "error"


# -- interceptor round-trip ------------------------------------------------


class _Echo(services.AIRuntimeServicer):
    def Infer(self, request, context):
        span = tracing.current_span()
        return runtime_pb2.InferResponse(
            text=span.trace_id if span else "", model_used="echo"
        )

    def StreamInfer(self, request, context):
        for tok in request.prompt.split():
            yield runtime_pb2.InferChunk(text=tok, done=False)
        yield runtime_pb2.InferChunk(text="", done=True)


@pytest.fixture(scope="module")
def echo_addr():
    server = rpc.create_server()
    rpc.add_to_server(services.RUNTIME, _Echo(), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_trace_id_propagates_client_to_server(echo_addr):
    with rpc.insecure_channel(echo_addr) as channel:
        stub = services.AIRuntimeStub(channel)
        with tracing.start_span("client-root") as root:
            resp = stub.Infer(runtime_pb2.InferRequest(prompt="hi"))
    # the handler observed a span carrying the CALLER's trace id
    assert resp.text == root.trace_id
    server_span = tracing.recent_spans(f"rpc.server.{SVC}/Infer")[-1]
    assert server_span.trace_id == root.trace_id
    assert server_span.parent_id == root.span_id


def test_rpc_metrics_count_unary_and_stream(echo_addr):
    before_c = _sample("aios_tpu_rpc_requests_total",
                       side="client", service=SVC, method="StreamInfer")
    before_s = _sample("aios_tpu_rpc_requests_total",
                       side="server", service=SVC, method="StreamInfer")
    with rpc.insecure_channel(echo_addr) as channel:
        stub = services.AIRuntimeStub(channel)
        chunks = list(
            stub.StreamInfer(runtime_pb2.InferRequest(prompt="a b c"))
        )
    assert len(chunks) == 4
    assert _sample("aios_tpu_rpc_requests_total", side="client",
                   service=SVC, method="StreamInfer") == before_c + 1
    assert _sample("aios_tpu_rpc_requests_total", side="server",
                   service=SVC, method="StreamInfer") == before_s + 1
    # latency histogram observed on both sides
    hist = REGISTRY.get("aios_tpu_rpc_latency_seconds")
    assert hist.labels(side="client", service=SVC,
                       method="StreamInfer").sample_count >= 1
    assert hist.labels(side="server", service=SVC,
                       method="StreamInfer").sample_count >= 1


def test_rpc_errors_counted_per_code(echo_addr):
    before = _sample("aios_tpu_rpc_errors_total", side="client", service=SVC,
                     method="LoadModel", code="UNIMPLEMENTED")
    with rpc.insecure_channel(echo_addr) as channel:
        stub = services.AIRuntimeStub(channel)
        with pytest.raises(grpc.RpcError):
            stub.LoadModel(runtime_pb2.LoadModelRequest(model_name="x"))
    assert _sample("aios_tpu_rpc_errors_total", side="client", service=SVC,
                   method="LoadModel", code="UNIMPLEMENTED") == before + 1
    assert _sample("aios_tpu_rpc_errors_total", side="server", service=SVC,
                   method="LoadModel", code="UNIMPLEMENTED") >= 1


def test_obs_disabled_env_opts_out(echo_addr, monkeypatch):
    monkeypatch.setenv("AIOS_OBS_DISABLED", "1")
    before = _sample("aios_tpu_rpc_requests_total",
                     side="client", service=SVC, method="Infer")
    with rpc.insecure_channel(echo_addr) as channel:
        stub = services.AIRuntimeStub(channel)
        stub.Infer(runtime_pb2.InferRequest(prompt="hi"))
    assert _sample("aios_tpu_rpc_requests_total",
                   side="client", service=SVC, method="Infer") == before


# -- /metrics + /healthz endpoint -----------------------------------------


def test_metrics_http_endpoint():
    server, port = start_metrics_server(port=0, health_fn=lambda: {"x": 1})
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "# TYPE aios_tpu_rpc_requests_total counter" in body
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ).read().decode())
        assert health["status"] == "ok" and health["x"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )
    finally:
        server.shutdown()


# -- the acceptance integration: StreamInfer end to end --------------------


@pytest.fixture(scope="module")
def runtime_with_metrics():
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    manager = ModelManager(num_slots=2, warm_compile=False)
    server, service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False, metrics_port=0
    )
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    stub = services.AIRuntimeStub(channel)
    stub.LoadModel(runtime_pb2.LoadModelRequest(
        model_name="obs-tiny", model_path="synthetic://tiny-test"
    ))
    yield stub, service, manager
    channel.close()
    server.stop(grace=None)
    if service.metrics_server is not None:
        service.metrics_server.shutdown()


def test_stream_infer_full_observability(runtime_with_metrics):
    stub, service, manager = runtime_with_metrics
    model_name = manager.get("obs-tiny").engine.cfg.name
    ttft_child = REGISTRY.get("aios_tpu_engine_ttft_seconds").labels(
        model=model_name
    )
    ttft_before = ttft_child.sample_count

    with tracing.start_span("agent-task") as root:
        chunks = list(stub.StreamInfer(runtime_pb2.InferRequest(
            prompt="hello", max_tokens=6, temperature=0.0
        )))
    assert chunks[-1].done

    # one server span carrying the propagated trace id
    server_span = tracing.recent_spans(f"rpc.server.{SVC}/StreamInfer")[-1]
    assert server_span.trace_id == root.trace_id
    assert server_span.parent_id == root.span_id
    # ... and the decode span nests under it (RPC -> decode leaf)
    decode_span = tracing.recent_spans("runtime.decode")[-1]
    assert decode_span.trace_id == root.trace_id
    assert decode_span.parent_id == server_span.span_id

    # a TTFT observation landed for this model
    assert ttft_child.sample_count == ttft_before + 1

    # tokens/sec + occupancy gauges exist for this model (occupancy reads
    # live state: 0 again after the stream retired, so just sample them)
    assert REGISTRY.sample(
        "aios_tpu_engine_batch_occupancy_ratio", {"model": model_name}
    ) is not None
    stream_chunks = REGISTRY.sample(
        "aios_tpu_runtime_stream_chunks_total", {"model": "obs-tiny"}
    )
    assert stream_chunks >= 1

    # all of it visible in the text exposition
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{service.metrics_port}/metrics", timeout=5
    ).read().decode()
    for needle in (
        f'aios_tpu_engine_ttft_seconds_count{{model="{model_name}"}}',
        f'aios_tpu_engine_tokens_per_second{{model="{model_name}"}}',
        f'aios_tpu_engine_batch_occupancy_ratio{{model="{model_name}"}}',
        'aios_tpu_runtime_stream_chunks_total{model="obs-tiny"}',
        'aios_tpu_rpc_requests_total{side="server",service="'
        + SVC + '",method="StreamInfer"}',
        "aios_tpu_runtime_infer_latency_seconds_bucket",
    ):
        assert needle in body, needle
