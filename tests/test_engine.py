"""Decode engine: prefill/decode consistency, sampling, slot reuse.

The key invariant (teacher-forcing test): running prefill + step-by-step
decode through the slot cache must produce exactly the tokens that greedy
argmax over the full-sequence forward produces — i.e. the incremental KV path
is numerically identical to the non-cached path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model as M
from aios_tpu.engine import sampling
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_engine():
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    return TPUEngine(TINY_TEST, params, num_slots=4, max_context=128, cache_dtype=jnp.float32)


def _full_greedy(params, cfg, prompt, n):
    """Reference: greedy generation via repeated full forward (no cache)."""
    toks = list(prompt)
    for _ in range(n):
        logits = M.forward_full(params, cfg, np.asarray([toks], np.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt) :]


def test_greedy_decode_matches_uncached_forward(tiny_engine):
    prompt = [3, 17, 91, 4, 55, 8]
    want = _full_greedy(tiny_engine.params, TINY_TEST, prompt, 10)
    got = tiny_engine.generate(prompt, max_new_tokens=10, temperature=0.0)
    assert got == want


def test_chunked_prefill_matches_monolithic(tiny_engine):
    """Admitting a prompt in 32-token chunks must yield the same first token
    and greedy continuation as one monolithic prefill."""
    prompt = (np.arange(1, 100) % 250 + 1).tolist()  # 99 tokens
    first_a = tiny_engine.prefill(0, prompt, temperature=0.0)
    toks_a = [int(t) for t in tiny_engine.step(8)[:, 0]]
    tiny_engine.release(0)

    pc = tiny_engine.start_chunked_prefill(1, prompt, temperature=0.0, chunk=32)
    steps = 0
    first_b = None
    while first_b is None:
        first_b = pc.step()
        steps += 1
    assert steps == 4 and pc.done  # 32 + 32 + 32 + 3
    toks_b = [int(t) for t in tiny_engine.step(8)[:, 1]]
    tiny_engine.release(1)

    assert first_b == first_a
    assert toks_b == toks_a


def test_chunked_prefill_int8_cache_matches_monolithic():
    """Chunked admission under the int8 KV cache quantizes rows on write
    exactly like the monolithic path (same per-row scales)."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    a = TPUEngine(TINY_TEST, params, num_slots=2, max_context=128,
                  cache_dtype=jnp.int8)
    b = TPUEngine(TINY_TEST, params, num_slots=2, max_context=128,
                  cache_dtype=jnp.int8)
    prompt = (np.arange(1, 80) % 250 + 1).tolist()
    first_a = a.prefill(0, prompt, temperature=0.0)
    toks_a = [int(t) for t in a.step(6)[:, 0]]
    pc = b.start_chunked_prefill(0, prompt, temperature=0.0, chunk=32)
    first_b = None
    while first_b is None:
        first_b = pc.step()
    toks_b = [int(t) for t in b.step(6)[:, 0]]
    assert first_b == first_a
    assert toks_b == toks_a


def test_chunked_prefill_rejects_non_bucket_chunk(tiny_engine):
    with pytest.raises(ValueError):
        tiny_engine.start_chunked_prefill(0, [1, 2, 3], chunk=48)


def test_warmup_compiles_every_bucket_and_step_size():
    """The readiness gate must leave NO graph uncompiled: a missing prefill
    bucket or decode step size compiles for seconds on the scheduler thread
    at first use (the regression behind the 2s agent TTFT: warmup's old
    4-token prompt bucketed to 16 every iteration, so larger buckets were
    never compiled)."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=128, cache_dtype=jnp.float32
    )
    engine.warmup(step_sizes=(1, 2), prefill_chunk=32)
    assert set(engine._prefill_fns) == set(engine.buckets)
    assert set(engine._step_fns) == {1, 2}
    # chunked-admission graphs: the mid chunk and every final bucket <= 32
    assert set(engine._chunk_fns) == {(32, False), (16, True), (32, True)}


def test_close_releases_state():
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=128, cache_dtype=jnp.float32
    )
    engine.prefill(0, [1, 2, 3], temperature=0.0)
    engine.close()
    assert engine.state == {} and engine.params is None
    assert not engine._prefill_fns and not engine._step_fns


def test_generate_respects_stop_tokens(tiny_engine):
    prompt = [3, 17, 91, 4, 55, 8]
    free_run = tiny_engine.generate(prompt, max_new_tokens=10, temperature=0.0)
    stopper = free_run[3]
    stopped = tiny_engine.generate(
        prompt, max_new_tokens=10, temperature=0.0, stop_tokens=(stopper,)
    )
    assert stopped == free_run[: free_run.index(stopper) + 1]


def test_concurrent_slots_are_independent(tiny_engine):
    """Two different prompts decoding in adjacent slots must produce the same
    tokens as each decoding alone (no cross-slot leakage)."""
    p1 = [5, 9, 2, 41]
    p2 = [88, 13, 60, 7, 19]
    solo1 = tiny_engine.generate(p1, max_new_tokens=6)
    solo2 = tiny_engine.generate(p2, max_new_tokens=6)

    t1 = tiny_engine.prefill(1, p1, temperature=0.0)
    t2 = tiny_engine.prefill(2, p2, temperature=0.0)
    got1, got2 = [t1], [t2]
    toks = tiny_engine.step(5)  # [5, S] — one dispatch, five tokens per slot
    got1.extend(int(t) for t in toks[:, 1])
    got2.extend(int(t) for t in toks[:, 2])
    tiny_engine.release(1)
    tiny_engine.release(2)
    assert got1 == solo1
    assert got2 == solo2


def test_slot_reuse_after_release(tiny_engine):
    p = [42, 42, 7]
    a = tiny_engine.generate(p, max_new_tokens=5, slot=3)
    b = tiny_engine.generate(p, max_new_tokens=5, slot=3)
    assert a == b


def test_prompt_bucketing_invariant(tiny_engine):
    """The same prompt must decode identically whatever bucket it lands in
    (padding rows must not leak into attention)."""
    prompt = [9] * 15  # bucket 16
    short = tiny_engine.generate(prompt, max_new_tokens=4)
    prompt_long = [1] * 17 + [9] * 15  # bucket 32; different prefix
    # invariance check: run 15-token prompt again, engine state unchanged
    again = tiny_engine.generate(prompt, max_new_tokens=4)
    assert short == again
    assert len(tiny_engine.generate(prompt_long, max_new_tokens=4)) == 4


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------


def test_top_p_filter_masks_tail():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    out = sampling.top_p_filter(logits, jnp.asarray([0.7]))
    # 0.5 kept (cum before = 0); 0.3 kept (cum before = 0.5 < 0.7);
    # 0.15 dropped (cum before = 0.8 >= 0.7)
    assert np.isfinite(np.asarray(out[0, :2])).all()
    assert np.isneginf(np.asarray(out[0, 2:])).all()


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = sampling.top_k_filter(logits, jnp.asarray([2]))
    assert np.isneginf(np.asarray(out[0, [0, 3]])).all()
    assert np.isfinite(np.asarray(out[0, [1, 2]])).all()


def test_sample_greedy_vs_stochastic_rows():
    logits = jnp.asarray([[0.0, 10.0, 0.0], [0.0, 10.0, 0.0]])
    toks = sampling.sample(
        logits,
        jax.random.PRNGKey(0),
        temperature=jnp.asarray([0.0, 1.0]),
        top_p=jnp.asarray([1.0, 1.0]),
    )
    assert int(toks[0]) == 1  # greedy row
    assert 0 <= int(toks[1]) < 3


def test_sampling_distribution_statistics():
    """Temperature-1 sampling over a known distribution approximates it."""
    probs = np.asarray([0.6, 0.3, 0.1])
    logits = jnp.broadcast_to(jnp.log(jnp.asarray(probs)), (2000, 3))
    toks = sampling.sample(
        logits,
        jax.random.PRNGKey(1),
        temperature=jnp.ones(2000),
        top_p=jnp.ones(2000),
    )
    counts = np.bincount(np.asarray(toks), minlength=3) / 2000
    np.testing.assert_allclose(counts, probs, atol=0.05)


def test_top_p_excludes_tail_statistically():
    probs = np.asarray([0.55, 0.35, 0.1])
    logits = jnp.broadcast_to(jnp.log(jnp.asarray(probs)), (500, 3))
    toks = sampling.sample(
        logits,
        jax.random.PRNGKey(2),
        temperature=jnp.ones(500),
        top_p=jnp.full(500, 0.6),
    )
    # nucleus at 0.6 keeps tokens 0 and 1 only
    assert set(np.asarray(toks).tolist()) <= {0, 1}


def test_host_params_quantize_before_transfer():
    """GGUF-style host (numpy) params with quantized serving: the engine
    quantizes on the host CPU backend and ships only quantized leaves, so
    dense weights never stage on the accelerator (the 7B-tier OOM guard).
    Tokens must match quantizing from device-resident params."""
    import numpy as np

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(21), dtype=jnp.float32)
    host_params = jax.tree.map(lambda a: np.asarray(a), params)
    eng_host = TPUEngine(TINY_TEST, host_params, num_slots=2, max_context=64,
                         cache_dtype=jnp.float32, quantize="int8")
    eng_dev = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                        cache_dtype=jnp.float32, quantize="int8")
    assert "q" in eng_host.params["layers"]["w_qkv"]
    out_h = eng_host.generate([1, 5, 9, 2], max_new_tokens=8, temperature=0.0)
    out_d = eng_dev.generate([1, 5, 9, 2], max_new_tokens=8, temperature=0.0)
    assert out_h == out_d
