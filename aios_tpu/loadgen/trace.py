"""Deterministic trace building: scenario + seed -> the full call list.

Everything random draws from per-tenant ``random.Random`` instances
seeded ``(scenario.seed, tenant.name)``, so tenants are independent (a
new tenant never perturbs another's schedule) and the whole trace is a
pure function of the scenario — the storm gate's determinism contract
rests here. The driver replays arrival TIMES on the wall clock; the
WORK (prompts, budgets, deadlines, task ids) is fixed at build time —
trace-driven, not generated on the fly.

Arrival curves (non-homogeneous Poisson via thinning for the shaped
ones):

  * ``poisson`` — exponential gaps at ``rps``;
  * ``uniform`` — evenly spaced (deadline probes want fixed cadence);
  * ``diurnal`` — rate swings sinusoidally between ``rps`` and
    ``rps * peak_ratio`` over ``period_secs`` (a whole diurnal cycle
    compressed into seconds);
  * ``burst`` — ``rps * peak_ratio`` during the first ``burst_secs`` of
    each ``period_secs`` cycle, ``rps`` otherwise (quota storms, thundering
    herds).

Agent tenants emit fork-shaped call FAMILIES: each parent call spawns
``fork_width`` children at small offsets whose prompts extend the
parent's prompt — the children share the parent's whole text as a
prefix, which is exactly the radix-cache / cache-aware-routing workload
(SGLang's observation that agent traffic is tree-shaped programs,
PAPERS.md).
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import asdict, dataclass
from typing import List

from .scenario import StormScenario, TenantSpec

_WORDS = (
    "plan", "probe", "route", "merge", "audit", "cache", "shard", "drain",
    "batch", "trace", "queue", "grant", "spill", "prune", "fetch", "score",
)


@dataclass(frozen=True)
class Call:
    """One scheduled request. ``t`` is seconds of virtual storm time;
    the driver maps it onto the wall clock."""

    t: float
    tenant: str
    klass: str
    task_id: str
    prompt: str
    max_tokens: int
    temperature: float
    streaming: bool
    deadline_ms: int
    level: str
    parent: str = ""  # parent task id for fork children ("" = root)
    # whether this call's STREAM TEXT joins the verdict fingerprint —
    # set at build time to greedy calls of cache-independent tenants
    # (no shared preamble, no forks). Cache-COUPLED prompts hit the
    # radix index at whatever state the wall clock left it in, and a
    # prefix HIT prefills through different XLA graph shapes than a
    # MISS — bitwise-different KV at near-tie logits can legally flip
    # an argmax, so their contract is counts + completion, not content.
    hash_stream: bool = False

    @property
    def must_complete(self) -> bool:
        """Greedy, non-abusive, deadline-free calls must COMPLETE in
        every run (polite tenants carry margins that make timing sheds
        impossible). Which quota-storm call wins the bucket race is
        timing, so abusive calls pin their admitted/shed COUNTS instead
        — and a deadline verdict is a function of live backlog + the
        observed rate at arrival, so deadline-carrying calls pin
        NOTHING deterministic (their outcomes ride the measured block;
        see report.py)."""
        return (
            self.temperature == 0.0
            and self.klass != "abusive"
            and self.deadline_ms == 0
        )


def _arrivals(t: TenantSpec, duration: float, rng: random.Random) -> List[float]:
    out: List[float] = []
    if t.arrival == "uniform":
        gap = 1.0 / t.rps
        x = gap * 0.5
        while x < duration:
            out.append(x)
            x += gap
        return out
    peak = t.rps * (t.peak_ratio if t.arrival in ("diurnal", "burst") else 1.0)

    def rate_at(x: float) -> float:
        if t.arrival == "poisson":
            return t.rps
        if t.arrival == "diurnal":
            # swing between base and peak over one period
            phase = 0.5 * (1.0 + math.sin(2.0 * math.pi * x / t.period_secs))
            return t.rps + (peak - t.rps) * phase
        # burst: peak inside the on-window at the start of each cycle
        return peak if (x % t.period_secs) < t.burst_secs else t.rps

    # thinning: draw candidate arrivals at the max rate, keep with
    # probability rate(t)/peak — exact for poisson (rate==peak)
    x = 0.0
    while True:
        x += rng.expovariate(peak)
        if x >= duration:
            return out
        if rng.random() * peak <= rate_at(x):
            out.append(x)


def _prompt_len(t: TenantSpec, rng: random.Random) -> int:
    # lognormal long tail around the median, hard-capped
    n = int(rng.lognormvariate(math.log(max(t.prompt_p50, 4)), t.prompt_sigma))
    return max(8, min(n, t.prompt_max))


def _text(rng: random.Random, n_chars: int, head: str) -> str:
    parts = [head]
    size = len(head)
    while size < n_chars:
        w = _WORDS[rng.randrange(len(_WORDS))]
        parts.append(" " + w)
        size += len(w) + 1
    return "".join(parts)[:max(n_chars, len(head))]


def _budget(t: TenantSpec, rng: random.Random) -> int:
    if t.max_tokens_max > t.max_tokens:
        return rng.randint(t.max_tokens, t.max_tokens_max)
    return t.max_tokens


def build_trace(sc: StormScenario) -> List[Call]:
    """The full storm, sorted by arrival time. Deterministic in
    (scenario contents, seed) — build twice, compare, it's ``==``."""
    calls: List[Call] = []
    for t in sc.tenants:
        rng = random.Random(f"{sc.seed}:{t.name}")
        preamble = ""
        if t.shared_prefix > 0:
            # ONE per-tenant preamble every call shares — the agent
            # system-prompt shape the prefix cache exists for
            preamble = _text(
                random.Random(f"{sc.seed}:{t.name}:preamble"),
                t.shared_prefix, f"[{t.name} preamble]",
            )
        for i, at in enumerate(_arrivals(t, sc.duration_secs, rng)):
            task = f"{t.name}-{i}"
            if t.quota_storm:
                # FIXED cost: every storm call is byte-identical in
                # price, so the admitted COUNT is bucket math, not a
                # race over which prompt was dearer (report.py pins it)
                prompt = _text(
                    random.Random(f"{sc.seed}:{t.name}:storm"),
                    t.prompt_p50, f"[{t.name} storm]",
                )
                budget = t.max_tokens
            else:
                head = f"[{t.name} r{i}]"
                prompt = (preamble + " " if preamble else "") + _text(
                    rng, _prompt_len(t, rng), head
                )
                budget = _budget(t, rng)
            cacheless = t.shared_prefix == 0 and t.fork_width == 0
            calls.append(Call(
                t=round(at, 4), tenant=t.name, klass=t.klass,
                task_id=task, prompt=prompt, max_tokens=budget,
                temperature=t.temperature, streaming=t.streaming,
                deadline_ms=t.deadline_ms, level=t.level,
                hash_stream=(
                    t.temperature == 0.0 and not t.quota_storm
                    and cacheless and t.deadline_ms == 0
                ),
            ))
            if t.fork_width > 0:
                # fork-shaped children extending the parent's prompt —
                # each child's prompt CONTAINS the parent's as a prefix
                for k in range(t.fork_width):
                    calls.append(Call(
                        t=round(at + t.fork_gap_secs * (k + 1), 4),
                        tenant=t.name, klass=t.klass,
                        task_id=f"{task}f{k}",
                        prompt=prompt + f" branch {k}: "
                        + _text(rng, 24, ""),
                        max_tokens=budget,
                        temperature=t.temperature,
                        streaming=t.streaming,
                        deadline_ms=t.deadline_ms, level=t.level,
                        parent=task,  # cache-coupled: counts, not content
                    ))
    calls.sort(key=lambda c: (c.t, c.task_id))
    return calls


def trace_fingerprint(calls: List[Call]) -> str:
    """sha256 over the whole schedule — the verdict's proof that two
    runs replayed identical work."""
    h = hashlib.sha256()
    for c in calls:
        h.update(json.dumps(asdict(c), sort_keys=True).encode())
    return h.hexdigest()[:16]
