"""git.* / code.* / self.* / container.* — developer & self-management tools.

Reference: tools/src/{git,code,self_update,container}/ (22 handlers).
Containers use podman (falling back to docker) as the reference does;
self.update/rebuild operate on this repo checkout instead of cargo builds.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

from . import ToolError, ToolSpec, run_cmd

# ---------------------------------------------------------------------------
# git.*
# ---------------------------------------------------------------------------


def _git(repo: str, *argv: str, timeout: float = 60) -> dict:
    if not repo:
        raise ToolError("missing repo path")
    return run_cmd(["git", "-C", repo, *argv], timeout=timeout)


def git_init(args: dict) -> dict:
    path = args.get("path")
    if not path:
        raise ToolError("missing path")
    Path(path).mkdir(parents=True, exist_ok=True)
    run_cmd(["git", "init", path], timeout=30)
    return {"initialized": path}


def git_clone(args: dict) -> dict:
    url, dest = args.get("url"), args.get("dest")
    if not url or not dest:
        raise ToolError("missing url or dest")
    out = run_cmd(["git", "clone", "--depth", "1", url, dest], timeout=300)
    return {"cloned": url, "dest": dest, "log": out["stderr"][-500:]}


def git_add(args: dict) -> dict:
    _git(args.get("repo", ""), "add", *(args.get("paths") or ["."]))
    return {"added": args.get("paths") or ["."]}


def git_commit(args: dict) -> dict:
    msg = args.get("message", "aios automated commit")
    out = _git(
        args.get("repo", ""),
        "-c", "user.email=aios@localhost", "-c", "user.name=aios",
        "commit", "-m", msg,
    )
    return {"committed": msg, "log": out["stdout"][-500:]}


def git_push(args: dict) -> dict:
    out = _git(args.get("repo", ""), "push", timeout=120)
    return {"pushed": True, "log": out["stderr"][-500:]}


def git_pull(args: dict) -> dict:
    out = _git(args.get("repo", ""), "pull", "--ff-only", timeout=120)
    return {"pulled": True, "log": out["stdout"][-500:]}


def git_branch(args: dict) -> dict:
    name = args.get("name")
    if name:
        _git(args.get("repo", ""), "checkout", "-b", name)
        return {"created": name}
    out = _git(args.get("repo", ""), "branch", "--list")
    return {"branches": [b.strip("* ") for b in out["stdout"].splitlines()]}


def git_status(args: dict) -> dict:
    out = _git(args.get("repo", ""), "status", "--porcelain")
    return {"dirty": bool(out["stdout"].strip()),
            "files": out["stdout"].splitlines()[:100]}


def git_log(args: dict) -> dict:
    out = _git(args.get("repo", ""), "log", "--oneline", "-n",
               str(args.get("limit", 20)))
    return {"log": out["stdout"].splitlines()}


def git_diff(args: dict) -> dict:
    out = _git(args.get("repo", ""), "diff", "--stat")
    return {"diff": out["stdout"][-10_000:]}


# ---------------------------------------------------------------------------
# code.*
# ---------------------------------------------------------------------------

_SCAFFOLDS = {
    "python": {
        "main.py": "def main():\n    print('hello from {name}')\n\n\n"
                   "if __name__ == '__main__':\n    main()\n",
        "README.md": "# {name}\n",
        "requirements.txt": "",
    },
    "web": {
        "index.html": "<!doctype html><title>{name}</title><h1>{name}</h1>\n",
        "style.css": "body {{ font-family: sans-serif; }}\n",
    },
}


def code_scaffold(args: dict) -> dict:
    name = args.get("name", "project")
    kind = args.get("kind", "python")
    dest = Path(args.get("dest", f"/tmp/aios/projects/{name}"))
    template = _SCAFFOLDS.get(kind)
    if template is None:
        raise ToolError(f"unknown scaffold kind {kind}; have {list(_SCAFFOLDS)}")
    dest.mkdir(parents=True, exist_ok=True)
    written = []
    for fname, content in template.items():
        (dest / fname).write_text(content.format(name=name))
        written.append(str(dest / fname))
    return {"project": name, "kind": kind, "files": written}


def code_generate(args: dict) -> dict:
    """AI code generation is routed through the runtime/gateway by the
    executor (this handler is replaced there); standalone it only writes
    provided content."""
    dest = args.get("dest")
    content = args.get("content")
    if not dest or content is None:
        raise ToolError(
            "code.generate without an AI backend needs dest + content"
        )
    p = Path(dest)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(content)
    return {"written": str(p), "bytes": len(content)}


# ---------------------------------------------------------------------------
# self.* — framework self-management (reference: tools/src/self_update/)
# ---------------------------------------------------------------------------


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[3]


def self_inspect(args: dict) -> dict:
    root = _repo_root()
    py_files = list(root.glob("aios_tpu/**/*.py"))
    return {
        "root": str(root),
        "python": sys.version.split()[0],
        "modules": len(py_files),
        "loc": sum(len(f.read_text(errors="ignore").splitlines())
                   for f in py_files),
    }


def self_update(args: dict) -> dict:
    out = run_cmd(["git", "-C", str(_repo_root()), "pull", "--ff-only"],
                  timeout=120)
    return {"updated": True, "log": out["stdout"][-500:]}


def self_rebuild(args: dict) -> dict:
    """Regenerate protos + recompile native components."""
    root = _repo_root()
    steps = []
    gen = root / "scripts" / "gen_protos.py"
    if gen.exists():
        run_cmd([sys.executable, str(gen)], timeout=120)
        steps.append("protos")
    native = root / "aios_tpu" / "native" / "build.py"
    if native.exists():
        run_cmd([sys.executable, str(native)], timeout=300)
        steps.append("native")
    return {"rebuilt": steps}


def self_health(args: dict) -> dict:
    import socket

    from ...services import DEFAULT_PORTS

    status = {}
    for name, port in DEFAULT_PORTS.items():
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.5):
                status[name] = "up"
        except OSError:
            status[name] = "down"
    return {"services": status}


# ---------------------------------------------------------------------------
# container.* — podman (fallback docker)
# ---------------------------------------------------------------------------


def _container_cli() -> str:
    for cli in ("podman", "docker"):
        if shutil.which(cli):
            return cli
    raise ToolError("no container runtime (podman/docker) on this host")


def container_create(args: dict) -> dict:
    image = args.get("image")
    if not image:
        raise ToolError("missing image")
    cli = _container_cli()
    argv = [cli, "create", "--name", args.get("name", ""), image]
    argv = [a for a in argv if a]
    out = run_cmd(argv, timeout=300)
    return {"container_id": out["stdout"].strip()}


def container_start(args: dict) -> dict:
    out = run_cmd([_container_cli(), "start", args.get("name", "")], timeout=60)
    return {"started": out["stdout"].strip()}


def container_stop(args: dict) -> dict:
    out = run_cmd([_container_cli(), "stop", args.get("name", "")], timeout=60)
    return {"stopped": out["stdout"].strip()}


def container_list(args: dict) -> dict:
    out = run_cmd([_container_cli(), "ps", "-a", "--format", "json"], timeout=30)
    try:
        containers = json.loads(out["stdout"] or "[]")
    except ValueError:
        containers = out["stdout"].splitlines()
    return {"containers": containers if isinstance(containers, list) else []}


def container_exec(args: dict) -> dict:
    name, cmd = args.get("name"), args.get("command")
    if not name or not cmd:
        raise ToolError("missing name or command")
    out = run_cmd([_container_cli(), "exec", name, "sh", "-c", cmd], timeout=120)
    return {"stdout": out["stdout"], "exit_code": out["exit_code"]}


def container_logs(args: dict) -> dict:
    out = run_cmd(
        [_container_cli(), "logs", "--tail", str(args.get("lines", 100)),
         args.get("name", "")],
        timeout=30,
    )
    return {"logs": (out["stdout"] + out["stderr"]).splitlines()[-200:]}


TOOLS = {
    "git.init": ToolSpec(git_init, "Initialize a git repo", idempotent=True),
    "git.clone": ToolSpec(git_clone, "Shallow-clone a repo",
                          timeout_ms=300_000),
    "git.add": ToolSpec(git_add, "Stage paths"),
    "git.commit": ToolSpec(git_commit, "Commit staged changes"),
    "git.push": ToolSpec(git_push, "Push to remote", timeout_ms=120_000),
    "git.pull": ToolSpec(git_pull, "Fast-forward pull", timeout_ms=120_000),
    "git.branch": ToolSpec(git_branch, "List/create branches"),
    "git.status": ToolSpec(git_status, "Working tree status", idempotent=True),
    "git.log": ToolSpec(git_log, "Recent commits", idempotent=True),
    "git.diff": ToolSpec(git_diff, "Diff stat", idempotent=True),
    "code.scaffold": ToolSpec(code_scaffold, "Scaffold a project skeleton"),
    "code.generate": ToolSpec(code_generate, "AI-assisted code generation"),
    "self.inspect": ToolSpec(self_inspect, "Framework source inventory",
                             idempotent=True),
    "self.update": ToolSpec(self_update, "git pull the framework",
                            requires_confirmation=True),
    "self.rebuild": ToolSpec(self_rebuild, "Regenerate protos/native code",
                             timeout_ms=300_000),
    "self.health": ToolSpec(self_health, "Probe all aiOS service ports",
                            idempotent=True),
    "container.create": ToolSpec(container_create, "Create a container"),
    "container.start": ToolSpec(container_start, "Start a container"),
    "container.stop": ToolSpec(container_stop, "Stop a container"),
    "container.list": ToolSpec(container_list, "List containers",
                               idempotent=True),
    "container.exec": ToolSpec(container_exec, "Exec a command in a container"),
    "container.logs": ToolSpec(container_logs, "Container logs",
                               idempotent=True),
}
