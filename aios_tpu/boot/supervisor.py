"""Service supervisor: topo-sorted start, health gates, restart caps.

Reference parity (initd/src/{main,service}.rs):
  * dependency-ordered startup via topological sort — runtime, memory,
    tools, gateway start first; the orchestrator depends on all four
    (initd/src/main.rs:74-131);
  * each service is spawned as a child process and gated on a TCP health
    probe before dependents start (ServiceSupervisor::wait_for_health,
    service.rs:42-82);
  * supervision loop reaps exits and restarts within a capped window
    (service.rs:97-129 + config [boot] max_restart_attempts);
  * clean-shutdown flag file (initd main.rs:161); a fatal boot error raises
    instead of the reference's emergency shell (we are not PID 1).

The mount/hostname/first-boot duties of the reference's PID-1 do not apply
on a managed TPU-VM host and are intentionally absent.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from .config import AiosConfig, load_config

log = logging.getLogger("aios.boot")


@dataclass
class ServiceDef:
    name: str
    module: str  # python -m <module>
    port: int
    deps: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)


def default_services(config=None) -> Dict[str, ServiceDef]:
    """The five-service topology. With a boot ``config``, the [models]
    serving knobs translate into AIOS_TPU_* env for every child
    (boot/config.serving_env) — one TOML section drives the whole stack's
    serving mode, like the reference's config.toml -> service flags."""
    from ..services import DEFAULT_PORTS

    env: Dict[str, str] = {}
    if config is not None:
        from .config import serving_env

        env = serving_env(config)
    return {
        "runtime": ServiceDef("runtime", "aios_tpu.runtime.service",
                              DEFAULT_PORTS["runtime"], env=dict(env)),
        "memory": ServiceDef("memory", "aios_tpu.memory.service",
                             DEFAULT_PORTS["memory"], env=dict(env)),
        "tools": ServiceDef("tools", "aios_tpu.tools.service",
                            DEFAULT_PORTS["tools"], env=dict(env)),
        "gateway": ServiceDef("gateway", "aios_tpu.gateway.service",
                              DEFAULT_PORTS["gateway"], env=dict(env)),
        "orchestrator": ServiceDef(
            "orchestrator", "aios_tpu.orchestrator.main",
            DEFAULT_PORTS["orchestrator"],
            deps=["runtime", "memory", "tools", "gateway"],
            env=dict(env),
        ),
    }


def topo_sort(services: Dict[str, ServiceDef]) -> List[str]:
    """Dependency-ordered service names (initd main.rs:74-131)."""
    order: List[str] = []
    seen: Dict[str, int] = {}  # 0=visiting, 1=done

    def visit(name: str) -> None:
        state = seen.get(name)
        if state == 1:
            return
        if state == 0:
            raise ValueError(f"dependency cycle at {name}")
        seen[name] = 0
        for dep in services[name].deps:
            visit(dep)
        seen[name] = 1
        order.append(name)

    for name in services:
        visit(name)
    return order


@dataclass
class Supervised:
    definition: ServiceDef
    process: Optional[subprocess.Popen] = None
    restarts: int = 0
    restart_times: List[float] = field(default_factory=list)
    gave_up: bool = False


class Supervisor:
    def __init__(
        self,
        config: Optional[AiosConfig] = None,
        services: Optional[Dict[str, ServiceDef]] = None,
    ):
        self.config = config or load_config()
        # default topology picks up the config's [models] serving knobs
        # (serving_env) so the TOML drives the whole stack's serving mode
        self.services = services or default_services(self.config)
        self.supervised: Dict[str, Supervised] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.max_restarts = int(self.config.get("boot", "max_restart_attempts", 5))
        self.restart_window = float(
            self.config.get("boot", "restart_window_seconds", 300)
        )
        self.health_timeout = float(
            self.config.get("boot", "health_timeout_seconds", 60)
        )

    # -- health -------------------------------------------------------------

    @staticmethod
    def port_open(port: int, host: str = "127.0.0.1", timeout: float = 1.0) -> bool:
        try:
            with socket.create_connection((host, port), timeout=timeout):
                return True
        except OSError:
            return False

    def wait_for_health(self, name: str) -> bool:
        port = self.services[name].port
        deadline = time.time() + self.health_timeout
        while time.time() < deadline:
            if self.port_open(port):
                return True
            entry = self.supervised.get(name)
            if entry and entry.process and entry.process.poll() is not None:
                return False  # died during startup
            time.sleep(0.5)
        return False

    # -- lifecycle ----------------------------------------------------------

    def _spawn(self, entry: Supervised) -> None:
        d = entry.definition
        env = {**os.environ, **d.env}
        entry.process = subprocess.Popen(
            [sys.executable, "-m", d.module],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        log.info("started %s (pid %d, :%d)", d.name, entry.process.pid, d.port)

    def boot(self) -> List[str]:
        """Start everything in dependency order; returns started names."""
        started = []
        flag = Path(self.config.data_dir) / "clean-shutdown"
        flag.unlink(missing_ok=True)
        for name in topo_sort(self.services):
            entry = Supervised(definition=self.services[name])
            self.supervised[name] = entry
            self._spawn(entry)
            if not self.wait_for_health(name):
                raise RuntimeError(
                    f"service {name} failed its health gate within "
                    f"{self.health_timeout}s"
                )
            started.append(name)
        self._thread = threading.Thread(target=self._supervise_loop,
                                        name="supervisor", daemon=True)
        self._thread.start()
        log.info("aiOS boot complete: %s", ", ".join(started))
        return started

    def _supervise_loop(self) -> None:
        while not self._stop.wait(2.0):
            for entry in self.supervised.values():
                p = entry.process
                if p is None or entry.gave_up or p.poll() is None:
                    continue
                now = time.time()
                entry.restart_times = [
                    t for t in entry.restart_times
                    if now - t < self.restart_window
                ]
                if len(entry.restart_times) >= self.max_restarts:
                    entry.gave_up = True
                    log.error("%s exceeded restart cap; giving up",
                              entry.definition.name)
                    continue
                entry.restarts += 1
                entry.restart_times.append(now)
                log.warning("%s exited (%s); restarting (%d in window)",
                            entry.definition.name, p.returncode,
                            len(entry.restart_times))
                try:
                    self._spawn(entry)
                except OSError as exc:
                    log.error("respawn %s failed: %s",
                              entry.definition.name, exc)

    def shutdown(self, clean: bool = True) -> None:
        """Tear down the service tree. clean=False reaps children after a
        FAILED boot without writing the clean-shutdown flag — the flag is
        how the next boot distinguishes a deliberate stop from a crash
        (reference initd main.rs:161), so a failed run must not bless
        itself."""
        self._stop.set()
        # reverse dependency order
        for name in reversed(topo_sort(self.services)):
            entry = self.supervised.get(name)
            if entry and entry.process and entry.process.poll() is None:
                entry.process.terminate()
        deadline = time.time() + 10
        for entry in self.supervised.values():
            if entry.process:
                try:
                    entry.process.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    entry.process.kill()
        if self._thread:
            self._thread.join(timeout=5)
        if clean:
            flag_dir = Path(self.config.data_dir)
            flag_dir.mkdir(parents=True, exist_ok=True)
            (flag_dir / "clean-shutdown").write_text(str(int(time.time())))
            log.info("clean shutdown complete")
        else:
            log.info("service tree reaped after failed boot (not clean)")


def main() -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from .hardware import detect

    hw = detect()
    log.info(
        "hardware: %d cores, %d MB RAM, TPU=%s",
        hw.cpu_threads, hw.memory_total_mb,
        ",".join(hw.tpu_devices) or "none",
    )
    sup = Supervisor()

    # SIGTERM must shut the tree down like SIGINT does: systemd's stop,
    # a bare `kill`, and container runtimes all send TERM — without this
    # the supervisor dies and ORPHANS all five services plus the agents
    # (the reference's initd reaps its tree the same way)
    import signal

    def _term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)

    # boot() runs inside the try: it spawns five services sequentially and
    # waits for readiness, a long window during which TERM/INT must still
    # tear down the partially-booted tree instead of orphaning it
    try:
        sup.boot()
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        sup.shutdown()
    except Exception:
        # a failed boot (e.g. a service missing its health gate) must also
        # tear down whatever did spawn before the error surfaces
        sup.shutdown(clean=False)
        raise
    return 0


if __name__ == "__main__":
    sys.exit(main())
