"""Unified observability substrate: metrics + tracing for every layer.

The serving literature (SGLang, RTP-LLM — PAPERS.md) treats first-class
runtime metrics as the prerequisite for scheduling/batching work; this
package is that substrate for the aiOS-TPU stack:

  * ``obs.metrics``     — thread-safe Prometheus-style registry
                          (Counter / Gauge / Histogram with labels, text
                          exposition, process-wide default registry);
  * ``obs.instruments`` — the ONE catalog of every metric the stack
                          registers (docs/OBSERVABILITY.md mirrors it);
  * ``obs.tracing``     — span-based tracing with W3C ``traceparent``
                          context propagation (goal -> task -> agent ->
                          RPC -> decode hierarchy);
  * ``obs.interceptors``— gRPC client/server interceptors wiring every
                          RPC into rpc_{requests,errors,latency} metrics
                          and the span tree (installed by aios_tpu.rpc);
  * ``obs.http``        — stdlib /metrics + /healthz endpoint each
                          service's serve() can start.

No third-party dependencies: prometheus_client is not in the image, so
the registry is self-contained stdlib code.
"""

from __future__ import annotations

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from .tracing import (  # noqa: F401
    Span,
    current_span,
    current_traceparent,
    parse_traceparent,
    recent_spans,
    start_span,
)
from .http import start_metrics_server, maybe_start_metrics_server  # noqa: F401
