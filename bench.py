#!/usr/bin/env python
"""Headline benchmarks: TPU decode throughput for the runtime's model tiers.

Prints ONE JSON line per benchmark config (flushed as each completes, so a
timeout still leaves the finished lines on stdout):

  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "p50_ttft_ms": N, "hbm_gbps": N, "hbm_util_v5e": N, ...}

Configs (BASELINE.md "benchmark configs to report"):
  1. tinyllama-1.1b  — 8-slot continuous-batch decode, int8 weights
     (the reference's 8-agent mixed load, config 3's operational tier)
  2. mistral-7b      — single-request decode, int8 weights (config 2)
  3. mistral-7b      — 8-slot continuous-batch decode, int8 weights
  4. --virtual-tp    — Mistral-geometry TP decode on a virtual CPU mesh
     (config 4's sharding path; perf numbers only meaningful on a real
     multi-chip slice, so this is gated behind the flag)
  5. --virtual-ep    — Qwen3-MoE-geometry expert-parallel int8 decode on a
     virtual CPU mesh (dp x ep x tp sharding proof; real MoE serving needs
     a multi-chip slice — 30B int8 weights exceed one chip's HBM)

Baseline: the reference runs llama.cpp on CPU at 5-15 tokens/sec for <=7B Q4
models (docs/HARDWARE.md:148, BASELINE.md); vs_baseline divides by the top of
that range (15 tok/s), i.e. the most favorable reading for the reference.

Method: synthetic weights built directly in the int8 serving layout
(throughput is weight-value-independent; model.init_quantized_params), 64-token
prompts, steady-state batched decode measured over multi-step scan dispatches
so host/relay latency is amortized exactly as the production continuous-
batching path does. p50 TTFT is the warm (post-compile) per-request prefill
latency. hbm_gbps = (weight bytes + mean KV bytes) per decode step x steps/s;
hbm_util_v5e divides by a v5e chip's ~819 GB/s peak.

Robustness (VERDICT r2 weak #1): the TPU backend behind the axon tunnel can
be transiently UNAVAILABLE at process start; backend init is probed in a
subprocess with backoff BEFORE the in-process jax import, and any config that
fails still emits a diagnostic JSON line instead of dying silently.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

V5E_HBM_GBPS = 819.0  # v5e chip peak HBM bandwidth
BASELINE_CPU_TPS = 15.0  # top of the reference's published range

# Bench JSON-line schema version: bump whenever line fields change shape
# or meaning, so scripts/benchdiff.py can REFUSE a cross-schema
# comparison instead of silently mis-diffing two incompatible captures
# (rides beside the platform/device_kind stamps every line carries).
BENCH_SCHEMA_VERSION = 1


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _platform_stamp() -> dict:
    """Which backend this process is ACTUALLY measuring. Every JSON
    result line carries it so CPU-side A/B numbers can never be mistaken
    for hardware numbers again (BENCH_r03–r05 benched a downed TPU
    tunnel without saying so). Deliberately side-effect-free: if jax is
    not imported yet (diagnostic lines before the backend probe), the
    stamp says so instead of initializing a backend just to label an
    error line."""
    jax = sys.modules.get("jax")
    if jax is None:
        hint = os.environ.get("JAX_PLATFORMS", "")
        return {
            "platform": "uninitialized",
            "device_kind": f"jax not imported (JAX_PLATFORMS={hint!r})",
        }
    try:
        dev = jax.devices()[0]
        return {
            "platform": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", str(dev)),
        }
    except Exception as e:  # backend died mid-run: stamp the failure
        return {"platform": "unavailable", "device_kind": repr(e)[:120]}


def _process_stamp() -> dict:
    """The fleet process identity (host id, role, rank, version —
    obs/fleet.py) on every line: a capture archived off a multi-host
    sweep says WHICH process produced it, not just which backend."""
    try:
        from aios_tpu.obs import fleet

        return {"process_info": fleet.process_identity("bench")}
    except Exception as e:  # import half-broken mid-bisect: stamp that
        return {"process_info": {"error": repr(e)[:120]}}


def emit(obj):
    stamped = dict(_platform_stamp())
    stamped["schema_version"] = BENCH_SCHEMA_VERSION
    stamped.update(_process_stamp())
    stamped.update(obj)  # an explicit platform/schema in obj wins
    print(json.dumps(stamped), flush=True)


def slo_block(model: str) -> dict:
    """TTFT/TPOT p50/p99 + windowed SLO attainment for one model, read
    from the flight-recorder ring and the SLO engine — the per-bench
    serving-quality block (ISSUE 8). Benches that route requests through
    a ContinuousBatcher / ReplicaPool attach this to their JSON line so
    every capture doubles as an SLO regression record."""
    from aios_tpu.obs import flightrec, slo

    tls = flightrec.RECORDER.recent(model=model, limit=512)
    ttfts = sorted(t.ttft_ms for t in tls if t.ttft_ms > 0)
    tpots = sorted(t.tpot_ms for t in tls if t.tpot_ms > 0)

    def pct(vals, p):
        if not vals:
            return 0.0
        idx = min(int(p * (len(vals) - 1) + 0.5), len(vals) - 1)
        return round(vals[idx], 3)

    block = {
        "requests": len(tls),
        "ttft_p50_ms": pct(ttfts, 0.5),
        "ttft_p99_ms": pct(ttfts, 0.99),
        "tpot_p50_ms": pct(tpots, 0.5),
        "tpot_p99_ms": pct(tpots, 0.99),
    }
    if model in slo.ENGINE.models():
        block["attainment"] = {
            objective: v["attainment"]
            for objective, v in slo.ENGINE.evaluate(model).items()
        }
    return block


def probe_backend(window_secs: float | None = None,
                  max_attempts: int | None = None) -> bool:
    """Probe backend init in a subprocess with capped backoff, so a
    transiently unavailable tunnel doesn't poison this process's cached jax
    backend.

    The probe budget is CAPPED — 3 attempts / 10 minutes by default
    (AIOS_BENCH_PROBE_SECS / AIOS_BENCH_PROBE_ATTEMPTS). BENCH_r05's
    wedged tunnel ate a silent 2-hour window and the round still produced
    nothing parseable; a bounded probe plus per-config diagnostic lines
    (main()) beats hoping the tunnel heals."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return True
    if window_secs is None:
        window_secs = float(os.environ.get("AIOS_BENCH_PROBE_SECS", 600))
    if max_attempts is None:
        max_attempts = int(os.environ.get("AIOS_BENCH_PROBE_ATTEMPTS", 3))
    # a wedged libtpu init HANGS rather than failing; this caps one attempt
    attempt_timeout = float(os.environ.get("AIOS_BENCH_PROBE_TIMEOUT", 180))
    deadline = time.time() + window_secs
    delay, attempt = 5.0, 0
    while True:
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                capture_output=True,
                text=True,
                timeout=attempt_timeout,
            )
            ok, detail = r.returncode == 0, r.stderr.strip()[-200:]
            if ok:
                log(f"backend probe ok ({r.stdout.strip()}) attempt {attempt}")
                return True
        except subprocess.TimeoutExpired:
            ok, detail = (
                False,
                f"probe timed out after {attempt_timeout:.0f}s (wedged tunnel?)",
            )
        remaining = deadline - time.time()
        log(f"[{time.strftime('%H:%M:%S')}] backend probe failed "
            f"(attempt {attempt}/{max_attempts}, "
            f"{max(remaining, 0) / 60:.0f} min left in window): {detail}")
        if attempt >= max_attempts or remaining <= delay:
            log("backend probe budget exhausted; emitting diagnostics")
            return False
        time.sleep(delay)
        delay = min(delay * 2, 300.0)


def bench_decode(name, cfg, *, num_slots, active_slots, max_context,
                 prompt_len, chunk, measure_chunks, quant_kv=False,
                 weight_mode="int8", profile_dir=None):
    """One decode-throughput config; returns the result dict."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.engine import TPUEngine

    t0 = time.time()
    params = model_mod.init_quantized_params(
        cfg, jax.random.PRNGKey(0), mode=weight_mode
    )
    weight_bytes = model_mod.serving_weight_bytes(params)
    engine = TPUEngine(
        cfg,
        params,
        num_slots=num_slots,
        max_context=max_context,
        cache_dtype=jnp.int8 if quant_kv else jnp.bfloat16,
        # production default: speculative serving is off, so the decode
        # scan skips the history scatter (ModelManager does the same)
        track_history=False,
    )
    load_s = time.time() - t0
    log(f"[{name}] params+engine in {load_s:.1f}s "
        f"({weight_bytes / 1e9:.2f} GB weights)")

    # prefill the active slots (compiles the prompt bucket once)
    t0 = time.time()
    prompt = list(range(1, prompt_len + 1))
    engine.prefill(0, prompt, temperature=0.7, top_p=0.95)  # compile
    ttfts = []
    for s in range(active_slots):
        t1 = time.time()
        engine.prefill(s, prompt, temperature=0.7, top_p=0.95)
        ttfts.append(time.time() - t1)
    log(f"[{name}] prefill x{active_slots} in {time.time() - t0:.1f}s "
        f"(first incl. compile)")

    # compile + warm the decode chunk
    t0 = time.time()
    engine.step(chunk)
    log(f"[{name}] decode chunk compile+run in {time.time() - t0:.1f}s")
    engine.step(chunk)  # warm

    # measured region
    t0 = time.time()
    for _ in range(measure_chunks):
        engine.step(chunk)
    dt = time.time() - t0
    final_lengths = [engine.slot_length(s) for s in range(active_slots)]
    # the engine's own serving counters (the same numbers /metrics
    # exposes): occupancy should be active_slots/num_slots at this point,
    # and any compile event AFTER the warm chunk would flag a mid-
    # measurement XLA stall poisoning tok/s
    engine_stats = engine.stats()
    # optional XLA profile of ONE steady-state dispatch, traced after the
    # timing loop AND after final_lengths so neither tok/s nor the HBM
    # estimate sees the extra step (VERDICT r4 item 4's step-time
    # breakdown comes from this trace)
    if profile_dir:
        import re

        # full name, not a truncation — int8/int4 variants must not
        # collide into one trace directory
        tag = re.sub(r"[^a-z0-9]+", "-", name.lower()).strip("-")
        pdir = os.path.join(profile_dir, tag)
        try:
            with jax.profiler.trace(pdir):
                engine.step(chunk)
            log(f"[{name}] XLA profile written to {pdir}")
        except Exception as e:  # noqa: BLE001 - diagnostic, keep benching
            log(f"[{name}] profile capture FAILED: {e!r}")
    engine.close()  # free HBM before the next config loads
    total_tokens = active_slots * chunk * measure_chunks
    tps = total_tokens / dt
    steps_per_s = chunk * measure_chunks / dt

    # HBM traffic: weights every step + mean KV rows read (k+v) per step
    final_len = float(sum(final_lengths)) / max(active_slots, 1)
    mean_len = final_len - chunk * measure_chunks / 2  # mid-measurement mean
    kv_itemsize = 1 if quant_kv else 2
    cache_bytes = (
        2 * cfg.num_layers * active_slots * max(mean_len, 0)
        * cfg.num_kv_heads * cfg.head_dim * kv_itemsize
    )
    hbm_gbps = (weight_bytes + cache_bytes) * steps_per_s / 1e9

    p50_ttft_ms = sorted(ttfts)[len(ttfts) // 2] * 1000.0
    log(f"[{name}] {total_tokens} tokens in {dt:.2f}s -> {tps:.1f} tok/s/chip "
        f"(batch {active_slots}); p50 warm TTFT {p50_ttft_ms:.0f} ms; "
        f"~{hbm_gbps:.0f} GB/s HBM")
    return {
        "metric": name,
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / BASELINE_CPU_TPS, 1),
        "p50_ttft_ms": round(p50_ttft_ms, 1),
        "hbm_gbps": round(hbm_gbps, 1),
        "hbm_util_v5e": round(hbm_gbps / V5E_HBM_GBPS, 3),
        "batch": active_slots,
        "kv_cache": "int8" if quant_kv else "bf16",
        "weights": weight_mode,
        # reference target: model load <5 s (docs/phases/04-AI-RUNTIME.md:
        # 331); ours covers synthetic init + engine/cache placement
        "load_s": round(load_s, 1),
        "batch_occupancy": engine_stats.get("batch_occupancy", 0.0),
        "decode_steps": engine_stats.get("decode_steps", 0),
        "xla_compiles": engine_stats.get("xla_compiles", 0),
        "xla_compile_s": engine_stats.get("xla_compile_s", 0.0),
    }


def bench_mixed_tier():
    """BASELINE config 3: operational + tactical tiers co-resident on ONE
    chip (the reference runs one llama-server per model and serializes into
    each); here TinyLlama-1.1B and Mistral-7B int8 share HBM and their
    batched decode dispatches interleave — aggregate tokens/sec across both
    tiers is the metric."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import MISTRAL_7B, TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine

    chunk, rounds = 64, 3
    engines = []
    try:
        t0 = time.time()
        for cfg, slots in ((TINYLLAMA_1_1B, 4), (MISTRAL_7B, 4)):
            params = model_mod.init_quantized_params(cfg, jax.random.PRNGKey(0))
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=1024,
                            cache_dtype=jnp.bfloat16)
            for s in range(slots):
                eng.prefill(s, list(range(1, 65)), temperature=0.7, top_p=0.95)
            eng.step(chunk)  # compile + warm THE MEASURED step size
            engines.append((cfg.name, eng, slots))
        log(f"[mixed-tier] both engines resident in {time.time() - t0:.1f}s")

        per_model = {}
        t0 = time.time()
        for _ in range(rounds):
            for name, eng, _ in engines:
                t1 = time.time()
                eng.step(chunk)
                per_model[name] = per_model.get(name, 0.0) + (time.time() - t1)
        dt = time.time() - t0
        total = sum(slots for _, _, slots in engines) * chunk * rounds
        tps = total / dt
        return {
            "metric": "mixed-tier co-resident decode (tinyllama + mistral-7b "
                      "int8, 4+4 slots, one chip)",
            "value": round(tps, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(tps / BASELINE_CPU_TPS, 1),
            "per_model_tps": {
                name: round(slots * chunk * rounds / per_model[name], 1)
                for name, _, slots in engines
            },
        }
    finally:
        for _, eng, _ in engines:
            eng.close()  # free HBM for the next config


def bench_agent_ttft():
    """BASELINE north-star secondary metric: p50 agent-task TTFT — request
    submission to FIRST SAMPLED TOKEN through the production continuous
    batcher (admission + bucketed prefill + on-device sample), 8 agent
    requests arriving at once. Measured at the token boundary, not the
    text-delta boundary: with synthetic weights the sampled ids are
    arbitrary, so incremental DEtokenization timing would measure the
    tokenizer's luck, not the serving stack.

    A second wave measures the paged+prefix-cache engine on the realistic
    agent pattern — every request re-sends the same 512-token system
    preamble — where admission is a page-table update for all but the
    first arrival."""
    import jax

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine

    def run_wave(engine, prompt):
        batcher = ContinuousBatcher(engine)
        try:
            handles = [
                batcher.submit(Request(prompt_ids=prompt, max_tokens=16,
                                       temperature=0.7, top_p=0.95))
                for _ in range(8)
            ]
            for h in handles:
                h.tokens()  # drain to completion
            return sorted(h.ttft_ms for h in handles)
        finally:
            batcher.shutdown()

    t0 = time.time()
    params = model_mod.init_quantized_params(TINYLLAMA_1_1B, jax.random.PRNGKey(0))
    engine = TPUEngine(TINYLLAMA_1_1B, params, num_slots=8, max_context=1024)
    engine.warmup()
    log(f"[agent-ttft] engine ready in {time.time() - t0:.1f}s (incl. warmup)")
    try:
        ttfts = run_wave(engine, list(range(1, 49)))
    finally:
        engine.close()
    p50 = ttfts[len(ttfts) // 2]
    log(f"[agent-ttft] p50 {p50:.0f} ms, p max {ttfts[-1]:.0f} ms over 8 agents")

    result = {
        "metric": "p50 agent-task TTFT, submission -> first token, continuous "
                  "batcher (8 concurrent agents, tinyllama int8)",
        "value": round(p50, 1),
        "unit": "ms",
        "vs_baseline": 0.0,  # the reference publishes no TTFT number
        "p_max_ms": round(ttfts[-1], 1),
    }
    try:
        t0 = time.time()
        pengine = TPUEngine(
            TINYLLAMA_1_1B, params, num_slots=8, max_context=1024,
            paged_pool_rows=8192, page_size=128,
        )
        try:
            pengine.warmup()
            log(f"[agent-ttft] paged engine ready in {time.time() - t0:.1f}s")
            preamble = list(range(3, 515))  # shared 512-token system prompt
            run_wave(pengine, preamble + [700])  # register the preamble
            pttfts = run_wave(pengine, preamble + [701, 702])  # all hit
        finally:
            pengine.close()  # even a failed warmup must release its HBM
        prefix_p50 = pttfts[len(pttfts) // 2]
        log(f"[agent-ttft] prefix-cache wave p50 {prefix_p50:.0f} ms "
            f"(512-token shared preamble)")
        result["prefix_cache_preamble_p50_ms"] = round(prefix_p50, 1)
    except Exception as e:  # the headline number stands; flag, don't fake
        log(f"[agent-ttft] prefix wave failed: {e!r}")
        result["prefix_wave_error"] = repr(e)[:200]
    return result


def bench_replica_pool(replicas: int):
    """--replicas N: shared-prefix agent waves through the serving
    ReplicaPool (aios_tpu/serving/) — 8 agents, two tenants, each tenant
    re-sending its own 512-token preamble. Measures aggregate tok/s AND
    routing quality: the prefix-routed fraction plus per-replica
    occupancy (peak while the wave is in flight and final), so a bench
    run can tell cache-aware routing from round-robin luck."""
    import jax

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.serving import ReplicaPool, ServingConfig

    t0 = time.time()
    params = model_mod.init_quantized_params(
        TINYLLAMA_1_1B, jax.random.PRNGKey(0)
    )
    engines = []
    for _ in range(replicas):
        eng = TPUEngine(
            TINYLLAMA_1_1B, params, num_slots=8, max_context=1024,
            paged_pool_rows=8192, page_size=128,
        )
        eng.warmup()
        engines.append(eng)
    pool = ReplicaPool(
        "bench-pool", engines, lambda e: ContinuousBatcher(e),
        ServingConfig(replicas=replicas),
    )
    log(f"[replica-pool] {replicas} replicas ready in {time.time() - t0:.1f}s")
    try:
        preambles = {  # two tenants, disjoint 512-token system prompts
            "tenant-a": list(range(3, 515)),
            "tenant-b": list(range(600, 1112)),
        }
        # register each prefix once, CONCURRENTLY: the second submit must
        # see the first still outstanding so least-loaded spreads the two
        # tenants across replicas (sequential warms would tie-break both
        # onto replica 0 and the wave would measure one replica)
        warm = [
            pool.submit(
                Request(prompt_ids=pre + [1], max_tokens=8, temperature=0.0),
                tenant=tenant,
            )
            for tenant, pre in preambles.items()
        ]
        for h in warm:
            h.tokens()

        peak = [0.0] * replicas
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                for i, r in enumerate(pool.replicas):
                    peak[i] = max(peak[i], r.occupancy())
                time.sleep(0.02)

        sampler_t = threading.Thread(target=sampler, daemon=True)
        sampler_t.start()
        t1 = time.time()
        handles = []
        for wave in range(3):
            for agent in range(8):
                tenant = ("tenant-a", "tenant-b")[agent % 2]
                handles.append(pool.submit(
                    Request(
                        prompt_ids=preambles[tenant] + [2 + wave, agent],
                        max_tokens=32, temperature=0.0,
                    ),
                    tenant=tenant,
                ))
        total_tokens = sum(len(h.tokens()) for h in handles)
        dt = time.time() - t1
        stop.set()
        sampler_t.join(timeout=2)
        stats = pool.stats()
        routed = {
            k.removeprefix("routed_"): int(v)
            for k, v in stats.items() if k.startswith("routed_")
        }
        n_routed = sum(routed.values()) or 1
        return {
            "metric": f"replica-pool shared-prefix agent waves "
                      f"({replicas} replicas, 8 agents, tinyllama int8)",
            "value": round(total_tokens / dt, 1),
            "unit": "tokens/sec",
            "vs_baseline": round(total_tokens / dt / BASELINE_CPU_TPS, 1),
            "replicas": replicas,
            "prefix_routed_ratio": round(
                routed.get("prefix", 0) / n_routed, 3
            ),
            "routing": routed,
            "per_replica_peak_occupancy": [round(p, 3) for p in peak],
            "per_replica_occupancy": [
                stats.get(f"replica{i}_occupancy", 0.0)
                for i in range(replicas)
            ],
            "slo": slo_block("bench-pool"),
        }
    finally:
        pool.shutdown()


def bench_spec_decode():
    """N-gram speculative decoding on the latency-sensitive path (BASELINE
    config 2: Mistral-7B single request). Decode at batch 1 is weight-
    bandwidth-bound, so verifying a 7-token draft costs about one plain
    step; every accepted draft token is nearly free. Acceptance depends on
    output repetitiveness — synthetic-weight greedy decode settles into a
    cycle, which is the full-acceptance regime (equivalent to the agent
    echo/quote workload), so `value` is the UPPER BOUND; `rounds_per_s` vs
    `plain_tok_per_s` gives the cost side (a verify round vs a plain step),
    and `accept_per_round` the measured acceptance."""
    import jax

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import MISTRAL_7B
    from aios_tpu.engine.engine import TPUEngine

    cfg = MISTRAL_7B
    t0 = time.time()
    params = model_mod.init_quantized_params(cfg, jax.random.PRNGKey(0))
    engine = TPUEngine(cfg, params, num_slots=1, max_context=4096)
    engine.prefill(0, list(range(1, 65)), temperature=0.0)
    log(f"[spec-decode] engine+prefill in {time.time() - t0:.1f}s")

    # plain single-request decode rate (the comparison base)
    engine.step(32)  # compile
    engine.step(32)  # warm
    t0 = time.time()
    for _ in range(3):
        engine.step(32)
    plain_tps = 96 / (time.time() - t0)

    # speculative: 16 verify rounds per dispatch, 7-token n-gram drafts
    engine.spec_step(16, draft_len=7)  # compile
    engine.spec_step(16, draft_len=7)  # warm (greedy cycle is live by now)
    t0 = time.time()
    tokens = 0
    rounds = 0
    for _ in range(3):
        _, counts = engine.spec_step(16, draft_len=7)
        tokens += int(counts[:, 0].sum())
        rounds += counts.shape[0]
    dt = time.time() - t0
    engine.close()
    spec_tps = tokens / dt
    rounds_per_s = rounds / dt
    log(f"[spec-decode] {tokens} tokens in {rounds} rounds, {dt:.2f}s -> "
        f"{spec_tps:.1f} tok/s (plain {plain_tps:.1f}, "
        f"{rounds_per_s:.1f} verify rounds/s)")
    return {
        "metric": "mistral-7b single-request n-gram speculative decode, "
                  "repetitive/echo workload upper bound (int8 serving)",
        "value": round(spec_tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(spec_tps / BASELINE_CPU_TPS, 1),
        "plain_tok_per_s": round(plain_tps, 1),
        "rounds_per_s": round(rounds_per_s, 1),
        "accept_per_round": round(tokens / max(rounds, 1) - 1, 2),
        "draft_len": 7,
    }


def bench_paged_kv():
    """Paged KV cache (SURVEY section 7.2): 16 slots x 4096 logical context
    backed by an 8192-row physical pool — 8x HBM oversubscription vs the
    dense cache — with identical outputs. Reports paged decode throughput
    against the dense engine on the same workload plus both cache
    footprints."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINYLLAMA_1_1B
    slots, ctx, chunk, rounds = 16, 4096, 64, 3
    row_bytes = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2
    results = {}
    params = model_mod.init_quantized_params(cfg, jax.random.PRNGKey(0))
    prefix_speedup = 0.0
    for mode, extra in (
        ("dense", {}),
        ("paged", {"paged_pool_rows": 8192, "page_size": 128}),
    ):
        eng = TPUEngine(
            cfg, params, num_slots=slots, max_context=ctx,
            cache_dtype=jnp.bfloat16, **extra,
        )
        for s in range(slots):
            eng.prefill(s, list(range(1, 65)), temperature=0.7, top_p=0.95)
        eng.step(chunk)  # compile + warm
        t0 = time.time()
        for _ in range(rounds):
            eng.step(chunk)
        dt = time.time() - t0
        results[mode] = slots * chunk * rounds / dt
        if mode == "paged":
            # prefix caching: an agent preamble resubmitted = prefill that
            # maps cached pages instead of recomputing them
            for s in range(slots):
                eng.release(s)
            preamble = list(range(3, 1028))  # 1025 tokens, 8 full blocks
            eng.prefill(0, preamble, temperature=0.0)  # compile + register
            eng.release(0)
            eng.prefill(0, preamble, temperature=0.0)  # compile the hit path
            eng.release(0)
            t0 = time.time()
            # disjoint tokens, same bucket: a true cold prefill
            eng.prefill(0, list(range(9000, 10025)), temperature=0.0)
            cold = time.time() - t0
            eng.release(0)
            t0 = time.time()
            eng.prefill(0, preamble, temperature=0.0)  # full prefix hit
            warm = time.time() - t0
            prefix_speedup = cold / max(warm, 1e-9)
            log(f"[paged-kv] prefix hit prefill {warm * 1e3:.0f} ms vs "
                f"cold {cold * 1e3:.0f} ms")
        eng.close()
        log(f"[paged-kv] {mode}: {results[mode]:.1f} tok/s")
    return {
        "metric": "paged KV cache decode, tinyllama 16 slots x 4096 ctx on an "
                  "8192-row pool (8x HBM oversubscription, int8 weights)",
        "value": round(results["paged"], 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(results["paged"] / BASELINE_CPU_TPS, 1),
        "dense_tok_per_s": round(results["dense"], 1),
        "dense_cache_gb": round(slots * ctx * row_bytes / 1e9, 2),
        "paged_pool_gb": round(8192 * row_bytes / 1e9, 2),
        "oversubscription": round(slots * ctx / 8192.0, 1),
        "prefix_hit_prefill_speedup": round(prefix_speedup, 1),
    }


def bench_host_tier():
    """Host-RAM KV spill tier behind the prefix cache (engine/paged.py
    HostPageStore): fill the index, evict it under pool pressure (pages
    spill device->host), resubmit the preamble (pages restore with a
    device_put + scatter) — reports the host-tier hit ratio and the
    restore-vs-recompute prefill latency. Tiny geometry on purpose: the
    path under test is memcpy + scatter, not model compute, so CPU
    fallback numbers are meaningful (--host-tier-smoke runs just this,
    assertion-free, as the host-tier regression probe)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(name="tiny-host-tier", max_context=512)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    # 16 usable pages; the preamble holds 10, the pressure prompt needs
    # 15 — reclaim must spill most of the preamble to the host tier
    eng = TPUEngine(
        cfg, params, num_slots=2, max_context=512,
        cache_dtype=jnp.float32, paged_pool_rows=512, page_size=32,
        prefix_host_bytes=256 << 20,
    )
    try:
        eng.warmup(step_sizes=(1,))  # compile prefill/step/restore graphs

        def cycle(seed):
            """Cold prefill -> pressure spill -> resubmit (restore);
            returns (cold_s, restore_s)."""
            rng = np.random.default_rng(seed)
            preamble = [int(t) for t in rng.integers(1, 500, 321)]  # 10 blk
            t0 = time.time()
            eng.prefill(0, preamble, temperature=0.0)  # registers blocks
            cold_s = time.time() - t0
            eng.release(0)
            pressure = [int(t) for t in rng.integers(1, 500, 480)]  # 15 blk
            before = eng.host_store.spills
            eng.prefill(0, pressure, temperature=0.0)  # reclaim -> spill
            eng.release(0)
            deadline = time.time() + 10
            while eng.host_store.spills - before < 2 \
                    and time.time() < deadline:
                time.sleep(0.02)  # spill worker drains its queue
            t0 = time.time()
            eng.prefill(0, preamble, temperature=0.0)  # host-tier restore
            restore_s = time.time() - t0
            eng.release(0)
            return cold_s, restore_s

        cycle(3)  # throwaway: compiles the hit-path tail chunk graphs
        cold, warm = cycle(4)  # steady-state measurement
        spilled = len(eng.host_store)
        stats = eng.stats()
    finally:
        eng.close()
    probes = stats.get("host_tier_hits", 0) + stats.get("host_tier_misses", 0)
    speedup = cold / max(warm, 1e-9)
    log(f"[host-tier] spilled {spilled} page(s); restore prefill "
        f"{warm * 1e3:.0f} ms vs recompute {cold * 1e3:.0f} ms "
        f"({stats.get('prefix_rows_restored', 0):.0f} rows restored)")
    return {
        "metric": "prefix-cache host tier spill->restore "
                  "(tiny geometry, restore vs recompute prefill)",
        "value": round(speedup, 2),
        "unit": "x prefill speedup (restore vs recompute)",
        "vs_baseline": round(speedup, 2),
        "recompute_prefill_ms": round(cold * 1e3, 1),
        "restore_prefill_ms": round(warm * 1e3, 1),
        "host_hit_ratio": round(
            stats.get("host_tier_hits", 0) / probes, 3
        ) if probes else 0.0,
        "pages_spilled": int(stats.get("host_tier_spills", 0)),
        "pages_restored": int(stats.get("host_tier_restores", 0)),
        "rows_restored": int(stats.get("prefix_rows_restored", 0)),
        "restore_dispatch_s": stats.get("host_tier_restore_s", 0.0),
    }


def bench_longctx(smoke: bool = False):
    """Long-context tier A/B (window+sink KV compression, ISSUE 13):
    admit several long prompts through chunked admission with
    compression off vs on and report PEAK resident KV pages (sampled
    after every admission chunk and decode dispatch) plus decode tok/s.
    The compression win is deterministic page accounting, not wall
    clock, so CPU fallback numbers are meaningful (the bench_host_tier
    rationale). The prefix cache is off so pruned pages actually return
    to the pool instead of lingering as index-held cold entries.
    ``smoke=True`` (--longctx-smoke) runs just the compressed arm:
    long prompt -> compression kicks in -> decode continues, exit 0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(name="tiny-longctx", max_context=1024)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    slots, decode_tokens = 4, 48
    rng = np.random.default_rng(7)
    prompts = [
        [int(t) for t in rng.integers(1, 500, 800)] for _ in range(slots)
    ]

    def run(compress: bool):
        kw = {}
        if compress:
            kw = dict(kv_compress_after=256, kv_sink_pages=1,
                      kv_window_pages=4)
        eng = TPUEngine(
            cfg, params, num_slots=slots, max_context=1024,
            cache_dtype=jnp.float32, paged_pool_rows=4096, page_size=32,
            prefix_cache=False, **kw,
        )
        peak = 0
        streams = [[] for _ in range(slots)]
        try:
            eng.warmup(step_sizes=(8,), prefill_chunk=128)
            for s, ids in enumerate(prompts):
                pc = eng.start_chunked_prefill(s, ids, chunk=128)
                first = pc.step()
                peak = max(peak, eng.allocator.pages_in_use())
                while first is None:
                    first = pc.step()
                    peak = max(peak, eng.allocator.pages_in_use())
                streams[s].append(first)
            t0 = time.time()
            done = 0
            while done < decode_tokens:
                toks = eng.step(8)
                peak = max(peak, eng.allocator.pages_in_use())
                for r in range(toks.shape[0]):
                    for s in range(slots):
                        streams[s].append(int(toks[r, s]))
                done += toks.shape[0]
            dt = time.time() - t0
            stats = eng.stats()
        finally:
            eng.close()
        tps = slots * decode_tokens / max(dt, 1e-9)
        return peak, tps, streams, stats

    if smoke:
        peak_on, tps_on, _, stats = run(True)
        log(f"[longctx] smoke: peak {peak_on} pages, "
            f"{stats.get('kv_compress_pages_pruned', 0):.0f} pruned, "
            f"{tps_on:.1f} tok/s")
        return {
            "metric": "long-context smoke (compression kicks in, decode "
                      "continues)",
            "value": float(stats.get("kv_compress_pages_pruned", 0)),
            "unit": "pages pruned",
            "vs_baseline": 1.0,
            "peak_resident_pages": peak_on,
            "compressed_slots": int(stats.get("kv_compress_slots", 0)),
        }

    peak_off, tps_off, streams_off, _ = run(False)
    peak_on, tps_on, streams_on, stats = run(True)
    peak_on2, _, streams_on2, _ = run(True)  # determinism across runs
    deterministic = streams_on == streams_on2 and peak_on == peak_on2
    ratio = peak_off / max(peak_on, 1)
    log(f"[longctx] peak pages off {peak_off} vs on {peak_on} "
        f"({ratio:.2f}x); tok/s off {tps_off:.1f} vs on {tps_on:.1f}; "
        f"deterministic={deterministic}")
    return {
        "metric": "long-context tier: peak resident KV pages, "
                  f"{slots} x 800-token prompts + {decode_tokens} decode "
                  "tokens, compression off vs on (window+sink)",
        "value": round(ratio, 2),
        "unit": "x peak KV page reduction (off/on)",
        "vs_baseline": round(ratio, 2),
        "peak_pages_off": peak_off,
        "peak_pages_on": peak_on,
        "tok_per_s_off": round(tps_off, 1),
        "tok_per_s_on": round(tps_on, 1),
        "pages_pruned": int(stats.get("kv_compress_pages_pruned", 0)),
        "compressed_slots": int(stats.get("kv_compress_slots", 0)),
        "streams_deterministic": deterministic,
    }


def bench_flight_dump():
    """Flight-recorder smoke (--flight-dump): serve a greedy wave
    through a tiny 2-replica pool, then verify the full observability
    round trip — per-request timelines in the ring, Chrome trace-event
    JSON rendering/parsing, SLO summary — without a single assertion
    (exit 0 always; the cheap regression probe for the recorder path,
    the --host-tier-smoke pattern)."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.obs import flightrec
    from aios_tpu.serving import ReplicaPool, ServingConfig

    cfg = TINY_TEST.scaled(name="flight-dump", max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    engines = [
        TPUEngine(cfg, params, num_slots=2, max_context=256,
                  cache_dtype=jnp.float32)
        for _ in range(2)
    ]
    pool = ReplicaPool(
        "flight-dump", engines, lambda e: ContinuousBatcher(e),
        ServingConfig(replicas=2),
    )
    try:
        handles = [
            pool.submit(
                Request(prompt_ids=[3 + i, 7, 11], max_tokens=12,
                        temperature=0.0),
                tenant=f"tenant-{i % 2}",
            )
            for i in range(6)
        ]
        for h in handles:
            h.tokens()
    finally:
        pool.shutdown()
    tls = flightrec.RECORDER.recent(model="flight-dump", limit=64)
    trace = flightrec.chrome_trace(
        tls, flightrec.RECORDER.model_events("flight-dump")
    )
    parsed = json.loads(json.dumps(trace))  # the round trip under test
    kinds = sorted({k for t in tls for _, k, _ in t.events})
    states = sorted({t.state for t in tls})
    log(f"[flight-dump] {len(tls)} timelines, "
        f"{len(parsed['traceEvents'])} trace events, kinds={kinds}")
    return {
        "metric": "flight recorder smoke (2-replica pool wave -> "
                  "timeline ring -> Chrome trace JSON)",
        "value": float(len(tls)),
        "unit": "timelines recorded",
        "vs_baseline": 1.0,
        "trace_events": len(parsed["traceEvents"]),
        "event_kinds": kinds,
        "states": states,
        "slo": slo_block("flight-dump"),
    }


def bench_chaos(seed: int = 42) -> int:
    """Seeded chaos storm (--chaos): the SAME fault schedule runs TWICE
    against fresh 2-replica pools — a replica scheduler crash (nth
    trigger) plus probabilistic dispatch delays — under a concurrent
    greedy wave. The verdict (exit code, unlike the assertion-free
    smokes) hard-fails on:

      * a STUCK request (a collector thread still blocked after the
        storm budget — the zero-leak contract);
      * an ABORTED stream (failover must complete every greedy request
        transparently: availability 1.0 is the SLO hard line);
      * NONDETERMINISM — the two runs' token streams, terminal states,
        and nth-mode injected-fault sequences must be identical
        (prob-mode delay faults shape load and are excluded: their hit
        counts ride thread timing by design).

    The storm runs FOUR ARMS, each twice: the plain pool; a DRAFT-MODE
    pool (ISSUE 11 — draft-model speculation attached, speculative
    batchers) so the determinism contract is pinned for the draft
    proposer's fused dispatches and failover-time draft-KV rebuilds; a
    LONGCTX pool (ISSUE 13 — paged KV compression); and a MEGA pool
    (ISSUE 19 — mega_ticks=8 device-resident decode windows) whose
    schedule adds pool.megatick_abort so a seeded device early-exit
    fires mid-window ON TOP of the crash/delay storm — and whose greedy
    streams must still match the plain arm token for token (greedy
    streams are dispatch-shape invariant).

    docs/TESTING.md wires scripts/chaos.sh (this scenario) next to
    scripts/analyze.sh as the pre-merge robustness gate."""
    import threading

    import jax
    import jax.numpy as jnp

    from aios_tpu import faults
    from aios_tpu.engine import model as model_mod, spec as spec_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.serving import ReplicaPool, ServingConfig

    n_req, max_tokens = 8, 32
    schedule = (
        f"seed={seed};pool.scheduler_crash=nth:10;"
        "dispatch.delay=prob:0.15,delay_ms=4"
    )
    cfg = TINY_TEST.scaled(name="chaos", max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    draft_model = spec_mod.DraftModel(cfg, params, quantize=None)

    def run_once(with_draft: bool, longctx: bool = False,
                 mega: bool = False):
        # the mega arm layers a seeded mid-window device abort on top of
        # the shared storm (passed per-arm so the other arms' schedules
        # — and their nth fingerprints — stay byte-identical)
        plan = faults.activate(
            schedule + ";pool.megatick_abort=nth:2,ticks=1" if mega
            else schedule
        )
        # the longctx arm serves a paged pool with window+sink KV
        # compression armed and prompts LONG enough to cross the
        # threshold mid-storm: pruning + masked decode + failover
        # re-prefill must all stay deterministic under the same seeded
        # fault schedule (ISSUE 13 chaos gate)
        eng_kw = {}
        if longctx:
            eng_kw = dict(paged_pool_rows=512, page_size=16,
                          prefix_cache=False, kv_compress_after=96,
                          kv_sink_pages=1, kv_window_pages=4)
        if mega:
            eng_kw = dict(mega_ticks=8)
        chunk = 8 if mega else 2
        engines = [
            TPUEngine(cfg, params, num_slots=2, max_context=256,
                      cache_dtype=jnp.float32,
                      draft=draft_model if with_draft else None,
                      **eng_kw)
            for _ in range(2)
        ]
        pool = ReplicaPool(
            "chaos", engines,
            lambda e: ContinuousBatcher(e, chunk_steps=chunk,
                                        admit_chunk_steps=chunk,
                                        speculative=with_draft,
                                        spec_draft_len=3),
            ServingConfig(replicas=2, failover_retries=3),
        )
        streams: dict = {}
        threads, handles = [], []
        prompt_tail = [7, 11] * 60 if longctx else [7, 11]
        try:
            for i in range(n_req):
                h = pool.submit(
                    Request(prompt_ids=[3 + i] + prompt_tail,
                            max_tokens=max_tokens, temperature=0.0,
                            request_id=f"chaos-{i}"),
                    tenant=f"tenant-{i % 2}",
                )
                t = threading.Thread(
                    target=lambda i=i, h=h: streams.__setitem__(
                        i, h.tokens()
                    ),
                    daemon=True,
                )
                t.start()
                handles.append(h)
                threads.append(t)
            stuck = 0
            for t in threads:
                t.join(timeout=180)
                stuck += int(t.is_alive())
        finally:
            pool.shutdown()
            faults.deactivate()
        return {
            "streams": [streams.get(i) for i in range(n_req)],
            "states": ["aborted" if h.aborted else "done"
                       for h in handles],
            "stuck": stuck,
            "aborted": sum(1 for h in handles if h.aborted),
            "restarts": pool.restarts,
            # the determinism fingerprint: schedule-determined (nth)
            # faults only — prob-mode hit counts ride thread timing
            "nth_faults": [
                (f["point"], f["hit"]) for f in plan.journal()
                if f["mode"] == "nth"
            ],
            "faults_total": len(plan.journal()),
        }

    arms = {}
    for arm, with_draft, longctx, mega in (
        ("plain", False, False, False), ("draft", True, False, False),
        ("longctx", False, True, False), ("mega", False, False, True),
    ):
        a = run_once(with_draft, longctx, mega)
        b = run_once(with_draft, longctx, mega)
        complete = all(
            s is not None and len(s) == max_tokens for s in a["streams"]
        )
        deterministic = (
            a["streams"] == b["streams"]
            and a["states"] == b["states"]
            and a["nth_faults"] == b["nth_faults"]
        )
        arms[arm] = {
            "a": a, "b": b, "complete": complete,
            "deterministic": deterministic,
            "stuck": a["stuck"] + b["stuck"],
            "aborted": a["aborted"] + b["aborted"],
        }
    stuck = sum(v["stuck"] for v in arms.values())
    aborted = sum(v["aborted"] for v in arms.values())
    deterministic = all(v["deterministic"] for v in arms.values())
    complete = all(v["complete"] for v in arms.values())
    # the schedule must have ARMED: an empty fired-fault journal (e.g. a
    # mis-spelled point name surviving a refactor) would otherwise pass
    # the whole gate vacuously — a storm that injected nothing proved
    # nothing
    armed = all(
        v["a"]["faults_total"] > 0 and v["b"]["faults_total"] > 0
        for v in arms.values()
    )
    if not armed:
        log("[chaos] FAULT SCHEDULE NEVER FIRED — the storm ran "
            "fault-free and the gate would have passed vacuously; check "
            "the schedule's point names against faults.POINTS")
    # the draft arm's streams must ALSO match the plain arm's: greedy
    # speculation may change dispatch counts, never tokens — even with
    # a mid-storm crash and a failover-time draft-KV rebuild
    spec_identical = (
        arms["draft"]["a"]["streams"] == arms["plain"]["a"]["streams"]
    )
    # same contract for the megagraph arm: K-tick device windows (with
    # a seeded mid-window abort forcing the early-exit path) may change
    # dispatch counts, never tokens — greedy streams are dispatch-shape
    # invariant, so chunk-8 mega output must equal the chunk-2 plain arm
    mega_identical = (
        arms["mega"]["a"]["streams"] == arms["plain"]["a"]["streams"]
    )
    # the abort must actually have FIRED in the mega arm (nth-mode, so
    # it is part of the determinism fingerprint too)
    mega_abort_fired = any(
        p == "pool.megatick_abort"
        for p, _ in arms["mega"]["a"]["nth_faults"]
    )
    if not mega_abort_fired:
        log("[chaos] pool.megatick_abort never fired in the mega arm — "
            "the early-exit path went unexercised")
    ok = (stuck == 0 and aborted == 0 and complete and deterministic
          and spec_identical and mega_identical and mega_abort_fired
          and armed)
    pa, da = arms["plain"]["a"], arms["draft"]["a"]
    la = arms["longctx"]["a"]
    ma = arms["mega"]["a"]
    log(f"[chaos] seed={seed} restarts plain="
        f"{pa['restarts']}/{arms['plain']['b']['restarts']} draft="
        f"{da['restarts']}/{arms['draft']['b']['restarts']} longctx="
        f"{la['restarts']}/{arms['longctx']['b']['restarts']} mega="
        f"{ma['restarts']}/{arms['mega']['b']['restarts']} "
        f"stuck={stuck} aborted={aborted} deterministic={deterministic} "
        f"draft_streams_match={spec_identical} "
        f"mega_streams_match={mega_identical} "
        f"mega_abort_fired={mega_abort_fired} "
        f"verdict={'PASS' if ok else 'FAIL'}")
    emit({
        "metric": "chaos storm (seeded crash + dispatch delay, "
                  "2-replica pool, plain + draft-speculation + "
                  "longctx-compression + megagraph-decode arms, each "
                  "run twice)",
        "value": 1.0 if ok else 0.0,
        "unit": "verdict (1 = pass)",
        "vs_baseline": 1.0 if ok else 0.0,
        "seed": seed,
        "schedule": schedule,
        "requests": n_req,
        "stuck": stuck,
        "aborted": aborted,
        "availability": round(
            1.0 - aborted / (2.0 * len(arms) * n_req), 4
        ),
        "replica_restarts": {
            arm: [v["a"]["restarts"], v["b"]["restarts"]]
            for arm, v in arms.items()
        },
        "faults_injected": {
            arm: [v["a"]["faults_total"], v["b"]["faults_total"]]
            for arm, v in arms.items()
        },
        "nth_fault_sequence": pa["nth_faults"],
        "nth_fault_sequence_draft": da["nth_faults"],
        "nth_fault_sequence_mega": ma["nth_faults"],
        "deterministic": deterministic,
        "draft_streams_match_plain": spec_identical,
        "mega_streams_match_plain": mega_identical,
        "mega_abort_fired": mega_abort_fired,
        "streams_complete": complete,
        "faults_armed": armed,
    })
    return 0 if ok else 1


def bench_storm(scenario_path: str = "", smoke: bool = False,
                chaos_seed: int | None = None) -> int:
    """Million-user storm gate (--storm): a seeded trace-driven tenant
    mix (aios_tpu/loadgen/) drives the FULL gRPC surface — Infer +
    StreamInfer through a live runtime service over a real replica pool
    — twice, and the deterministic verdict (per-tenant counts, greedy
    stream hashes, PASS against the scenario's declared SLO targets)
    must be identical across the runs. Composes with --chaos: the same
    storm runs under a seeded fault schedule (replica crash + dispatch
    delays) and transparent failover must still complete every
    deterministic stream.

    Full mode (not --smoke) additionally proves the autoscaling closed
    loop (serving/autoscale.py) on direct pools:

      * induced overload -> the controller scales replicas to the
        ceiling, then walks the degrade ladder (spec off -> jump off ->
        shed best-effort) — with greedy token streams pinned identical
        to an untouched control pool across every ladder transition;
      * a healthy steady-state run leaves the controller provably
        quiescent (zero actions).
    """
    import contextlib
    import os as _os

    from aios_tpu import faults
    from aios_tpu.loadgen import (
        StormDriver, build_report, build_trace, load_scenario,
    )
    from aios_tpu.obs import slo
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    from aios_tpu.loadgen.scenario import (
        default_scenario_path, time_scale_env,
    )

    here = _os.path.dirname(_os.path.abspath(__file__))
    if not scenario_path:
        scenario_path = default_scenario_path(here, smoke)
    sc = load_scenario(scenario_path)
    trace = build_trace(sc)
    time_scale = time_scale_env()
    schedule = (
        f"seed={chaos_seed};pool.scheduler_crash=nth:10;"
        "dispatch.delay=prob:0.1,delay_ms=3"
        if chaos_seed is not None else ""
    )

    @contextlib.contextmanager
    def _env(**kv):
        old = {k: _os.environ.get(k) for k in kv}
        _os.environ.update({k: str(v) for k, v in kv.items()})
        try:
            yield
        finally:
            for k, v in old.items():
                if v is None:
                    _os.environ.pop(k, None)
                else:
                    _os.environ[k] = v

    def run_once(tag: str) -> dict:
        # fresh windows per run: the SLO engine and recorder are
        # process-global and the verdict reads the live /debug/slo
        slo.ENGINE.clear()
        plan = faults.activate(schedule) if schedule else None
        server = service = manager = None
        env = dict(
            AIOS_TPU_REPLICAS=sc.replicas,
            AIOS_TPU_PAGED_KV="auto",
            AIOS_TPU_MAX_QUEUE=sc.max_queue,
            AIOS_TPU_TENANT_TOKENS_PER_SEC=sc.tenant_tokens_per_sec,
            AIOS_TPU_TENANT_BURST_TOKENS=sc.tenant_burst_tokens,
        )
        try:
            with _env(**env):
                manager = ModelManager(
                    num_slots=sc.num_slots, warm_compile=False
                )
                manager.load_model(
                    sc.model, "synthetic://tiny-test",
                    context_length=sc.context,
                )
                server, service, port = serve(
                    address="127.0.0.1:0", manager=manager, block=False,
                    metrics_port=0,
                )
            driver = StormDriver(
                f"127.0.0.1:{port}", sc.model,
                metrics_port=service.metrics_port,
                time_scale=time_scale,
            )
            try:
                # prologue: prime compiles + a clean observed-rate
                # window, so deadline feasibility judges run a (cold)
                # and run b (warm) identically
                driver.warmup()
                outcomes = driver.run(trace)
                surface = driver.slo_surface()
            finally:
                driver.close()
            report = build_report(sc, trace, outcomes, surface)
            report["faults_injected"] = (
                len(plan.journal()) if plan is not None else None
            )
            pool = manager.models[sc.model].pool
            report["measured"]["replica_restarts"] = pool.restarts
            return report
        finally:
            try:
                if server is not None:
                    server.stop(grace=None)
                if service is not None \
                        and service.metrics_server is not None:
                    service.metrics_server.shutdown()
                if manager is not None:
                    manager.unload_model(sc.model)
            except Exception as e:  # noqa: BLE001 - teardown is best-effort
                log(f"[storm] teardown issue ({tag}): {e!r}")
            if plan is not None:
                faults.deactivate()

    a = run_once("a")
    b = run_once("b")
    deterministic = a["verdict"] == b["verdict"]
    verdict_diff = None
    if not deterministic:
        # field-level diff so a FAIL names the diverging keys instead of
        # dumping two whole verdicts at the operator
        verdict_diff = {}
        for k in set(a["verdict"]) | set(b["verdict"]):
            va, vb = a["verdict"].get(k), b["verdict"].get(k)
            if va != vb:
                verdict_diff[k] = {"a": va, "b": vb}
        log(f"[storm] NONDETERMINISTIC verdict keys: "
            f"{sorted(verdict_diff)}")
    chaos_armed = (
        chaos_seed is None
        or ((a["faults_injected"] or 0) > 0
            and (b["faults_injected"] or 0) > 0)
    )
    ok = a["pass"] and b["pass"] and deterministic and chaos_armed
    auto = None
    if not smoke:
        auto = _storm_autoscale_arms()
        ok = ok and auto["ok"]
    log(f"[storm] scenario={sc.name} seed={sc.seed} calls={len(trace)} "
        f"pass_a={a['pass']} pass_b={b['pass']} "
        f"deterministic={deterministic} chaos_armed={chaos_armed} "
        + (f"autoscale_ok={auto['ok']} " if auto is not None else "")
        + f"verdict={'PASS' if ok else 'FAIL'}")
    emit({
        "metric": "storm gate (seeded trace-driven tenant mix over the "
                  "live gRPC surface, run twice"
                  + (", under seeded faults" if chaos_seed is not None
                     else "")
                  + ("" if smoke else "; + autoscale closed-loop arms")
                  + ")",
        "value": 1.0 if ok else 0.0,
        "unit": "verdict (1 = pass)",
        "vs_baseline": 1.0 if ok else 0.0,
        "scenario": sc.name,
        "scenario_path": _os.path.relpath(scenario_path, here),
        "seed": sc.seed,
        "calls": len(trace),
        "deterministic": deterministic,
        "chaos": chaos_seed,
        "chaos_armed": chaos_armed,
        "faults_injected": [a["faults_injected"], b["faults_injected"]],
        "verdict_a": a["verdict"],
        "verdict_diff": verdict_diff,
        "measured_a": a["measured"],
        "measured_b": b["measured"],
        "autoscale": auto,
    })
    return 0 if ok else 1


def _storm_autoscale_arms() -> dict:
    """The closed-loop halves of the storm gate (full --storm mode):
    induced overload must scale up then degrade (streams pinned
    identical to a control pool across every ladder transition), and a
    healthy run must leave the controller quiescent."""
    import threading as _threading
    import time as _time

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.obs import flightrec
    from aios_tpu.obs.slo import SLOConfig, SLOEngine
    from aios_tpu.serving import (
        AutoscaleConfig, AutoscaleController, ReplicaPool, ServingConfig,
    )

    cfg = TINY_TEST.scaled(name="storm-auto", max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)

    def make_engine():
        return TPUEngine(cfg, params, num_slots=4, max_context=256,
                         cache_dtype=jnp.float32, track_history=True)

    def make_pool(name):
        return ReplicaPool(
            name, [make_engine()],
            lambda e: ContinuousBatcher(e, chunk_steps=2,
                                        admit_chunk_steps=2,
                                        speculative=True),
            ServingConfig(replicas=1),
        )

    def wave(pool, n=6, max_tokens=48):
        handles = [
            pool.submit(Request(prompt_ids=[3 + i, 7, 11, 13], priority=1,
                                max_tokens=max_tokens, temperature=0.0,
                                request_id=f"auto-{i}"))
            for i in range(n)
        ]
        return [h.tokens() for h in handles]

    # control pool: the token-identity reference, untouched by any
    # controller
    control = make_pool("storm-auto")
    control_streams = wave(control)
    control.shutdown()

    # overload arm: tight targets make real latencies burn hard; the
    # controller must scale to the ceiling then walk the whole ladder
    # WHILE a greedy wave is in flight (transitions land mid-stream)
    tight = SLOEngine(SLOConfig(ttft_ms=0.01, tpot_ms=0.01, target=0.99,
                                window_secs=600, min_samples=4))
    pool = make_pool("storm-auto")
    ctl = AutoscaleController(
        pool,
        AutoscaleConfig(max_replicas=2, hold_ticks=1, cooldown_secs=0.0,
                        interval_secs=0.02),
        engine_factory=make_engine, slo_engine=tight,
    )
    seed_streams = wave(pool, n=4, max_tokens=8)  # latency evidence
    for tl in flightrec.RECORDER.recent(model="storm-auto", limit=64):
        tight.observe(tl)
    ticker_stop = _threading.Event()

    def ticker():
        while not ticker_stop.wait(0.02):
            ctl.tick()

    th = _threading.Thread(target=ticker, daemon=True)
    th.start()
    try:
        overload_streams = wave(pool)
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline and (
            len(pool.replicas) < 2 or pool.degrade_level < 3
        ):
            _time.sleep(0.05)
    finally:
        ticker_stop.set()
        th.join(timeout=5)
    actions = ctl.actions()
    scaled = any(a["action"] == "scale_up" for a in actions)
    rungs = [a.get("rung") for a in actions if a["action"] == "degrade"]
    ladder_complete = rungs[:3] == ["spec_off", "jump_off",
                                   "shed_best_effort"]
    # streams pinned across the transitions the ticker made mid-wave
    post_streams = wave(pool)  # fully degraded: still token-identical
    streams_ok = (
        overload_streams == control_streams
        and post_streams == control_streams
    )
    pool.shutdown()

    # quiescent arm: the SAME real traffic against generous targets —
    # the controller must take zero actions
    calm = SLOEngine(SLOConfig(ttft_ms=60_000, tpot_ms=60_000,
                               target=0.9, window_secs=600,
                               min_samples=4))
    pool2 = make_pool("storm-auto")
    ctl2 = AutoscaleController(
        pool2,
        AutoscaleConfig(max_replicas=2, hold_ticks=1, cooldown_secs=0.0),
        engine_factory=make_engine, slo_engine=calm,
    )
    wave(pool2, n=4, max_tokens=8)
    for tl in flightrec.RECORDER.recent(model="storm-auto", limit=64):
        calm.observe(tl)
    for _ in range(10):
        ctl2.tick()
    quiescent = len(ctl2.actions()) == 0
    pool2.shutdown()

    ok = scaled and ladder_complete and streams_ok and quiescent
    return {
        "ok": ok,
        "scale_up": scaled,
        "ladder": rungs,
        "ladder_complete": ladder_complete,
        "streams_identical_across_transitions": streams_ok,
        "quiescent_zero_actions": quiescent,
        "actions": [
            {k: a.get(k) for k in ("action", "cause", "level", "replicas")}
            for a in actions
        ],
    }


def bench_dispatch():
    """Pipelined-decode A/B through the production continuous batcher
    (AIOS_TPU_DECODE_PIPELINE): 8 concurrent greedy requests per wave,
    1-step dispatches — the dispatch-bound regime the pipeline targets
    (every decode chunk pays the full Python→dispatch→host-sync round
    trip) — with identical token streams asserted across arms.

    Both arms stay resident and waves ALTERNATE off/on; the headline is
    the MEDIAN of per-pair tok/s ratios. This container's CPU
    availability swings ~2x on a seconds timescale (shared cores +
    cgroup throttling), so a single long A then B measurement mostly
    measures the weather; tight pairing + median cancels the bursts.
    Tiny geometry on purpose: the quantity under test is the
    host<->device dispatch seam, not model compute, so CPU numbers are
    meaningful and this is the one decode-throughput probe a chipless
    container can produce real deltas for."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(
        name="micro-dispatch", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=256, max_context=512,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    chunk, max_tokens, slots, pairs = 16, 256, 8, 9

    def wave(batcher):
        handles = [
            batcher.submit(Request(prompt_ids=[3 + i, 17, 91],
                                   max_tokens=max_tokens, temperature=0.0))
            for i in range(slots)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        return sum(len(t) for t in out) / (time.time() - t0), out

    arms = []  # (engine, batcher) for pipeline off, on
    try:
        for pipeline in (False, True):
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                            cache_dtype=jnp.float32)
            eng.warmup(step_sizes=(2, chunk), prefill_chunk=0)
            batcher = ContinuousBatcher(
                eng, chunk_steps=chunk, admit_chunk_steps=2,
                pipeline=pipeline,
            )
            wave(batcher)  # steady state before any measured pair
            arms.append((eng, batcher))
        ratios, identical = [], True
        tps = {False: [], True: []}
        for pair in range(pairs):
            # alternate which arm goes first so slow drifts in container
            # CPU availability cancel within the pair set
            order = (0, 1) if pair % 2 == 0 else (1, 0)
            got = {}
            for idx in order:
                got[idx] = wave(arms[idx][1])
            identical = identical and got[0][1] == got[1][1]
            ratios.append(got[1][0] / max(got[0][0], 1e-9))
            tps[False].append(got[0][0])
            tps[True].append(got[1][0])
        gaps = {
            p: b.host_gap_seconds / max(b.decode_dispatches, 1) * 1e3
            for p, (_, b) in zip((False, True), arms)
        }
        flushes = arms[1][1].flushes
    finally:
        for eng, batcher in arms:
            batcher.shutdown()
            eng.close()
    ratios_sorted = sorted(ratios)
    speedup = statistics.median(ratios)
    q25 = ratios_sorted[len(ratios) // 4]
    q75 = ratios_sorted[-1 - len(ratios) // 4]
    log(f"[dispatch] pipeline off med {statistics.median(tps[False]):.0f} "
        f"tok/s (gap {gaps[False]:.2f} ms) -> on med "
        f"{statistics.median(tps[True]):.0f} tok/s (gap {gaps[True]:.2f} "
        f"ms); per-pair ratios {['%.2f' % r for r in ratios]}, median "
        f"{speedup:.2f}x (IQR {q25:.2f}-{q75:.2f}), identical={identical}")
    return {
        "metric": "pipelined decode loop A/B, continuous batcher "
                  f"(batch {slots}, {chunk}-step dispatches, {pairs} "
                  "order-alternated paired waves, micro geometry)",
        "value": round(speedup, 3),
        "unit": "x tok/s (pipeline on vs off, median of paired waves)",
        "vs_baseline": round(speedup, 3),
        "tps_pipeline_off": round(statistics.median(tps[False]), 1),
        "tps_pipeline_on": round(statistics.median(tps[True]), 1),
        "pair_ratios": [round(r, 3) for r in ratios],
        "ratio_iqr": [round(q25, 3), round(q75, 3)],
        "host_gap_ms_off": round(gaps[False], 3),
        "host_gap_ms_on": round(gaps[True], 3),
        "pipeline_flushes": int(flushes),
        "tokens_identical": bool(identical),
        "slo": slo_block("micro-dispatch"),
        # this container: 2 shared cores, XLA's compute threads saturate
        # both, and the scheduler's host phase is ~2 ms against 20+ ms
        # dispatches — the structural ceiling for overlap here is ~10%.
        # The mechanism (identical streams, dispatch worker overlap) is
        # what this probe regression-guards; absolute gains need the TPU
        # (device compute does not contend with the host there).
        "cpu_cores": os.cpu_count(),
    }


def bench_mega():
    """Multi-tick decode megagraph A/B (AIOS_TPU_MEGA_TICKS, ISSUE 19):
    8 concurrent greedy requests per wave through the production
    batcher, K=1 single-tick dispatches vs K=8 device-resident windows,
    identical token streams asserted across arms.

    Same pairing discipline as bench_dispatch (both arms resident, waves
    order-alternated, median of per-pair tok/s ratios) because this
    container's CPU availability swings ~2x on a seconds timescale. The
    DETERMINISTIC headline is the decode-dispatch reduction: the K=1 arm
    pays one host round-trip per tick while the K=8 arm retires up to 8
    ticks per dispatch — a count, not a timing, so it holds on any
    backend. Wall-clock on CPU understates the win (XLA executes inline
    in the dispatching thread, so the readback it amortizes is cheap
    here); the host gap per dispatch is reported for both arms."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(
        name="micro-mega", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=256, max_context=512,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    K, max_tokens, slots, pairs = 8, 256, 8, 9

    def wave(batcher):
        eng = batcher.engine
        d0 = eng.mega_dispatches if eng.mega_ticks else eng.decode_steps
        handles = [
            batcher.submit(Request(prompt_ids=[3 + i, 17, 91],
                                   max_tokens=max_tokens, temperature=0.0))
            for i in range(slots)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        dt = time.time() - t0
        d1 = eng.mega_dispatches if eng.mega_ticks else eng.decode_steps
        return sum(len(t) for t in out) / dt, out, d1 - d0

    arms = []  # (engine, batcher) for K=1, K=8
    try:
        for mega in (0, K):
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                            cache_dtype=jnp.float32, mega_ticks=mega)
            # the K=1 arm dispatches 1-tick scan graphs (chunk_steps=1:
            # one host round-trip per token — the loop mega replaces);
            # the K=8 arm dispatches K-tick device windows
            eng.warmup(step_sizes=(1, K) if not mega else (K,),
                       prefill_chunk=0)
            batcher = ContinuousBatcher(
                eng, chunk_steps=1 if not mega else K,
                admit_chunk_steps=1 if not mega else K, pipeline=True,
            )
            wave(batcher)  # steady state before any measured pair
            arms.append((eng, batcher))
        ratios, identical = [], True
        tps = {0: [], 1: []}
        disp = {0: 0, 1: 0}
        for pair in range(pairs):
            order = (0, 1) if pair % 2 == 0 else (1, 0)
            got = {}
            for idx in order:
                got[idx] = wave(arms[idx][1])
            identical = identical and got[0][1] == got[1][1]
            ratios.append(got[1][0] / max(got[0][0], 1e-9))
            for idx in (0, 1):
                tps[idx].append(got[idx][0])
                disp[idx] += got[idx][2]
        gaps = {
            idx: b.host_gap_seconds / max(b.decode_dispatches, 1) * 1e3
            for idx, (_, b) in enumerate(arms)
        }
        mega_ticks_run = arms[1][0].mega_tick_total
    finally:
        for eng, batcher in arms:
            batcher.shutdown()
            eng.close()
    # deterministic headline: decode dispatches the K=8 windows replaced
    # (greedy wave, fixed budgets — identical on every backend)
    reduction = disp[0] / max(disp[1], 1)
    ratios_sorted = sorted(ratios)
    speedup = statistics.median(ratios)
    q25 = ratios_sorted[len(ratios) // 4]
    q75 = ratios_sorted[-1 - len(ratios) // 4]
    log(f"[mega] K=1 med {statistics.median(tps[0]):.0f} tok/s "
        f"(gap {gaps[0]:.2f} ms, {disp[0]} dispatches) -> K={K} med "
        f"{statistics.median(tps[1]):.0f} tok/s (gap {gaps[1]:.2f} ms, "
        f"{disp[1]} dispatches, {mega_ticks_run} ticks); dispatch "
        f"reduction {reduction:.1f}x, wall median {speedup:.2f}x "
        f"(IQR {q25:.2f}-{q75:.2f}), identical={identical}")
    return {
        "metric": "multi-tick decode megagraph A/B, continuous batcher "
                  f"(batch {slots}, K=1 vs K={K}, {pairs} "
                  "order-alternated paired waves, micro geometry)",
        "value": round(reduction, 3),
        "unit": f"x decode-dispatch reduction (K={K} vs K=1, "
                "greedy wave)",
        "vs_baseline": round(reduction, 3),
        "wallclock_ratio_median": round(speedup, 3),
        "pair_ratios": [round(r, 3) for r in ratios],
        "ratio_iqr": [round(q25, 3), round(q75, 3)],
        "tps_k1": round(statistics.median(tps[0]), 1),
        "tps_k8": round(statistics.median(tps[1]), 1),
        "dispatches_k1": int(disp[0]),
        "dispatches_k8": int(disp[1]),
        "mega_ticks_run": int(mega_ticks_run),
        "host_gap_ms_k1": round(gaps[0], 3),
        "host_gap_ms_k8": round(gaps[1], 3),
        "tokens_identical": bool(identical),
        "slo": slo_block("micro-mega"),
        # CPU-bench caveat (docs/ENGINE_PERF.md): XLA executes inline in
        # the dispatching thread here, so the amortized host round-trip
        # is a small slice of each dispatch — the dispatch-count
        # reduction is the backend-independent signal; the wall-clock
        # delta needs the TPU.
        "cpu_cores": os.cpu_count(),
    }


def bench_tsdb():
    """Tsdb ON/OFF overhead A/B (AIOS_TPU_TSDB, ISSUE 20): 8 concurrent
    greedy requests per wave through the production pipelined batcher,
    with ONE shared engine+batcher across both arms — the quantity under
    test is the process-level sampler, not engine config. The OFF arm is
    the unarmed module (TSDB None + no sampler thread = the zero-cost
    contract); the ON arm runs the real background sampler over the
    global registry at 20x the default cadence, so the measured overhead
    upper-bounds production's.

    Same pairing discipline as bench_dispatch (waves order-alternated,
    median of per-pair tok/s ratios) because this container's CPU
    availability swings ~2x on a seconds timescale. The sampler is
    read-only on the serving path by construction, so the gate is
    threefold: token streams identical across arms, ZERO post-warmup
    compile events in either arm (a sampler that perturbed dispatch
    shapes would recompile), and a median ratio ~1.0."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.obs import tsdb as tsdb_mod
    from aios_tpu.obs.tsdb import Tsdb, TsdbConfig

    cfg = TINY_TEST.scaled(
        name="micro-tsdb", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=256, max_context=512,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    chunk, max_tokens, slots, pairs = 16, 256, 8, 9

    ring_cfg = TsdbConfig()
    ring_cfg.step_secs = 0.05  # 20x the default sampling rate
    ring = Tsdb(cfg=ring_cfg)  # over the global registry, like production

    def wave(batcher):
        handles = [
            batcher.submit(Request(prompt_ids=[3 + i, 17, 91],
                                   max_tokens=max_tokens, temperature=0.0))
            for i in range(slots)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        return sum(len(t) for t in out) / (time.time() - t0), out

    prev = tsdb_mod.install(None)
    eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                    cache_dtype=jnp.float32)
    batcher = None
    try:
        eng.warmup(step_sizes=(2, chunk), prefill_chunk=0)
        batcher = ContinuousBatcher(eng, chunk_steps=chunk,
                                    admit_chunk_steps=2, pipeline=True)
        wave(batcher)  # steady state before any measured pair
        compiles_warm = eng.compile_events
        ratios, identical = [], True
        tps = {False: [], True: []}
        for pair in range(pairs):
            order = (False, True) if pair % 2 == 0 else (True, False)
            got = {}
            for armed in order:
                if armed:
                    tsdb_mod.install(ring)
                    ring.start()
                else:
                    ring.stop()
                    tsdb_mod.install(None)
                got[armed] = wave(batcher)
            identical = identical and got[False][1] == got[True][1]
            ratios.append(got[True][0] / max(got[False][0], 1e-9))
            for armed in (False, True):
                tps[armed].append(got[armed][0])
        ring.stop()
        tsdb_mod.install(None)
        compile_delta = eng.compile_events - compiles_warm
        stats = ring.stats()
    finally:
        ring.stop()
        tsdb_mod.install(prev)
        if batcher is not None:
            batcher.shutdown()
        eng.close()
    ratios_sorted = sorted(ratios)
    ratio = statistics.median(ratios)
    q25 = ratios_sorted[len(ratios) // 4]
    q75 = ratios_sorted[-1 - len(ratios) // 4]
    log(f"[tsdb] off med {statistics.median(tps[False]):.0f} tok/s -> on "
        f"med {statistics.median(tps[True]):.0f} tok/s; per-pair ratios "
        f"{['%.2f' % r for r in ratios]}, median {ratio:.2f}x "
        f"(IQR {q25:.2f}-{q75:.2f}); {stats['passes']} sample passes over "
        f"{stats['series']} series; identical={identical}, "
        f"post-warmup compiles={compile_delta}")
    return {
        "metric": "tsdb sampler ON/OFF A/B, continuous batcher "
                  f"(batch {slots}, {chunk}-step dispatches, {pairs} "
                  "order-alternated paired waves, sampler at "
                  f"{ring_cfg.step_secs:g}s cadence, micro geometry)",
        "value": round(ratio, 3),
        "unit": "x tok/s (tsdb on vs off, median of paired waves)",
        "vs_baseline": round(ratio, 3),
        "tps_tsdb_off": round(statistics.median(tps[False]), 1),
        "tps_tsdb_on": round(statistics.median(tps[True]), 1),
        "pair_ratios": [round(r, 3) for r in ratios],
        "ratio_iqr": [round(q25, 3), round(q75, 3)],
        "sample_passes": int(stats["passes"]),
        "series_sampled": int(stats["series"]),
        "dropped_series": int(stats["dropped_series"]),
        "tokens_identical": bool(identical),
        "post_warmup_compiles": int(compile_delta),
        "slo": slo_block("micro-tsdb"),
        "cpu_cores": os.cpu_count(),
    }


def bench_devprof():
    """Device-time attribution (obs/devprof.py): emit the per-graph cost
    ledger as JSON — {dispatches, est FLOPs/bytes, sampled
    device-seconds, MFU/HBM util where the roofline is known} per graph
    kind — plus a devprof ON-vs-OFF overhead A/B through the pipelined
    continuous batcher.

    Two phases on purpose: the LEDGER phase runs sequential
    single-request greedy waves so its per-graph dispatch counts are
    deterministic — that snapshot is what scripts/benchdiff.py diffs
    against a committed baseline (the per-graph regression sentinel) —
    and only then do order-alternated concurrent pairs measure the
    sampling overhead (median of paired tok/s ratios, the bench_dispatch
    methodology; sampled at 4x the default rate, so the measured
    overhead upper-bounds production's)."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(
        name="micro-devprof", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=256, max_context=512,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    chunk, slots, pairs = 16, 8, 7

    def build(dev_on):
        saved = {
            k: os.environ.get(k)
            for k in ("AIOS_TPU_DEVPROF", "AIOS_TPU_DEVPROF_SAMPLE")
        }
        os.environ["AIOS_TPU_DEVPROF"] = "1" if dev_on else "0"
        if dev_on:
            os.environ["AIOS_TPU_DEVPROF_SAMPLE"] = "8"
        try:
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                            cache_dtype=jnp.float32)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        eng.warmup(step_sizes=(2, chunk), prefill_chunk=0)
        return eng, ContinuousBatcher(
            eng, chunk_steps=chunk, admit_chunk_steps=2, pipeline=True,
        )

    def wave(batcher, n=slots, max_tokens=128):
        handles = [
            batcher.submit(Request(prompt_ids=[3 + i, 17, 91],
                                   max_tokens=max_tokens, temperature=0.0))
            for i in range(n)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        return sum(len(t) for t in out) / (time.time() - t0), out

    arms = []
    try:
        for dev_on in (False, True):
            arms.append(build(dev_on))
        eng_on, b_on = arms[1]
        # phase 1 — deterministic ledger: sequential single-request
        # waves (no admission-timing variance in the chunk-size choice)
        for i in range(6):
            b_on.submit(Request(prompt_ids=[5 + i, 9, 42], max_tokens=32,
                                temperature=0.0)).tokens()
        ledger = eng_on.devprof_snapshot()
        # phase 2 — overhead A/B: both arms resident, waves alternate
        wave(arms[0][1])
        wave(b_on)
        ratios, identical = [], True
        for pair in range(pairs):
            order = (0, 1) if pair % 2 == 0 else (1, 0)
            got = {}
            for idx in order:
                got[idx] = wave(arms[idx][1])
            identical = identical and got[0][1] == got[1][1]
            ratios.append(got[1][0] / max(got[0][0], 1e-9))
    finally:
        for eng, batcher in arms:
            batcher.shutdown()
            eng.close()
    ratios_sorted = sorted(ratios)
    ratio = statistics.median(ratios)
    q25 = ratios_sorted[len(ratios) // 4]
    q75 = ratios_sorted[-1 - len(ratios) // 4]
    graphs = (ledger or {}).get("graphs", {})
    total_dev_s = sum(
        g.get("device_seconds", 0.0) for g in graphs.values()
    )
    log(f"[devprof] ledger graphs {sorted(graphs)} total est device "
        f"{total_dev_s:.4f}s; on/off ratio median {ratio:.3f} "
        f"(IQR {q25:.3f}-{q75:.3f}), identical={identical}")
    return {
        "metric": "devprof per-graph device-time ledger + sampling "
                  f"overhead A/B (micro geometry, {pairs} "
                  "order-alternated paired waves)",
        "value": round(ratio, 3),
        "unit": "x tok/s (devprof on vs off, median of paired waves; "
                "1.0 = free)",
        "vs_baseline": round(ratio, 3),
        "devprof": ledger,
        "device_seconds_total": round(total_dev_s, 4),
        "pair_ratios": [round(r, 3) for r in ratios],
        "ratio_iqr": [round(q25, 3), round(q75, 3)],
        "tokens_identical": bool(identical),
        # this container's CPU availability swings ~2x on a seconds
        # timescale; the median of tightly-alternated pairs is the
        # defensible statistic, the IQR is the honesty bar
        "cpu_cores": os.cpu_count(),
    }


def bench_structured():
    """Jump-ahead A/B on a schema-forced JSON workload through the
    production continuous batcher (AIOS_TPU_JUMP_AHEAD): waves of greedy
    structured-output requests, jump-ahead off vs on, with identical
    token streams asserted across arms.

    The HEADLINE is the engine dispatch-count reduction — forced-run
    chains (schema key literals, '":', '",', closers) collapse from one
    masked dispatch per token into one multi-token verify dispatch —
    which is exact and deterministic on any backend (decode_steps
    counters, not wall-clock). Wall-clock rides along with the
    bench_dispatch recipe (order-alternated tightly-paired waves,
    median-of-ratios) because this container's CPU availability swings
    ~2x on a seconds timescale; on TPU every saved dispatch is a saved
    weight-streaming pass, so the dispatch ratio is the durable number."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer

    cfg = TINY_TEST.scaled(
        name="micro-structured", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=320, max_context=512,  # ByteTokenizer ids reach 257
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {
            "tool": {
                "type": "string",
                "enum": ["read_file", "write_file", "list_dir",
                         "run_command"],
            },
            "target": {"type": "string", "enum": ["workspace", "scratch"]},
            "recursive": {"type": "boolean"},
            "note": {"type": "string"},
        },
        "required": ["tool", "target", "recursive", "note"],
    }
    slots, max_tokens, pairs = 4, 96, 9

    def wave(batcher):
        eng = batcher.engine
        steps0 = eng.decode_steps
        handles = [
            batcher.submit(Request(
                prompt_ids=tok.encode(f"emit json {i}"),
                max_tokens=max_tokens, temperature=0.0,
                stop_ids=(tok.eos_id,), json_schema=schema,
            ))
            for i in range(slots)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        dt = time.time() - t0
        toks = sum(len(t) for t in out)
        return toks / dt, out, eng.decode_steps - steps0, toks

    arms = []  # (engine, batcher) for jump off, on
    try:
        for jump in (False, True):
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                            cache_dtype=jnp.float32)
            eng.warmup(step_sizes=(2, 16), prefill_chunk=0,
                       masked_step=True)
            batcher = ContinuousBatcher(
                eng, chunk_steps=16, admit_chunk_steps=2, tokenizer=tok,
                jump_ahead=jump,
            )
            wave(batcher)  # steady state before any measured pair
            arms.append((eng, batcher))
        ratios, identical = [], True
        dispatches = {False: 0, True: 0}
        tokens_total = {False: 0, True: 0}
        tps = {False: [], True: []}
        for pair in range(pairs):
            order = (0, 1) if pair % 2 == 0 else (1, 0)
            got = {}
            for idx in order:
                got[idx] = wave(arms[idx][1])
            identical = identical and got[0][1] == got[1][1]
            ratios.append(got[1][0] / max(got[0][0], 1e-9))
            for idx, jump in ((0, False), (1, True)):
                tps[jump].append(got[idx][0])
                dispatches[jump] += got[idx][2]
                tokens_total[jump] += got[idx][3]
        jump_stats = arms[1][0].stats()
    finally:
        for eng, batcher in arms:
            batcher.shutdown()
            eng.close()
    reduction = dispatches[False] / max(dispatches[True], 1)
    wall = statistics.median(ratios)
    log(f"[structured] schema-forced dispatches {dispatches[False]} -> "
        f"{dispatches[True]} ({reduction:.2f}x fewer; "
        f"{jump_stats.get('jump_tokens', 0)} tokens via "
        f"{jump_stats.get('jump_dispatches', 0)} jump dispatches); "
        f"wall-clock median {wall:.2f}x, identical={identical}")
    return {
        "metric": "jump-ahead constrained decode A/B, schema-forced JSON "
                  f"(batch {slots}, {pairs} order-alternated paired "
                  "waves, micro geometry)",
        # the deterministic headline: engine dispatches per identical
        # token stream, jump-ahead off vs on
        "value": round(reduction, 3),
        "unit": "x fewer engine dispatches (jump-ahead on vs off)",
        "vs_baseline": round(reduction, 3),
        "dispatches_off": int(dispatches[False]),
        "dispatches_on": int(dispatches[True]),
        "tokens_per_wave_set": int(tokens_total[True]),
        "jump_dispatches": int(jump_stats.get("jump_dispatches", 0)),
        "jump_tokens": int(jump_stats.get("jump_tokens", 0)),
        "tps_jump_off": round(statistics.median(tps[False]), 1),
        "tps_jump_on": round(statistics.median(tps[True]), 1),
        "wall_ratio_median": round(wall, 3),
        "pair_ratios": [round(r, 3) for r in ratios],
        "tokens_identical": bool(identical),
        "cpu_cores": os.cpu_count(),
    }


def bench_draft():
    """Draft-model speculation A/B on a CHAT-SHAPED (non-repetitive)
    prompt set through the production continuous batcher: waves of
    greedy requests, draft speculation off (plain decode) vs on
    (AIOS_TPU_DRAFT_MODEL-style pairing), identical token streams
    asserted across arms.

    The HEADLINE is the serving-model dispatch-count reduction — each
    verify round streams the serving weights once and emits
    1 + accepted-drafts tokens, so decode_steps(off)/decode_steps(on)
    IS the weight-bandwidth win — which is exact and deterministic on
    any backend, reported beside the measured acceptance ratio.
    Wall-clock rides along per the docs/ENGINE_PERF.md CPU-noise recipe
    (order-alternated tightly-paired waves, median-of-ratios + IQR).

    The synthetic draft shares the serving model's weights (acceptance
    ~1.0): random-weight models have near-flat logits, so a quantized
    or smaller random draft measures quantization tie-breaking, not the
    machinery. This probe therefore regression-guards the MECHANISM and
    reports the perfect-draft upper bound; the real int4-TinyLlama
    acceptance (and the absolute tok/s) need the TPU rerun with real
    weights — the standing ENGINE_PERF caveat. The n-gram proposer wins
    nothing here by construction (no prompt repetition), which is
    exactly the traffic the draft model exists for."""
    import statistics

    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod, spec as spec_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer

    cfg = TINY_TEST.scaled(
        name="micro-draft", num_layers=1, hidden_size=32,
        intermediate_size=64, num_heads=2, num_kv_heads=1, head_dim=16,
        vocab_size=320, max_context=512,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    draft = spec_mod.DraftModel(cfg, params, quantize=None)
    tok = ByteTokenizer()
    slots, max_tokens, pairs, draft_len = 4, 96, 9, 7
    chat = [
        "hey, can you summarize what happened in the standup today?",
        "what's the fastest way to get from the airport downtown?",
        "draft a short apology email for missing the deadline",
        "explain why the sky looks red at sunset, briefly",
    ]

    def wave(batcher):
        eng = batcher.engine
        steps0 = eng.decode_steps
        handles = [
            batcher.submit(Request(
                prompt_ids=tok.encode(chat[i % len(chat)]),
                max_tokens=max_tokens, temperature=0.0,
            ))
            for i in range(slots)
        ]
        t0 = time.time()
        out = [h.tokens() for h in handles]
        dt = time.time() - t0
        toks = sum(len(t) for t in out)
        return toks / dt, out, eng.decode_steps - steps0, toks

    arms = []  # (engine, batcher) for draft off, on
    try:
        for use_draft in (False, True):
            eng = TPUEngine(cfg, params, num_slots=slots, max_context=512,
                            cache_dtype=jnp.float32,
                            draft=draft if use_draft else None)
            eng.warmup(step_sizes=(2, 16), prefill_chunk=0,
                       spec_sizes=(2, 16) if use_draft else (),
                       spec_draft_len=draft_len)
            batcher = ContinuousBatcher(
                eng, chunk_steps=16, admit_chunk_steps=2,
                speculative=use_draft, spec_draft_len=draft_len,
            )
            wave(batcher)  # steady state before any measured pair
            arms.append((eng, batcher))
        ratios, identical = [], True
        dispatches = {False: 0, True: 0}
        tokens_total = {False: 0, True: 0}
        tps = {False: [], True: []}
        for pair in range(pairs):
            order = (0, 1) if pair % 2 == 0 else (1, 0)
            got = {}
            for idx in order:
                got[idx] = wave(arms[idx][1])
            identical = identical and got[0][1] == got[1][1]
            ratios.append(got[1][0] / max(got[0][0], 1e-9))
            for idx, use_draft in ((0, False), (1, True)):
                tps[use_draft].append(got[idx][0])
                dispatches[use_draft] += got[idx][2]
                tokens_total[use_draft] += got[idx][3]
        draft_stats = arms[1][0].stats()
    finally:
        for eng, batcher in arms:
            batcher.shutdown()
            eng.close()
    reduction = dispatches[False] / max(dispatches[True], 1)
    ratios_sorted = sorted(ratios)
    wall = statistics.median(ratios)
    q25 = ratios_sorted[len(ratios) // 4]
    q75 = ratios_sorted[-1 - len(ratios) // 4]
    acceptance = float(draft_stats.get("draft_acceptance", 0.0))
    log(f"[draft] chat-shaped decode steps {dispatches[False]} -> "
        f"{dispatches[True]} ({reduction:.2f}x fewer verify passes; "
        f"acceptance {acceptance:.2f}, "
        f"{draft_stats.get('draft_ingest_dispatches', 0)} ingest); "
        f"wall-clock median {wall:.2f}x (IQR {q25:.2f}-{q75:.2f}), "
        f"identical={identical}")
    return {
        "metric": "draft-model speculation A/B, chat-shaped greedy set "
                  f"(batch {slots}, {pairs} order-alternated paired "
                  "waves, micro geometry, perfect-draft upper bound)",
        # the deterministic headline: serving-model decode dispatches
        # (weight-streaming passes) per identical token stream
        "value": round(reduction, 3),
        "unit": "x fewer serving-model dispatches (draft on vs off)",
        "vs_baseline": round(reduction, 3),
        "dispatches_off": int(dispatches[False]),
        "dispatches_on": int(dispatches[True]),
        "tokens_per_wave_set": int(tokens_total[True]),
        "acceptance_ratio": round(acceptance, 3),
        "draft_proposed_tokens": int(
            draft_stats.get("draft_proposed_tokens", 0)
        ),
        "draft_ingest_dispatches": int(
            draft_stats.get("draft_ingest_dispatches", 0)
        ),
        "tps_draft_off": round(statistics.median(tps[False]), 1),
        "tps_draft_on": round(statistics.median(tps[True]), 1),
        "wall_ratio_median": round(wall, 3),
        "ratio_iqr": [round(q25, 3), round(q75, 3)],
        "pair_ratios": [round(r, 3) for r in ratios],
        "tokens_identical": bool(identical),
        "cpu_cores": os.cpu_count(),
    }


def bench_moe_gather():
    """Gathered-expert MoE decode A/B on the real chip: a ~2.3B-param
    MoE geometry (32 experts, top-4 — qwen3-moe-style, scaled to fit one
    chip's HBM comfortably) decoded single-request with the gathered path
    (streams only the routed experts' weights; AIOS_TPU_MOE_GATHER opt-in)
    vs the dense-all-experts path. Measured r3: gather 126.5 vs dense
    216.4 tok/s — the expert gather costs more than the skipped streaming
    saves at this geometry, which is why dense is the engine default;
    qwen3-30b-a3b itself needs a multi-chip slice (--virtual-ep)."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import QWEN3_30B_A3B
    from aios_tpu.engine.engine import TPUEngine

    cfg = QWEN3_30B_A3B.scaled(
        name="qwen3-moe-2b-geometry",
        vocab_size=32000,
        hidden_size=1024,
        intermediate_size=2816,
        moe_intermediate_size=1408,
        num_layers=16,
        num_heads=16,
        num_kv_heads=4,
        head_dim=64,
        num_experts=32,
        num_experts_per_tok=4,
        max_context=1024,
    )
    params = model_mod.init_quantized_params(cfg, jax.random.PRNGKey(0))
    weight_bytes = model_mod.serving_weight_bytes(params)
    chunk, rounds = 64, 2
    results = {}
    for impl in ("gather", "dense"):
        eng = TPUEngine(cfg, params, num_slots=1, max_context=1024,
                        cache_dtype=jnp.bfloat16)
        # force each arm explicitly (the engine default is dense; gather
        # is the AIOS_TPU_MOE_GATHER opt-in, sparse-eligible here: 1*4<32)
        eng._moe_impl = "gather" if impl == "gather" else None
        eng.prefill(0, list(range(1, 65)), temperature=0.7, top_p=0.95)
        eng.step(chunk)  # compile
        eng.step(chunk)  # warm
        t0 = time.time()
        for _ in range(rounds):
            eng.step(chunk)
        dt = time.time() - t0
        eng.close()
        results[impl] = chunk * rounds / dt
        log(f"[moe-gather] {impl}: {results[impl]:.1f} tok/s")
    speedup = results["gather"] / max(results["dense"], 1e-9)
    return {
        "metric": "moe gathered-expert single-request decode "
                  "(2.3B geometry, 32 experts top-4, int8)",
        "value": round(results["gather"], 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(results["gather"] / BASELINE_CPU_TPS, 1),
        "dense_all_experts_tok_per_s": round(results["dense"], 1),
        "gather_speedup": round(speedup, 2),
        "weights_gb": round(weight_bytes / 1e9, 2),
    }


def bench_int8_kv_ragged_ab():
    """A/B the env-gated int8-KV ragged kernel (AIOS_TPU_INT8_RAGGED) on a
    long-context int8-KV TinyLlama: flag OFF = the dequantizing XLA
    full-cache read, flag ON = int8 pages stream through the Pallas kernel
    with valid-rows-only DMA. The flag is read at trace time, so each arm
    builds a fresh engine. This is the measurement the kernel family is
    gated on (docs/HARDWARE.md 'pending chip measurement')."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINYLLAMA_1_1B
    params = model_mod.init_quantized_params(cfg, jax.random.PRNGKey(0))
    chunk, rounds, ctx = 64, 2, 4096
    results = {}
    prior = os.environ.get("AIOS_TPU_INT8_RAGGED")
    try:
        for arm, flag in (("xla_dequant", ""), ("int8_ragged_kernel", "1")):
            if flag:
                os.environ["AIOS_TPU_INT8_RAGGED"] = flag
            else:
                os.environ.pop("AIOS_TPU_INT8_RAGGED", None)
            eng = TPUEngine(cfg, params, num_slots=8, max_context=ctx,
                            cache_dtype=jnp.int8)
            # mid-length caches so the ragged DMA win is visible
            for s_ in range(8):
                eng.prefill(s_, list(range(1, 1025)), temperature=0.7,
                            top_p=0.95)
            eng.step(chunk)  # compile
            eng.step(chunk)  # warm
            t0 = time.time()
            for _ in range(rounds):
                eng.step(chunk)
            dt = time.time() - t0
            eng.close()
            results[arm] = 8 * chunk * rounds / dt
            log(f"[int8-ragged-ab] {arm}: {results[arm]:.1f} tok/s")
    finally:
        if prior is None:
            os.environ.pop("AIOS_TPU_INT8_RAGGED", None)
        else:
            os.environ["AIOS_TPU_INT8_RAGGED"] = prior
    speedup = results["int8_ragged_kernel"] / max(
        results["xla_dequant"], 1e-9
    )
    return {
        "metric": "int8-KV ragged kernel A/B, tinyllama 8 slots @ 1k/4096 "
                  "ctx (env-gated kernel vs XLA dequant path)",
        "value": round(results["int8_ragged_kernel"], 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(
            results["int8_ragged_kernel"] / BASELINE_CPU_TPS, 1
        ),
        "xla_dequant_tok_per_s": round(results["xla_dequant"], 1),
        "kernel_speedup": round(speedup, 2),
    }


def bench_orchestrator_e2e():
    """BASELINE config 5: the full 5-service stack (memory, tools, runtime
    with the real TinyLlama engine, gateway, orchestrator + live autonomy
    loop) wired over localhost gRPC in-process. Two latencies: p50 goal
    submit->completed through goal_engine -> task_planner -> heuristic
    executor -> real tool gRPC (pure orchestration), and p50
    gateway.Infer -> runtime -> TPU decode (the serving chain agents'
    think() rides). The AI-reasoning TTFT is bench_agent_ttft's number."""
    import os
    import tempfile

    import jax

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import api_gateway_pb2, common_pb2, orchestrator_pb2

    import shutil

    tmp = tempfile.mkdtemp(prefix="aios-bench-e2e-")
    servers = []
    autonomy = None
    saved_keys = {}
    on_tpu = jax.default_backend() == "tpu"
    model_src = "synthetic://tinyllama-1.1b" if on_tpu else "synthetic://tiny-test"
    try:
        from aios_tpu.memory.service import serve as serve_memory

        mem_server, _, mem_port = serve_memory(address="127.0.0.1:0", block=False)
        servers.append(mem_server)

        from aios_tpu.tools.executor import ToolExecutor
        from aios_tpu.tools.service import serve as serve_tools

        tools_server, _, tools_port = serve_tools(
            address="127.0.0.1:0",
            executor=ToolExecutor(
                audit_path=os.path.join(tmp, "audit.db"),
                backup_dir=os.path.join(tmp, "backups"),
                plugin_dir=os.path.join(tmp, "plugins"),
            ),
            block=False,
        )
        servers.append(tools_server)

        from aios_tpu.runtime.model_manager import ModelManager
        from aios_tpu.runtime.service import serve as serve_runtime

        manager = ModelManager(num_slots=8, warm_compile=on_tpu)
        manager.load_model("tinyllama-e2e", model_src)
        rt_server, _, rt_port = serve_runtime(
            address="127.0.0.1:0", manager=manager, block=False
        )
        servers.append(rt_server)

        for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY", "QWEN3_API_KEY"):
            saved_keys[var] = os.environ.pop(var, None)
        from aios_tpu.gateway.router import RequestRouter
        from aios_tpu.gateway.service import serve as serve_gateway

        gw_server, _, gw_port = serve_gateway(
            address="127.0.0.1:0",
            router=RequestRouter(runtime_address=f"127.0.0.1:{rt_port}"),
            block=False,
        )
        servers.append(gw_server)

        from aios_tpu.orchestrator.autonomy import AutonomyConfig
        from aios_tpu.orchestrator.clients import ServiceClients
        from aios_tpu.orchestrator.main import build_orchestrator
        from aios_tpu.orchestrator.service import serve as serve_orch

        clients = ServiceClients(
            runtime_addr=f"127.0.0.1:{rt_port}",
            tools_addr=f"127.0.0.1:{tools_port}",
            memory_addr=f"127.0.0.1:{mem_port}",
            gateway_addr=f"127.0.0.1:{gw_port}",
        )
        service, autonomy, *_ = build_orchestrator(
            data_dir=os.path.join(tmp, "orch"),
            clients=clients,
            autonomy_config=AutonomyConfig(tick_interval=0.05),
        )
        autonomy.start()
        orch_server, _, orch_port = serve_orch(
            address="127.0.0.1:0", service=service, block=False
        )
        servers.append(orch_server)
        orch = services.OrchestratorStub(
            rpc.insecure_channel(f"127.0.0.1:{orch_port}")
        )
        gw = services.ApiGatewayStub(rpc.insecure_channel(f"127.0.0.1:{gw_port}"))

        # gateway -> runtime -> TPU decode chain (warm first); distinct
        # prompts per call — identical prompts would hit the gateway's
        # response cache and measure a dict lookup, not the serving chain
        def infer_once(i):
            t0 = time.time()
            gw.Infer(api_gateway_pb2.ApiInferRequest(
                prompt=f"status check {i}", max_tokens=32, temperature=0.7,
            ), timeout=60)
            return time.time() - t0

        infer_once(0)  # warm/compile
        infer_lat = sorted(infer_once(i + 1) for i in range(6))

        # full goal flow: submit -> decompose -> heuristic -> tool -> done
        def goal_once():
            t0 = time.time()
            g = orch.SubmitGoal(orchestrator_pb2.SubmitGoalRequest(
                description="check disk usage", priority=5,
            ))
            deadline = time.time() + 30
            while time.time() < deadline:
                st = orch.GetGoalStatus(common_pb2.GoalId(id=g.id))
                if st.goal.status in ("completed", "failed"):
                    return time.time() - t0, st.goal.status
                time.sleep(0.02)
            return time.time() - t0, "timeout"

        goal_once()  # warm the tick/tool path
        runs = [goal_once() for _ in range(6)]
        lats = sorted(r[0] for r in runs)
        ok = sum(1 for r in runs if r[1] == "completed")
        p50_goal = lats[len(lats) // 2]
        p50_infer = infer_lat[len(infer_lat) // 2]
        log(f"[orch-e2e] p50 goal {p50_goal*1000:.0f} ms ({ok}/6 completed); "
            f"p50 gateway infer(32 tok) {p50_infer*1000:.0f} ms")
        return {
            "metric": "full-orchestrator e2e p50 goal latency "
                      "(submit->tool->completed, 5 live services)",
            "value": round(p50_goal * 1000.0, 1),
            "unit": "ms",
            "vs_baseline": 0.0,
            "goals_completed": ok,
            "p50_gateway_infer_32tok_ms": round(p50_infer * 1000.0, 1),
            "model": model_src.removeprefix("synthetic://"),
        }
    finally:
        if autonomy is not None:
            autonomy.stop()
        for server in servers:
            server.stop(grace=None)
        for var, val in saved_keys.items():
            if val is not None:
                os.environ[var] = val
        shutil.rmtree(tmp, ignore_errors=True)


def _force_virtual_cpu_mesh(n: int = 8):
    """Point this process at an n-device virtual CPU mesh (a site hook in
    this image can re-force the TPU platform after import, hence both the
    env var and the config update)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def bench_virtual_tp():
    """Config 4's code path on a virtual 8-device CPU mesh: numbers are NOT
    chip performance, they prove the sharded int8 decode executes."""
    _force_virtual_cpu_mesh(8)
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import MISTRAL_7B
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    cfg = MISTRAL_7B.scaled(
        hidden_size=256, intermediate_size=512, num_layers=4, vocab_size=1024,
        num_heads=8, num_kv_heads=4, head_dim=32, sliding_window=None,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    plan = ShardingPlan(build_mesh(8, dp=2, sp=1, tp=4))
    engine = TPUEngine(
        cfg, params, num_slots=8, max_context=256, cache_dtype=jnp.float32,
        shardings=plan, quantize=True,
    )
    for s in range(8):
        engine.prefill(s, list(range(1, 33)), temperature=0.7)
    engine.step(8)
    t0 = time.time()
    engine.step(32)
    dt = time.time() - t0
    emit({
        "metric": "mistral-geometry int8+TP decode, dp=2 x tp=4 virtual CPU mesh "
                  "(sharding proof, not chip perf)",
        "value": round(8 * 32 / dt, 1),
        "unit": "tokens/sec (virtual mesh)",
        "vs_baseline": 0.0,
    })


def bench_virtual_ep():
    """MoE decode under expert parallelism on a virtual 8-device CPU mesh
    (dp=2 x ep=2 x tp=2): numbers are NOT chip performance, they prove the
    expert-sharded int8 MoE decode executes. Real MoE serving targets a
    multi-chip slice — qwen3-30b-a3b int8 is ~30 GB of weights, beyond one
    v5e chip's 16 GB HBM by design."""
    _force_virtual_cpu_mesh(8)
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import QWEN3_30B_A3B
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    cfg = QWEN3_30B_A3B.scaled(
        hidden_size=128, intermediate_size=256, moe_intermediate_size=64,
        num_layers=4, vocab_size=1024, num_heads=8, num_kv_heads=4,
        head_dim=16, num_experts=16, num_experts_per_tok=4,
    )
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    plan = ShardingPlan(build_mesh(8, dp=2, sp=1, ep=2, tp=2))
    engine = TPUEngine(
        cfg, params, num_slots=8, max_context=256, cache_dtype=jnp.float32,
        shardings=plan, quantize=True,
    )
    for s in range(8):
        engine.prefill(s, list(range(1, 33)), temperature=0.7)
    engine.step(8)
    t0 = time.time()
    engine.step(32)
    dt = time.time() - t0
    emit({
        "metric": "qwen3-moe-geometry int8+EP decode, dp=2 x ep=2 x tp=2 "
                  "virtual CPU mesh (sharding proof, not chip perf)",
        "value": round(8 * 32 / dt, 1),
        "unit": "tokens/sec (virtual mesh)",
        "vs_baseline": 0.0,
    })


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-tp", action="store_true",
                    help="run the sharded int8 decode on a virtual CPU mesh")
    ap.add_argument("--virtual-ep", action="store_true",
                    help="run the expert-parallel MoE decode on a virtual CPU mesh")
    ap.add_argument("--skip-mistral", action="store_true")
    ap.add_argument("--fast", action="store_true",
                    help="headline decode configs only (no serving-feature "
                         "A/Bs) — bounded-time mode for capped drivers")
    ap.add_argument("--profile", metavar="DIR", default="",
                    help="capture an XLA profiler trace of one steady-state "
                         "decode dispatch per config into DIR/<config>/")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="also bench the serving ReplicaPool with N "
                         "replicas (shared-prefix agent waves; emits "
                         "prefix-routed ratio + per-replica occupancy)")
    ap.add_argument("--host-tier-smoke", action="store_true",
                    help="run ONLY the prefix-cache host-tier "
                         "spill->restore exercise (assertion-free, CPU "
                         "fallback fine, always exit 0) — the cheap "
                         "regression probe for the host spill tier")
    ap.add_argument("--longctx-smoke", action="store_true",
                    help="run ONLY the long-context probe: a long prompt "
                         "admits chunked, window+sink KV compression "
                         "kicks in, decode continues (assertion-free, "
                         "CPU fallback fine, always exit 0)")
    ap.add_argument("--devprof", action="store_true",
                    help="run ONLY the device-time attribution probe: "
                         "emit the per-graph cost ledger JSON (the "
                         "scripts/benchdiff.py regression-sentinel "
                         "input) + the devprof on/off overhead A/B "
                         "(assertion-free, CPU fallback fine, exit 0)")
    ap.add_argument("--tsdb", action="store_true",
                    help="run ONLY the tsdb sampler overhead A/B: one "
                         "engine+batcher, tsdb off vs the real sampler "
                         "thread at 20x cadence, order-alternated paired "
                         "waves — token streams and post-warmup compile "
                         "counts must be identical across arms "
                         "(assertion-free, always exit 0)")
    ap.add_argument("--flight-dump", action="store_true",
                    help="run ONLY the flight-recorder smoke: a tiny "
                         "2-replica pool wave whose request timelines "
                         "are dumped as Chrome trace JSON + SLO summary "
                         "(assertion-free, always exit 0)")
    ap.add_argument("--chaos", action="store_true",
                    help="run ONLY the seeded chaos storm (crash + "
                         "dispatch-delay faults on a 2-replica pool, "
                         "run twice): exit NON-ZERO on any stuck "
                         "request, aborted stream, or nondeterministic "
                         "re-run — the pre-merge robustness gate "
                         "(scripts/chaos.sh, docs/FAULTS.md)")
    ap.add_argument("--chaos-seed", type=int, default=42, metavar="N",
                    help="fault-schedule seed for --chaos (default 42)")
    ap.add_argument("--storm", action="store_true",
                    help="run ONLY the million-user storm gate: a seeded "
                         "trace-driven tenant mix (aios_tpu/loadgen/) "
                         "drives the live gRPC surface twice — exit "
                         "NON-ZERO on a FAIL verdict or any "
                         "deterministic-fingerprint divergence. Composes "
                         "with --chaos (same storm under seeded faults). "
                         "Full mode adds the autoscale closed-loop arms "
                         "(scripts/preflight.sh, docs/TESTING.md)")
    ap.add_argument("--storm-scenario", metavar="PATH", default="",
                    help="scenario file for --storm (default: the "
                         "committed scenarios/storm_reference.toml, or "
                         "storm_smoke.toml with --smoke)")
    ap.add_argument("--smoke", action="store_true",
                    help="with --storm: the small CI scenario, "
                         "determinism pair only (no autoscale arms) — "
                         "the preflight gate")
    args = ap.parse_args()

    if args.storm:
        try:
            return bench_storm(
                args.storm_scenario, smoke=args.smoke,
                chaos_seed=args.chaos_seed if args.chaos else None,
            )
        except Exception as e:  # a crashed harness is a FAIL, loudly
            log(f"[storm] HARNESS FAILED: {e!r}")
            emit({"metric": "storm gate (seeded trace-driven tenant mix "
                            "over the live gRPC surface, run twice)",
                  "value": 0.0, "unit": "verdict (1 = pass)",
                  "vs_baseline": 0.0, "error": repr(e)[:300]})
            return 1

    if args.chaos:
        try:
            return bench_chaos(args.chaos_seed)
        except Exception as e:  # a crashed harness is a FAIL, loudly
            log(f"[chaos] HARNESS FAILED: {e!r}")
            emit({"metric": "chaos storm (seeded crash + dispatch "
                            "delay, 2-replica pool, run twice)",
                  "value": 0.0, "unit": "verdict (1 = pass)",
                  "vs_baseline": 0.0, "error": repr(e)[:300]})
            return 1

    if args.devprof:
        try:
            emit(bench_devprof())
        except Exception as e:  # assertion-free: diagnose, never fail
            log(f"[devprof] FAILED: {e!r}")
            emit({"metric": "devprof per-graph device-time ledger + "
                            "sampling overhead A/B",
                  "value": 0.0, "unit": "n/a", "vs_baseline": 0.0,
                  "error": repr(e)[:300]})
        return 0

    if args.tsdb:
        try:
            emit(bench_tsdb())
        except Exception as e:  # assertion-free: diagnose, never fail
            log(f"[tsdb] FAILED: {e!r}")
            emit({"metric": "tsdb sampler ON/OFF overhead A/B",
                  "value": 0.0, "unit": "n/a", "vs_baseline": 0.0,
                  "error": repr(e)[:300]})
        return 0

    if args.flight_dump:
        try:
            emit(bench_flight_dump())
        except Exception as e:  # assertion-free: diagnose, never fail
            log(f"[flight-dump] FAILED: {e!r}")
            emit({"metric": "flight recorder smoke (2-replica pool wave "
                            "-> timeline ring -> Chrome trace JSON)",
                  "value": 0.0, "unit": "n/a", "vs_baseline": 0.0,
                  "error": repr(e)[:300]})
        return 0

    if args.host_tier_smoke:
        try:
            emit(bench_host_tier())
        except Exception as e:  # assertion-free: diagnose, never fail
            log(f"[host-tier] FAILED: {e!r}")
            emit({"metric": "prefix-cache host tier spill->restore "
                            "(tiny geometry, restore vs recompute prefill)",
                  "value": 0.0, "unit": "n/a", "vs_baseline": 0.0,
                  "error": repr(e)[:300]})
        return 0

    if args.longctx_smoke:
        try:
            emit(bench_longctx(smoke=True))
        except Exception as e:  # assertion-free: diagnose, never fail
            log(f"[longctx] FAILED: {e!r}")
            emit({"metric": "long-context smoke (compression kicks in, "
                            "decode continues)",
                  "value": 0.0, "unit": "n/a", "vs_baseline": 0.0,
                  "error": repr(e)[:300]})
        return 0

    if args.virtual_tp:
        bench_virtual_tp()
        return 0
    if args.virtual_ep:
        bench_virtual_ep()
        return 0

    # config table built BEFORE the backend probe (aios_tpu.engine.config
    # is jax-free): a failed probe still knows every planned config and
    # can emit one diagnostic line each
    from aios_tpu.engine.config import MISTRAL_7B, TINYLLAMA_1_1B

    # Measured on v5e (r3 A/B sweeps): bf16 KV beats int8 KV at these
    # context lengths (dequant math > bandwidth saved); 64-step scan chunks
    # beat 32; XLA's int8 x bf16 dot beats the Pallas qmm at decode sizes;
    # the ragged attention kernel auto-enables for Mistral geometry
    # (model._ragged_min_c rule, +11%).
    failures = 0
    configs = [
        dict(
            name="tinyllama-1.1b batched decode throughput (8 slots, int8 serving)",
            cfg=TINYLLAMA_1_1B, num_slots=8, active_slots=8, max_context=1024,
            prompt_len=64, chunk=128, measure_chunks=3, quant_kv=False,
        ),
        dict(
            name="mistral-7b single-request decode (int8 serving)",
            cfg=MISTRAL_7B, num_slots=1, active_slots=1, max_context=1024,
            prompt_len=64, chunk=64, measure_chunks=3, quant_kv=False,
        ),
        dict(
            name="mistral-7b batched decode throughput (8 slots, int8 serving)",
            cfg=MISTRAL_7B, num_slots=8, active_slots=8, max_context=1024,
            prompt_len=64, chunk=128, measure_chunks=2, quant_kv=False,
        ),
        # int4 serving (ops/int4_matmul.py): half the int8 weight bytes —
        # the decode path is weight-bandwidth-bound, so this is the
        # headline single-chip throughput lever for the 7B tier
        dict(
            name="mistral-7b batched decode throughput (8 slots, int4 serving)",
            cfg=MISTRAL_7B, num_slots=8, active_slots=8, max_context=1024,
            prompt_len=64, chunk=128, measure_chunks=2, quant_kv=False,
            weight_mode="int4",
        ),
        dict(
            name="mistral-7b single-request decode (int4 serving)",
            cfg=MISTRAL_7B, num_slots=1, active_slots=1, max_context=1024,
            prompt_len=64, chunk=64, measure_chunks=3, quant_kv=False,
            weight_mode="int4",
        ),
    ]
    if args.skip_mistral:
        configs = configs[:1]
    extra = [] if args.skip_mistral else [bench_mixed_tier, bench_spec_decode]
    extra.extend([
        bench_paged_kv, bench_host_tier, bench_longctx, bench_dispatch,
        bench_mega, bench_tsdb, bench_devprof, bench_structured, bench_draft,
        bench_agent_ttft, bench_moe_gather, bench_int8_kv_ragged_ab,
        bench_orchestrator_e2e,
    ])
    if args.fast:
        extra = []
    if args.replicas > 1:
        # explicit opt-in rides along even in --fast mode
        def bench_replica_pool_n():
            return bench_replica_pool(args.replicas)

        bench_replica_pool_n.__name__ = "bench_replica_pool"
        extra.append(bench_replica_pool_n)

    if not probe_backend():
        # bounded-probe exhaustion (wedged tunnel): one parseable
        # diagnostic line PER planned config, exit 0 — the capture
        # harness records a diagnosed round instead of an empty timeout
        # (the BENCH_r05 rc=124/parsed:null failure mode)
        for c in configs:
            emit({
                "metric": c["name"],
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
                "error": "TPU backend unavailable within probe budget",
            })
        for fn in extra:
            emit({
                "metric": fn.__name__,
                "value": 0.0,
                "unit": "n/a",
                "vs_baseline": 0.0,
                "error": "TPU backend unavailable within probe budget",
            })
        return 0

    import jax

    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    for c in configs:
        name = c.pop("name")
        cfg = c.pop("cfg")
        try:
            emit(bench_decode(name, cfg, profile_dir=args.profile or None, **c))
        except Exception as e:  # emit a diagnostic line, keep going
            failures += 1
            log(f"[{name}] FAILED: {e!r}")
            emit({
                "metric": name,
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
                "error": repr(e)[:300],
            })
    for fn in extra:
        try:
            emit(fn())
        except Exception as e:
            log(f"[{fn.__name__}] FAILED: {e!r}")
            emit({"metric": fn.__name__, "value": 0.0, "unit": "n/a",
                  "vs_baseline": 0.0, "error": repr(e)[:300]})
    return 1 if failures == len(configs) else 0


if __name__ == "__main__":
    sys.exit(main())
