"""Checkpoint/resume: serving-weight checkpoints and train-state resume.

The aux-subsystem layer the reference lacks (SURVEY.md section 5
"Checkpoint/resume": goals persist in SQLite, models don't) — here model
state checkpoints with the same crash-resume semantics.
"""

import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import checkpoint as ckpt
from aios_tpu.engine import model as M
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.tokenizer import (
    ByteTokenizer,
    SentencePieceBPE,
    tokenizer_from_dict,
    tokenizer_to_dict,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def test_params_roundtrip(tmp_path):
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    ckpt.save_params(str(tmp_path), params)
    assert ckpt.is_checkpoint_dir(str(tmp_path))
    back = ckpt.load_params(str(tmp_path), like=params)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        back,
    )


def test_model_checkpoint_roundtrip_and_manager_load(tmp_path):
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32)
    d = str(tmp_path / "model")
    ckpt.save_model_checkpoint(d, TINY_TEST, params, ByteTokenizer())
    assert ckpt.is_model_checkpoint(d)

    cfg2, params2, tok2 = ckpt.load_model_checkpoint(d)
    assert cfg2 == TINY_TEST
    assert isinstance(tok2, ByteTokenizer)

    # the runtime's LoadModel path recognizes prepared checkpoint dirs
    from aios_tpu.runtime.model_manager import ModelManager

    mgr = ModelManager(num_slots=2, warm_compile=False, quantize=False)
    m = mgr.load_model("from-ckpt", d, context_length=64)
    assert m.state == "ready"
    out = m.engine.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)
    ref_engine_params = jax.tree.map(jnp.asarray, params)
    from aios_tpu.engine.engine import TPUEngine

    ref = TPUEngine(TINY_TEST, ref_engine_params, num_slots=2, max_context=64)
    assert out == ref.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)


def test_spbpe_tokenizer_serde():
    pieces = ["▁", "h", "e", "l", "o", "lo", "llo", "ello", "hello", "▁hello"]
    tok = SentencePieceBPE(
        tokens=["<unk>", "<s>", "</s>", *pieces, "<0x41>"],
        scores=[0.0, 0.0, 0.0, *([-1.0] * len(pieces)), 0.0],
        token_types=[2, 3, 3, *([1] * len(pieces)), 6],
    )
    d = tokenizer_to_dict(tok)
    tok2 = tokenizer_from_dict(d)
    text = "hello"
    assert tok2.encode(text) == tok.encode(text)
    assert tok2.decode(tok.encode(text, add_bos=False)) == "hello"


def test_checkpoint_manager_retention_and_restore(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path), max_to_keep=2)
    tree = {"a": jnp.arange(4, dtype=jnp.float32), "step": jnp.int32(0)}
    for s in (1, 2, 3):
        mgr.save(s, {"a": tree["a"] * s, "step": jnp.int32(s)})
    assert mgr.latest_step() == 3
    back = mgr.restore(like=tree)
    assert int(back["step"]) == 3
    np.testing.assert_allclose(np.asarray(back["a"]), np.arange(4) * 3)
    mgr.close()


def test_train_loop_resume(tmp_path):
    from aios_tpu.engine.train import make_optimizer, train_loop

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {
                "tokens": jnp.asarray(
                    rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32
                ),
                "loss_mask": jnp.ones((2, 16), jnp.float32),
            }

    d = str(tmp_path / "train")
    opt = make_optimizer(warmup_steps=1, total_steps=10)
    losses = []
    state = train_loop(
        cfg, params, batches(3), optimizer=opt, checkpoint_dir=d,
        save_every=2, on_metrics=lambda s, m: losses.append(float(m["loss"])),
    )
    assert int(state["step"]) == 3 and len(losses) == 3

    # resume: a fresh call continues from step 3, not from scratch
    state2 = train_loop(
        cfg, params, batches(2), optimizer=opt, checkpoint_dir=d, save_every=10
    )
    assert int(state2["step"]) == 5


def test_prepare_model_script(tmp_path):
    out = tmp_path / "prepared"
    env_script = Path(__file__).resolve().parent.parent / "scripts" / "prepare_model.py"
    proc = subprocess.run(
        [
            sys.executable,
            str(env_script),
            "synthetic://tiny-test",
            str(out),
            "--dtype",
            "f32",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env={
            "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": str(Path(__file__).resolve().parent.parent),
            "HOME": str(tmp_path),
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert ckpt.is_model_checkpoint(str(out))


def test_prepared_quantized_checkpoint_serves_without_requantize(tmp_path):
    """prepare_model --quantize saves {"q","s"} serving leaves; restoring
    through the model manager serves them as-is (no re-quantization, no
    dense transient), and decode matches quantizing at load time."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import checkpoint as ckpt
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(31), dtype=jnp.float32)
    qparams = M.quantize_params(params, mode="int8")
    out_dir = tmp_path / "prepared-int8"
    ckpt.save_model_checkpoint(str(out_dir), TINY_TEST, qparams, ByteTokenizer())

    cfg2, params2, tok2 = ckpt.load_model_checkpoint(str(out_dir))
    assert "q" in params2["layers"]["w_qkv"]
    # engine with quantize set must NOT re-quantize already-quantized leaves
    eng = TPUEngine(cfg2, params2, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, quantize="int8")
    ref = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, quantize="int8")
    prompt = [1, 5, 9, 2]
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0)
    assert got == want


def test_prequantized_checkpoint_refused_under_sharding_plan():
    """Prepared quantized checkpoints are single-chip artifacts (fused
    layout has no TP rule); a sharded engine must refuse them clearly."""
    import jax
    import jax.numpy as jnp
    import pytest

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(32), dtype=jnp.float32)
    qp = M.quantize_params(params, mode="int8")
    plan = ShardingPlan(build_mesh(tp=2, n_devices=2))
    with pytest.raises(ValueError, match="single-chip"):
        TPUEngine(TINY_TEST, qp, num_slots=2, max_context=64,
                  shardings=plan, quantize="int8")
