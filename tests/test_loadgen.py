"""Storm load generation: scenario validation, trace determinism, the
verdict contract, and a scaled-down live-gRPC storm e2e.

The trace builder is a pure function of (scenario, seed) — the storm
gate's determinism contract rests on that, so it is pinned here at unit
level; bench.py --storm (scripts/preflight.sh) pins the full twice-run
verdict equality over the live service.
"""

import os

import pytest

from aios_tpu.loadgen import (
    Outcome,
    StormDriver,
    build_report,
    build_trace,
    load_scenario,
    trace_fingerprint,
)
from aios_tpu.loadgen.scenario import (
    SLOTargets,
    StormScenario,
    TenantSpec,
)

SCENARIOS = os.path.join(os.path.dirname(__file__), "..", "scenarios")


def scenario(**over):
    base = dict(
        name="unit", seed=11, duration_secs=4.0, model="loadgen-unit",
        tenants=(
            TenantSpec(name="chat", klass="interactive", rps=2.0,
                       streaming=True),
            TenantSpec(name="agents", klass="agent", rps=1.0,
                       shared_prefix=80, fork_width=2),
            TenantSpec(name="bulk", klass="batch", rps=1.0,
                       arrival="diurnal", peak_ratio=4.0,
                       period_secs=2.0),
            TenantSpec(name="storm", klass="abusive", rps=6.0,
                       arrival="burst", peak_ratio=6.0, period_secs=2.0,
                       burst_secs=0.5, prompt_p50=100, max_tokens=40,
                       quota_storm=True),
            TenantSpec(name="probe", klass="reactive", rps=0.5,
                       arrival="uniform", deadline_ms=60_000),
        ),
    )
    base.update(over)
    return StormScenario(**base)


# ---------------------------------------------------------------------------
# scenario spec
# ---------------------------------------------------------------------------


def test_committed_scenarios_load_and_validate():
    for fname in ("storm_reference.toml", "storm_smoke.toml"):
        sc = load_scenario(os.path.join(SCENARIOS, fname))
        assert sc.tenants and sc.duration_secs > 0
        assert sc.slo.attainment <= 1.0
        classes = {t.klass for t in sc.tenants}
        # the reference mix must keep exercising the interesting paths
        assert "abusive" in classes and "agent" in classes


def test_scenario_validation_fails_loudly(tmp_path):
    bad = tmp_path / "bad.toml"
    bad.write_text(
        "[scenario]\nname='x'\n[[tenants]]\nname='t'\nclass='nope'\n"
    )
    with pytest.raises(ValueError, match="unknown class"):
        load_scenario(str(bad))
    empty = tmp_path / "empty.toml"
    empty.write_text("[scenario]\nname='x'\n")
    with pytest.raises(ValueError, match="at least one"):
        load_scenario(str(empty))
    dup = tmp_path / "dup.toml"
    dup.write_text(
        "[scenario]\nname='x'\n"
        "[[tenants]]\nname='t'\n[[tenants]]\nname='t'\n"
    )
    with pytest.raises(ValueError, match="duplicate"):
        load_scenario(str(dup))
    unknown_key = tmp_path / "k.toml"
    unknown_key.write_text(
        "[scenario]\nname='x'\n[[tenants]]\nname='t'\nrsp=3\n"
    )
    with pytest.raises(ValueError, match="unknown keys"):
        load_scenario(str(unknown_key))


# ---------------------------------------------------------------------------
# trace builder
# ---------------------------------------------------------------------------


def test_trace_is_deterministic_and_seed_sensitive():
    sc = scenario()
    a, b = build_trace(sc), build_trace(sc)
    assert a == b
    assert trace_fingerprint(a) == trace_fingerprint(b)
    c = build_trace(scenario(seed=12))
    assert trace_fingerprint(a) != trace_fingerprint(c)


def test_trace_tenant_independence():
    """Adding a tenant never perturbs another tenant's schedule (each
    draws from its own (seed, name) stream)."""
    sc = scenario()
    solo = StormScenario(
        name="unit", seed=11, duration_secs=4.0, model="loadgen-unit",
        tenants=(sc.tenant("chat"),),
    )
    full_chat = [c for c in build_trace(sc) if c.tenant == "chat"]
    assert [c for c in build_trace(solo)] == full_chat


def test_arrivals_sorted_and_inside_duration():
    sc = scenario()
    calls = build_trace(sc)
    ts = [c.t for c in calls]
    assert ts == sorted(ts)
    roots = [c for c in calls if not c.parent]
    assert all(0 <= c.t < sc.duration_secs for c in roots)


def test_burst_curve_concentrates_arrivals():
    sc = scenario(tenants=(
        TenantSpec(name="b", klass="abusive", rps=4.0, arrival="burst",
                   peak_ratio=10.0, period_secs=2.0, burst_secs=0.5,
                   quota_storm=True),
    ), duration_secs=8.0)
    calls = build_trace(sc)
    in_burst = sum(1 for c in calls if (c.t % 2.0) < 0.5)
    # the on-window is 25% of the cycle at 10x rate: expect the large
    # majority of arrivals inside it
    assert in_burst / len(calls) > 0.6


def test_fork_children_share_parent_prefix_and_pin_nothing():
    calls = build_trace(scenario())
    parents = {c.task_id: c for c in calls if not c.parent}
    kids = [c for c in calls if c.parent]
    assert kids, "agent tenant must fork"
    for k in kids:
        p = parents[k.parent]
        assert k.prompt.startswith(p.prompt)  # the radix workload
        assert k.t > p.t
        assert not k.hash_stream  # cache-coupled: counts, not content
        assert k.must_complete


def test_quota_storm_calls_fixed_cost_and_excluded():
    calls = [c for c in build_trace(scenario()) if c.klass == "abusive"]
    assert len({(len(c.prompt), c.max_tokens) for c in calls}) == 1
    assert all(not c.must_complete and not c.hash_stream for c in calls)


def test_deadline_calls_excluded_from_determinism():
    calls = [c for c in build_trace(scenario()) if c.deadline_ms > 0]
    assert calls
    assert all(not c.must_complete and not c.hash_stream for c in calls)


def test_long_tail_prompt_lengths():
    sc = scenario(tenants=(
        TenantSpec(name="t", rps=20.0, prompt_p50=50, prompt_sigma=0.8,
                   prompt_max=400),
    ), duration_secs=10.0)
    lens = [len(c.prompt) for c in build_trace(sc)]
    med = sorted(lens)[len(lens) // 2]
    assert 30 <= med <= 110  # around the p50
    assert max(lens) > 2 * med  # a real tail
    assert max(lens) <= 400  # capped


# ---------------------------------------------------------------------------
# verdict contract
# ---------------------------------------------------------------------------


def _outcome(c, status="ok", shed_cause="", text="tok tok", ttft=5.0,
             chunks=3, wall=50.0):
    return Outcome(call=c, status=status, shed_cause=shed_cause,
                   text=text, ttft_ms=ttft, chunks=chunks, wall_ms=wall)


def test_report_pass_and_deterministic_fields():
    sc = scenario()
    calls = build_trace(sc)
    outcomes = [
        _outcome(c) if c.must_complete or c.deadline_ms
        else _outcome(c, status="shed", shed_cause="quota", text="")
        for c in calls
    ]
    rep = build_report(sc, calls, outcomes, {"live": True})
    assert rep["pass"] and rep["verdict"]["pass"]
    v = rep["verdict"]
    assert v["trace_sha"] == trace_fingerprint(calls)
    # deadline tenants live in measured, not the deterministic verdict
    assert "probe" not in v["tenants"]
    assert "probe" in rep["measured"]["deadline_tenants"]
    # hashes cover exactly the hash_stream calls
    assert len(v["stream_hashes"]) == sum(
        1 for c in calls if c.hash_stream
    )
    # identical outcomes -> identical verdict (the == the bench uses)
    rep2 = build_report(sc, calls, list(outcomes), {"other": "surface"})
    assert rep2["verdict"] == v  # the live surface is measured-only


def test_report_fails_on_missing_deterministic_stream():
    sc = scenario()
    calls = build_trace(sc)
    outcomes = [_outcome(c) for c in calls]
    victim = next(o for o in outcomes if o.call.must_complete)
    victim.status, victim.shed_cause = "shed", "queue_full"
    rep = build_report(sc, calls, outcomes, {})
    assert not rep["pass"]
    assert victim.call.task_id in rep["verdict"]["deterministic_missing"]


def test_report_fails_on_attainment_miss_and_errors():
    sc = scenario(slo=SLOTargets(ttft_ms=1.0, attainment=0.99))
    calls = build_trace(sc)
    outcomes = [_outcome(c, ttft=500.0) for c in calls]
    rep = build_report(sc, calls, outcomes, {})
    assert not rep["pass"]  # every ttft over the 1 ms target
    sc2 = scenario()
    outcomes2 = [_outcome(c) for c in calls]
    outcomes2[0].status, outcomes2[0].detail = "error", "boom"
    rep2 = build_report(sc2, calls, outcomes2, {})
    assert not rep2["pass"] and rep2["verdict"]["errors"] == 1


def test_availability_excludes_quota_and_deadline_sheds():
    sc = scenario()
    calls = build_trace(sc)
    outcomes = []
    for c in calls:
        if c.klass == "abusive":
            outcomes.append(_outcome(c, status="shed",
                                     shed_cause="quota", text=""))
        elif c.deadline_ms:
            outcomes.append(_outcome(c, status="shed",
                                     shed_cause="deadline", text=""))
        else:
            outcomes.append(_outcome(c))
    rep = build_report(sc, calls, outcomes, {})
    # the plane failed nothing it owed: policy + feasibility refusals
    assert rep["measured"]["availability"] == 1.0
    assert rep["pass"]


# ---------------------------------------------------------------------------
# live e2e (scaled down; bench.py --storm is the full gate)
# ---------------------------------------------------------------------------


def test_mini_storm_over_live_grpc(monkeypatch):
    """A tiny trace through the REAL service surface: streams complete,
    tenant counts land, the quota storm sheds with retry-after, and the
    verdict passes."""
    from aios_tpu.obs import slo as slo_mod
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    sc = StormScenario(
        name="mini", seed=3, duration_secs=1.2, model="storm-mini",
        replicas=1, context=256, num_slots=2,
        tenant_tokens_per_sec=1.0, tenant_burst_tokens=300.0,
        tenants=(
            TenantSpec(name="chat", klass="interactive", rps=2.5,
                       prompt_p50=30, prompt_max=60, max_tokens=6,
                       streaming=True),
            TenantSpec(name="storm", klass="abusive", rps=5.0,
                       arrival="burst", peak_ratio=4.0,
                       period_secs=1.0, burst_secs=0.4,
                       prompt_p50=100, max_tokens=60,
                       quota_storm=True),
        ),
    )
    monkeypatch.setenv("AIOS_TPU_TENANT_TOKENS_PER_SEC",
                       str(sc.tenant_tokens_per_sec))
    monkeypatch.setenv("AIOS_TPU_TENANT_BURST_TOKENS",
                       str(sc.tenant_burst_tokens))
    manager = ModelManager(num_slots=sc.num_slots, warm_compile=False)
    server = service = None
    try:
        manager.load_model(sc.model, "synthetic://tiny-test",
                           context_length=sc.context)
        server, service, port = serve(
            address="127.0.0.1:0", manager=manager, block=False,
            metrics_port=0,
        )
        driver = StormDriver(f"127.0.0.1:{port}", sc.model,
                             metrics_port=service.metrics_port)
        try:
            driver.warmup(n=1)
            calls = build_trace(sc)
            outcomes = driver.run(calls, join_timeout=120)
            surface = driver.slo_surface()
        finally:
            driver.close()
        rep = build_report(sc, calls, outcomes, surface)
        assert rep["verdict"]["stuck"] == 0
        assert rep["verdict"]["errors"] == 0
        v = rep["verdict"]["tenants"]
        assert v["chat"]["completed"] == v["chat"]["submitted"]
        # the storm overran its bucket: sheds happened, with the
        # retry-after hint the contract promises
        assert v["storm"]["shed"] > 0
        shed = [o for o in outcomes if o.status == "shed"]
        assert all(o.shed_cause == "quota" for o in shed)
        assert any(o.retry_after_ms > 0 for o in shed)
        # the live /debug/slo surface saw the storm's model
        assert sc.model in surface.get("models", {})
        assert rep["pass"]
    finally:
        if server is not None:
            server.stop(grace=None)
        if service is not None and service.metrics_server is not None:
            service.metrics_server.shutdown()
        manager.unload_model(sc.model)
        slo_mod.ENGINE.clear()
