"""Layered TOML configuration.

Reference parity (initd/src/config.rs:14-34 + config/default-config.toml):
the 9-section schema — system / boot / models / api / memory / security /
networking / agents / monitoring — loaded from /etc/aios/config.toml with
full defaults when the file is absent, plus env-var overrides for service
addresses (handled in aios_tpu.services) and model/runtime knobs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from .._compat import tomllib
from pathlib import Path
from typing import Any, Dict, List

DEFAULT_CONFIG_PATH = "/etc/aios/config.toml"


def _default_sections() -> Dict[str, Dict[str, Any]]:
    return {
        "system": {
            "hostname": "aios-tpu",
            "log_level": "info",
            "data_dir": "/tmp/aios",
        },
        "boot": {
            "health_timeout_seconds": 60,
            "max_restart_attempts": 5,
            "restart_window_seconds": 300,
            "emergency_shell": False,
        },
        "models": {
            "model_dir": "/var/lib/aios/models",
            "default_context": 4096,
            "num_slots": 8,
            "warm_compile": True,
            "autoload": True,
            # TPU serving knobs -> AIOS_TPU_* env for the runtime child
            # (serving_env(); docs/CONFIG.md documents each)
            "quantize": "",          # "" = auto; "0"/"1"/"int8"/"int4"
            "kv_cache": "",          # "int8" halves KV footprint/traffic
            # paged KV pool + prompt-prefix cache: "auto" sizes the pool
            # from the model's slots x context (dense-cache HBM + one
            # slot of slack) — the production default, so the 8 agents'
            # shared preambles hit the prefix index instead of re-
            # prefilling (BASELINE.md <200 ms agent-response target).
            # An integer sets a fixed row budget; 0 = dense slot cache.
            "paged_kv_rows": "auto",
            # host-RAM spill tier behind the prefix cache: evicted prefix
            # pages' KV is kept in host memory inside this byte budget
            # and restored device-side on a later hash-chain hit instead
            # of re-prefilled ("" / 0 = off; docs/CONFIG.md). The restore
            # floor skips the tier for chains shorter than N pages.
            "prefix_host_bytes": "",
            "host_restore_min_pages": "",
            # long-context tier (docs/ENGINE_PERF.md): window+sink KV
            # compression — past kv_compress_after rows a slot's paged KV
            # prunes to kv_sink_pages leading + kv_window_pages trailing
            # pages ("" / 0 = off, exact full attention); prompts >=
            # seq_prefill_min rows prefill in one dispatch sharded over
            # the mesh's sp axis ("" / 0 = off; needs sp > 1 in mesh).
            "kv_compress_after": "",
            "kv_sink_pages": "",
            "kv_window_pages": "",
            "seq_prefill_min": "",
            "speculative": False,    # n-gram speculative decode
            # draft-model speculation: pair each managed model with a
            # small draft (preset name or weights path, e.g. "tinyllama")
            # served int4 — the serving model verifies its proposals in
            # one dispatch (docs/ENGINE_PERF.md). "" = n-gram only.
            # spec_reprobe_secs: how long an auto-disabled proposer stays
            # suspended before probe dispatches re-measure ("" = 10 s).
            "draft_model": "",
            "spec_reprobe_secs": "",
            # pipelined decode loop: dispatch N+1 enqueues while dispatch
            # N's tokens are emitted/detokenized (docs/ENGINE_PERF.md);
            # unified_step folds every decode chunk size into ONE
            # dynamic-n XLA graph (greedy-identical; opt-in). "" = off.
            "decode_pipeline": "",
            "unified_step": "",
            # device-resident multi-tick decode megagraph: up to this
            # many decode ticks per dispatch with on-device sampling,
            # stop detection and budget/cap checks (early exit when no
            # slot needs another tick; docs/ENGINE_PERF.md). "" = off.
            "mega_ticks": "",
            # grammar jump-ahead for constrained/structured decoding
            # (multi-token forced runs in one dispatch; default ON) and
            # the radix-tree prefix index (default ON) — tri-state
            # escape hatches; spec_min_accept floors the speculative
            # EWMA acceptance ratio (0/"" = never auto-disable).
            "jump_ahead": "",
            "prefix_radix": "",
            "spec_min_accept": "",
            "json_mode": "",         # "force" = reference json_object parity
            "guided_toolcalls": False,  # schema-guided reasoning replies
            # multi-chip serving mesh, e.g. "tp=4" (BASELINE config 4:
            # Mistral-7B TP over a v5e-4) or "dp=2,sp=2,tp=2"; "" = one
            # chip. With sp > 1, models whose KV cache exceeds the
            # per-chip HBM budget automatically shard their context axis
            # over sp (the long-context degradation path — paging is
            # dropped for those models since pages cannot split across
            # sp shards).
            "mesh": "",
            # serving layer (docs/SERVING.md): replicas per managed model
            # behind the cache-aware router; per-tenant token-bucket
            # quota (tokens/sec + burst, 0 = off); bounded admission
            # queue per replica (an EXPLICIT max_queue = 0 means
            # unbounded, same as the env knob); deadline-feasibility
            # rate floor. "" = unset (serving defaults apply).
            "replicas": "",
            "tenant_tokens_per_sec": "",
            "tenant_burst_tokens": "",
            "max_queue": "",
            "assumed_tps": "",
        },
        "api": {
            "claude_model": "claude-sonnet-4-20250514",
            "openai_model": "gpt-5",
            "qwen3_model": "qwen3:30b-128k",
            "claude_monthly_budget": 100.0,
            "openai_monthly_budget": 50.0,
        },
        "memory": {
            "operational_capacity": 10000,
            "working_retention_days": 30,
            "longterm_retention_days": 365,
            "migration_interval_seconds": 300,
        },
        "security": {
            "audit_db": "/tmp/aios/ledger/audit.db",
            "cert_dir": "/tmp/aios/certs",
            "secrets_path": "/etc/aios/secrets.toml",
            "sandbox_memory_mb": 256,
        },
        "networking": {
            "bind_host": "127.0.0.1",
            "console_port": 9090,
            "cluster_enabled": False,
        },
        "agents": {
            "config_dir": "/etc/aios/agents",
            "default_agents": ["system", "network", "security"],
            "max_restart_attempts": 5,
            "heartbeat_seconds": 10,
            "poll_seconds": 2,
        },
        "monitoring": {
            "proactive_interval_seconds": 60,
            "cpu_threshold": 90.0,
            "memory_threshold": 85.0,
            "disk_threshold": 90.0,
        },
    }


@dataclass
class AiosConfig:
    sections: Dict[str, Dict[str, Any]] = field(default_factory=_default_sections)
    source_path: str = ""

    def get(self, section: str, key: str, default: Any = None) -> Any:
        return self.sections.get(section, {}).get(key, default)

    def section(self, name: str) -> Dict[str, Any]:
        return dict(self.sections.get(name, {}))

    @property
    def data_dir(self) -> str:
        return os.environ.get("AIOS_DATA_DIR") or self.get(
            "system", "data_dir", "/tmp/aios"
        )


def load_config(path: str | None = None) -> AiosConfig:
    """Defaults deep-merged with the TOML file when present."""
    path = path or os.environ.get("AIOS_CONFIG", DEFAULT_CONFIG_PATH)
    sections = _default_sections()
    source = ""
    p = Path(path)
    if p.is_file():
        try:
            loaded = tomllib.loads(p.read_text())
            for name, values in loaded.items():
                if isinstance(values, dict):
                    sections.setdefault(name, {}).update(values)
                else:
                    sections.setdefault("system", {})[name] = values
            source = str(p)
        except (OSError, ValueError):
            pass
    return AiosConfig(sections=sections, source_path=source)


def serving_env(cfg: "AiosConfig") -> Dict[str, str]:
    """Translate [models] serving knobs into the AIOS_TPU_* env the
    runtime/gateway/orchestrator children read (docs/CONFIG.md) — the
    boot-config analog of the reference's config.toml -> llama-server
    flag plumbing (initd/src/config.rs:14-34).

    Env beats config (the convention everywhere in this codebase): a knob
    the operator already exported is NOT injected, so config supplies
    defaults without clobbering an explicit override. A malformed value
    warns and is skipped — one bad tuning knob must not take down boot
    (the lenient pattern of model_manager's env parsers).
    """
    import logging

    log = logging.getLogger("aios.boot.config")
    m = cfg.section("models")
    env: Dict[str, str] = {}

    def put(key: str, value: str) -> None:
        if key in os.environ:
            log.info("%s already set in env; config value ignored", key)
        else:
            env[key] = value

    if str(m.get("quantize", "")) != "":
        put("AIOS_TPU_QUANTIZE", str(m["quantize"]))
    if m.get("kv_cache"):
        put("AIOS_TPU_KV_CACHE", str(m["kv_cache"]))
    paged = m.get("paged_kv_rows", "auto")
    if str(paged).strip().lower() == "auto":
        put("AIOS_TPU_PAGED_KV", "auto")
    else:
        try:
            rows = int(paged or 0)
        except (TypeError, ValueError):
            log.warning(
                "[models] paged_kv_rows=%r is not an integer or 'auto'; "
                "ignored", paged,
            )
            rows = 0
        if rows > 0:
            put("AIOS_TPU_PAGED_KV", str(rows))
    if m.get("mesh"):
        put("AIOS_TPU_MESH", str(m["mesh"]))
    if m.get("speculative"):
        put("AIOS_TPU_SPECULATIVE", "1")
    if m.get("draft_model"):
        put("AIOS_TPU_DRAFT_MODEL", str(m["draft_model"]))
    # tri-state decode-loop knobs: "" = unset (config/engine defaults
    # apply); an explicit false forwards too, so config can turn OFF a
    # ModelConfig.decode_pipeline/unified_step default
    for cfg_key, env_key in (
        ("decode_pipeline", "AIOS_TPU_DECODE_PIPELINE"),
        ("unified_step", "AIOS_TPU_UNIFIED_STEP"),
        ("jump_ahead", "AIOS_TPU_JUMP_AHEAD"),
        ("prefix_radix", "AIOS_TPU_PREFIX_RADIX"),
    ):
        raw = m.get(cfg_key, "")
        if raw in ("", None):
            continue
        truthy = str(raw).strip().lower() in ("1", "true", "on", "yes")
        put(env_key, "1" if truthy else "0")
    if m.get("json_mode"):
        put("AIOS_TPU_JSON_MODE", str(m["json_mode"]))
    if m.get("guided_toolcalls"):
        put("AIOS_TPU_GUIDED_TOOLCALLS", "1")
    # SLO autoscaling closed loop (docs/RUNBOOK.md §8): [models]
    # autoscale = true attaches the burn controller to every pool
    if m.get("autoscale"):
        put("AIOS_TPU_AUTOSCALE", "1")
    # serving-layer knobs (docs/SERVING.md): numeric; "" = unset (the
    # serving defaults apply). max_queue forwards an EXPLICIT 0 too —
    # it means unbounded, not "use the default bound".
    for cfg_key, env_key, zero_ok in (
        # prefix_host_bytes forwards an EXPLICIT 0 too — it means "host
        # tier off", overriding a ModelConfig.prefix_host_bytes default
        ("prefix_host_bytes", "AIOS_TPU_PREFIX_HOST_BYTES", True),
        ("host_restore_min_pages", "AIOS_TPU_HOST_RESTORE_MIN_PAGES", False),
        ("replicas", "AIOS_TPU_REPLICAS", False),
        ("tenant_tokens_per_sec", "AIOS_TPU_TENANT_TOKENS_PER_SEC", False),
        ("tenant_burst_tokens", "AIOS_TPU_TENANT_BURST_TOKENS", False),
        ("max_queue", "AIOS_TPU_MAX_QUEUE", True),
        ("assumed_tps", "AIOS_TPU_ASSUMED_TPS", False),
        # an explicit 0 forwards (it means "never auto-disable",
        # overriding a ModelConfig.spec_min_accept default)
        ("spec_min_accept", "AIOS_TPU_SPEC_MIN_ACCEPT", True),
        ("spec_reprobe_secs", "AIOS_TPU_SPEC_REPROBE_SECS", False),
        # failover_retries = 0 forwards (failover OFF, overriding the
        # serving default of 2)
        ("failover_retries", "AIOS_TPU_FAILOVER_RETRIES", True),
        ("failover_backoff_ms", "AIOS_TPU_FAILOVER_BACKOFF_MS", False),
        # long-context tier: an explicit kv_compress_after / seq_prefill
        # 0 forwards (compression / sp-sharded prefill OFF, overriding a
        # ModelConfig default)
        ("kv_compress_after", "AIOS_TPU_KV_COMPRESS_AFTER", True),
        ("kv_sink_pages", "AIOS_TPU_KV_SINK_PAGES", False),
        ("kv_window_pages", "AIOS_TPU_KV_WINDOW_PAGES", False),
        ("seq_prefill_min", "AIOS_TPU_SEQ_PREFILL_MIN", True),
        # an explicit 0 forwards (megagraph OFF, overriding a
        # ModelConfig.mega_ticks default)
        ("mega_ticks", "AIOS_TPU_MEGA_TICKS", True),
        # SLO autoscaler policy (serving/autoscale.py; only meaningful
        # with autoscale = true above)
        ("autoscale_max_replicas", "AIOS_TPU_AUTOSCALE_MAX_REPLICAS",
         False),
        ("autoscale_interval_secs", "AIOS_TPU_AUTOSCALE_INTERVAL_SECS",
         False),
        ("autoscale_up_burn", "AIOS_TPU_AUTOSCALE_UP_BURN", False),
        ("autoscale_down_burn", "AIOS_TPU_AUTOSCALE_DOWN_BURN", False),
        ("autoscale_hold_ticks", "AIOS_TPU_AUTOSCALE_HOLD_TICKS", False),
        # an explicit 0 forwards (cooldown OFF — hold ticks remain the
        # only damping)
        ("autoscale_cooldown_secs", "AIOS_TPU_AUTOSCALE_COOLDOWN_SECS",
         True),
    ):
        raw = m.get(cfg_key, "")
        if raw in ("", None):
            continue
        try:
            value = float(raw)
        except (TypeError, ValueError):
            log.warning("[models] %s=%r is not a number; ignored",
                        cfg_key, raw)
            continue
        if value > 0 or (value == 0 and zero_ok):
            put(env_key, str(int(value) if value == int(value) else value))
    # [faults]: deterministic fault injection (docs/FAULTS.md). The
    # schedule string IS the AIOS_TPU_FAULTS grammar; a separate `seed`
    # key prepends for convenience. Deliberately env-beats-config like
    # everything else — an operator running a live chaos drill via env
    # wins over a config left armed.
    f = cfg.section("faults")
    schedule = str(f.get("schedule", "") or "").strip()
    if schedule:
        seed = f.get("seed", "")
        if str(seed).strip() and "seed=" not in schedule:
            schedule = f"seed={seed};{schedule}"
        put("AIOS_TPU_FAULTS", schedule)
    return env
