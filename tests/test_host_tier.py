"""Host-RAM KV spill tier behind the prefix cache (ISSUE 4).

Evicting a prefix page used to throw its computed KV away; with a
HostPageStore configured the page spills device->host on eviction and a
later hash-chain hit restores it with a device_put + scatter instead of a
prefill forward pass. The engine-level tests prove the acceptance
contract: fill the cache -> force eviction -> resubmit -> the prefix rows
come back from the host tier (prefix_rows_restored, zero recompute for
the restored region) and the decoded output is token-identical to the
recompute path. The store/allocator units run in the fast tier (pure
Python, no jit).
"""

import time

import numpy as np
import pytest

from aios_tpu.engine.paged import (
    HOST_OVERLAP_DISCOUNT,
    HostPageStore,
    PageAllocator,
    PoolExhausted,
    PrefixIndex,
)


# ---------------------------------------------------------------------------
# HostPageStore units (fast tier)
# ---------------------------------------------------------------------------


def _entry(n_bytes=1024):
    return {"k": np.zeros(n_bytes // 2, np.int8),
            "v": np.zeros(n_bytes // 2, np.int8)}


def test_store_budget_evicts_lru():
    s = HostPageStore(max_bytes=3 * 1024)
    for h in (b"a", b"b", b"c"):
        s.put(h, _entry())
    assert s.bytes_resident == 3 * 1024
    s.match_chain([b"a"])  # refresh a: b becomes the LRU victim
    s.put(b"d", _entry())
    assert s.peek_chain([b"b"]) == 0  # evicted
    assert s.peek_chain([b"a"]) == 1
    assert s.bytes_resident == 3 * 1024
    assert s.spills == 4


def test_store_oversized_entry_rejected():
    s = HostPageStore(max_bytes=512)
    s.put(b"big", _entry(1024))
    assert len(s) == 0 and s.bytes_resident == 0


def test_store_match_chain_is_longest_prefix():
    s = HostPageStore(max_bytes=1 << 20)
    for h in (b"1", b"2", b"4"):
        s.put(h, _entry())
    got = s.match_chain([b"1", b"2", b"3", b"4"])
    assert [h for h, _ in got] == [b"1", b"2"]  # stops at the first miss
    assert s.hits == 1
    got = s.match_chain([b"9"])
    assert got == [] and s.misses == 1


def test_store_peek_does_not_touch_lru_or_counters():
    s = HostPageStore(max_bytes=2 * 1024)
    s.put(b"a", _entry())
    s.put(b"b", _entry())
    for _ in range(5):
        assert s.peek_chain([b"a", b"b"]) == 2
    assert s.hits == 0 and s.misses == 0
    # a was NOT refreshed by the peeks: it is still the LRU victim
    s.put(b"c", _entry())
    assert s.peek_chain([b"a"]) == 0


def test_store_discard_counts_restores():
    s = HostPageStore(max_bytes=1 << 20)
    s.put(b"a", _entry())
    s.put(b"b", _entry())
    s.discard([b"a"], restored=True)
    s.discard([b"b"])  # plain invalidation
    s.discard([b"missing"], restored=True)  # no-op
    assert s.restores == 1
    assert len(s) == 0 and s.bytes_resident == 0


# ---------------------------------------------------------------------------
# PageAllocator: refcount accessor + restore-path allocation (fast tier)
# ---------------------------------------------------------------------------


def test_allocator_refcount_accessor():
    a = PageAllocator(num_pages=5, page_size=16, num_slots=2, max_blocks=4)
    a.ensure(0, 16)
    page = int(a.tables[0, 0])
    assert a.refcount(page) == 1
    a.incref(page)
    assert a.refcount(page) == 2
    a.decref(page)
    a.free_slot(0)
    assert a.refcount(page) == 0  # back on the free list


def test_alloc_pages_and_append_owned():
    a = PageAllocator(num_pages=9, page_size=16, num_slots=2, max_blocks=8)
    shared = a.alloc_pages(1)
    a.map_shared(0, shared)  # rc 2: alloc_pages + map_shared
    fresh = a.alloc_pages(2)
    assert len(set(fresh) | set(shared)) == 3
    a.append_owned(0, fresh)
    assert a.slot_rows_backed(0) == 3 * 16
    assert [int(p) for p in a.tables[0, :3]] == shared + fresh
    for p in fresh:
        assert a.refcount(p) == 1
    with pytest.raises(PoolExhausted):
        a.alloc_pages(100)
    assert a.free_pages == 8 - 3  # failed alloc left nothing allocated
    a.free_slot(0)
    a.decref(shared[0])  # the alloc_pages reference
    assert a.free_pages == 8


def test_reclaim_uses_public_refcount_and_spills(monkeypatch):
    """PrefixIndex.reclaim goes through allocator.refcount() and hands
    evicted entries to the spill hook BEFORE their references drop."""
    a = PageAllocator(num_pages=6, page_size=16, num_slots=2, max_blocks=4)
    ix = PrefixIndex(a, max_pages=10)
    a.ensure(0, 3 * 16)
    pages = [int(p) for p in a.tables[0, :3]]
    ix.put([b"h1", b"h2", b"h3"], pages)
    a.free_slot(0)  # index now sole owner (rc 1 each)
    seen = []

    def spill(evicted):
        for h, p in evicted:
            assert a.refcount(p) == 1, "spill must run before the decref"
            seen.append((h, p))

    ix.spill = spill
    freed = ix.reclaim(2)
    assert freed == 2
    assert [h for h, _ in seen] == [b"h1", b"h2"]  # coldest first
    for _, p in seen:
        assert a.refcount(p) == 0  # freed after the capture


def test_spill_hook_failure_degrades_to_plain_eviction():
    a = PageAllocator(num_pages=4, page_size=16, num_slots=1, max_blocks=3)
    ix = PrefixIndex(a, max_pages=10)
    a.ensure(0, 2 * 16)
    pages = [int(p) for p in a.tables[0, :2]]
    ix.put([b"x", b"y"], pages)
    a.free_slot(0)

    def bad_spill(evicted):
        raise RuntimeError("host store broke")

    ix.spill = bad_spill
    assert ix.reclaim(2) == 2  # pages still freed, no exception
    assert a.free_pages == 3


# ---------------------------------------------------------------------------
# engine integration (slow tier, pattern of tests/test_paged.py)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def params():
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model
    from aios_tpu.engine.config import TINY_TEST

    return model.init_params(TINY_TEST, jax.random.PRNGKey(1),
                             dtype=jnp.float32)


def make_engine(params, host_bytes=64 << 20, **kw):
    import jax.numpy as jnp

    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    kw.setdefault("num_slots", 2)
    kw.setdefault("max_context", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("paged_pool_rows", 256)
    kw.setdefault("page_size", 32)
    return TPUEngine(TINY_TEST, params, prefix_host_bytes=host_bytes, **kw)


def _force_spill(eng, rng, min_entries=2, blocks=6):
    """Register a big disjoint prompt so the allocator's reclaim evicts
    (and spills) the coldest index entries, then wait for the spill
    worker to drain."""
    pressure = [int(t) for t in rng.integers(1, 500, blocks * 32 + 8)]
    eng.prefill(0, pressure, temperature=0.0)
    eng.release(0)
    deadline = time.time() + 20
    # wait for a FULL drain (_spill_pending == 0), not just min_entries:
    # a worker still landing the tail of a batch between a test's two
    # snapshots would skew counts taken at different times
    while (len(eng.host_store) < min_entries or eng._spill_pending) \
            and time.time() < deadline:
        time.sleep(0.02)
    assert len(eng.host_store) >= min_entries, "spill worker never drained"
    assert eng._spill_pending == 0, "spill backlog never drained"
    return pressure


@pytest.mark.slow
def test_spill_restore_token_identical(params):
    """THE acceptance path: fill prefix cache -> force eviction (spill)
    -> resubmit the same prompt -> rows restore from the host tier with
    zero prefill recompute for the restored region, and the decoded
    output is token-identical to the recompute path."""
    rng = np.random.default_rng(7)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]  # 3 full blocks

    ref_eng = make_engine(params, host_bytes=0)  # recompute path
    ref = ref_eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    ref_eng.close()

    eng = make_engine(params)
    assert eng.host_store is not None
    cold = eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    assert cold == ref
    assert eng.prefix_rows_restored == 0
    _force_spill(eng, rng)
    reused_before = eng.prefix_rows_reused
    again = eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    assert again == ref  # token-identical to the recompute path
    # the spilled region came back via the host tier, not prefill and
    # not the HBM index (its entries were evicted by the reclaim)
    assert eng.prefix_rows_restored >= 2 * 32
    assert eng.prefix_rows_reused == reused_before
    assert eng.host_store.restores >= 2
    stats = eng.stats()
    assert stats["prefix_rows_restored"] == eng.prefix_rows_restored
    assert stats["host_tier_restores"] >= 2
    eng.close()


@pytest.mark.slow
def test_restored_pages_reregister_in_hbm_index(params):
    """After a restore the hashes are back in the HBM index: a THIRD
    submission maps them as plain prefix pages (rows_reused moves,
    rows_restored does not)."""
    rng = np.random.default_rng(8)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    eng = make_engine(params)
    ref = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    _force_spill(eng, rng)
    assert eng.generate(prompt, max_new_tokens=16, temperature=0.0) == ref
    restored = eng.prefix_rows_restored
    assert restored > 0
    third = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    assert third == ref
    assert eng.prefix_rows_restored == restored  # no second restore
    assert eng.prefix_rows_reused >= restored  # HBM hit this time
    eng.close()


@pytest.mark.slow
def test_reclaim_spill_restore_interleaving_invariants(params):
    """Allocator-pressure reclaim + restore interleaving: pool exhaustion
    triggers reclaim(), evicted pages spill to host, a later request
    restores them, and refcounts/free-list stay consistent — no page is
    simultaneously free-listed and mapped."""
    rng = np.random.default_rng(9)
    eng = make_engine(params)
    prompts = [
        [int(t) for t in rng.integers(1, 500, 70 + 10 * i)] for i in range(4)
    ]
    for _ in range(3):  # several pressure/restore rounds
        for p in prompts:
            eng.prefill(0, p, temperature=0.0)
            eng.step(2)
            eng.release(0)
    deadline = time.time() + 20
    while eng._spill_pending and time.time() < deadline:
        time.sleep(0.02)
    alloc = eng.allocator
    free = set(alloc._free[0])
    indexed = set(eng.prefix_index.snapshot().values())
    mapped = set()
    for s in range(eng.num_slots):
        used = int(alloc._blocks_used[s])
        mapped.update(int(p) for p in alloc.tables[s, :used])
    # a free-listed page must not be mapped anywhere nor indexed
    assert not (free & indexed), (free, indexed)
    assert not (free & mapped), (free, mapped)
    for p in free:
        assert alloc.refcount(p) == 0
    for p in indexed:
        assert alloc.refcount(p) >= 1
    # accounting balances: every usable page is free or referenced
    usable = alloc.num_pages - alloc.replicas
    held = [p for p in range(1, alloc.local_pages) if alloc.refcount(p) > 0]
    assert len(free) + len(held) == usable
    assert eng.host_store.spills > 0 and eng.host_store.restores > 0
    eng.close()


@pytest.mark.slow
def test_restore_min_pages_floor(params):
    """A host chain shorter than the floor is skipped: the prompt
    prefills normally (still token-identical), nothing restores."""
    rng = np.random.default_rng(10)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]  # 3 full blocks
    eng = make_engine(params, host_restore_min_pages=8)
    ref = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    _force_spill(eng, rng)
    assert eng.generate(prompt, max_new_tokens=16, temperature=0.0) == ref
    assert eng.prefix_rows_restored == 0  # floor kept the tier out
    assert eng.host_store.restores == 0
    eng.close()


@pytest.mark.slow
def test_overlap_rows_credit_host_tier_at_discount(params):
    """The router's overlap probe scores host-resident rows at
    HOST_OVERLAP_DISCOUNT — lower than HBM residency, higher than
    nothing — without touching store LRU/counters."""
    rng = np.random.default_rng(11)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]  # 3 full blocks
    eng = make_engine(params)
    eng.prefill(0, prompt, temperature=0.0)
    eng.release(0)
    assert eng.prefix_overlap_rows(prompt) == 96  # all HBM
    _force_spill(eng, rng, min_entries=2)
    hits, misses = eng.host_store.hits, eng.host_store.misses
    rows = eng.prefix_overlap_rows(prompt)
    n_host = eng.host_store.peek_chain(eng.prefix_hashes(prompt))
    assert n_host >= 2
    assert rows == int(n_host * 32 * HOST_OVERLAP_DISCOUNT)
    assert 0 < rows < 96
    # read-only probe: no hit/miss movement
    assert (eng.host_store.hits, eng.host_store.misses) == (hits, misses)
    eng.close()


@pytest.mark.slow
def test_warmup_leaves_host_store_empty(params):
    eng = make_engine(params, paged_pool_rows=1024)
    eng.warmup(step_sizes=(1,))
    assert len(eng.host_store) == 0
    assert len(eng.prefix_index.snapshot()) == 0
    # the tier still works after warmup
    rng = np.random.default_rng(12)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    ref = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    assert eng.generate(prompt, max_new_tokens=8, temperature=0.0) == ref
    eng.close()


@pytest.mark.slow
def test_host_tier_disabled_without_budget(params):
    """No budget -> no store, no spill thread; eviction behaves exactly
    as before the tier existed."""
    eng = make_engine(params, host_bytes=0)
    assert eng.host_store is None and eng._spill_thread is None
    assert eng.prefix_index.spill is None
    rng = np.random.default_rng(13)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    ref = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    big = [int(t) for t in rng.integers(1, 500, 200)]
    eng.prefill(0, big, temperature=0.0)
    eng.release(0)
    assert eng.generate(prompt, max_new_tokens=8, temperature=0.0) == ref
    assert eng.prefix_rows_restored == 0
    eng.close()


@pytest.mark.slow
def test_int8_pool_spill_restore(params):
    """The int8 page pool spills and restores its scales alongside the
    quantized KV — output identical to the int8 recompute path."""
    import jax.numpy as jnp

    rng = np.random.default_rng(14)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    ref_eng = make_engine(params, host_bytes=0, cache_dtype=jnp.int8)
    ref = ref_eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    ref_eng.close()
    eng = make_engine(params, cache_dtype=jnp.int8)
    assert eng.generate(prompt, max_new_tokens=16, temperature=0.0) == ref
    _force_spill(eng, rng)
    entry = next(iter(eng.host_store._entries.values()))
    assert set(entry) == {"k", "v", "k_s", "v_s"}
    assert eng.generate(prompt, max_new_tokens=16, temperature=0.0) == ref
    assert eng.prefix_rows_restored > 0
    eng.close()
