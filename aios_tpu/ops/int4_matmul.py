"""Int4-weight matmul: packed nibbles stream from HBM, dequantize in VMEM.

The reference's production weight format is GGUF Q4_K_M — ~4.5 bits per
weight with group-wise scales (SURVEY.md section 7 hard-part #2). This
module is the serving-time equivalent for the TPU decode path: symmetric
int4 with one scale per ``group`` rows of the contraction dim per output
channel, which quarters the weight bytes streamed per decode step relative
to bf16 (and halves them relative to the int8 path in
``quantized_matmul.py``). Batched decode is HBM-bandwidth-bound on exactly
those bytes, so this is a direct throughput lever for the 7B tier.

Storage: two weight rows pack into one byte — within each group of
``group`` rows, byte ``r`` holds row ``r`` in the low nibble and row
``r + group/2`` in the high nibble, both offset-binary (q+8 in [0, 15]).
This split-half layout lets the kernel unpack a [group/2, N] byte tile
into a [group, N] int tile with a single sublane concatenate — no
interleave shuffle. Scales are one f32 per (group, output channel).

Native ``jnp.int4`` arrays are not used: this JAX build's int4 path is
unreliable on the CPU backend (array creation recurses), and packed uint8
gives the same HBM bytes with full control over the unpack.

The Pallas kernel dequantizes tile-by-tile in VMEM (scale applied on the
weight tile, one MXU dot per K-block); the jnp reference implementation is
the CPU fallback and the parity ground truth, mirroring the module layout
of ``quantized_matmul.py``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Rows of the contraction dim per scale. 128 divides every matmul dim of
# every supported model tier (engine/config.py) and matches the kernel's
# minimum K block, so each weight tile sees whole groups.
GROUP = 128


def pick_group(K: int) -> int:
    """Largest supported scale-group size dividing K (0 if none).

    128 everywhere it fits (it divides every matmul dim of every real
    model tier); smaller power-of-two groups keep the tiny test geometries
    on the same storage format via the jnp reference path.
    """
    for g in (GROUP, 64, 32, 16):
        if K % g == 0:
            return g
    return 0


def supports_int4(K: int, N: int, group: int = None) -> bool:
    """Whether a [K, N] weight can take the int4 serving *storage* layout."""
    g = pick_group(K) if group is None else group
    return g != 0 and K % g == 0 and g % 2 == 0


def kernel_supported(K: int, N: int, group: int) -> bool:
    """Whether the Pallas kernel can serve this layout (alignment: the
    K block equals the scale group, and both tiling dims are 128-lane)."""
    return group % GROUP == 0 and N % 128 == 0 and K % group == 0


# Clip-factor candidates for the per-group MSE search: pure round-to-
# nearest (1.0) plus mild clipping. Clipping the group absmax shrinks the
# quantization step for every inlier at the cost of saturating the few
# outliers — on gaussian-ish weight groups the MSE-optimal factor is
# usually 0.8-0.9, cutting RTN error ~20-30%.
CLIP_CANDIDATES = (1.0, 0.9, 0.8, 0.7)


def quantize_int4(
    w: jnp.ndarray, group: int = None, optimize_clip: bool = True
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Group-wise symmetric int4 quantization along the contraction dim.

    For ``w`` [..., K, N] returns (packed uint8 [..., K/2, N],
    scales f32 [..., K/group, 1, N]). Leading batch axes (stacked layers,
    stacked experts) pass through — and are mapped over one slice at a
    time so the clip search's temporaries stay at one layer's footprint.

    ``optimize_clip`` picks, per (group, output channel), the scale among
    ``CLIP_CANDIDATES * absmax / 7`` minimizing the squared reconstruction
    error (RTN-with-clip); disable for the exact legacy absmax behavior.
    """
    *lead, K, N = w.shape
    if group is None:
        group = pick_group(K)
    if not supports_int4(K, N, group):
        raise ValueError(f"no int4 group layout for weight shape {w.shape}")
    if lead:
        flat = w.reshape(-1, K, N)
        packed, scales = jax.lax.map(
            lambda x: quantize_int4(x, group, optimize_clip), flat
        )
        return (
            packed.reshape(*lead, K // 2, N),
            scales.reshape(*lead, K // group, 1, N),
        )
    wf = w.astype(jnp.float32).reshape(K // group, group, N)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    if optimize_clip:
        best_err = None
        best_scale = scale
        for c in CLIP_CANDIDATES:
            s = jnp.where(absmax > 0, c * absmax / 7.0, 1.0)
            qc = jnp.clip(jnp.round(wf / s), -8, 7)
            err = jnp.sum((wf - qc * s) ** 2, axis=-2, keepdims=True)
            if best_err is None:
                best_err, best_scale = err, s
            else:
                take = err < best_err
                best_err = jnp.where(take, err, best_err)
                best_scale = jnp.where(take, s, best_scale)
        scale = best_scale
    q = jnp.clip(jnp.round(wf / scale), -8, 7).astype(jnp.int32)
    # split-half packing within each group: low nibble rows [0, g/2),
    # high nibble rows [g/2, g)
    q = (q + 8).astype(jnp.uint8).reshape(*lead, K // group, 2, group // 2, N)
    packed = q[..., 0, :, :] | (q[..., 1, :, :] << 4)
    return packed.reshape(*lead, K // 2, N), scale.astype(jnp.float32)


def unpack_int4(packed: jnp.ndarray, group: int = GROUP) -> jnp.ndarray:
    """Packed uint8 [..., K/2, N] -> int8 [..., K, N] (no scales applied)."""
    *lead, Kh, N = packed.shape
    K = Kh * 2
    p = packed.reshape(*lead, K // group, group // 2, N).astype(jnp.int32)
    lo = (p & 0xF) - 8
    hi = (p >> 4) - 8
    w = jnp.concatenate([lo, hi], axis=-2)  # [..., K/group, group, N]
    return w.reshape(*lead, K, N).astype(jnp.int8)


def infer_group(packed: jnp.ndarray, scale: jnp.ndarray) -> int:
    """Recover the scale-group size from the leaf shapes (no metadata)."""
    return packed.shape[-2] * 2 // scale.shape[-3]


def dequantize_int4(
    packed: jnp.ndarray,
    scale: jnp.ndarray,
    group: int = None,
    dtype=jnp.bfloat16,
) -> jnp.ndarray:
    """Full dequantization (load/conversion paths, not the decode hot loop)."""
    *lead, Kh, N = packed.shape
    K = Kh * 2
    if group is None:
        group = infer_group(packed, scale)
    w = unpack_int4(packed, group).reshape(*lead, K // group, group, N)
    w = w.astype(jnp.float32) * scale
    return w.reshape(*lead, K, N).astype(dtype)


def _w4_kernel(x_ref, p_ref, s_ref, o_ref, acc_scr, *, group: int):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    bk2, bn = p_ref.shape  # [bk/2, bn] packed bytes; bk == group
    p = p_ref[:].astype(jnp.int32)
    lo = (p & 0xF) - 8
    hi = (p >> 4) - 8
    w = jnp.concatenate([lo, hi], axis=0)  # [bk, bn] int32, split-half order
    # scale on the weight tile (group-wise scales can't post-scale the acc);
    # the bf16 copy lives only in VMEM
    w = (w.astype(jnp.float32) * s_ref[0]).astype(x_ref.dtype)
    acc_scr[:] += jax.lax.dot_general(
        x_ref[:],
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)


def _pick_bn(N: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if N % c == 0:
            return c
    return 0


M_BLOCK = 256  # as quantized_matmul.M_BLOCK: bounds VMEM for prefill-sized M


@functools.partial(jax.jit, static_argnames=("group", "interpret"))
def _w4mm_2d(x, packed, scale, group=GROUP, interpret=False):
    M, K = x.shape
    N = packed.shape[1]
    bm = M if M <= M_BLOCK else M_BLOCK
    bk, bn = group, _pick_bn(N)
    grid = (M // bm, N // bn, K // bk)
    kernel = functools.partial(_w4_kernel, group=group)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, i, j: (m, j)),
            pl.BlockSpec((bk // 2, bn), lambda m, i, j: (j, i)),
            pl.BlockSpec((1, 1, bn), lambda m, i, j: (j, 0, i)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, i, j: (m, i)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, packed, scale)


def int4_matmul(
    x: jnp.ndarray,  # [..., K] activations (bf16/f32)
    packed: jnp.ndarray,  # [K/2, N] packed nibbles
    scale: jnp.ndarray,  # [K/group, 1, N] f32
    *,
    group: int = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(packed) without the dequantized weight touching HBM."""
    if group is None:
        group = infer_group(packed, scale)
    if not kernel_supported(packed.shape[0] * 2, packed.shape[1], group):
        raise ValueError(
            f"int4 kernel needs 128-aligned group/N (got group={group}, "
            f"shape {packed.shape}); use int4_matmul_reference"
        )
    K = packed.shape[0] * 2
    N = packed.shape[1]
    lead = x.shape[:-1]
    M = 1
    for d in lead:
        M *= d
    x2 = x.reshape(M, K)
    pad = (-M) % (8 if M <= M_BLOCK else M_BLOCK)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _w4mm_2d(x2, packed, scale, group=group, interpret=interpret)
    if pad:
        out = out[:M]
    return out.reshape(*lead, N)


def int4_matmul_reference(x, packed, scale, group: int = None):
    """Dequantize-then-matmul ground truth (CPU fallback).

    Dequantizes to bf16 exactly like the kernel's VMEM tile so parity
    tests compare like-for-like rounding.
    """
    if group is None:
        group = infer_group(packed, scale)
    w = dequantize_int4(packed, scale, group, dtype=jnp.bfloat16)
    y = jax.lax.dot_general(
        x.astype(jnp.bfloat16),
        w,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y.astype(x.dtype)
