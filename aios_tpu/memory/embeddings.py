"""Hash-projected bag-of-words embeddings + hybrid search scoring.

Reference parity: long-term memory embeds text as 64-dim hash-projected
bag-of-words vectors and searches with a cosine/keyword hybrid
(memory/src/longterm.rs:14-66). Same scheme here (vectorized in numpy):
each lowercase word hashes to a dimension and a sign; vectors are
L2-normalized; search scores are a blend of cosine similarity and keyword
overlap so exact term matches can't be drowned out by the projection noise.
"""

from __future__ import annotations

import hashlib
import re
from typing import List, Sequence

import numpy as np

DIM = 64
_WORD_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> List[str]:
    return _WORD_RE.findall(text.lower())


def _word_projection(word: str) -> tuple[int, float]:
    digest = hashlib.md5(word.encode("utf-8")).digest()
    dim = int.from_bytes(digest[:4], "little") % DIM
    sign = 1.0 if digest[4] & 1 else -1.0
    return dim, sign


def embed(text: str) -> np.ndarray:
    """64-dim L2-normalized hash embedding of ``text``."""
    v = np.zeros(DIM, dtype=np.float32)
    for word in _tokenize(text):
        dim, sign = _word_projection(word)
        v[dim] += sign
    norm = float(np.linalg.norm(v))
    if norm > 0:
        v /= norm
    return v


def cosine(a: np.ndarray, b: np.ndarray) -> float:
    denom = float(np.linalg.norm(a) * np.linalg.norm(b))
    if denom == 0:
        return 0.0
    return float(np.dot(a, b) / denom)


def keyword_overlap(query: str, text: str) -> float:
    q = set(_tokenize(query))
    if not q:
        return 0.0
    t = set(_tokenize(text))
    return len(q & t) / len(q)


def hybrid_score(query: str, query_vec: np.ndarray, text: str, vec: np.ndarray) -> float:
    """Blend of vector similarity and exact keyword overlap in [0, 1]."""
    cos = max(0.0, cosine(query_vec, vec))
    kw = keyword_overlap(query, text)
    return 0.5 * cos + 0.5 * kw


def rank(
    query: str, texts: Sequence[str], vecs: Sequence[np.ndarray]
) -> List[tuple[int, float]]:
    qv = embed(query)
    scored = [
        (i, hybrid_score(query, qv, texts[i], vecs[i])) for i in range(len(texts))
    ]
    scored.sort(key=lambda x: x[1], reverse=True)
    return scored
