"""Ragged batched-decode attention over the slot KV cache.

The decode hot loop attends one new query token per slot against that slot's
cache rows [0, length]. A naive XLA implementation reads the *entire*
[C, KH, D] cache for every slot every step; this kernel instead DMAs only
the blocks that contain valid rows (double-buffered HBM→VMEM, overlapping
copy with compute), so a slot that is 100 tokens into a 8192-row cache reads
~1% of the naive bandwidth. Sliding-window models additionally skip blocks
below the window start.

Layout: caches stay exactly as the engine stores them — [B, C, KH, D]
reshaped (free) to [B, C, KH*D] so VMEM tiles are lane-aligned. Grid is
(B,); each program owns one slot and runs the online-softmax recurrence over
its kv blocks with per-kv-head MXU dots.

ONE kernel body serves both cache dtypes (`quantized` is a trace-time
flag): bf16 caches stream as-is; int8 caches stream as int8 (half the HBM
bytes) with their per-(row, kv-head) scales DMA'd alongside and folded into
the score and value dots — s[g,c] = (q·k_i8)[g,c]·ks[c],
out = (p·vs) @ v_i8 — so the dequantized cache never materializes.

This is the TPU-native replacement for the per-request attention inside
llama.cpp's decode loop (SURVEY.md section 2.3 / section 3.2 "THE hot loop").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    len_ref,  # SMEM [B] int32
    q_ref,  # VMEM [1, H, D]
    k_hbm,  # ANY  [B, C, KH*D]  (bf16, or int8 when quantized)
    v_hbm,  # ANY  [B, C, KH*D]
    *rest,  # quantized: ks_hbm [B, KH, C] f32, vs_hbm [B, KH, C] f32, o_ref
    #         else: o_ref
    # (scales arrive head-major so the lane dim is the 128-aligned cache
    #  axis — a [.., C, KH] layout would DMA-slice KH lanes, which Mosaic
    #  rejects for KH < 128)
    num_kv_heads: int,
    head_dim: int,
    block_kv: int,
    window: Optional[int],
    sm_scale: float,
    quantized: bool = False,
):
    if quantized:
        ks_hbm, vs_hbm, o_ref = rest
    else:
        (o_ref,) = rest
    b = pl.program_id(0)
    KH, D, bk = num_kv_heads, head_dim, block_kv
    H = q_ref.shape[1]
    G = H // KH

    length = len_ref[b]  # row `length` holds the just-written token
    total = length + 1
    n_blk = pl.cdiv(total, bk)
    if window is not None:
        start_blk = jnp.maximum(total - window, 0) // bk
    else:
        start_blk = jnp.int32(0)

    if quantized:
        q = q_ref[0].astype(jnp.float32) * sm_scale  # [H, D]
    else:
        q = q_ref[0] * sm_scale

    def body(k_buf, v_buf, sems, ks_buf=None, vs_buf=None):
        def dma(buf_hbm, scr, slot, blk, sem_idx):
            return pltpu.make_async_copy(
                buf_hbm.at[b, pl.ds(blk * bk, bk)],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        def dma_scales(buf_hbm, scr, slot, blk, sem_idx):
            # head-major scales: slice the lane (cache) axis, heads full
            return pltpu.make_async_copy(
                buf_hbm.at[b, :, pl.ds(blk * bk, bk)],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        def start_all(slot, blk):
            dma(k_hbm, k_buf, slot, blk, 0).start()
            dma(v_hbm, v_buf, slot, blk, 1).start()
            if quantized:
                dma_scales(ks_hbm, ks_buf, slot, blk, 2).start()
                dma_scales(vs_hbm, vs_buf, slot, blk, 3).start()

        def wait_all(slot, blk):
            dma(k_hbm, k_buf, slot, blk, 0).wait()
            dma(v_hbm, v_buf, slot, blk, 1).wait()
            if quantized:
                dma_scales(ks_hbm, ks_buf, slot, blk, 2).wait()
                dma_scales(vs_hbm, vs_buf, slot, blk, 3).wait()

        start_all(0, start_blk)

        def loop(i, carry):
            m, l, acc = carry  # [H, 1], [H, 1], [H, D] f32
            slot = jax.lax.rem(i - start_blk, 2)

            @pl.when(i + 1 < n_blk)
            def _prefetch():
                start_all(1 - slot, i + 1)

            wait_all(slot, i)
            kb = k_buf[slot]  # [bk, KH*D]
            vb = v_buf[slot]
            ksb = ks_buf[slot] if quantized else None  # [KH, bk] f32
            vsb = vs_buf[slot] if quantized else None

            cols = i * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
            valid = cols <= length
            if window is not None:
                valid = jnp.logical_and(valid, cols > length - window)

            # scores for all H query heads, grouped by kv head
            parts = []
            for h in range(KH):
                qh = q[h * G : (h + 1) * G, :]  # [G, D]
                kh = kb[:, h * D : (h + 1) * D]  # [bk, D]
                if quantized:
                    kh = kh.astype(jnp.float32)
                sh = jax.lax.dot_general(
                    qh,
                    kh,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [G, bk] — int8 magnitudes are exact in f32
                if quantized:
                    sh = sh * ksb[h][None, :]
                parts.append(sh)
            s = jnp.concatenate(parts, axis=0)  # [H, bk]
            s = jnp.where(valid, s, NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new)  # [H, bk]
            p = jnp.where(valid, p, 0.0)  # fully-masked tile => p would be 1
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)

            outs = []
            pv = p if quantized else p.astype(vb.dtype)
            for h in range(KH):
                ph = pv[h * G : (h + 1) * G, :]  # [G, bk]
                if quantized:
                    ph = ph * vsb[h][None, :]
                vh = vb[:, h * D : (h + 1) * D]  # [bk, D]
                if quantized:
                    vh = vh.astype(jnp.float32)
                outs.append(
                    jax.lax.dot_general(
                        ph,
                        vh,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            acc_new = acc * alpha + jnp.concatenate(outs, axis=0)
            return m_new, l_new, acc_new

        init = (
            jnp.full((H, 1), NEG_INF, jnp.float32),
            jnp.zeros((H, 1), jnp.float32),
            jnp.zeros((H, D), jnp.float32),
        )
        m, l, acc = jax.lax.fori_loop(start_blk, n_blk, loop, init)
        safe_l = jnp.where(l <= 0.0, 1.0, l)
        o_ref[0] = (acc / safe_l).astype(o_ref.dtype)

    if quantized:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, bk, KH * D), jnp.int8),
            v_buf=pltpu.VMEM((2, bk, KH * D), jnp.int8),
            sems=pltpu.SemaphoreType.DMA((2, 4)),
            ks_buf=pltpu.VMEM((2, KH, bk), jnp.float32),
            vs_buf=pltpu.VMEM((2, KH, bk), jnp.float32),
        )
    else:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, bk, KH * D), k_hbm.dtype),
            v_buf=pltpu.VMEM((2, bk, KH * D), v_hbm.dtype),
            sems=pltpu.SemaphoreType.DMA((2, 2)),
        )


def pick_block_kv(C: int, preferred: int = 256) -> int:
    """Largest power-of-two block <= preferred that divides the cache."""
    bk = min(preferred, C)
    while bk > 1 and C % bk:
        bk //= 2
    return bk


def _ragged_call(q, k_cache, v_cache, lengths, scales, *, window, block_kv,
                 interpret):
    """Shared pallas_call plumbing for both cache dtypes."""
    B, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    bk = pick_block_kv(C) if block_kv is None else min(block_kv, C)
    if C % bk:
        raise ValueError(
            f"block_kv {bk} must evenly divide cache length {C}"
        )
    quantized = scales is not None
    if quantized and bk % 128 and not interpret:
        # Mosaic tiles lanes at 128: a smaller block would DMA-slice an
        # unaligned lane extent of the caches (interpret mode has no
        # such constraint and the tests use tiny blocks there)
        raise ValueError(
            f"int8 ragged kernel needs 128-aligned kv blocks, got {bk} "
            f"(cache length {C})"
        )
    kernel = functools.partial(
        _decode_kernel,
        num_kv_heads=KH,
        head_dim=D,
        block_kv=bk,
        window=window,
        sm_scale=1.0 / float(np.sqrt(D)),
        quantized=quantized,
    )
    cache_specs = [pl.BlockSpec(memory_space=pltpu.ANY)] * (
        2 + (2 if quantized else 0)
    )
    args = [
        lengths.astype(jnp.int32),
        q,
        k_cache.reshape(B, C, KH * D),
        v_cache.reshape(B, C, KH * D),
    ]
    if quantized:
        # engine stores scales [B, C, KH]; the kernel wants them head-major
        # [B, KH, C] so its DMA slices the 128-aligned cache axis on lanes.
        # The transpose costs ~3% of one int8 cache sweep (f32 scales are
        # 4/D of the cache bytes) — second-order next to the ragged win.
        args.extend(s.transpose(0, 2, 1) for s in scales)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths
            pl.BlockSpec((1, H, D), lambda b: (b, 0, 0)),
            *cache_specs,  # caches (+ scales) stay in HBM
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b: (b, 0, 0)),
        interpret=interpret,
    )(*args)


@functools.partial(
    jax.jit, static_argnames=("window", "block_kv", "interpret")
)
def decode_attention(
    q: jnp.ndarray,  # [B, H, D] — one new query per slot
    k_cache: jnp.ndarray,  # [B, C, KH, D]
    v_cache: jnp.ndarray,  # [B, C, KH, D]
    lengths: jnp.ndarray,  # [B] int32; row `lengths[b]` is the newest token
    *,
    window: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged decode attention; returns [B, H, D]."""
    return _ragged_call(
        q, k_cache, v_cache, lengths, None,
        window=window, block_kv=block_kv, interpret=interpret,
    )


@functools.partial(
    jax.jit, static_argnames=("window", "block_kv", "interpret")
)
def decode_attention_int8(
    q: jnp.ndarray,  # [B, H, D] — one new query per slot
    k_cache: jnp.ndarray,  # [B, C, KH, D] int8
    v_cache: jnp.ndarray,  # [B, C, KH, D] int8
    k_scales: jnp.ndarray,  # [B, C, KH] f32
    v_scales: jnp.ndarray,  # [B, C, KH] f32
    lengths: jnp.ndarray,  # [B] int32
    *,
    window: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged decode attention over an INT8 KV cache; returns [B, H, D]."""
    return _ragged_call(
        q, k_cache, v_cache, lengths, (k_scales, v_scales),
        window=window, block_kv=block_kv, interpret=interpret,
    )


def decode_attention_int8_reference(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,  # [B, C, KH, D] int8
    v_cache: jnp.ndarray,
    k_scales: jnp.ndarray,  # [B, C, KH] f32
    v_scales: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dequantize-then-attend ground truth for the int8 kernel (fp32)."""
    kf = k_cache.astype(jnp.float32) * k_scales[..., None]
    vf = v_cache.astype(jnp.float32) * v_scales[..., None]
    return decode_attention_reference(
        q, kf, vf, lengths, window=window
    )


def decode_attention_reference(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Naive jnp ragged decode attention (CPU fallback + parity truth)."""
    B, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    G = H // KH
    qg = q.reshape(B, KH, G, D)
    s = jnp.einsum("bkgd,bckd->bkgc", qg, k_cache).astype(jnp.float32)
    s = s / np.sqrt(D)
    cols = jnp.arange(C)[None, :]
    mask = cols <= lengths[:, None]
    if window is not None:
        mask = mask & (cols > lengths[:, None] - window)
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgc,bckd->bkgd", p, v_cache)
    return out.reshape(B, H, D)
