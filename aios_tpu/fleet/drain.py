"""Graceful drain: hand the host's work to the fleet, then leave.

A kill -9 is already survivable — PR 17's resume ladder re-hands every
stream a dead decode host was holding, and the membership plane marks
the corpse ``dead`` after a timeout. But survivable is not graceful:
the sources eat a full suspect/dead detection window, in-flight KV
pushes hit a black hole, and the host's hot radix chains die with it.
This module is the cooperative exit: ``POST /fleet/drain`` (or
``fleetctl drain``) walks the host through the closed
:data:`DRAIN_PHASES` ladder —

    serving    normal operation (the implicit phase of every healthy
               host; descriptors omit nothing — peers treat a missing
               phase as "serving")
    draining   admission sheds NEW work with the closed
               ``draining_host`` cause; the Handoff servicer refuses
               new handoffs and aborts LIVE handoff streams UNAVAILABLE
               so each source's resume ladder re-hands prompt+emitted
               to a survivor (tokens already relayed are never lost);
               local pools drain; hot radix chains push through kvx to
               the least-loaded surviving peer
    leaving    terminal: the descriptor announces ``phase=leaving`` so
               peers stop routing to this host *before* it dies, then
               the process exits 0

The protocol runs on a worker thread — the HTTP handler that triggered
it answers 202 immediately. ``request_drain`` is idempotent: a second
POST while draining reports the current phase instead of starting a
second protocol. Routers (``pick_decode``, ``gprefix.best_peer``) skip
any peer whose phase is not "serving", so the announce at phase flip is
the fleet-visible half of the contract.

Knobs (docs/CONFIG.md "Fleet fault domain"):
``AIOS_TPU_FLEET_DRAIN_TIMEOUT_SECS`` bounds the pool-drain wait;
``AIOS_TPU_FLEET_DRAIN_PUSH_BYTES`` bounds the hot-chain push (0
disables it).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..analysis.locks import make_lock

log = logging.getLogger("aios.fleet.drain")

__all__ = [
    "DRAIN_PHASES", "DrainCoordinator", "arm", "disarm", "phase",
    "draining", "request_drain",
]

# THE closed drain-phase enum (pinned by test_obs_lint): descriptor
# "phase" values and the /fleet/drain response vocabulary.
DRAIN_PHASES = ("serving", "draining", "leaving")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def drain_timeout_secs() -> float:
    """Bound on the in-flight pool-drain wait
    (AIOS_TPU_FLEET_DRAIN_TIMEOUT_SECS); past it the host leaves anyway
    — the sources' resume ladder covers whatever was cut."""
    return max(_env_float("AIOS_TPU_FLEET_DRAIN_TIMEOUT_SECS", 10.0), 0.0)


def drain_push_bytes() -> int:
    """Byte budget for the farewell hot-chain push
    (AIOS_TPU_FLEET_DRAIN_PUSH_BYTES, 0 disables): the leaving host's
    hottest cached pages move to a survivor so the fleet keeps the
    cache warmth this host accumulated."""
    return max(int(_env_float("AIOS_TPU_FLEET_DRAIN_PUSH_BYTES",
                              float(32 << 20))), 0)


class DrainCoordinator:
    """Per-process drain state machine. The lock guards ONLY the phase
    flag and thread handle — the protocol itself (pool drains, kvx
    pushes, announces) runs on the worker thread outside every lock."""

    def __init__(self, manager,
                 exit_fn: Callable[[int], None] = os._exit) -> None:
        self.manager = manager
        self.exit_fn = exit_fn
        self._lock = make_lock("drain")
        #: guarded_by _lock
        self._phase = "serving"
        #: guarded_by _lock
        self._thread: Optional[threading.Thread] = None

    def phase(self) -> str:
        with self._lock:
            return self._phase

    def request_drain(self, timeout_s: Optional[float] = None) -> str:
        """Start (or report) the drain. Idempotent: the first call flips
        serving->draining and spawns the protocol thread; later calls
        just return the current phase."""
        with self._lock:
            if self._phase != "serving":
                return self._phase
            self._phase = "draining"
            t = threading.Thread(
                target=self._run,
                args=(drain_timeout_secs() if timeout_s is None
                      else max(float(timeout_s), 0.0),),
                name="fleet-drain", daemon=True,
            )
            self._thread = t
        self._event("draining")
        t.start()
        return "draining"

    # -- the protocol (worker thread; no locks held across any step) --------

    def _run(self, timeout_s: float) -> None:
        from ..serving import admission

        log.warning("graceful drain started (timeout %.1fs)", timeout_s)
        # 1. close the front door: every pool sheds NEW admissions with
        #    the closed draining_host cause; live handoff streams abort
        #    at the servicer's per-token check (the abort IS the signal
        #    that drives each source's resume ladder)
        admission.set_host_draining(True)
        # 2. wait for local in-flight streams to finish (bounded — past
        #    the timeout the host leaves and failover covers the rest)
        for m in self._ready_models():
            if m.pool is not None:
                left = timeout_s
                t0 = time.monotonic()
                m.pool.drain(max(left, 0.01))
                timeout_s = max(timeout_s - (time.monotonic() - t0), 0.0)
        # 3. farewell push: move the hottest cached chains to the
        #    least-loaded survivor so the warmth survives the host
        try:
            self._push_hot_chains()
        except Exception:  # noqa: BLE001 - the push is best-effort by
            # design; a failed farewell must never block the exit
            log.exception("drain hot-chain push failed; leaving anyway")
        # 4. terminal announce: peers see phase=leaving and stop routing
        #    here before the process dies
        with self._lock:
            self._phase = "leaving"
        self._event("leaving")
        self._announce()
        log.warning("graceful drain complete; exiting 0")
        self.exit_fn(0)

    def _ready_models(self) -> list:
        try:
            return list(self.manager.ready_models())
        except Exception:  # noqa: BLE001 - a torn-down manager mid-exit
            return []

    def _push_hot_chains(self) -> None:
        """Export this host's most-recently-used cached pages (HBM
        chains first, then the host spill tier) and push them to one
        surviving peer, bounded by the drain push-bytes budget."""
        from . import disagg, kvx

        budget = drain_push_bytes()
        if budget <= 0:
            return
        plane = disagg.PLANE
        if plane is None:
            return
        for m in self._ready_models():
            engine = m.engine
            if engine is None:
                continue
            target = plane.pick_decode(m.name)
            if target is None:
                log.warning("%s: no surviving peer for the drain push",
                            m.name)
                continue
            host, addr = target
            pairs, total = self._collect_hot(engine, budget)
            if not pairs:
                log.warning("%s: no hot pages to push on drain", m.name)
                continue
            accepted = kvx.push_chain(addr, m.name, pairs, peer=host)
            # warning on purpose: this is the last operationally
            # significant act of a dying host, and smoke harnesses read
            # it off stderr after the exit
            log.warning(
                "%s: drain push moved %d/%d hot pages (%.1f MB) to %s",
                m.name, accepted, len(pairs), total / 1e6, host,
            )

    @staticmethod
    def _collect_hot(engine, budget_bytes: int
                     ) -> Tuple[List[tuple], int]:
        """(hash, entry) pairs for the engine's hottest pages within the
        byte budget. Per-hash exports (chains of length one): the
        digest's iteration order need not be chain order, and content
        addressing means the receiver reassembles prefixes itself."""
        pairs: List[tuple] = []
        total = 0
        seen = set()
        hbm = []
        if getattr(engine, "prefix_index", None) is not None:
            hbm = [h for h, _ in engine.prefix_index.digest(256)]
        for h in hbm:
            if total >= budget_bytes:
                return pairs, total
            for hh, entry in engine.export_hashes([h], max_pages=1):
                nb = sum(int(a.nbytes) for a in entry.values())
                if pairs and total + nb > budget_bytes:
                    return pairs, total
                pairs.append((hh, entry))
                seen.add(hh)
                total += nb
        store = getattr(engine, "host_store", None)
        if store is not None:
            for h in reversed(store.stored_hashes(256)):  # MRU first
                if h in seen:
                    continue
                if total >= budget_bytes:
                    break
                for hh, _crc, entry in store.export_chain([h]):
                    nb = sum(int(a.nbytes) for a in entry.values())
                    if pairs and total + nb > budget_bytes:
                        return pairs, total
                    pairs.append((hh, entry))
                    total += nb
        return pairs, total

    def _announce(self) -> None:
        from ..obs import fleet

        reg = fleet.FLEET
        if reg is not None:
            try:
                reg.announce_once()
            except Exception:  # noqa: BLE001 - partitioned peers must
                # not block the exit; they will mark us dead on their own
                log.exception("drain farewell announce failed")

    def _event(self, to: str) -> None:
        from ..obs import flightrec

        flightrec.RECORDER.model_event(
            "fleet", "drain", phase=to,
        )
        log.warning("drain phase -> %s", to)


# -- process-wide coordinator ------------------------------------------------

COORD: Optional[DrainCoordinator] = None


def arm(manager, exit_fn: Callable[[int], None] = os._exit
        ) -> DrainCoordinator:
    """Arm the drain coordinator (runtime serve() calls this alongside
    the data plane); ``exit_fn`` is injectable for tests."""
    global COORD
    COORD = DrainCoordinator(manager, exit_fn=exit_fn)
    return COORD


def disarm() -> None:
    """Test isolation."""
    global COORD
    COORD = None


def phase() -> str:
    """The host's drain phase — "serving" whenever the coordinator is
    unarmed (solo host), so descriptors stay honest for free."""
    c = COORD
    return c.phase() if c is not None else "serving"


def draining() -> bool:
    """True once a drain has started (draining or leaving) — the
    Handoff servicer's refuse/abort gate."""
    return phase() != "serving"


def request_drain(timeout_s: Optional[float] = None) -> str:
    """Module-level front door for the HTTP route; returns the phase
    (or "serving" with a log when nothing is armed)."""
    c = COORD
    if c is None:
        log.warning("drain requested but no coordinator is armed")
        return "serving"
    return c.request_drain(timeout_s)
