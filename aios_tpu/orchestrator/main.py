"""Orchestrator process wiring: service + background loops.

Reference parity (agent-core/src/main.rs:592-798): builds the shared state
and spawns the background loops — management console, health checker, agent
spawner, autonomy loop, proactive generator, scheduler, event bus, cluster
prune — then serves gRPC on :50051. All cross-service calls go through
gRPC stubs exactly as the reference's ServiceClients do.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

import grpc

from ..proto_gen import api_gateway_pb2, memory_pb2, runtime_pb2, tools_pb2
from .agent_router import AgentRouter
from .autonomy import (
    TOKEN_BUDGETS,
    AutonomyConfig,
    AutonomyLoop,
    InferenceCancelled,
)
from .clients import HealthChecker, ServiceClients, ServiceRegistry
from .cluster import ClusterManager, RemoteExecutor
from .event_bus import EventBus, Subscription
from .goal_engine import GoalEngine
from .management import ManagementConsole
from .proactive import ProactiveGenerator
from .scheduler import GoalScheduler
from .service import OrchestratorService, serve
from .task_planner import TACTICAL, TaskPlanner
from .telemetry import DecisionLogger, ResultAggregator

log = logging.getLogger("aios.orchestrator.main")


def build_orchestrator(
    data_dir: str = "/tmp/aios/orchestrator",
    clients: Optional[ServiceClients] = None,
    autonomy_config: Optional[AutonomyConfig] = None,
):
    """Construct the full orchestrator state (no sockets yet)."""
    os.makedirs(data_dir, exist_ok=True)
    clients = clients or ServiceClients()

    # --- gRPC glue ---------------------------------------------------------

    def _infer_future(method, request, cancel_event):
        """Run a unary infer as a cancellable future: when cancel_event
        fires (CancelGoal mid-inference), cancel the gRPC call — the
        server's RPC-termination callback then aborts the downstream
        decode/cloud call — and raise InferenceCancelled so the autonomy
        loop stops without recording a failure."""
        fut = method.future(request, timeout=150)
        if cancel_event is None:
            return fut.result()
        while True:
            if cancel_event.is_set():
                fut.cancel()
                raise InferenceCancelled()
            try:
                return fut.result(timeout=0.1)
            except grpc.FutureTimeoutError:
                continue

    def gateway_infer(prompt: str, level: str = "", max_tokens: int = 0,
                      json_schema: str = "", cancel_event=None) -> str:
        """max_tokens carries the autonomy loop's per-level reasoning budget
        (autonomy.TOKEN_BUDGETS; reference autonomy.rs:596-607);
        json_schema the guided tool_calls shape (AIOS_TPU_GUIDED_TOOLCALLS),
        honored by the local TPU provider; cancel_event aborts the call
        mid-flight when its goal is cancelled."""
        resp = _infer_future(
            clients.gateway.Infer,
            api_gateway_pb2.ApiInferRequest(
                prompt=prompt,
                max_tokens=max_tokens,
                preferred_provider=(autonomy_config or AutonomyConfig()).preferred_provider,
                allow_fallback=True,
                requesting_agent="autonomy-loop",
                json_schema=json_schema,
            ),
            cancel_event,
        )
        return resp.text

    def runtime_infer(prompt: str, level: str = "", max_tokens: int = 0,
                      json_schema: str = "", cancel_event=None) -> str:
        resp = _infer_future(
            clients.runtime.Infer,
            runtime_pb2.InferRequest(
                prompt=prompt,
                max_tokens=max_tokens,
                intelligence_level=level or "tactical",
                requesting_agent="autonomy-loop",
                json_schema=json_schema,
            ),
            cancel_event,
        )
        return resp.text

    def execute_tool(tool: str, agent_id: str, args: dict) -> dict:
        resp = clients.tools.Execute(
            tools_pb2.ExecuteRequest(
                tool_name=tool,
                agent_id=agent_id,
                input_json=json.dumps(args).encode(),
                reason="autonomy",
            ),
            timeout=120,
        )
        output = {}
        if resp.output_json:
            try:
                output = json.loads(resp.output_json)
            except ValueError:
                pass
        return {"success": resp.success, "output": output, "error": resp.error}

    def memory_context(description: str, max_tokens: int) -> str:
        try:
            resp = clients.memory.AssembleContext(
                memory_pb2.ContextRequest(
                    task_description=description, max_tokens=max_tokens
                ),
                timeout=5,
            )
            return "\n".join(f"[{c.source}] {c.content}" for c in resp.chunks)
        except grpc.RpcError:
            return ""

    def tool_catalog() -> list:
        try:
            resp = clients.tools.ListTools(
                tools_pb2.ListToolsRequest(), timeout=5
            )
            return [t.name for t in resp.tools]
        except grpc.RpcError:
            return []

    def loaded_models() -> list:
        try:
            from ..proto_gen import common_pb2

            resp = clients.runtime.ListModels(common_pb2.Empty(), timeout=5)
            return [m.model_name for m in resp.models if m.status == "ready"]
        except grpc.RpcError:
            return []

    def serving_stats() -> dict:
        """Per-model serving counters from the runtime HealthCheck's
        `<model>.serving` detail strings ("k=v,k=v") — the proactive
        generator's pool-exhaustion / slot-starvation feed."""
        try:
            from ..proto_gen import common_pb2

            resp = clients.runtime.HealthCheck(common_pb2.Empty(), timeout=5)
        except grpc.RpcError:
            return {}
        out: dict = {}
        for key, raw in resp.details.items():
            if not key.endswith(".serving"):
                continue
            stats: dict = {}
            for pair in raw.split(","):
                k, _, v = pair.partition("=")
                try:
                    stats[k] = float(v)
                except ValueError:
                    continue
            out[key[: -len(".serving")]] = stats
        return out

    # --- components --------------------------------------------------------

    engine = GoalEngine(os.path.join(data_dir, "goals.db"))
    engine.recover()
    # planner decomposition runs at the tactical budget (8192 tokens)
    _plan_budget = TOKEN_BUDGETS[TACTICAL]
    planner = TaskPlanner(
        gateway_infer=lambda p: gateway_infer(p, TACTICAL, _plan_budget),
        runtime_infer=lambda p: runtime_infer(p, TACTICAL, _plan_budget),
    )
    router = AgentRouter()
    cluster = ClusterManager()
    aggregator = ResultAggregator()
    decisions = DecisionLogger()
    autonomy = AutonomyLoop(
        engine=engine,
        planner=planner,
        router=router,
        execute_tool=execute_tool,
        gateway_infer=gateway_infer,
        runtime_infer=runtime_infer,
        memory_context=memory_context,
        tool_catalog=tool_catalog,
        aggregator=aggregator,
        decisions=decisions,
        cluster=cluster,
        remote=RemoteExecutor(),
        config=autonomy_config,
    )
    scheduler = GoalScheduler(
        lambda d, p: engine.submit_goal(d, p, source="scheduler"),
        db_path=os.path.join(data_dir, "scheduler.db"),
    )
    event_bus = EventBus(
        submit_goal=lambda d, p: engine.submit_goal(d, p, source="event")
    )
    event_bus.subscribe(Subscription(
        pattern="service.unhealthy",
        min_severity="error",
        goal_template="Remediate unhealthy service reported by {source}",
        priority=9,
    ))
    from .event_bus import Event

    def _on_health_failure(name: str, failures: int) -> None:
        # >= 6 consecutive failures becomes a remediation goal via the bus
        # (proactive.rs:144-159 threshold)
        if failures >= 6:
            event_bus.publish(Event(
                "service.unhealthy", name, severity="error",
                data={"failures": failures},
            ))

    health = HealthChecker(on_failure=_on_health_failure)
    proactive = ProactiveGenerator(
        submit_goal=lambda d, p: engine.submit_goal(d, p, source="proactive"),
        active_goal_descriptions=lambda: [
            g.description for g in engine.active_goals()
        ],
        health_failures=health.failure_snapshot,
        failed_agents=lambda: [a.agent_id for a in router.dead_agents()],
        serving_stats=serving_stats,
    )
    service = OrchestratorService(
        engine=engine,
        planner=planner,
        router=router,
        autonomy=autonomy,
        scheduler=scheduler,
        cluster=cluster,
        aggregator=aggregator,
        loaded_models=loaded_models,
    )
    # run()'s console needs the same runtime-counters feed the proactive
    # generator uses — return it explicitly so the wiring stays fail-fast
    # (an ad-hoc service attribute + getattr fallback would degrade to a
    # silent None feed on the next refactor)
    return (service, autonomy, scheduler, proactive, health, event_bus,
            serving_stats)


def run(
    data_dir: str = "/tmp/aios/orchestrator",
    grpc_address: Optional[str] = None,
    console_port: int = 9090,
    spawn_agents: bool = True,
    block: bool = True,
):
    """Boot the full orchestrator process (main.rs:592-798 equivalent)."""
    (service, autonomy, scheduler, proactive, health, _bus,
     serving_stats) = build_orchestrator(data_dir)
    autonomy.start()
    scheduler.start()
    proactive.start()
    health.start()
    console = ManagementConsole(
        service, port=console_port, serving_stats=serving_stats,
        service_health=lambda: {
            name: fails == 0
            for name, fails in health.failure_snapshot().items()
        },
    )
    console.start()

    spawner = None
    if spawn_agents:
        from ..agents.spawner import AgentSpawner

        spawner = AgentSpawner()
        spawner.start()

    server, service, port = serve(address=grpc_address, service=service,
                                  block=False)
    log.info("orchestrator up: grpc :%s console :%s", port, console.bound_port)

    def shutdown():
        """Stop every loop run() started (embedders/tests; the supervisor
        child never calls it — it dies with the process)."""
        autonomy.stop()
        scheduler.stop()
        proactive.stop()
        health.stop()
        if spawner is not None:
            spawner.stop()
        console.stop()
        server.stop(grace=None)

    if block:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return server, service, console, autonomy, spawner, shutdown


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    run(data_dir=os.environ.get("AIOS_DATA_DIR", "/tmp/aios/orchestrator"))
