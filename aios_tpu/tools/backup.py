"""Pre-execution backups + rollback for reversible tools.

Reference parity (tools/src/backup.rs): before a reversible tool runs, the
affected file/directory is copied into the backup cache keyed by execution
id; `rollback(execution_id)` restores it.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from pathlib import Path
from typing import Dict, Optional


class BackupManager:
    def __init__(self, backup_dir: str = "/tmp/aios/backups"):
        self.backup_dir = Path(backup_dir)
        self.backup_dir.mkdir(parents=True, exist_ok=True)
        self._index: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._load_index()

    def _index_path(self) -> Path:
        return self.backup_dir / "index.json"

    def _load_index(self) -> None:
        try:
            self._index = json.loads(self._index_path().read_text())
        except (OSError, ValueError):
            self._index = {}

    def _save_index(self) -> None:
        self._index_path().write_text(json.dumps(self._index))

    def backup_path_for(self, execution_id: str, target: str) -> Optional[str]:
        """Snapshot ``target`` (file or dir) before a reversible mutation."""
        src = Path(target)
        if not src.exists():
            # record intent so rollback can delete a newly-created path
            with self._lock:
                self._index[execution_id] = {
                    "target": target,
                    "backup": "",
                    "existed": False,
                    "timestamp": time.time(),
                }
                self._save_index()
            return None
        dest = self.backup_dir / f"{execution_id}-{uuid.uuid4().hex[:8]}"
        if src.is_dir():
            shutil.copytree(src, dest)
        else:
            shutil.copy2(src, dest)
        with self._lock:
            self._index[execution_id] = {
                "target": target,
                "backup": str(dest),
                "existed": True,
                "timestamp": time.time(),
            }
            self._save_index()
        return str(dest)

    def rollback(self, execution_id: str) -> tuple[bool, str]:
        with self._lock:
            entry = self._index.get(execution_id)
        if entry is None:
            return False, f"no backup recorded for execution {execution_id}"
        target = Path(entry["target"])
        if not entry["existed"]:
            # target did not exist before -> undo means delete
            if target.is_dir():
                shutil.rmtree(target, ignore_errors=True)
            elif target.exists():
                target.unlink()
            return True, f"removed {target}"
        backup = Path(entry["backup"])
        if not backup.exists():
            return False, f"backup blob missing for {execution_id}"
        if target.exists():
            if target.is_dir():
                shutil.rmtree(target)
            else:
                target.unlink()
        if backup.is_dir():
            shutil.copytree(backup, target)
        else:
            os.makedirs(target.parent, exist_ok=True)
            shutil.copy2(backup, target)
        return True, f"restored {target}"

    def has_backup(self, execution_id: str) -> bool:
        with self._lock:
            return execution_id in self._index
