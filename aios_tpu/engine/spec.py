"""Device-side n-gram (prompt-lookup) speculative decoding.

Batched decode on TPU is HBM-bandwidth-bound on the *weights*: a decode
step streams every matmul weight once whether it scores 1 token or 8 per
slot. So verifying K draft tokens in one ``model.verify_step`` costs about
the same wall-clock as a single decode step, and every accepted draft is a
nearly free token. This module supplies the drafts and the acceptance rule;
the whole loop — propose, verify, accept, update — runs on device under
``lax.scan`` (engine.TPUEngine.spec_step), so R speculative rounds are ONE
dispatch with no host round-trips in between.

Drafts come from prompt-lookup (n-gram matching against the slot's own
token history), which needs no draft model and shines on exactly the
workload the reference serves: agent loops re-emitting JSON tool calls,
file contents, and quoted context (SURVEY.md section 3.1 — tool results are
fed back into the next reasoning round verbatim). The token history is a
device-resident ``[S, C+pad]`` int32 buffer carried in the engine's decode
state; the proposer is a vectorized compare over it.

Acceptance is exact for greedy slots (temperature < GREEDY_EPS): a draft
token is accepted iff it equals the model's own argmax at that position, so
speculative greedy decoding emits the identical token sequence as plain
greedy decoding, just in fewer dispatches. Slots sampling at temperature > 0
simply don't speculate — they emit their usual 1 sampled token per round
from the first logits row, which is numerically a plain decode step. The
two kinds of slots mix freely in one batch.

Reference equivalence: llama.cpp's ``--draft``/lookup decoding behind
llama-server (SURVEY.md section 2.3); built TPU-first instead of ported.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# The closed proposer enum: every aios_tpu_spec_* metric's ``proposer``
# label, the batcher's fallback ladder (draft -> ngram) and the per-
# proposer EWMA dicts all iterate THIS tuple — obs-lint pins it so the
# label can never grow an unbounded dimension.
SPEC_PROPOSERS = ("ngram", "draft")

# Extra columns appended to the history buffer beyond max_context so the
# post-verify scatter (rows lengths+1 .. lengths+1+K) never has to clamp —
# clamping would collide several writes onto one column, and scatter order
# for duplicate indices is undefined. Bounds the draft length.
HISTORY_PAD = 32


def init_history(num_slots: int, max_context: int) -> jnp.ndarray:
    """Device token-history buffer. Invariant maintained by the engine:
    ``history[s, 0:lengths[s]] `` are the tokens whose K/V sit in cache rows
    ``[0, lengths[s])`` and ``history[s, lengths[s]]`` is the pending
    ``last_tokens[s]``. Columns beyond that are garbage."""
    return jnp.zeros((num_slots, max_context + HISTORY_PAD), jnp.int32)


def propose_ngram(
    history: jnp.ndarray,  # [S, C+pad] int32
    lengths: jnp.ndarray,  # [S] int32 — history[0:lengths+1) is known
    draft_len: int,
    ngram: int,
    max_context: int,
    min_pos: Optional[jnp.ndarray] = None,  # [S] int32 search floor
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Propose up to ``draft_len`` tokens per slot by prompt lookup.

    Finds the most recent earlier occurrence of the trailing ``ngram``
    tokens (ending at the pending last token, history col ``lengths``) and
    proposes the tokens that followed it. Vectorized over slots and match
    positions — one fused compare/reduce, no host involvement.

    ``min_pos`` clamps the match search to positions >= min_pos[s] — the
    window+sink KV compression guard: a pruned slot's proposals must come
    from its LIVE trailing window (the engine passes the slot's window
    start), never from context the serving attention can no longer read,
    or acceptance would be judged against evidence the model doesn't see.
    min_pos[s] = 0 (or None) leaves the search unrestricted.

    Returns (drafts [S, draft_len] int32 with -1 beyond each slot's count,
    num_drafts [S] int32). The count is clamped so the verify step's
    accepted rows stay within the cache: lengths + num_drafts <= C-2.
    """
    S, W = history.shape
    n, K = int(ngram), int(draft_len)
    C = int(max_context)
    last = lengths  # history col of the pending last token
    p = jnp.arange(W)
    # trailing pattern: history[last-n+1 .. last]
    pat_idx = jnp.clip(last[:, None] - n + 1 + jnp.arange(n)[None, :], 0, W - 1)
    pattern = jnp.take_along_axis(history, pat_idx, axis=1)  # [S, n]
    # match[s, p] = window of n tokens starting at p equals the pattern.
    # Static shift + pad, NOT a [S, W] gather — an index-array gather here
    # lowers to a serialized TPU gather that costs as much as the whole
    # verify forward (measured 6.9 ms vs 8.5 ms on v5e).
    match = jnp.ones((S, W), jnp.bool_)
    for i in range(n):
        shifted = history if i == 0 else jnp.concatenate(
            [history[:, i:], jnp.full((S, i), -1, history.dtype)], axis=1
        )
        match = match & (shifted == pattern[:, i : i + 1])
    # the window must end strictly before the trailing pattern's start...
    valid = p[None, :] <= (last - n)[:, None]
    # ...and exist at all (need n+1 known tokens: the pattern plus history)
    valid = valid & (last[:, None] >= n)
    if min_pos is not None:
        # live-rows clamp (window+sink KV compression): the whole match
        # window — and therefore its continuation — starts at or past the
        # slot's live window start
        valid = valid & (p[None, :] >= min_pos[:, None])
    hit = match & valid
    # Prefer the most recent occurrence that still has a FULL draft's worth
    # of known continuation after it; fall back to the most recent partial
    # one. Plain "most recent" degenerates on token runs (…x x x x): the
    # freshest window ends right at the tail, leaving 1 known continuation
    # token, and acceptance collapses to ~1/round.
    full = hit & (p[None, :] <= (last - n - K + 1)[:, None])
    cand = jnp.where(full, p[None, :], -1)
    best_full = jnp.max(cand, axis=1)
    best_any = jnp.max(jnp.where(hit, p[None, :], -1), axis=1)
    best = jnp.where(best_full >= 0, best_full, best_any)  # -1 = none
    start = best + n  # first draft token's history col
    known = last - start + 1  # continuation tokens actually known
    room = (C - 2) - last  # cache rows the verify step may consume
    num = jnp.clip(jnp.minimum(known, room), 0, K)
    num = jnp.where(best >= 0, num, 0)
    didx = jnp.clip(start[:, None] + jnp.arange(K)[None, :], 0, W - 1)
    drafts = jnp.take_along_axis(history, didx, axis=1)
    drafts = jnp.where(jnp.arange(K)[None, :] < num[:, None], drafts, -1)
    return drafts, num


class DraftModel:
    """Small-model draft proposer beside :func:`propose_ngram`.

    The reference's intelligence hierarchy ships TinyLlama 1.1B alongside
    the Mistral/DeepSeek/Qwen tiers as separate llama.cpp processes; here
    the small tier becomes a true DRAFT MODEL for the serving tier
    (RTP-LLM-style, PAPERS.md): it runs K autoregressive greedy steps per
    speculative round and the serving model verifies the whole draft
    through the existing ``model.verify_step(_paged)`` machinery in one
    weight-bandwidth-bound dispatch. int4 weights (``ops/int4_matmul.py``
    via the ``model.matmul`` ladder) keep the draft's HBM cost near-free
    next to the serving tier's.

    This object owns only the draft's CONFIG + quantized params (shared
    read-only across a pool's replica engines); each engine materializes
    its own slot-aligned KV state with :meth:`init_state` and keeps it in
    sync on accept/reject/retire through the draft-spec graphs
    (engine._draft_spec_impl). The sync invariant is simply that draft
    cache rows ``[0, d_len)`` hold the K/V of ``history[:, 0:d_len)`` —
    the same contract the serving cache keeps with its ``lengths`` — so
    rejected draft rows become unreadable (and safely overwritable) the
    moment ``d_len`` is clamped back to the verified length.

    The draft must share the serving model's TOKENIZER: proposals are
    token ids fed straight into the verify forward, so a vocab mismatch
    is a config error, not a quality problem.
    """

    def __init__(self, cfg, params, *, quantize: Optional[str] = "int4"):
        # deferred: engine.py imports this module at load time (the
        # checkpoint.py cycle-safe pattern)
        from . import model
        from .engine import _is_prequantized, _prequantized_mode

        self.cfg = cfg
        if quantize is True:
            quantize = "int8"
        elif not quantize:
            quantize = None
        elif quantize not in ("int8", "int4"):
            raise ValueError(f"unknown draft quantize mode {quantize!r}")
        if _is_prequantized(params):
            # a prepared checkpoint's STORED mode wins (the engine's
            # _resolve_stored_mode convention): requantizing would need
            # the dense source, which a prepared tree no longer carries
            self.quant_mode = _prequantized_mode(params)
        else:
            if quantize is not None:
                # fused single-chip serving layout: the draft only ever
                # runs single-device (the engine refuses it under a
                # sharding plan)
                params = model.quantize_params(
                    jax.tree.map(jnp.asarray, params), mode=quantize
                )
            self.quant_mode = quantize
        self.params = jax.tree.map(jnp.asarray, params)

    def init_state(self, num_slots: int, max_context: int,
                   cache_dtype=jnp.bfloat16):
        """Fresh slot-aligned draft decode state: a dense KV cache sized
        to the SERVING model's context (rows map 1:1 onto history
        columns) plus per-slot lengths. The draft tier is small, so the
        dense layout costs little even beside a paged serving cache."""
        from . import model

        k, v = model.init_kv_cache(
            self.cfg, num_slots, max_context, cache_dtype
        )
        return {
            "k": k,
            "v": v,
            "lengths": jnp.zeros((num_slots,), jnp.int32),
        }

    def weight_bytes(self) -> int:
        from . import model

        return model.serving_weight_bytes(self.params)


def accept_counts(drafts: jnp.ndarray, argmax_rows: jnp.ndarray) -> jnp.ndarray:
    """Longest accepted draft prefix per slot.

    drafts [S, K] (-1 padded), argmax_rows [S, K+1] — the model's greedy
    prediction at each verified position. Draft j is provisionally correct
    iff it equals argmax_rows[:, j]; the accepted run stops at the first
    mismatch (the -1 padding can never match). Returns a [S] int32 in
    [0, K].
    """
    m = (drafts == argmax_rows[:, : drafts.shape[1]]).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(m, axis=1), axis=1)
