"""Multi-node cluster manager + remote execution clients.

Reference parity:
  * ClusterManager (agent-core/src/cluster.rs): node registry keyed by
    node_id, 30 s heartbeat timeout, least-loaded routing by
    cpu + task-ratio score (cluster.rs:110-128), dead-node pruning
    (136-158), gated on AIOS_CLUSTER_ENABLED=true (cluster.rs:43);
  * RemoteExecutor (agent-core/src/remote_exec.rs): channel-cached gRPC
    clients to remote orchestrators/tool registries — submit_remote_goal,
    execute_remote_tool (remote_exec.rs:45-102).

TPU note (SURVEY.md section 2.4): this is the *orchestration-level*
multi-node plane and stays gRPC; multi-chip/multi-host model execution lives
below the runtime service boundary as JAX meshes over ICI/DCN.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

NODE_TIMEOUT = 30.0


@dataclass
class ClusterNode:
    node_id: str
    hostname: str
    address: str
    agents: List[str] = field(default_factory=list)
    metadata: Dict[str, str] = field(default_factory=dict)
    max_tasks: int = 10
    cpu_usage: float = 0.0
    memory_usage: float = 0.0
    active_tasks: int = 0
    last_heartbeat: float = field(default_factory=time.monotonic)

    @property
    def alive(self) -> bool:
        return time.monotonic() - self.last_heartbeat < NODE_TIMEOUT

    @property
    def load_score(self) -> float:
        """cpu + task-ratio blend (cluster.rs:110-128); lower is better."""
        task_ratio = self.active_tasks / max(self.max_tasks, 1)
        return self.cpu_usage / 100.0 + task_ratio


def cluster_enabled() -> bool:
    return os.environ.get("AIOS_CLUSTER_ENABLED", "").lower() == "true"


class ClusterManager:
    def __init__(self):
        self._nodes: Dict[str, ClusterNode] = {}
        self._lock = threading.Lock()

    def register(self, node: ClusterNode) -> None:
        with self._lock:
            self._nodes[node.node_id] = node

    def heartbeat(
        self, node_id: str, cpu: float, memory: float, active_tasks: int
    ) -> bool:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None:
                return False
            n.cpu_usage = cpu
            n.memory_usage = memory
            n.active_tasks = active_tasks
            n.last_heartbeat = time.monotonic()
            return True

    def nodes(self, include_dead: bool = False) -> List[ClusterNode]:
        with self._lock:
            out = list(self._nodes.values())
        return out if include_dead else [n for n in out if n.alive]

    def least_loaded(self) -> Optional[ClusterNode]:
        live = [n for n in self.nodes() if n.active_tasks < n.max_tasks]
        if not live:
            return None
        return min(live, key=lambda n: n.load_score)

    def prune_dead(self) -> List[str]:
        with self._lock:
            dead = [nid for nid, n in self._nodes.items() if not n.alive]
            for nid in dead:
                del self._nodes[nid]
            return dead


class RemoteExecutor:
    """Channel-cached clients to other nodes' orchestrator/tool services."""

    def __init__(self):
        self._channels: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _channel(self, address: str):
        from .. import rpc

        with self._lock:
            ch = self._channels.get(address)
            if ch is None:
                ch = rpc.insecure_channel(address)
                self._channels[address] = ch
            return ch

    def submit_remote_goal(
        self, address: str, description: str, priority: int = 5
    ) -> str:
        from ..proto_gen import orchestrator_pb2
        from ..services import OrchestratorStub

        stub = OrchestratorStub(self._channel(address))
        resp = stub.SubmitGoal(
            orchestrator_pb2.SubmitGoalRequest(
                description=description, priority=priority, source="cluster"
            ),
            timeout=10,
        )
        return resp.id

    def execute_remote_tool(
        self, address: str, tool_name: str, input_json: bytes, agent_id: str
    ):
        from ..proto_gen import tools_pb2
        from ..services import ToolRegistryStub

        stub = ToolRegistryStub(self._channel(address))
        return stub.Execute(
            tools_pb2.ExecuteRequest(
                tool_name=tool_name, agent_id=agent_id, input_json=input_json
            ),
            timeout=30,
        )

    def close(self) -> None:
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
