#!/usr/bin/env bash
# Static concurrency & dispatch-discipline analysis over aios_tpu/.
#
# Thin wrapper so CI jobs, pre-push hooks, and humans all invoke the ONE
# entry point the tier-1 test uses (tests/test_analysis.py calls
# aios_tpu.analysis.__main__.main directly — local runs and CI cannot
# diverge). Exit 1 on any unwaived finding.
#
# Usage:
#   scripts/analyze.sh                  # human-readable report
#   scripts/analyze.sh --json          # machine-readable findings
#   scripts/analyze.sh --rule lock-order --rule guarded-by
#   scripts/analyze.sh --waived        # show waived findings + reasons
#
# Rule catalog, lock registry, and waiver policy: docs/ANALYSIS.md
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m aios_tpu.analysis "$@"
