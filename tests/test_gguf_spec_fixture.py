"""llama.cpp-layout GGUF parity via an INDEPENDENT spec-derived encoder.

VERDICT r2 missing #1 / weak #3: the original GGUF tests round-tripped the
repo's own writer, so a shared misreading of a block layout would pass. The
encoder here is written byte-by-byte from the GGUF spec and ggml-quants
block definitions (the format llama.cpp itself writes —
/root/reference/runtime/src/model_manager.rs:187-263 serves exactly these
files), NOT from aios_tpu/engine/gguf.py. Every expected value is computed
symbolically from the spec formulas on hand-chosen bit patterns, so a
nibble-order swap, a 6-bit scale-packing misread, a wrong chunk order, or a
missed q/k permutation in the reader fails loudly.

Also covers the SentencePiece-BPE merge-ORDER contract (llama.cpp merges by
highest score, not left-to-right) via a vocab where the two orders diverge.
"""

import struct

import numpy as np
import pytest

from aios_tpu.engine.gguf import GGUFFile
from aios_tpu.engine.tokenizer import SentencePieceBPE
from aios_tpu.engine.weights import params_from_gguf

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow

# ---------------------------------------------------------------------------
# Independent GGUF v3 encoder (from the spec; no aios_tpu writer imports)
# ---------------------------------------------------------------------------

ALIGN = 32
# ggml type ids (ggml.h enum ggml_type)
F32, F16, Q8_0, Q4_K, Q6_K = 0, 1, 8, 12, 14


def _u64(v):
    return struct.pack("<Q", v)


def _u32(v):
    return struct.pack("<I", v)


def _s(text: bytes | str):
    b = text.encode() if isinstance(text, str) else text
    return _u64(len(b)) + b


def _kv(key, vtype, payload):
    return _s(key) + _u32(vtype) + payload


def _kv_u32(key, v):
    return _kv(key, 4, _u32(v))


def _kv_f32(key, v):
    return _kv(key, 6, struct.pack("<f", v))


def _kv_str(key, v):
    return _kv(key, 8, _s(v))


def _kv_arr_str(key, items):
    return _kv(key, 9, _u32(8) + _u64(len(items)) + b"".join(_s(i) for i in items))


def _kv_arr_f32(key, items):
    return _kv(
        key, 9, _u32(6) + _u64(len(items)) + struct.pack(f"<{len(items)}f", *items)
    )


def _kv_arr_i32(key, items):
    return _kv(
        key, 9, _u32(5) + _u64(len(items)) + struct.pack(f"<{len(items)}i", *items)
    )


def write_gguf(path, metadata_blobs, tensors):
    """tensors: list of (name, shape_row_major, ggml_type, raw_bytes).

    GGUF stores dims innermost-first (ne[0] = fastest axis), so a row-major
    (rows, cols) array is declared as dims [cols, rows]. Tensor offsets are
    relative to the 32-aligned start of the data section, each aligned 32.
    """
    out = bytearray()
    out += b"GGUF" + _u32(3) + _u64(len(tensors)) + _u64(len(metadata_blobs))
    for blob in metadata_blobs:
        out += blob
    offset = 0
    infos = bytearray()
    blobs = []
    for name, shape, gtype, raw in tensors:
        dims = list(shape)[::-1]
        infos += _s(name) + _u32(len(dims))
        for d in dims:
            infos += _u64(d)
        infos += _u32(gtype) + _u64(offset)
        blobs.append((offset, raw))
        offset += len(raw) + (-len(raw)) % ALIGN
    out += infos
    out += b"\x00" * ((-len(out)) % ALIGN)  # data section starts aligned
    base = len(out)
    for off, raw in blobs:
        out += b"\x00" * (base + off - len(out))
        out += raw
    path.write_bytes(bytes(out))


# ---------------------------------------------------------------------------
# Block encoders + spec-formula expected values
# ---------------------------------------------------------------------------


def f16b(x):
    return np.float16(x).tobytes()


def encode_q8_0(d_scales, q):
    """Q8_0: per 32-block, f16 d then 32 int8. value[i] = d * q[i]."""
    q = np.asarray(q, np.int8).reshape(-1, 32)
    out = b""
    expected = []
    for d, row in zip(d_scales, q):
        out += f16b(d) + row.tobytes()
        expected.append(np.float32(np.float16(d)) * row.astype(np.float32))
    return out, np.concatenate(expected)


def pack_k_scales(sc, mn):
    """The 12-byte 6-bit scale/min packing of Q4_K/Q5_K (ggml-quants
    get_scale_min_k4, inverted): sub-blocks 0-3 live in the low 6 bits of
    bytes 0-3 (scales) and 4-7 (mins); sub-blocks 4-7 pack their low nibbles
    into bytes 8-11 and their high 2 bits into the top bits of bytes 0-7."""
    b = bytearray(12)
    for j in range(4):
        b[j] = (sc[j] & 63) | ((sc[j + 4] >> 4) << 6)
        b[j + 4] = (mn[j] & 63) | ((mn[j + 4] >> 4) << 6)
        b[j + 8] = (sc[j + 4] & 0xF) | ((mn[j + 4] & 0xF) << 4)
    return bytes(b)


def encode_q4_k(d, dmin, sc, mn, q):
    """Q4_K super-block (256 values, 144 bytes): f16 d, f16 dmin, 12-byte
    packed 6-bit scales/mins, 128 bytes of nibbles. Values come in 4 chunks
    of 64: chunk c's 32 bytes hold sub-block 2c in the LOW nibbles and
    sub-block 2c+1 in the HIGH nibbles.
    value[sub j][i] = d * sc[j] * q4 - dmin * mn[j]."""
    q = np.asarray(q, np.uint8).reshape(8, 32)
    qs = bytearray()
    for c in range(4):
        lo, hi = q[2 * c], q[2 * c + 1]
        qs += bytes((int(l) | (int(h) << 4)) for l, h in zip(lo, hi))
    block = f16b(d) + f16b(dmin) + pack_k_scales(sc, mn) + bytes(qs)
    assert len(block) == 144
    df, mf = np.float32(np.float16(d)), np.float32(np.float16(dmin))
    expected = np.concatenate(
        [df * sc[j] * q[j].astype(np.float32) - mf * mn[j] for j in range(8)]
    )
    return block, expected


def encode_q6_k(d, scales, q):
    """Q6_K super-block (256 values, 210 bytes): ql[128] (low 4 bits),
    qh[64] (high 2 bits), 16 int8 scales (one per 16 values), f16 d.
    Two half-blocks of 128; within a half, element l of run r (r = 0..3,
    runs are y[l], y[l+32], y[l+64], y[l+96]) stores its high bits in
    qh[l] >> 2r and its low nibble in ql[l] (runs 0-1, low/high nibble) or
    ql[l+32] (runs 2-3). value = d * scales[...] * (q - 32)."""
    q = np.asarray(q, np.uint8).reshape(2, 4, 32)  # [half, run, l]
    ql = bytearray()
    qh = bytearray()
    for h in range(2):
        lo = [q[h, r] & 0xF for r in range(4)]
        for l in range(32):
            ql.append(int(lo[0][l]) | (int(lo[2][l]) << 4))
        for l in range(32):
            ql.append(int(lo[1][l]) | (int(lo[3][l]) << 4))
        for l in range(32):
            qh.append(
                int(q[h, 0, l] >> 4)
                | (int(q[h, 1, l] >> 4) << 2)
                | (int(q[h, 2, l] >> 4) << 4)
                | (int(q[h, 3, l] >> 4) << 6)
            )
    scales = np.asarray(scales, np.int8)
    block = bytes(ql) + bytes(qh) + scales.tobytes() + f16b(d)
    assert len(block) == 210
    df = np.float32(np.float16(d))
    expected = np.empty(256, np.float32)
    for h in range(2):
        for r in range(4):
            for l in range(32):
                sc = scales[8 * h + 2 * r + l // 16]
                expected[128 * h + 32 * r + l] = (
                    df * np.float32(sc) * (np.float32(q[h, r, l]) - 32.0)
                )
    return block, expected


# ---------------------------------------------------------------------------
# Block-level parity
# ---------------------------------------------------------------------------


def _read_single(tmp_path, gtype, shape, raw):
    path = tmp_path / "one.gguf"
    write_gguf(
        path,
        [_kv_str("general.architecture", "llama")],
        [("t", shape, gtype, raw)],
    )
    return GGUFFile(str(path)).load_tensor("t", dtype=np.float32)


def test_q8_0_block_parity(tmp_path):
    rng = np.random.default_rng(0)
    q = rng.integers(-127, 128, 64, dtype=np.int8)
    raw, expected = encode_q8_0([0.5, -1.25], q)
    got = _read_single(tmp_path, Q8_0, (2, 32), raw)
    np.testing.assert_allclose(got.reshape(-1), expected, rtol=0, atol=0)


def test_q4_k_block_parity_exercises_scale_packing(tmp_path):
    # scales/mins > 31 exercise the split high-2-bit packing of sub-blocks
    # 4..7 — the single most misread part of the format
    sc = [1, 7, 31, 63, 33, 47, 55, 63]
    mn = [0, 3, 21, 63, 32, 44, 62, 63]
    rng = np.random.default_rng(1)
    q = rng.integers(0, 16, 256, dtype=np.uint8)
    raw, expected = encode_q4_k(0.25, 0.125, sc, mn, q)
    got = _read_single(tmp_path, Q4_K, (1, 256), raw)
    np.testing.assert_allclose(got.reshape(-1), expected, rtol=0, atol=0)


def test_q6_k_block_parity(tmp_path):
    rng = np.random.default_rng(2)
    q = rng.integers(0, 64, 256, dtype=np.uint8)  # full 6-bit range
    scales = rng.integers(-128, 128, 16, dtype=np.int8)
    raw, expected = encode_q6_k(-0.375, scales, q)
    got = _read_single(tmp_path, Q6_K, (1, 256), raw)
    np.testing.assert_allclose(got.reshape(-1), expected, rtol=0, atol=0)


def test_q4_k_multi_row_tensor(tmp_path):
    """Rows are independent block streams; a 2-row tensor must not bleed."""
    rng = np.random.default_rng(3)
    raws, exps = [], []
    for i in range(2):
        raw, exp = encode_q4_k(
            0.5 + i, 0.25, [j + 1 + i for j in range(8)],
            [j + i for j in range(8)], rng.integers(0, 16, 256, np.uint8),
        )
        raws.append(raw)
        exps.append(exp)
    got = _read_single(tmp_path, Q4_K, (2, 256), b"".join(raws))
    np.testing.assert_allclose(got, np.stack(exps), rtol=0, atol=0)


# ---------------------------------------------------------------------------
# Full llama.cpp-layout model file -> engine params
# ---------------------------------------------------------------------------


def _permute_hf_to_gguf(w, n_head):
    """llama.cpp's convert_hf_to_gguf.py q/k row permutation (the fixture
    writes the GGUF layout; the reader must invert it)."""
    out_dim = w.shape[0]
    return (
        w.reshape(n_head, 2, out_dim // n_head // 2, w.shape[1])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def _q8_0_tensor(rng, rows, cols):
    q = rng.integers(-127, 128, rows * cols, dtype=np.int8)
    d = rng.uniform(0.01, 0.1, rows * cols // 32)
    raw, expected = encode_q8_0(d, q)
    return raw, expected.reshape(rows, cols)


VOCAB = (
    ["<unk>", "<s>", "</s>", "▁", "a", "b", "c", "ab", "bc"]
    + [f"<0x{i:02X}>" for i in range(256)]
)
SCORES = [0.0, 0.0, 0.0, -10.0, -20.0, -20.0, -20.0, -5.0, -1.0] + [0.0] * 256
TYPES = [2, 3, 3] + [1] * 6 + [6] * 256


def _write_tiny_llama_gguf(path, rng):
    """A complete llama-architecture GGUF in llama.cpp's tensor layout:
    (out, in)-shaped Q8_0 matrices, permuted attn_q/attn_k, F32 norms,
    real metadata keys, real tokenizer arrays. Geometry: E=64, 2 layers,
    4 heads / 2 kv heads (Q8_0's 32-block divides every row)."""
    E, F_, L, H, KH, D = 64, 96, 2, 4, 2, 16
    V = len(VOCAB)
    meta = [
        _kv_str("general.architecture", "llama"),
        _kv_str("general.name", "spec-fixture"),
        _kv_u32("llama.block_count", L),
        _kv_u32("llama.context_length", 128),
        _kv_u32("llama.embedding_length", E),
        _kv_u32("llama.feed_forward_length", F_),
        _kv_u32("llama.attention.head_count", H),
        _kv_u32("llama.attention.head_count_kv", KH),
        _kv_f32("llama.attention.layer_norm_rms_epsilon", 1e-5),
        _kv_f32("llama.rope.freq_base", 10000.0),
        _kv_str("tokenizer.ggml.model", "llama"),
        _kv_arr_str("tokenizer.ggml.tokens", VOCAB),
        _kv_arr_f32("tokenizer.ggml.scores", SCORES),
        _kv_arr_i32("tokenizer.ggml.token_type", TYPES),
        _kv_u32("tokenizer.ggml.bos_token_id", 1),
        _kv_u32("tokenizer.ggml.eos_token_id", 2),
    ]
    tensors = []
    expected = {"layers": []}

    def add(name, rows, cols, permute_heads=None):
        raw, exp = _q8_0_tensor(rng, rows, cols)
        if permute_heads is not None:
            # Store the llama.cpp-permuted layout; `exp` stays the HF-layout
            # ground truth the reader must recover. The permutation shuffles
            # whole rows and 32 | cols, so permuting the per-row block runs
            # of the raw stream reproduces exactly what convert_hf_to_gguf
            # writes (same grid, same bytes).
            nb = cols // 32
            blocks = np.frombuffer(raw, np.uint8).reshape(rows * nb, 34)
            row_order = _permute_hf_to_gguf(
                np.arange(rows).reshape(rows, 1), permute_heads
            ).reshape(-1)
            blk_order = (row_order[:, None] * nb + np.arange(nb)[None, :]).reshape(-1)
            raw = blocks[blk_order].tobytes()
        tensors.append((name, (rows, cols), Q8_0, raw))
        return exp

    expected["embed"] = add("token_embd.weight", V, E)
    for i in range(L):
        p = f"blk.{i}."
        norm1 = rng.uniform(0.5, 1.5, E).astype(np.float32)
        norm2 = rng.uniform(0.5, 1.5, E).astype(np.float32)
        tensors.append((p + "attn_norm.weight", (E,), F32, norm1.tobytes()))
        tensors.append((p + "ffn_norm.weight", (E,), F32, norm2.tobytes()))
        layer = {
            "attn_norm": norm1,
            "ffn_norm": norm2,
            "wq": add(p + "attn_q.weight", H * D, E, permute_heads=H).T,
            "wk": add(p + "attn_k.weight", KH * D, E, permute_heads=KH).T,
            "wv": add(p + "attn_v.weight", KH * D, E).T,
            "wo": add(p + "attn_output.weight", E, H * D).T,
            "w_gate": add(p + "ffn_gate.weight", F_, E).T,
            "w_up": add(p + "ffn_up.weight", F_, E).T,
            "w_down": add(p + "ffn_down.weight", E, F_).T,
        }
        expected["layers"].append(layer)
    fnorm = rng.uniform(0.5, 1.5, E).astype(np.float32)
    tensors.append(("output_norm.weight", (E,), F32, fnorm.tobytes()))
    expected["final_norm"] = fnorm
    expected["lm_head"] = add("output.weight", V, E).T
    write_gguf(path, meta, tensors)
    return expected


def test_llamacpp_layout_model_loads_with_exact_weights(tmp_path):
    rng = np.random.default_rng(7)
    path = tmp_path / "spec-fixture.gguf"
    expected = _write_tiny_llama_gguf(path, rng)
    params, cfg = params_from_gguf(str(path))
    assert cfg.num_layers == 2 and cfg.num_heads == 4 and cfg.num_kv_heads == 2
    np.testing.assert_allclose(params["embed"], expected["embed"], rtol=0, atol=0)
    np.testing.assert_allclose(
        params["final_norm"], expected["final_norm"], rtol=0, atol=0
    )
    np.testing.assert_allclose(
        params["lm_head"], expected["lm_head"], rtol=0, atol=0
    )
    for key in ("attn_norm", "ffn_norm", "wq", "wk", "wv", "wo",
                "w_gate", "w_up", "w_down"):
        got = params["layers"][key]
        want = np.stack([expected["layers"][i][key] for i in range(2)])
        np.testing.assert_allclose(got, want, rtol=0, atol=0, err_msg=key)


def test_fixture_decodes_coherently_through_runtime(tmp_path):
    """LoadModel on the fixture file through the real model manager: the
    tokenizer comes from the GGUF metadata and greedy decode through the
    engine matches the uncached full forward on the same weights."""
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.runtime.model_manager import ModelManager

    rng = np.random.default_rng(11)
    path = tmp_path / "spec-fixture.gguf"
    _write_tiny_llama_gguf(path, rng)
    manager = ModelManager(num_slots=2, warm_compile=False)
    managed = manager.load_model("fixture", str(path), context_length=64)
    assert managed.state == "ready"
    m = manager.models["fixture"]
    assert isinstance(m.tokenizer, SentencePieceBPE)

    ids = m.tokenizer.encode("abc")
    assert ids[0] == m.tokenizer.bos_id
    got = m.engine.generate(ids, max_new_tokens=6, temperature=0.0)
    logits_params = {
        k: (jnp.asarray(v) if not isinstance(v, dict)
            else {kk: jnp.asarray(vv) for kk, vv in v.items()})
        for k, v in m.engine.params.items()
    }
    toks = list(ids)
    want = []
    for _ in range(6):
        logits = M.forward_full(
            logits_params, m.config, np.asarray([toks], np.int32)
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        toks.append(nxt)
    assert got == want


def test_fixture_serves_int4_through_runtime(tmp_path, monkeypatch):
    """The same llama.cpp-layout fixture served with int4 weights
    (AIOS_TPU_QUANTIZE=int4): load succeeds, the engine holds packed-nibble
    leaves, and batched greedy decode matches the full forward on the SAME
    quantized params — the real-GGUF -> int4 serving contract."""
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.runtime.model_manager import ModelManager

    rng = np.random.default_rng(12)
    path = tmp_path / "spec-fixture-int4.gguf"
    _write_tiny_llama_gguf(path, rng)
    manager = ModelManager(num_slots=2, warm_compile=False, quantize="int4")
    managed = manager.load_model("fixture4", str(path), context_length=64)
    assert managed.state == "ready"
    m = manager.models["fixture4"]
    assert m.engine.quant_mode == "int4"
    assert "q4" in m.engine.params["layers"]["w_qkv"]

    ids = m.tokenizer.encode("abc")
    got = m.engine.generate(ids, max_new_tokens=6, temperature=0.0)
    toks = list(ids)
    want = []
    for _ in range(6):
        logits = M.forward_full(
            m.engine.params, m.config, np.asarray([toks], np.int32)
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        toks.append(nxt)
    assert got == want


# ---------------------------------------------------------------------------
# Tokenizer merge-order contract
# ---------------------------------------------------------------------------


def _tok_from_metadata():
    md = {
        "tokenizer.ggml.tokens": VOCAB,
        "tokenizer.ggml.scores": SCORES,
        "tokenizer.ggml.token_type": TYPES,
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
    }
    return SentencePieceBPE.from_gguf_metadata(md)


def test_sp_bpe_merges_by_score_not_left_to_right():
    """'abc' with vocab {ab: -5, bc: -1, no abc}: llama.cpp's SP-BPE applies
    the HIGHEST-score merge first, so b+c fuse before a can grab b ->
    [▁, a, bc]. A left-to-right/longest-first tokenizer would produce
    [▁, ab, c] — a silent divergence on every real vocab."""
    tok = _tok_from_metadata()
    ids = tok.encode("abc", add_bos=False)
    pieces = [tok.tokens[i] for i in ids]
    assert pieces == ["▁", "a", "bc"], pieces


def test_sp_bpe_byte_fallback_on_unknown_chars():
    tok = _tok_from_metadata()
    ids = tok.encode("aZ", add_bos=False)
    pieces = [tok.tokens[i] for i in ids]
    assert "a" in pieces
    assert f"<0x{ord('Z'):02X}>" in pieces
    assert tok.decode(ids) == "aZ"


# ---------------------------------------------------------------------------
# qwen3-architecture fixture: gpt2 (byte-level BPE) tokenizer + QK-norm
# ---------------------------------------------------------------------------


def _write_tiny_qwen3_gguf(path, rng):
    """A qwen3-architecture GGUF: QK-norm tensors, no q/k permutation
    (convert_hf_to_gguf permutes llama/mistral only), and the gpt2
    tokenizer family — byte-level vocab, rank-ordered merges, chatml
    control tokens — that the Qwen3/Qwen3-MoE/DeepSeek tiers embed."""
    from aios_tpu.engine.tokenizer import _bytes_to_unicode

    E, F_, L, H, KH, D = 64, 96, 2, 4, 2, 16
    alphabet = sorted(set(_bytes_to_unicode().values()))
    merges = ["h i", "Ġ h", "Ġh i"]
    specials = ["<|im_start|>", "<|im_end|>", "<|endoftext|>"]
    vocab = alphabet + [m.replace(" ", "") for m in merges] + specials
    types = [1] * (len(alphabet) + len(merges)) + [3] * len(specials)
    V = len(vocab)
    meta = [
        _kv_str("general.architecture", "qwen3"),
        _kv_str("general.name", "qwen3-fixture"),
        _kv_u32("qwen3.block_count", L),
        _kv_u32("qwen3.context_length", 128),
        _kv_u32("qwen3.embedding_length", E),
        _kv_u32("qwen3.feed_forward_length", F_),
        _kv_u32("qwen3.attention.head_count", H),
        _kv_u32("qwen3.attention.head_count_kv", KH),
        _kv_u32("qwen3.attention.key_length", D),
        _kv_f32("qwen3.attention.layer_norm_rms_epsilon", 1e-6),
        _kv_f32("qwen3.rope.freq_base", 1000000.0),
        _kv_str("tokenizer.ggml.model", "gpt2"),
        _kv_str("tokenizer.ggml.pre", "qwen2"),
        _kv_arr_str("tokenizer.ggml.tokens", vocab),
        _kv_arr_str("tokenizer.ggml.merges", merges),
        _kv_arr_i32("tokenizer.ggml.token_type", types),
        _kv_u32("tokenizer.ggml.eos_token_id", vocab.index("<|im_end|>")),
    ]
    tensors = []

    def add(name, rows, cols):
        raw, _ = _q8_0_tensor(rng, rows, cols)
        tensors.append((name, (rows, cols), Q8_0, raw))

    add("token_embd.weight", V, E)
    for i in range(L):
        p = f"blk.{i}."
        for nm, dim in (("attn_norm", E), ("ffn_norm", E),
                        ("attn_q_norm", D), ("attn_k_norm", D)):
            tensors.append((
                p + nm + ".weight", (dim,), F32,
                rng.uniform(0.5, 1.5, dim).astype(np.float32).tobytes(),
            ))
        add(p + "attn_q.weight", H * D, E)
        add(p + "attn_k.weight", KH * D, E)
        add(p + "attn_v.weight", KH * D, E)
        add(p + "attn_output.weight", E, H * D)
        add(p + "ffn_gate.weight", F_, E)
        add(p + "ffn_up.weight", F_, E)
        add(p + "ffn_down.weight", E, F_)
    tensors.append((
        "output_norm.weight", (E,), F32,
        rng.uniform(0.5, 1.5, E).astype(np.float32).tobytes(),
    ))
    add("output.weight", V, E)
    write_gguf(path, meta, tensors)
    return vocab


def test_qwen3_gguf_fixture_through_runtime(tmp_path):
    """LoadModel on a qwen3-arch GGUF: config picks up QK-norm geometry,
    the tokenizer dispatches to byte-level BPE, the chatml template's
    control tokens encode to single ids, and greedy decode through the
    engine matches the uncached full forward."""
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.engine.tokenizer import ByteLevelBPE, render_chat
    from aios_tpu.runtime.model_manager import ModelManager

    rng = np.random.default_rng(23)
    path = tmp_path / "qwen3-fixture.gguf"
    vocab = _write_tiny_qwen3_gguf(path, rng)
    manager = ModelManager(num_slots=2, warm_compile=False)
    managed = manager.load_model("qwen3-fixture", str(path), context_length=64)
    assert managed.state == "ready"
    m = manager.models["qwen3-fixture"]
    assert m.config.qk_norm and m.config.head_dim == 16
    assert isinstance(m.tokenizer, ByteLevelBPE)
    assert m.tokenizer.bos_id is None
    assert m.tokenizer.eos_id == vocab.index("<|im_end|>")

    text = render_chat("qwen3-fixture", "hi")
    ids = m.tokenizer.encode(text, add_bos=False)
    # chat scaffolding control tokens must be single ids, and "hi" one
    # merged token (the "h i" merge; no space marker after a newline)
    assert ids.count(vocab.index("<|im_start|>")) == 2
    assert vocab.index("hi") in ids  # "h i" merge applied (follows newline)
    assert m.tokenizer.decode(ids).endswith("assistant\n")

    got = m.engine.generate(ids[:8], max_new_tokens=5, temperature=0.0)
    params = {
        k: (jnp.asarray(v) if not isinstance(v, dict)
            else {kk: jnp.asarray(vv) for kk, vv in v.items()})
        for k, v in m.engine.params.items()
    }
    toks = list(ids[:8])
    want = []
    for _ in range(5):
        logits = M.forward_full(
            params, m.config, np.asarray([toks], np.int32)
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        toks.append(nxt)
    assert got == want
