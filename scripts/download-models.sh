#!/usr/bin/env bash
# Fetch the GGUF weights for the local model tiers.
#
# TPU-native equivalent of /root/reference/scripts/download-models.sh: same
# model set (the runtime's intelligence ladder, model_manager.rs:462-518),
# same GGUF artifacts — the TPU runtime dequantizes GGUF into HBM-resident
# int8/bf16 params at load (aios_tpu/engine/gguf.py) instead of handing the
# file to llama.cpp.
#
# Usage: scripts/download-models.sh [--dest DIR] [--tier tiny|tactical|all]
set -euo pipefail

DEST=/var/lib/aios/models
TIER=tiny

while [[ $# -gt 0 ]]; do
  case "$1" in
    --dest) DEST="$2"; shift 2 ;;
    --tier) TIER="$2"; shift 2 ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done

mkdir -p "$DEST"

# name|url|sha256 (sha256 empty = skip verification)
TINY="tinyllama-1.1b-chat-v1.0.Q4_K_M.gguf|https://huggingface.co/TheBloke/TinyLlama-1.1B-Chat-v1.0-GGUF/resolve/main/tinyllama-1.1b-chat-v1.0.Q4_K_M.gguf|"
MISTRAL="mistral-7b-instruct-v0.2.Q4_K_M.gguf|https://huggingface.co/TheBloke/Mistral-7B-Instruct-v0.2-GGUF/resolve/main/mistral-7b-instruct-v0.2.Q4_K_M.gguf|"

case "$TIER" in
  tiny)     MODELS=("$TINY") ;;
  tactical) MODELS=("$MISTRAL") ;;
  all)      MODELS=("$TINY" "$MISTRAL") ;;
  *) echo "unknown tier: $TIER" >&2; exit 2 ;;
esac

for spec in "${MODELS[@]}"; do
  IFS='|' read -r name url sha <<< "$spec"
  out="$DEST/$name"
  if [[ -f "$out" ]]; then
    echo "[models] $name already present, skipping"
    continue
  fi
  echo "[models] fetching $name"
  curl -fL --retry 3 --retry-delay 5 -o "$out.part" "$url"
  if [[ -n "$sha" ]]; then
    echo "$sha  $out.part" | sha256sum -c -
  fi
  mv "$out.part" "$out"
done

echo "[models] done; $(ls -lh "$DEST" | tail -n +2 | wc -l) file(s) in $DEST"
