"""Tokenizers: GGUF-embedded SentencePiece-BPE, HF wrapper, byte fallback.

llama-server tokenizes with the vocab embedded in the GGUF file; to replace
it with zero extra assets we implement the same SentencePiece-style BPE
(greedy best-score pair merging with byte fallback) directly over the GGUF
metadata arrays (tokenizer.ggml.tokens/scores/token_type). When a HF model
directory is available we defer to transformers instead. Chat templating for
the reference's prompt/system_prompt pair (runtime.proto InferRequest)
follows each family's native format.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# token_type values in GGUF (llama.cpp llama_token_type)
TOKEN_TYPE_NORMAL = 1
TOKEN_TYPE_UNKNOWN = 2
TOKEN_TYPE_CONTROL = 3
TOKEN_TYPE_USER_DEFINED = 4
TOKEN_TYPE_BYTE = 6

SPIECE_SPACE = "▁"  # ▁


class BaseTokenizer:
    bos_id: Optional[int] = None
    eos_id: Optional[int] = None

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError


@dataclass
class SentencePieceBPE(BaseTokenizer):
    """SentencePiece-style BPE over a GGUF vocab (llama/mistral models)."""

    tokens: List[str]
    scores: List[float]
    token_types: List[int]
    bos_id: Optional[int] = 1
    eos_id: Optional[int] = 2
    add_prefix_space: bool = True
    _index: Dict[str, int] = field(default_factory=dict, repr=False)
    _byte_ids: Dict[int, int] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        self._index = {t: i for i, t in enumerate(self.tokens)}
        for i, (tok, typ) in enumerate(zip(self.tokens, self.token_types)):
            if typ == TOKEN_TYPE_BYTE and tok.startswith("<0x") and tok.endswith(">"):
                self._byte_ids[int(tok[3:-1], 16)] = i

    @classmethod
    def from_gguf_metadata(cls, md: dict) -> "SentencePieceBPE":
        tokens = md["tokenizer.ggml.tokens"]
        n = len(tokens)
        return cls(
            tokens=tokens,
            scores=list(md.get("tokenizer.ggml.scores", [0.0] * n)),
            token_types=list(md.get("tokenizer.ggml.token_type", [1] * n)),
            bos_id=int(md.get("tokenizer.ggml.bos_token_id", 1)),
            eos_id=int(md.get("tokenizer.ggml.eos_token_id", 2)),
        )

    @property
    def vocab_size(self) -> int:
        return len(self.tokens)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        if self.add_prefix_space and not text.startswith(" "):
            text = " " + text
        text = text.replace(" ", SPIECE_SPACE)

        # initial symbols: one per character; unknowns byte-fall-back at the end
        symbols = list(text)

        def piece_score(s: str) -> Optional[float]:
            i = self._index.get(s)
            if i is None:
                return None
            return self.scores[i] if i < len(self.scores) else 0.0

        # greedy best-score merge (SentencePiece BPE semantics)
        while len(symbols) > 1:
            best_idx, best_score = -1, None
            for i in range(len(symbols) - 1):
                merged = symbols[i] + symbols[i + 1]
                sc = piece_score(merged)
                if sc is not None and (best_score is None or sc > best_score):
                    best_idx, best_score = i, sc
            if best_idx < 0:
                break
            symbols[best_idx : best_idx + 2] = [symbols[best_idx] + symbols[best_idx + 1]]

        ids: List[int] = []
        if add_bos and self.bos_id is not None:
            ids.append(self.bos_id)
        for sym in symbols:
            idx = self._index.get(sym)
            if idx is not None:
                ids.append(idx)
                continue
            for b in sym.encode("utf-8"):  # byte fallback
                bid = self._byte_ids.get(b)
                if bid is not None:
                    ids.append(bid)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[str] = []
        byte_run: List[int] = []

        def flush_bytes():
            if byte_run:
                out.append(bytes(byte_run).decode("utf-8", errors="replace"))
                byte_run.clear()

        for i in ids:
            if not 0 <= i < len(self.tokens):
                continue
            typ = self.token_types[i] if i < len(self.token_types) else 1
            if typ == TOKEN_TYPE_BYTE:
                tok = self.tokens[i]
                byte_run.append(int(tok[3:-1], 16))
                continue
            flush_bytes()
            if typ == TOKEN_TYPE_CONTROL:
                continue
            out.append(self.tokens[i])
        flush_bytes()
        return "".join(out).replace(SPIECE_SPACE, " ").lstrip(" ")


class HFTokenizer(BaseTokenizer):
    """transformers-backed tokenizer for HF model directories."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


class ByteTokenizer(BaseTokenizer):
    """256-symbol byte tokenizer — synthetic models, benches, smoke tests."""

    bos_id = 256
    eos_id = 257

    @property
    def vocab_size(self) -> int:
        return 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


# ---------------------------------------------------------------------------
# Serialization (model checkpoints carry their tokenizer, like GGUF does)
# ---------------------------------------------------------------------------


def tokenizer_to_dict(tok: BaseTokenizer) -> dict:
    if isinstance(tok, SentencePieceBPE):
        return {
            "type": "spbpe",
            "tokens": tok.tokens,
            "scores": tok.scores,
            "token_types": tok.token_types,
            "bos_id": tok.bos_id,
            "eos_id": tok.eos_id,
            "add_prefix_space": tok.add_prefix_space,
        }
    if isinstance(tok, HFTokenizer):
        return {"type": "hf", "path": tok._tok.name_or_path}
    return {"type": "byte"}


def tokenizer_from_dict(d: dict) -> BaseTokenizer:
    t = d.get("type", "byte")
    if t == "spbpe":
        return SentencePieceBPE(
            tokens=list(d["tokens"]),
            scores=list(d["scores"]),
            token_types=list(d["token_types"]),
            bos_id=d.get("bos_id"),
            eos_id=d.get("eos_id"),
            add_prefix_space=d.get("add_prefix_space", True),
        )
    if t == "hf":
        return HFTokenizer(d["path"])
    return ByteTokenizer()


# ---------------------------------------------------------------------------
# Chat templating (llama-server applied the GGUF chat template; we do the
# same per model family for the prompt/system_prompt pair)
# ---------------------------------------------------------------------------


def render_chat(
    family: str, prompt: str, system_prompt: str = ""
) -> str:
    """Render a single-turn chat for the given model family."""
    fam = family.lower()
    if "tinyllama" in fam or "zephyr" in fam:
        parts = []
        if system_prompt:
            parts.append(f"<|system|>\n{system_prompt}</s>\n")
        parts.append(f"<|user|>\n{prompt}</s>\n<|assistant|>\n")
        return "".join(parts)
    if "mistral" in fam:
        sys = f"{system_prompt}\n\n" if system_prompt else ""
        return f"[INST] {sys}{prompt} [/INST]"
    if "qwen" in fam or "deepseek" in fam or "chatml" in fam:
        parts = []
        if system_prompt:
            parts.append(f"<|im_start|>system\n{system_prompt}<|im_end|>\n")
        parts.append(f"<|im_start|>user\n{prompt}<|im_end|>\n<|im_start|>assistant\n")
        return "".join(parts)
    sys = f"System: {system_prompt}\n\n" if system_prompt else ""
    return f"{sys}User: {prompt}\n\nAssistant:"
