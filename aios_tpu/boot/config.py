"""Layered TOML configuration.

Reference parity (initd/src/config.rs:14-34 + config/default-config.toml):
the 9-section schema — system / boot / models / api / memory / security /
networking / agents / monitoring — loaded from /etc/aios/config.toml with
full defaults when the file is absent, plus env-var overrides for service
addresses (handled in aios_tpu.services) and model/runtime knobs.
"""

from __future__ import annotations

import os
import tomllib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List

DEFAULT_CONFIG_PATH = "/etc/aios/config.toml"


def _default_sections() -> Dict[str, Dict[str, Any]]:
    return {
        "system": {
            "hostname": "aios-tpu",
            "log_level": "info",
            "data_dir": "/tmp/aios",
        },
        "boot": {
            "health_timeout_seconds": 60,
            "max_restart_attempts": 5,
            "restart_window_seconds": 300,
            "emergency_shell": False,
        },
        "models": {
            "model_dir": "/var/lib/aios/models",
            "default_context": 4096,
            "num_slots": 8,
            "warm_compile": True,
            "autoload": True,
        },
        "api": {
            "claude_model": "claude-sonnet-4-20250514",
            "openai_model": "gpt-5",
            "qwen3_model": "qwen3:30b-128k",
            "claude_monthly_budget": 100.0,
            "openai_monthly_budget": 50.0,
        },
        "memory": {
            "operational_capacity": 10000,
            "working_retention_days": 30,
            "longterm_retention_days": 365,
            "migration_interval_seconds": 300,
        },
        "security": {
            "audit_db": "/tmp/aios/ledger/audit.db",
            "cert_dir": "/tmp/aios/certs",
            "secrets_path": "/etc/aios/secrets.toml",
            "sandbox_memory_mb": 256,
        },
        "networking": {
            "bind_host": "127.0.0.1",
            "console_port": 9090,
            "cluster_enabled": False,
        },
        "agents": {
            "config_dir": "/etc/aios/agents",
            "default_agents": ["system", "network", "security"],
            "max_restart_attempts": 5,
            "heartbeat_seconds": 10,
            "poll_seconds": 2,
        },
        "monitoring": {
            "proactive_interval_seconds": 60,
            "cpu_threshold": 90.0,
            "memory_threshold": 85.0,
            "disk_threshold": 90.0,
        },
    }


@dataclass
class AiosConfig:
    sections: Dict[str, Dict[str, Any]] = field(default_factory=_default_sections)
    source_path: str = ""

    def get(self, section: str, key: str, default: Any = None) -> Any:
        return self.sections.get(section, {}).get(key, default)

    def section(self, name: str) -> Dict[str, Any]:
        return dict(self.sections.get(name, {}))

    @property
    def data_dir(self) -> str:
        return os.environ.get("AIOS_DATA_DIR") or self.get(
            "system", "data_dir", "/tmp/aios"
        )


def load_config(path: str | None = None) -> AiosConfig:
    """Defaults deep-merged with the TOML file when present."""
    path = path or os.environ.get("AIOS_CONFIG", DEFAULT_CONFIG_PATH)
    sections = _default_sections()
    source = ""
    p = Path(path)
    if p.is_file():
        try:
            loaded = tomllib.loads(p.read_text())
            for name, values in loaded.items():
                if isinstance(values, dict):
                    sections.setdefault(name, {}).update(values)
                else:
                    sections.setdefault("system", {})[name] = values
            source = str(p)
        except (OSError, ValueError):
            pass
    return AiosConfig(sections=sections, source_path=source)
