"""gRPC client/server interceptors: rpc_* metrics + trace propagation.

Installed by ``aios_tpu.rpc`` on every server (``create_server``) and
client channel (``insecure_channel``), so all six services and all their
stubs get, with zero per-service code:

  * ``aios_tpu_rpc_requests_total{side,service,method}``
  * ``aios_tpu_rpc_errors_total{side,service,method,code}``
  * ``aios_tpu_rpc_latency_seconds{side,service,method}``
  * a server span per RPC, parented to the caller's span through the
    ``traceparent`` metadata entry the client interceptor injects.

Set ``AIOS_OBS_DISABLED=1`` to serve without interceptors (perf A/B).
"""

from __future__ import annotations

import collections
import time
from typing import Optional, Tuple

import grpc

from . import instruments, tracing

TRACE_METADATA_KEY = "traceparent"


def _split_method(full_method: str) -> Tuple[str, str]:
    """"/aios.runtime.AIRuntime/Infer" -> ("aios.runtime.AIRuntime", "Infer")."""
    parts = (full_method or "/unknown/unknown").lstrip("/").split("/", 1)
    if len(parts) != 2:
        return full_method, "unknown"
    return parts[0], parts[1]


def _record(side: str, service: str, method: str, t0: float,
            code: Optional[grpc.StatusCode]) -> None:
    instruments.RPC_LATENCY.labels(
        side=side, service=service, method=method
    ).observe(time.perf_counter() - t0)
    if code is not None and code != grpc.StatusCode.OK:
        instruments.RPC_ERRORS.labels(
            side=side, service=service, method=method, code=code.name
        ).inc()


# -- server ----------------------------------------------------------------


class ServerObsInterceptor(grpc.ServerInterceptor):
    """Wraps every handler behavior with a span + the rpc_* metrics."""

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        service, method = _split_method(handler_call_details.method)
        traceparent = ""
        for key, value in handler_call_details.invocation_metadata or ():
            if key == TRACE_METADATA_KEY:
                traceparent = value
        request_streaming = handler.request_streaming
        response_streaming = handler.response_streaming
        behavior = (
            handler.stream_stream if request_streaming and response_streaming
            else handler.stream_unary if request_streaming
            else handler.unary_stream if response_streaming
            else handler.unary_unary
        )
        span_name = f"rpc.server.{service}/{method}"

        def observe_start() -> float:
            instruments.RPC_REQUESTS.labels(
                side="server", service=service, method=method
            ).inc()
            return time.perf_counter()

        if response_streaming:

            def wrapped(request_or_iterator, context):
                t0 = observe_start()
                code: Optional[grpc.StatusCode] = grpc.StatusCode.OK
                try:
                    with tracing.continue_span(traceparent, span_name):
                        yield from behavior(request_or_iterator, context)
                    code = _ctx_code(context) or grpc.StatusCode.OK
                except BaseException as exc:
                    code = _ctx_code(context) or _code_of(exc)
                    raise
                finally:
                    _record("server", service, method, t0, code)

        else:

            def wrapped(request_or_iterator, context):
                t0 = observe_start()
                code: Optional[grpc.StatusCode] = grpc.StatusCode.OK
                try:
                    with tracing.continue_span(traceparent, span_name):
                        response = behavior(request_or_iterator, context)
                    code = _ctx_code(context) or grpc.StatusCode.OK
                    return response
                except BaseException as exc:
                    code = _ctx_code(context) or _code_of(exc)
                    raise
                finally:
                    _record("server", service, method, t0, code)

        factory = getattr(
            grpc,
            ("stream_" if request_streaming else "unary_")
            + ("stream" if response_streaming else "unary")
            + "_rpc_method_handler",
        )
        return factory(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


def _ctx_code(context) -> Optional[grpc.StatusCode]:
    """The status the handler set on its ServicerContext (set_code /
    abort), if any — the authoritative server-side code for both the
    return path (set_code + normal return) and the abort path (abort
    raises a BARE Exception after setting it)."""
    getter = getattr(context, "code", None)
    if callable(getter):
        try:
            code = getter()
            if isinstance(code, grpc.StatusCode):
                return code
        except Exception:  # noqa: BLE001 - private-ish API; degrade
            pass
    return None


def _code_of(exc: BaseException) -> grpc.StatusCode:
    """Fallback status mapping for exceptions when the context carries no
    explicit code."""
    if isinstance(exc, grpc.RpcError):
        try:
            return exc.code()  # type: ignore[return-value]
        except Exception:  # noqa: BLE001
            return grpc.StatusCode.UNKNOWN
    if isinstance(exc, NotImplementedError):
        return grpc.StatusCode.UNIMPLEMENTED
    return grpc.StatusCode.UNKNOWN


# -- client ----------------------------------------------------------------


class _ClientCallDetails(
    collections.namedtuple(
        "_ClientCallDetails",
        ("method", "timeout", "metadata", "credentials", "wait_for_ready",
         "compression"),
    ),
    grpc.ClientCallDetails,
):
    pass


class ClientObsInterceptor(
    grpc.UnaryUnaryClientInterceptor,
    grpc.UnaryStreamClientInterceptor,
    grpc.StreamUnaryClientInterceptor,
    grpc.StreamStreamClientInterceptor,
):
    """Injects traceparent metadata + records client-side rpc_* metrics."""

    def _prepare(self, client_call_details):
        service, method = _split_method(client_call_details.method)
        metadata = list(client_call_details.metadata or ())
        traceparent = tracing.current_traceparent()
        if traceparent:
            metadata.append((TRACE_METADATA_KEY, traceparent))
        details = _ClientCallDetails(
            client_call_details.method,
            client_call_details.timeout,
            metadata,
            client_call_details.credentials,
            getattr(client_call_details, "wait_for_ready", None),
            getattr(client_call_details, "compression", None),
        )
        instruments.RPC_REQUESTS.labels(
            side="client", service=service, method=method
        ).inc()
        return details, service, method, time.perf_counter()

    def _attach(self, call, service: str, method: str, t0: float):
        def on_done(*_args) -> None:
            try:
                code = call.code()
            except Exception:  # noqa: BLE001
                code = grpc.StatusCode.UNKNOWN
            _record("client", service, method, t0, code)

        add_done = getattr(call, "add_done_callback", None)
        if add_done is not None:
            add_done(on_done)
        elif not call.add_callback(on_done):
            on_done()  # already terminated
        return call

    def intercept_unary_unary(self, continuation, client_call_details, request):
        details, service, method, t0 = self._prepare(client_call_details)
        return self._attach(continuation(details, request), service, method, t0)

    def intercept_unary_stream(self, continuation, client_call_details, request):
        details, service, method, t0 = self._prepare(client_call_details)
        return self._attach(continuation(details, request), service, method, t0)

    def intercept_stream_unary(
        self, continuation, client_call_details, request_iterator
    ):
        details, service, method, t0 = self._prepare(client_call_details)
        return self._attach(
            continuation(details, request_iterator), service, method, t0
        )

    def intercept_stream_stream(
        self, continuation, client_call_details, request_iterator
    ):
        details, service, method, t0 = self._prepare(client_call_details)
        return self._attach(
            continuation(details, request_iterator), service, method, t0
        )


_SERVER_INTERCEPTOR = ServerObsInterceptor()
_CLIENT_INTERCEPTOR = ClientObsInterceptor()


def server_interceptors() -> Tuple[grpc.ServerInterceptor, ...]:
    return (_SERVER_INTERCEPTOR,)


def intercept_client_channel(channel: grpc.Channel) -> grpc.Channel:
    return grpc.intercept_channel(channel, _CLIENT_INTERCEPTOR)
