"""The metric catalog: every instrument the stack registers, in one place.

Naming convention (enforced by tests/test_obs_lint.py):
  * prefix ``aios_tpu_``, snake_case ``[a-z0-9_]`` only;
  * unit suffix from the approved set: ``_seconds``, ``_bytes``,
    ``_total`` (counts and count-valued gauges), ``_ratio``,
    ``_per_second``, ``_usd_total`` (spend counters end in ``_total``
    with the currency inline), ``_info`` (the Prometheus info-gauge
    convention: constant 1, identity in labels — unitless by design).

Keeping every definition here (rather than scattered at point of use)
makes drift visible in review, keeps duplicate-registration impossible,
and gives the lint test one import to check. Hot paths resolve label
children once and hold them (see ContinuousBatcher) — ``labels()`` is a
dict lookup under a lock, fine for RPC rates, too slow per decoded token.

docs/OBSERVABILITY.md mirrors this catalog; update both together.
"""

from __future__ import annotations

from .metrics import Counter, Gauge, Histogram

# -- RPC layer (client + server interceptors, aios_tpu/rpc.py) -------------

RPC_REQUESTS = Counter(
    "aios_tpu_rpc_requests_total",
    "RPCs started, by side (client|server), service, and method",
    ("side", "service", "method"),
)
RPC_ERRORS = Counter(
    "aios_tpu_rpc_errors_total",
    "RPCs finished non-OK, by side, service, method, and status code",
    ("side", "service", "method", "code"),
)
RPC_LATENCY = Histogram(
    "aios_tpu_rpc_latency_seconds",
    "RPC wall time start->termination (streams: until exhausted)",
    ("side", "service", "method"),
)

# -- engine: decode loop + continuous batcher ------------------------------

ENGINE_DECODE_STEPS = Counter(
    "aios_tpu_engine_decode_steps_total",
    "Decode steps executed (each advances every active slot one token)",
    ("model",),
)
ENGINE_TOKENS = Counter(
    "aios_tpu_engine_generated_tokens_total",
    "Tokens emitted to request streams by the continuous batcher",
    ("model",),
)
ENGINE_TOKENS_PER_SECOND = Gauge(
    "aios_tpu_engine_tokens_per_second",
    "Recent decode throughput per model (tokens/sec/chip, ~1 s window)",
    ("model",),
)
ENGINE_TTFT = Histogram(
    "aios_tpu_engine_ttft_seconds",
    "Submission -> first sampled token through the continuous batcher",
    ("model",),
)
ENGINE_OCCUPANCY = Gauge(
    "aios_tpu_engine_batch_occupancy_ratio",
    "Active decode slots / total slots (scrape-time)",
    ("model",),
)
ENGINE_SLOTS_IN_USE = Gauge(
    "aios_tpu_engine_slots_in_use_total",
    "Active decode slots (scrape-time)",
    ("model",),
)
ENGINE_QUEUE_DEPTH = Gauge(
    "aios_tpu_engine_queue_depth_total",
    "Requests waiting for a slot (admission backlog, scrape-time)",
    ("model",),
)
ENGINE_KV_PAGES_IN_USE = Gauge(
    "aios_tpu_engine_kv_pages_in_use_total",
    "Paged-KV physical pages currently mapped (scrape-time)",
    ("model",),
)
ENGINE_KV_PAGE_UTILIZATION = Gauge(
    "aios_tpu_engine_kv_page_utilization_ratio",
    "Paged-KV pages in use / pool capacity (scrape-time)",
    ("model",),
)
ENGINE_PREFIX_HITS = Gauge(
    "aios_tpu_engine_prefix_cache_hits_total",
    "Prompt-prefix cache hits (monotonic, read from the prefix index)",
    ("model",),
)
ENGINE_PREFIX_MISSES = Gauge(
    "aios_tpu_engine_prefix_cache_misses_total",
    "Prompt-prefix cache misses (monotonic, read from the prefix index)",
    ("model",),
)
ENGINE_REQUESTS_COMPLETED = Counter(
    "aios_tpu_engine_requests_completed_total",
    "Requests retired normally (EOS / max_tokens / full cache)",
    ("model",),
)
ENGINE_REQUESTS_CANCELLED = Counter(
    "aios_tpu_engine_requests_cancelled_total",
    "Requests cancelled by the caller (gRPC disconnect, unload)",
    ("model",),
)
ENGINE_POOL_EVICTIONS = Counter(
    "aios_tpu_engine_pool_evictions_total",
    "Live requests retired to free KV pages under pool exhaustion",
    ("model",),
)
ENGINE_XLA_COMPILES = Counter(
    "aios_tpu_engine_xla_compiles_total",
    "XLA graph builds by kind "
    "(step|masked|prefill|chunk|spec|jump|hist|restore)",
    ("model", "kind"),
)
ENGINE_XLA_COMPILE_SECONDS = Histogram(
    "aios_tpu_engine_xla_compile_seconds",
    "First-dispatch wall time of each new XLA graph (trace+compile stall)",
    ("model", "kind"),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 80.0, 160.0),
)

# -- decode dispatch loop (pipelined batcher, AIOS_TPU_DECODE_PIPELINE) ----
# The dispatch family watches the host<->device seam of the decode loop:
# how long the host spends between consecutive decode dispatches (the
# device-idle window in the sync loop — the pipeline exists to hide it),
# whether a pipelined dispatch is currently in flight, and how often the
# pipeline had to drain early (constrained ticks, evictions, idle).

ENGINE_DISPATCH_HOST_GAP = Histogram(
    "aios_tpu_engine_dispatch_host_gap_seconds",
    "Host wall time between consecutive decode dispatches (emit/detok/"
    "retire/bookkeeping; the device idles through this unless pipelined)",
    ("model",),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 1.0),
)
ENGINE_DISPATCH_INFLIGHT = Gauge(
    "aios_tpu_engine_dispatch_inflight_total",
    "Pipelined decode dispatches enqueued but not yet consumed, summed "
    "over the model's replica batchers (0..replicas; scrape-time)",
    ("model",),
)
ENGINE_DISPATCH_FLUSHES = Counter(
    "aios_tpu_engine_dispatch_flushes_total",
    "Pipelined decode flushes by cause "
    "(constrained|spec|evict|idle)",
    ("model", "cause"),
)

# -- grammar jump-ahead decoding (engine.jump_step; batching constrained
# tick) — monotonic engine counters read at scrape time, SUMMED over a
# per-model WeakSet of live replica engines (set_function is last-writer-
# wins; the aios_tpu_prefix_host_* aggregation pattern).

ENGINE_JUMP_DISPATCHES = Gauge(
    "aios_tpu_engine_jump_ahead_dispatches_total",
    "Multi-token jump-ahead dispatches (each replaced a chain of masked "
    "single-token dispatches; monotonic, summed over replica engines)",
    ("model",),
)
ENGINE_JUMP_TOKENS = Gauge(
    "aios_tpu_engine_jump_ahead_tokens_total",
    "Grammar-forced tokens emitted via jump-ahead runs (monotonic, "
    "summed over replica engines)",
    ("model",),
)

# -- multi-tick decode megagraph (engine.mega_step) — same WeakSet-summed
# monotonic engine counters as the jump family. dispatches * K - ticks
# is the early-exit savings; ticks / dispatches the realized window.

ENGINE_MEGA_DISPATCHES = Gauge(
    "aios_tpu_engine_mega_dispatches_total",
    "Multi-tick decode megagraph dispatches (each replaced up to K "
    "single-tick dispatches; monotonic, summed over replica engines)",
    ("model",),
)
ENGINE_MEGA_TICKS = Gauge(
    "aios_tpu_engine_mega_ticks_total",
    "REAL decode ticks run inside megagraph dispatches (k per dispatch, "
    "k <= K on early exit; monotonic, summed over replica engines)",
    ("model",),
)

# -- speculative decoding (engine.spec_step / spec_step_draft) -------------
# Rounds/accepted are engine counters (WeakSet-summed like the jump
# family); the acceptance ratio is the per-batcher EWMA driving the
# AIOS_TPU_SPEC_MIN_ACCEPT auto-disable, averaged over live replica
# batchers at scrape time. Every series carries the ``proposer`` label —
# the CLOSED enum spec.SPEC_PROPOSERS (ngram | draft), pinned by
# test_obs_lint — so the draft-model and prompt-lookup proposers read as
# separate series and the ladder's fallbacks are visible in the metrics.

SPEC_ROUNDS = Gauge(
    "aios_tpu_spec_rounds_total",
    "Speculative verify rounds dispatched by proposer (ngram|draft; "
    "monotonic, summed over replica engines)",
    ("model", "proposer"),
)
SPEC_ACCEPTED = Gauge(
    "aios_tpu_spec_accepted_total",
    "Draft tokens accepted by speculative verify (emitted tokens minus "
    "the one guaranteed token per slot-round; by proposer, monotonic, "
    "summed over replica engines)",
    ("model", "proposer"),
)
SPEC_ACCEPTANCE = Gauge(
    "aios_tpu_spec_acceptance_ratio",
    "EWMA draft-acceptance ratio (accepted / proposed) per model and "
    "proposer, averaged over replica batchers; drives the per-proposer "
    "AIOS_TPU_SPEC_MIN_ACCEPT auto-disable ladder",
    ("model", "proposer"),
)

# -- long-context tier (docs/ENGINE_PERF.md "Long-context tier") -----------
# Window+sink KV compression + sequence-sharded prefill. Counters are
# monotonic engine counters read at scrape time, SUMMED over the
# per-model WeakSet of live replica engines (the jump/spec pattern);
# the resident gauge reads live allocator state.

KV_COMPRESS_SLOTS = Gauge(
    "aios_tpu_kv_compress_slots_total",
    "Slots whose KV crossed the compression threshold and pruned to "
    "sink + window pages (monotonic, summed over replica engines)",
    ("model",),
)
KV_COMPRESS_PAGES_PRUNED = Gauge(
    "aios_tpu_kv_compress_pages_pruned_total",
    "KV pages released back to the pool by window+sink pruning "
    "(monotonic, summed over replica engines)",
    ("model",),
)
KV_COMPRESS_RESIDENT = Gauge(
    "aios_tpu_kv_compress_resident_pages",
    "Pages currently resident for compressed slots (sink + trailing "
    "window + partial block; scrape-time, summed over replica engines)",
    ("model",),
)
PREFILL_SEQ_SHARDED = Gauge(
    "aios_tpu_prefill_seq_sharded_total",
    "Prompts admitted through the sequence-sharded (sp-axis ring/"
    "Ulysses) prefill path instead of chunked admission (monotonic, "
    "summed over replica engines)",
    ("model",),
)

# -- prefix-cache host spill tier (engine/paged.py HostPageStore) ----------
# Monotonic store counters surface as count-valued gauges read at scrape
# time (the ENGINE_PREFIX_* pattern); only the restore latency is a true
# histogram observed on the restore path.

PREFIX_HOST_BYTES = Gauge(
    "aios_tpu_prefix_host_resident_bytes",
    "Host-RAM bytes holding spilled prefix-page KV (scrape-time)",
    ("model",),
)
PREFIX_HOST_SPILLS = Gauge(
    "aios_tpu_prefix_host_spills_total",
    "Prefix pages spilled device->host on HBM eviction (monotonic)",
    ("model",),
)
PREFIX_HOST_RESTORES = Gauge(
    "aios_tpu_prefix_host_restores_total",
    "Prefix pages restored host->device into fresh pool pages (monotonic)",
    ("model",),
)
PREFIX_HOST_HITS = Gauge(
    "aios_tpu_prefix_host_hits_total",
    "Host-tier chain probes that found at least one spilled page "
    "(monotonic)",
    ("model",),
)
PREFIX_HOST_MISSES = Gauge(
    "aios_tpu_prefix_host_misses_total",
    "Host-tier chain probes that found nothing (monotonic)",
    ("model",),
)
PREFIX_HOST_MISSES_CORRUPT = Gauge(
    "aios_tpu_prefix_host_corrupt_total",
    "Spilled pages whose crc32 failed verification at restore probe "
    "time — dropped and recomputed instead of restored (monotonic)",
    ("model",),
)
PREFIX_HOST_RESTORE_SECONDS = Histogram(
    "aios_tpu_prefix_host_restore_seconds",
    "Host-side wall time to stage + dispatch one host->device prefix "
    "restore (the scatter itself is async and overlaps tail prefill)",
    ("model",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0),
)

# -- SLO engine (obs/slo.py, fed by the obs/flightrec.py recorder) ---------
# Labeled (model, objective) with ``objective`` drawn from the closed
# slo.OBJECTIVES enum (ttft|tpot|availability). The per-tenant breakdown
# deliberately stays in /debug/slo JSON — a tenant x model label product
# would be unbounded (the test_serving_label_conventions rationale).

SLO_ATTAINMENT = Gauge(
    "aios_tpu_slo_attainment_ratio",
    "Fraction of windowed requests meeting the objective's target "
    "(objective=ttft|tpot|availability; scrape-time, sliding window)",
    ("model", "objective"),
)
SLO_BURN_RATE = Gauge(
    "aios_tpu_slo_burn_rate_ratio",
    "Error-budget burn rate: (1 - attainment) / (1 - target); 1.0 burns "
    "exactly at budget, >1 eats future budget (scrape-time)",
    ("model", "objective"),
)
SLO_BREACHES = Counter(
    "aios_tpu_slo_breaches_total",
    "Windowed attainment fell below target (edge-triggered per "
    "(model, objective); each breach freezes a flight-recorder snapshot)",
    ("model", "objective"),
)

# -- runtime service -------------------------------------------------------

RUNTIME_INFER_LATENCY = Histogram(
    "aios_tpu_runtime_infer_latency_seconds",
    "Per-model inference RPC wall time (rpc = Infer|StreamInfer)",
    ("model", "rpc"),
)
RUNTIME_STREAM_CHUNKS = Counter(
    "aios_tpu_runtime_stream_chunks_total",
    "Text chunks emitted by StreamInfer",
    ("model",),
)
RUNTIME_MODELS_READY = Gauge(
    "aios_tpu_runtime_models_ready_total",
    "Models in the ready state (scrape-time)",
)

# -- serving layer (replica pool + router + admission, aios_tpu/serving/) --
# Labeled by the MANAGED model name (pool name), not the config name —
# two managed models of the same architecture must not collapse into one
# series. ``replica`` is the replica index (bounded by the replica count).

SERVING_REPLICAS = Gauge(
    "aios_tpu_serving_replicas_total",
    "Live replicas in the pool (scrape-time)",
    ("model",),
)
SERVING_REPLICA_OCCUPANCY = Gauge(
    "aios_tpu_serving_replica_occupancy_ratio",
    "Per-replica active decode slots / total slots (scrape-time)",
    ("model", "replica"),
)
SERVING_ROUTING_DECISIONS = Counter(
    "aios_tpu_serving_routing_decisions_total",
    "Replica selections by reason (prefix|sticky|least_loaded|spill|single)",
    ("model", "reason"),
)
SERVING_SHED = Counter(
    "aios_tpu_serving_shed_total",
    "Requests shed at the front door, by cause "
    "(quota|deadline|queue_full|draining)",
    ("model", "cause"),
)
SERVING_QUOTA_REJECTIONS = Counter(
    "aios_tpu_serving_quota_rejections_total",
    "Token-bucket quota rejections per tenant",
    ("tenant",),
)
SERVING_QUEUE_WAIT = Histogram(
    "aios_tpu_serving_queue_wait_seconds",
    "Submission -> batcher admission (slot assignment) wall time",
    ("model",),
)
SERVING_REPLICA_RESTARTS = Counter(
    "aios_tpu_serving_replica_restarts_total",
    "Replica batchers respawned after a scheduler crash "
    "(the spawner-style restart counter, serving-side)",
    ("model",),
)
SERVING_FAILOVERS = Counter(
    "aios_tpu_serving_failover_total",
    "In-flight requests re-routed after a replica failure, by outcome "
    "(resumed = resubmitted to a surviving replica; exhausted = retry "
    "budget spent, surfaced as UNAVAILABLE + retry-after)",
    ("model", "outcome"),
)

# -- SLO autoscaler (serving/autoscale.py, docs/RUNBOOK.md §8) -------------
# ``action`` and ``cause`` are the CLOSED autoscale.ACTIONS / CAUSES
# enums; the controller pre-registers every (action, cause) child by
# iterating both tuples at construction (the SLO-objectives pattern), so
# a new action is a reviewed enum change, not a stray label value.

AUTOSCALE_ACTIONS = Counter(
    "aios_tpu_autoscale_actions_total",
    "SLO-burn autoscaler actions (action=scale_up|scale_down|degrade|"
    "restore off the windowed burn rate; cause=burn|ceiling|recovery|"
    "kill_switch). Every action also lands on the flight recorder's "
    "model lane with level/replica evidence",
    ("model", "action", "cause"),
)

# -- device-time attribution (obs/devprof.py, docs/OBSERVABILITY.md) -------
# Armed by AIOS_TPU_DEVPROF; every series' ``graph`` label is drawn from
# the CLOSED devprof.GRAPH_KINDS enum (the engine registers the children
# by iterating it — the SLO-objectives pattern), and all per-graph
# series are monotonic ledger counters read at scrape time, SUMMED over
# the per-model WeakSet of live replica ledgers (set_function is
# last-writer-wins — the aios_tpu_prefix_host_* lesson). Only the
# tenant counter is a true Counter, and it carries the tenant label
# ALONE (the quota-metric precedent: a tenant x model product is
# unbounded; the per-model breakdown lives in /debug/devprof JSON).

DEVPROF_DISPATCHES = Gauge(
    "aios_tpu_devprof_dispatches_total",
    "Device dispatches per serving-graph kind (graph in the closed "
    "devprof.GRAPH_KINDS enum; monotonic, summed over replica ledgers)",
    ("model", "graph"),
)
DEVPROF_DEVICE_SECONDS = Gauge(
    "aios_tpu_devprof_device_seconds_total",
    "Estimated device-busy seconds per graph kind: mean sampled "
    "completion time extrapolated over all dispatches (monotonic-ish, "
    "summed over replica ledgers; raw even when the roofline is unknown)",
    ("model", "graph"),
)
DEVPROF_MFU = Gauge(
    "aios_tpu_devprof_mfu_ratio",
    "Model FLOPs utilization per graph kind: static cost_analysis FLOPs "
    "of sampled dispatches / sampled seconds / the device_kind's peak "
    "FLOP/s (docs/HARDWARE.md roofline table; omitted on unknown kinds)",
    ("model", "graph"),
)
DEVPROF_HBM_UTIL = Gauge(
    "aios_tpu_devprof_hbm_bandwidth_utilization_ratio",
    "HBM bandwidth utilization per graph kind: cost_analysis bytes of "
    "sampled dispatches / sampled seconds / the device_kind's peak "
    "HBM bytes/s (docs/HARDWARE.md; omitted on unknown kinds)",
    ("model", "graph"),
)
DEVPROF_TENANT_SECONDS = Counter(
    "aios_tpu_devprof_tenant_device_seconds_total",
    "Estimated device-seconds billed per tenant at request retirement "
    "(timeline attribution: per-dispatch ledger means split by batch "
    "occupancy + measured prefill time; per-model detail in "
    "/debug/devprof)",
    ("tenant",),
)

# -- fleet telemetry plane (obs/fleet.py, docs/OBSERVABILITY.md) -----------
# Every series is labeled (host, role) — host ids are one-per-process
# (bounded by fleet size, never per-request), and the transitions
# counter's ``state`` label is the CLOSED fleet.MEMBER_STATES enum
# (up|suspect|dead); the registry pre-registers every (host, role,
# state) child by iterating the tuple when a member is first seen (the
# autoscale/SLO registration pattern).

FLEET_MEMBER_UP = Gauge(
    "aios_tpu_fleet_member_up_total",
    "1 while the member's heartbeat is fresh, 0 once the failure "
    "detector marks it suspect/dead (the fleet 'up' boolean, per host "
    "and role)",
    ("host", "role"),
)
FLEET_TRANSITIONS = Counter(
    "aios_tpu_fleet_member_transitions_total",
    "Membership state-machine edges by destination state (state in the "
    "closed fleet.MEMBER_STATES enum: up|suspect|dead; every edge also "
    "lands in the transition journal and on the fleet recorder lane)",
    ("host", "role", "state"),
)
FLEET_SCRAPE_FAILURES = Counter(
    "aios_tpu_fleet_scrape_failures_total",
    "Federation/stitch fetches of a live member's endpoint that failed "
    "(the host drops out of that /metrics/fleet response — absence plus "
    "this counter is the signal)",
    ("host", "role"),
)
FLEET_KVX_PAGES = Counter(
    "aios_tpu_fleet_kvx_pages_total",
    "HostPageStore entries shipped over the fleet transfer plane, by "
    "direction (closed kvx.KVX_DIRECTIONS enum: push = prefill host "
    "streaming pages out, pull = decode host fetching on miss)",
    ("model", "direction"),
)
FLEET_KVX_BYTES = Counter(
    "aios_tpu_fleet_kvx_bytes_total",
    "Payload bytes shipped over the fleet transfer plane, by direction "
    "(same closed direction enum as the pages counter; packed wire "
    "bytes, crc envelopes excluded)",
    ("model", "direction"),
)
FLEET_KVX_FAILURES = Counter(
    "aios_tpu_fleet_kvx_failures_total",
    "Transfers that failed and fell back to local prefill, by cause "
    "(closed kvx.KVX_FAIL_CAUSES enum — crc_mismatch is the receiving "
    "end of the verified-at-both-ends contract rejecting a payload)",
    ("model", "cause"),
)
FLEET_ROUTE = Counter(
    "aios_tpu_fleet_route_total",
    "Fleet-level routing decisions by reason (closed "
    "router.FLEET_ROUTE_REASONS enum: the sticky -> overlap -> "
    "least-loaded ladder extended fleet-wide, plus the disagg handoff "
    "outcomes)",
    ("model", "reason"),
)
FLEET_PEER_BREAKER = Gauge(
    "aios_tpu_fleet_peer_breaker_state_total",
    "Per-peer circuit-breaker state as an index into the closed "
    "breaker.BREAKER_STATES enum (0=closed, 1=open, 2=half_open; "
    "anything non-zero means the peer is quarantined — routed around "
    "until consecutive successful probes clear it). host is the "
    "OBSERVING side of the edge",
    ("host", "peer"),
)
FLEET_ANNOUNCE_FAILURES = Counter(
    "aios_tpu_fleet_announce_failures_total",
    "Heartbeat announces that never got a reply, per peer address — "
    "a climbing single-peer count with members still up is the "
    "asymmetric-partition signature (RUNBOOK §11)",
    ("peer",),
)

# -- process identity (obs/fleet.py stamp, every metrics endpoint) ---------

PROCESS_INFO = Gauge(
    "aios_tpu_process_info",
    "Process identity info-gauge (constant 1): host id, multihost rank, "
    "service role, package version — joins federated scrapes and bench "
    "captures to the process that produced them",
    ("host", "rank", "role", "version"),
)

# -- fault injection (aios_tpu/faults/, docs/FAULTS.md) --------------------

FAULTS_INJECTED = Counter(
    "aios_tpu_faults_injected_total",
    "Faults fired by the seeded injection layer (point = injection-point "
    "name from faults.POINTS, mode = nth|prob|after)",
    ("point", "mode"),
)

# -- black-box time series (obs/tsdb.py, docs/OBSERVABILITY.md) ------------
# Armed by AIOS_TPU_TSDB; the ring samples every registered instrument,
# including this family (its own bookkeeping is three series — noise-
# free). The queries counter's ``verb`` label is the CLOSED
# tsdb.QUERY_VERBS enum, pre-registered by iterating the tuple at ring
# construction (the autoscale/SLO registration pattern); the series /
# dropped gauges are fn-backed live state (monotonic for dropped).

TSDB_SAMPLES = Counter(
    "aios_tpu_tsdb_sample_passes_total",
    "Sampler passes completed (one pass reads the whole registry and "
    "appends one point per live series)",
)
TSDB_SERIES = Gauge(
    "aios_tpu_tsdb_series_total",
    "Series currently tracked by the ring (scrape-time; bounded by "
    "AIOS_TPU_TSDB_MAX_SERIES)",
)
TSDB_DROPPED = Gauge(
    "aios_tpu_tsdb_dropped_series_total",
    "Distinct series refused by the cardinality cap (monotonic, "
    "scrape-time) — the no-silent-truncation contract: a non-zero value "
    "means the ring is blind to that many series",
)
TSDB_QUERIES = Counter(
    "aios_tpu_tsdb_queries_total",
    "/debug/tsdb expressions evaluated, by verb (the closed "
    "tsdb.QUERY_VERBS enum: raw|rate|avg|min|max|p50|p90|p95|p99)",
    ("verb",),
)

# -- incident bundles (obs/incidents.py, docs/OBSERVABILITY.md) ------------
# ``cause`` is the CLOSED incidents.TRIGGER_CAUSES enum, pre-registered
# by iterating the tuple at store construction; suppressed counts the
# per-(model, cause) cooldown swallowing a trigger burst — fired +
# suppressed is the true trigger rate.

INCIDENTS = Counter(
    "aios_tpu_incidents_total",
    "Incident bundles frozen, by trigger cause (closed "
    "incidents.TRIGGER_CAUSES enum; each bundle = tsdb window + "
    "flightrec snapshot + fault journal + devprof + lock-watchdog "
    "state, served at /debug/incidents)",
    ("cause",),
)
INCIDENTS_SUPPRESSED = Counter(
    "aios_tpu_incidents_suppressed_total",
    "Triggers swallowed by the per-(model, cause) cooldown — a burst "
    "freezes exactly one bundle; this counter keeps the rest visible",
    ("cause",),
)

# -- orchestrator ----------------------------------------------------------

GOAL_TASKS = Counter(
    "aios_tpu_goal_tasks_total",
    "Task outcomes recorded by the result aggregator (outcome=success|failure)",
    ("outcome",),
)
GOAL_TASK_TOKENS = Counter(
    "aios_tpu_goal_task_tokens_total",
    "Model tokens consumed by recorded task outcomes",
)
GOAL_TASK_DURATION = Histogram(
    "aios_tpu_goal_task_duration_seconds",
    "Wall time of recorded task outcomes",
)
DECISIONS = Counter(
    "aios_tpu_decisions_total",
    "Decisions logged, by intelligence level",
    ("level",),
)
SCHEDULER_FIRED = Counter(
    "aios_tpu_scheduler_fired_total",
    "Cron schedules fired into goal submission",
)
ROUTER_TASKS = Counter(
    "aios_tpu_router_tasks_total",
    "Task routing outcomes (outcome=routed|ai_path|no_capable_agent)",
    ("outcome",),
)

# -- agents ----------------------------------------------------------------

AGENT_RESTARTS = Counter(
    "aios_tpu_agent_restarts_total",
    "Agent child-process restarts by the spawner",
    ("agent",),
)

# -- api gateway -----------------------------------------------------------

GATEWAY_SPEND = Counter(
    "aios_tpu_gateway_spend_usd_total",
    "Cloud spend recorded against provider budgets (USD)",
    ("provider",),
)
GATEWAY_TOKENS = Counter(
    "aios_tpu_gateway_tokens_total",
    "Cloud tokens by provider and direction (input|output)",
    ("provider", "direction"),
)

# -- memory tiers ----------------------------------------------------------

MEMORY_TIER_LOOKUPS = Counter(
    "aios_tpu_memory_tier_lookups_total",
    "Tier lookups (tier=operational|working|longterm|knowledge, "
    "result=hit|miss)",
    ("tier", "result"),
)

# -- tools -----------------------------------------------------------------

TOOL_INVOCATIONS = Counter(
    "aios_tpu_tool_invocations_total",
    "Tool executions recorded in the audit ledger (outcome=success|failure)",
    ("tool", "outcome"),
)
