#!/usr/bin/env bash
# Operator CLI for a running aiOS-TPU stack.
#
# The reference ships service management inside its initd + systemctl tool
# handlers (/root/reference/scripts/*, service.* tools); on a TPU VM the
# equivalents are this script's probes against the five gRPC services and
# the console's REST API.
#
# Usage: scripts/aiosctl.sh <command>
#   status    one line per service: port reachability
#   health    orchestrator + runtime health detail (console /api/*)
#   serving   per-model TPU serving counters (slots, pages, prefix, queue)
#   goals     recent goals through the console
#   submit "<text>"   submit a goal
#   cancel <goal-id>  cancel a goal (also aborts its in-flight AI work)
#   logs [service]    tail the supervisor's per-service logs
#   start|stop|restart    systemd unit control (install --systemd first)
set -euo pipefail

CONSOLE=${AIOS_CONSOLE:-http://127.0.0.1:9090}
LOG_DIR=${AIOS_LOG_DIR:-/var/lib/aios/data/logs}

# console host:port derived from AIOS_CONSOLE so `status` probes the same
# endpoint the REST subcommands talk to
CONSOLE_HP=${CONSOLE#*://}; CONSOLE_HP=${CONSOLE_HP%%/*}
CONSOLE_HOST=${CONSOLE_HP%%:*}
CONSOLE_PORT=${CONSOLE_HP##*:}; [[ "$CONSOLE_PORT" == "$CONSOLE_HOST" ]] && CONSOLE_PORT=80

# service addresses honor the same AIOS_*_ADDR env overrides the service
# clients use (host:port), so aiosctl can point at a non-default stack
# (e.g. the e2e test stack on ephemeral ports)
addr_port() { local a="${1:-}"; echo "${a##*:}"; }
addr_host() { local a="${1:-}" h; h="${a%%:*}"; echo "${h:-127.0.0.1}"; }
declare -A PORTS=(
  [orchestrator]=$(addr_port "${AIOS_ORCHESTRATOR_ADDR:-:50051}")
  [tools]=$(addr_port "${AIOS_TOOLS_ADDR:-:50052}")
  [memory]=$(addr_port "${AIOS_MEMORY_ADDR:-:50053}")
  [gateway]=$(addr_port "${AIOS_GATEWAY_ADDR:-:50054}")
  [runtime]=$(addr_port "${AIOS_RUNTIME_ADDR:-:50055}")
  [console]=$CONSOLE_PORT
)
declare -A HOSTS=(
  [orchestrator]=$(addr_host "${AIOS_ORCHESTRATOR_ADDR:-}")
  [tools]=$(addr_host "${AIOS_TOOLS_ADDR:-}")
  [memory]=$(addr_host "${AIOS_MEMORY_ADDR:-}")
  [gateway]=$(addr_host "${AIOS_GATEWAY_ADDR:-}")
  [runtime]=$(addr_host "${AIOS_RUNTIME_ADDR:-}")
  [console]=$CONSOLE_HOST
)

probe() {  # probe <host> <port> — the subshell opens and closes the socket
  (exec 3<>"/dev/tcp/$1/$2") 2>/dev/null
}

cmd=${1:-status}
case "$cmd" in
  status)
    rc=0
    for name in orchestrator tools memory gateway runtime console; do
      port=${PORTS[$name]}
      host=${HOSTS[$name]}
      if probe "$host" "$port"; then
        echo "$name :$port up"
      else
        echo "$name :$port DOWN"
        rc=1
      fi
    done
    exit $rc
    ;;
  health)
    curl -fsS "$CONSOLE/api/health" && echo
    curl -fsS "$CONSOLE/api/status" && echo
    ;;
  serving)
    curl -fsS "$CONSOLE/api/serving" && echo
    ;;
  goals)
    curl -fsS "$CONSOLE/api/goals" && echo
    ;;
  submit)
    [[ $# -ge 2 ]] || { echo "usage: aiosctl.sh submit \"<goal>\"" >&2; exit 2; }
    curl -fsS -X POST "$CONSOLE/api/goals" \
      -H 'Content-Type: application/json' \
      -d "{\"description\": $(python3 -c 'import json,sys; print(json.dumps(sys.argv[1]))' "$2")}" && echo
    ;;
  cancel)
    [[ $# -ge 2 ]] || { echo "usage: aiosctl.sh cancel <goal-id>" >&2; exit 2; }
    curl -fsS -X POST "$CONSOLE/api/goals/$2/cancel" && echo
    ;;
  logs)
    svc=${2:-}
    if [[ -d "$LOG_DIR" ]]; then
      shopt -s nullglob
      logs=("$LOG_DIR"/*.log)
      shopt -u nullglob
      if [[ -n "$svc" ]]; then
        tail -n 100 -f "$LOG_DIR/$svc.log"
      elif [[ ${#logs[@]} -gt 0 ]]; then
        tail -n 20 "${logs[@]}"
      else
        echo "no logs yet in $LOG_DIR"
      fi
    elif command -v journalctl >/dev/null; then
      journalctl -u aios.service -n 100 ${svc:+-g "$svc"} --no-pager
    else
      echo "no $LOG_DIR and no journalctl" >&2; exit 1
    fi
    ;;
  start|stop|restart)
    sudo systemctl "$cmd" aios.service
    ;;
  *)
    echo "unknown command: $cmd (status|health|serving|goals|submit|cancel|logs|start|stop|restart)" >&2
    exit 2
    ;;
esac
