"""Cache-aware replica selection (SGLang-style, arXiv:2312.07104).

aiOS traffic is shared-prefix by construction: every agent rebuilds its
prompt from the same system/task preamble each reasoning round. On a
multi-replica pool the throughput lever is therefore WHERE a request
lands — the replica already holding the prompt's prefix pages serves it
with a page-table update instead of a prefill. Selection order:

  1. **sticky** — a ``task_id`` continuation goes back to the replica
     that served the task before (its whole conversation KV lives there);
  2. **prefix** — score every replica by prefix-cache overlap with the
     prompt ids (a read-only peek at the replica's prefix index — the
     radix tree ``paged.RadixPrefixIndex`` by default, which credits
     PARTIAL-node overlap: a prompt diverging inside another prompt's
     cached run still scores the blocks it shares — no hit/miss
     counters touched, no LRU refresh, no node splits) and take the
     best one when the overlap covers at least ``overlap_min_ratio``
     of the prompt. Rows resident only in a replica's host spill tier
     (``paged.HostPageStore``) count at
     ``paged.HOST_OVERLAP_DISCOUNT``: a restorable prefix is a memcpy,
     not free, so routing still prefers true HBM residency but credits
     the replica that can restore over one that must recompute;
  3. **least_loaded** — otherwise, fewest outstanding tokens (queued
     prompt+budget plus live remaining budget) wins.

The pool overrides a full chosen replica with the least-loaded one that
still has queue room (reason ``spill``) before the admission queue-bound
gate sheds.
"""

from __future__ import annotations

from collections import OrderedDict

from ..analysis.locks import make_lock
from typing import List, Optional, Sequence, Tuple

_STICKY_CAPACITY = 4096  # task ids are client input; LRU-bound the map


class Router:
    def __init__(self, overlap_min_ratio: float = 0.25) -> None:
        self.overlap_min_ratio = overlap_min_ratio
        self._sticky: "OrderedDict[str, int]" = OrderedDict()  #: guarded_by _lock
        self._lock = make_lock("router")

    def select(self, replicas: Sequence, prompt_ids: List[int],
               task_id: str = "",
               hashes: Optional[List[bytes]] = None,
               detail: Optional[dict] = None) -> Tuple[int, str]:
        """Pick a replica index for a request. ``replicas`` are
        Replica-shaped objects (``overlap_rows(ids, hashes=None)``,
        ``outstanding_tokens()``); returns (index, reason). ``hashes``
        are the prompt's precomputed block digests (the ``bytes`` sha256
        chain of ``paged.chain_hashes``) — the pool hashes once so N
        replicas don't each redo the sha256 chain. A caller-supplied
        ``detail`` dict receives the decision's evidence (best overlap
        rows — host-discounted rows included, per the replica's probe —
        and the threshold it was held to) for the flight recorder."""
        if len(replicas) == 1:
            return 0, "single"
        sticky = self._sticky_for(task_id, len(replicas))
        if sticky is not None:
            return sticky, "sticky"
        best, best_rows = -1, 0
        for i, r in enumerate(replicas):
            rows = r.overlap_rows(prompt_ids, hashes=hashes)
            if rows > best_rows:
                best, best_rows = i, rows
        threshold = max(1, int(len(prompt_ids) * self.overlap_min_ratio))
        if detail is not None:
            detail["overlap_rows"] = best_rows
            detail["overlap_threshold"] = threshold
        if best >= 0 and best_rows >= threshold:
            return best, "prefix"
        return self.least_loaded(replicas), "least_loaded"

    @staticmethod
    def least_loaded(replicas: Sequence) -> int:
        return min(
            range(len(replicas)),
            key=lambda i: replicas[i].outstanding_tokens(),
        )

    def _sticky_for(self, task_id: str, n: int) -> Optional[int]:
        if not task_id:
            return None
        with self._lock:
            idx = self._sticky.get(task_id)
            if idx is None:
                return None
            self._sticky.move_to_end(task_id)
            # a shrunk pool (failed replica) invalidates the binding
            return idx if idx < n else None

    def note_routed(self, task_id: str, idx: int) -> None:
        """Record where a task landed so its continuations stay put."""
        if not task_id:
            return
        with self._lock:
            self._sticky[task_id] = idx
            self._sticky.move_to_end(task_id)
            while len(self._sticky) > _STICKY_CAPACITY:
                self._sticky.popitem(last=False)
