"""Deterministic, seeded fault injection for the serving plane.

The reference aiOS survives component failure by design — the spawner
restarts crashed agents and the intelligence hierarchy degrades tier by
tier — but recovery code nobody can *provoke* is recovery code nobody
has tested. This module gives the TPU serving plane named injection
points compiled into its hot paths:

    pool.scheduler_crash    the batcher scheduler thread raises mid-tick
    dispatch.delay          the decode loop sleeps before a dispatch
    host_store.restore_fail the host-tier restore dies mid-scatter
    host_store.corrupt      a spilled page's bytes flip (crc32 catches it)
    rpc.unavailable         a server RPC aborts UNAVAILABLE + retry-after
    allocator.pressure      alloc_pages raises PoolExhausted
    admission.clock_skew    the deadline gate sees a skewed clock

Each point is a **near-zero-cost no-op** unless a schedule is active:
the hot-path call is one module-global ``None`` check. A schedule comes
from ``AIOS_TPU_FAULTS`` (or boot ``[faults]`` -> that env, or
:func:`activate` in tests/bench)::

    AIOS_TPU_FAULTS="seed=42;pool.scheduler_crash=nth:3;\
dispatch.delay=prob:0.25,delay_ms=20;admission.clock_skew=after:5,skew_ms=2000"

Triggers (the fire decision is a pure function of ``(seed, point,
hit-index)`` for ``nth``/``prob`` — the same seed and call pattern
reproduce the same injected-fault sequence, which is what makes a chaos
run a *regression test* instead of a dice roll):

  * ``nth:N``  — fire exactly on the Nth hit of the point (one-shot);
  * ``prob:P`` — fire each hit with probability P, drawn from a
    per-point PRNG seeded with ``(seed, point)`` — one draw per hit;
  * ``after:T`` — fire on every hit once T seconds have elapsed since
    activation (wall-clock; for live chaos drills, not determinism).

Optional ``key=value`` params ride after the trigger: ``delay_ms``
(dispatch.delay, net.delay), ``skew_ms`` (admission.clock_skew),
``retry_after_ms`` (rpc.unavailable), ``after_msgs`` (net.drop_after).
The ``net.*`` points additionally take STRING-valued scoping params —
``src=``/``dst=`` (fleet host ids) and ``surface=`` ("rpc"/"http") —
and count hits per ``(src, dst)`` edge, so the k-th send on one edge
fires deterministically regardless of other edges' traffic; ``until=M``
widens an ``nth:N`` one-shot into the held window ``[N, M]``
(docs/FAULTS.md "Per-edge network faults").

Every fired fault is counted by ``aios_tpu_faults_injected_total{point,
mode}``, recorded on the flight recorder's model lane as a ``fault``
event, and appended to a bounded in-process journal (:func:`fired`) so
a chaos harness can assert the injected sequence was identical across
re-runs. See docs/FAULTS.md.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..analysis.locks import make_lock
from ..obs import instruments as obs

log = logging.getLogger("aios.faults")

__all__ = [
    "POINTS", "MODES", "FaultAction", "InjectedFault", "activate",
    "deactivate", "active", "point", "fired", "install_from_env",
]

# The closed catalog of injection points. A schedule naming anything
# else logs and skips it (the lenient-env pattern) — a typo must not
# silently arm nothing while the operator believes chaos is running.
POINTS = (
    "pool.scheduler_crash",
    "dispatch.delay",
    "host_store.restore_fail",
    "host_store.corrupt",
    "rpc.unavailable",
    "allocator.pressure",
    "admission.clock_skew",
    # decode-host loss mid-handoff (aios_tpu/fleet/disagg.py): the
    # servicer aborts the stream — or, with exit=1, kills the whole
    # process (the disagg smoke's real host kill) — and the prefill
    # host re-hands the stream to a survivor
    "fleet.host_kill",
    # per-EDGE network faults (aios_tpu/faults/net.py): scoped by
    # src=/dst= host-id params (string-valued) and an optional
    # surface= filter ("rpc" | "http"), hit-counted PER EDGE so the
    # k-th send on one edge fires deterministically no matter how
    # other edges interleave. Injected at the shared rpc client
    # interceptor and the obs/fleet.py HTTP helpers — membership,
    # federation, KVX, and Handoff all traverse one fault surface.
    "net.partition",          # both directions refused
    "net.partition_oneway",   # src->dst dropped, reverse clean
    "net.delay",              # per-edge latency (delay_ms)
    "net.drop_after",         # stream severed after after_msgs messages
    # multi-tick decode megagraph (engine.py _mega_dispatch): caps the
    # device while-loop's abort_after operand mid-window (ticks param)
    # so the early-exit path fires with slots still live — the chaos
    # storm's proof that a k<K readback retires/streams correctly
    "pool.megatick_abort",
)

MODES = ("nth", "prob", "after")

# journal bound: a chaos storm fires tens of faults, not thousands; the
# cap only guards against a runaway prob:1.0 schedule on a hot point
_MAX_JOURNAL = 4096

# parameter defaults per point: a schedule that names the point but not
# its magnitude still injects SOMETHING — a fired fault that is secretly
# a no-op would count in the metric/journal while exercising nothing
_PARAM_DEFAULTS: Dict[str, Dict[str, float]] = {
    "dispatch.delay": {"delay_ms": 10.0},
    "admission.clock_skew": {"skew_ms": 1000.0},
    "rpc.unavailable": {"retry_after_ms": 1000.0},
    "net.delay": {"delay_ms": 50.0},
    "net.drop_after": {"after_msgs": 3.0},
    "pool.megatick_abort": {"ticks": 1.0},
}

# param keys whose values are strings, not floats — the per-edge scoping
# of the net.* points. Any OTHER non-float param value still drops the
# whole entry (the lenient-env contract tests pin).
_STR_PARAMS = ("src", "dst", "surface")


class InjectedFault(RuntimeError):
    """The exception a crash-class injection point raises. Distinct type
    so recovery-path tests can assert the abort they observe is the one
    they injected, not an unrelated failure."""


@dataclass(frozen=True)
class FaultAction:
    """What a fired point tells its call site to do. ``hit`` is the
    1-based hit index at fire time (the journal's determinism anchor)."""

    point: str
    mode: str
    hit: int
    delay_s: float = 0.0
    skew_s: float = 0.0
    retry_after_ms: int = 1000
    # fleet.host_kill only: True = the call site should take the whole
    # PROCESS down (os._exit), not just abort the stream — the disagg
    # smoke's real host kill. Default False so in-process tests drive
    # the same recovery path without dying.
    exit: bool = False
    # net.drop_after only: how many stream messages flow before the
    # sever (the mid-transfer cut the resume ladder must survive)
    after_msgs: int = 3
    # pool.megatick_abort only: cap the megagraph's abort_after operand
    # at this many ticks (0 = the call site's half-window default) —
    # the injected "host attention needed" demand that forces the
    # device loop's early-exit branch mid-window
    ticks: int = 0


@dataclass
class _PointSpec:
    mode: str
    arg: float  # N for nth, P for prob, T seconds for after
    params: Dict[str, float] = field(default_factory=dict)
    # string-valued params (src/dst/surface) — the net.* edge scoping
    strs: Dict[str, str] = field(default_factory=dict)


class FaultPlan:
    """One activated schedule: per-point triggers, seeded PRNGs, hit
    counters, and the fired-fault journal."""

    def __init__(self, schedule: Dict[str, _PointSpec], seed: int) -> None:
        self.seed = seed
        self.schedule = schedule
        self.activated_at = time.monotonic()
        self._lock = make_lock("faults")
        #: guarded_by _lock
        self._hits: Dict[str, int] = {}
        #: guarded_by _lock
        self._journal: deque = deque(maxlen=_MAX_JOURNAL)
        # per-point PRNG seeded by (seed, point): the k-th draw decides
        # the k-th hit no matter how points interleave across threads
        self._rngs: Dict[str, random.Random] = {
            name: random.Random(f"{seed}:{name}") for name in schedule
        }

    def check(self, name: str, model: str = "",
              edge: Optional[Tuple[str, str]] = None,
              surface: str = "") -> Optional[FaultAction]:
        spec = self.schedule.get(name)
        if spec is None:
            return None
        # edge/surface scoping (net.* points): a spec scoped to a
        # src/dst/surface it does not match neither fires NOR consumes
        # a hit — unrelated traffic must not shift the hit index the
        # determinism contract anchors on.
        want_src = spec.strs.get("src", "")
        want_dst = spec.strs.get("dst", "")
        if want_src or want_dst:
            if edge is None:
                return None
            if want_src and edge[0] != want_src:
                return None
            if want_dst and edge[1] != want_dst:
                return None
        want_surface = spec.strs.get("surface", "")
        if want_surface and surface != want_surface:
            return None
        # per-edge points count hits PER EDGE: the k-th send on one
        # edge is the same k no matter how other edges interleave
        key = name if edge is None else f"{name}|{edge[0]}->{edge[1]}"
        with self._lock:
            hit = self._hits.get(key, 0) + 1
            self._hits[key] = hit
            if spec.mode == "nth":
                # until=M widens the one-shot to the window [N, M] —
                # a held partition, not a single dropped send
                until = int(spec.params.get("until", 0.0))
                if until > 0:
                    fire = int(spec.arg) <= hit <= until
                else:
                    fire = hit == int(spec.arg)
            elif spec.mode == "prob":
                rng = self._rngs.get(key)
                if rng is None:
                    rng = self._rngs[key] = random.Random(
                        f"{self.seed}:{key}"
                    )
                fire = rng.random() < spec.arg
            else:  # after
                fire = (
                    time.monotonic() - self.activated_at >= spec.arg
                )
            if not fire:
                return None
            act = FaultAction(
                point=name, mode=spec.mode, hit=hit,
                delay_s=spec.params.get("delay_ms", 0.0) / 1e3,
                skew_s=spec.params.get("skew_ms", 0.0) / 1e3,
                retry_after_ms=int(spec.params.get("retry_after_ms", 1000)),
                exit=bool(spec.params.get("exit", 0.0)),
                after_msgs=int(spec.params.get("after_msgs", 3.0)),
                ticks=int(spec.params.get("ticks", 0.0)),
            )
            entry = {"point": name, "mode": spec.mode, "hit": hit,
                     "model": model}
            if edge is not None:
                entry["edge"] = f"{edge[0]}->{edge[1]}"
            self._journal.append(entry)
        self._record(act, model)
        return act

    def _record(self, act: FaultAction, model: str) -> None:
        """Observability for a fired fault — outside the plan lock (the
        recorder and metric children take their own)."""
        obs.FAULTS_INJECTED.labels(point=act.point, mode=act.mode).inc()
        from ..obs import flightrec, incidents  # late: obs import order

        flightrec.RECORDER.model_event(
            model or "faults", "fault",
            point=act.point, mode=act.mode, hit=act.hit,
        )
        # a fired fault is an incident trigger — the bundle freezes the
        # telemetry window around the injection (no-op when unarmed;
        # the per-(model, cause) cooldown keeps fault storms bounded)
        incidents.notify(model or "faults", "fault",
                         point=act.point, mode=act.mode, hit=act.hit)
        log.warning(
            "fault injected: %s (%s, hit %d)%s",
            act.point, act.mode, act.hit,
            f" on {model}" if model else "",
        )

    def journal(self) -> List[dict]:
        with self._lock:
            return list(self._journal)


# The active plan. None = faults disabled; the hot-path cost of a
# disabled point() is one global load + is-None check.
_PLAN: Optional[FaultPlan] = None
_swap = threading.Lock()  # activate/deactivate only — never on hot paths


def point(name: str, model: str = "",
          edge: Optional[Tuple[str, str]] = None,
          surface: str = "") -> Optional[FaultAction]:
    """The hot-path call: None when no schedule is active or the point
    does not fire; a :class:`FaultAction` telling the call site what to
    inject otherwise. ``edge=(src_host, dst_host)`` scopes the per-edge
    net points; ``surface`` ("rpc"/"http") narrows them further."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.check(name, model, edge=edge, surface=surface)


def active() -> bool:
    return _PLAN is not None


def fired() -> List[dict]:
    """The active plan's fired-fault journal (empty when inactive) —
    ordered ``{point, mode, hit, model}`` dicts, the determinism
    fingerprint chaos re-runs compare."""
    plan = _PLAN
    return plan.journal() if plan is not None else []


def activate(spec: str, seed: Optional[int] = None) -> FaultPlan:
    """Arm a schedule programmatically (tests, ``bench.py --chaos``).
    ``spec`` uses the ``AIOS_TPU_FAULTS`` grammar; an explicit ``seed``
    overrides the spec's ``seed=`` entry. Returns the plan (its
    ``journal()`` is the run's injected-fault sequence)."""
    global _PLAN
    schedule, spec_seed = _parse(spec)
    plan = FaultPlan(schedule, seed if seed is not None else spec_seed)
    with _swap:
        _PLAN = plan
    if schedule:
        log.warning(
            "fault injection ACTIVE (seed %d): %s", plan.seed,
            ", ".join(
                f"{n}={s.mode}:{s.arg:g}" for n, s in schedule.items()
            ),
        )
    return plan


def deactivate() -> None:
    global _PLAN
    with _swap:
        _PLAN = None


def install_from_env() -> None:
    """Arm (or disarm) from ``AIOS_TPU_FAULTS`` — called at import so a
    booted process carries its schedule from birth, and callable again
    after an env change (tests)."""
    raw = os.environ.get("AIOS_TPU_FAULTS", "").strip()
    if raw:
        activate(raw)
    else:
        deactivate()


def _parse(spec: str) -> Tuple[Dict[str, _PointSpec], int]:
    """``seed=42;point=mode:arg[,k=v...];...`` -> (schedule, seed).
    Malformed entries log and drop (never take down a boot)."""
    schedule: Dict[str, _PointSpec] = {}
    seed = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        name, rest = name.strip(), rest.strip()
        if name == "seed":
            try:
                seed = int(rest)
            except ValueError:
                log.warning("AIOS_TPU_FAULTS: bad seed %r ignored", rest)
            continue
        if name not in POINTS:
            log.warning(
                "AIOS_TPU_FAULTS: unknown point %r ignored (known: %s)",
                name, ", ".join(POINTS),
            )
            continue
        head, *params = rest.split(",")
        mode, _, arg = head.partition(":")
        mode = mode.strip()
        if mode not in MODES:
            log.warning(
                "AIOS_TPU_FAULTS: %s: unknown trigger %r ignored "
                "(known: %s)", name, mode, ", ".join(MODES),
            )
            continue
        try:
            argv = float(arg)
        except ValueError:
            log.warning(
                "AIOS_TPU_FAULTS: %s: bad trigger arg %r ignored",
                name, arg,
            )
            continue
        kv: Dict[str, float] = dict(_PARAM_DEFAULTS.get(name, ()))
        sv: Dict[str, str] = {}
        ok = True
        for p in params:
            k, _, v = p.partition("=")
            k = k.strip()
            if k in _STR_PARAMS:
                sv[k] = v.strip()
                continue
            try:
                kv[k] = float(v)
            except ValueError:
                log.warning(
                    "AIOS_TPU_FAULTS: %s: bad param %r ignored — "
                    "dropping the whole entry", name, p,
                )
                ok = False
        if ok:
            schedule[name] = _PointSpec(mode, argv, kv, sv)
    return schedule, seed


install_from_env()
