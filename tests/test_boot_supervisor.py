"""Real-process boot: the supervisor starts the five services as `python -m`
children, gates on health, restarts crashed children, and caps restarts.

This is the process-level equivalent of the reference's QEMU boot test
(/root/reference/tests/e2e/test_boot.sh:36-91: boot real processes, poll
health, assert ready) — VERDICT r2 item 6 flagged that the supervisor's
topo-start/health-gate/restart path had zero test coverage.

The children are real service processes on ephemeral ports (AIOS_*_ADDR
overrides); the runtime child imports JAX on CPU, so this is the slowest
test in the suite (~1 min) and lives in its own file.
"""

import os
import socket
import time

import pytest

from aios_tpu.boot.config import AiosConfig, _default_sections
from aios_tpu.boot.supervisor import ServiceDef, Supervisor, topo_sort

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _build_supervisor(tmp_path, max_restarts=5):
    ports = {name: _free_port()
             for name in ("runtime", "memory", "tools", "gateway", "orchestrator")}
    shared_env = {
        "JAX_PLATFORMS": "cpu",
        # this image's TPU-tunnel site hook registers its PJRT plugin in
        # every python process when this var is set, and a wedged tunnel
        # then hangs even JAX_PLATFORMS=cpu children at import — the boot
        # e2e is CPU-only, so disable the hook for the service children
        "PALLAS_AXON_POOL_IPS": "",
        "AIOS_DATA_DIR": str(tmp_path / "data"),
        "AIOS_AUDIT_DB": str(tmp_path / "audit.db"),
        "AIOS_MODEL_DIR": str(tmp_path / "no-models"),  # autoload no-op
        **{f"AIOS_{n.upper()}_ADDR": f"127.0.0.1:{p}" for n, p in ports.items()},
    }
    services = {
        "runtime": ServiceDef("runtime", "aios_tpu.runtime.service",
                              ports["runtime"], env=shared_env),
        "memory": ServiceDef("memory", "aios_tpu.memory.service",
                             ports["memory"], env=shared_env),
        "tools": ServiceDef("tools", "aios_tpu.tools.service",
                            ports["tools"], env=shared_env),
        "gateway": ServiceDef("gateway", "aios_tpu.gateway.service",
                              ports["gateway"], env=shared_env),
        "orchestrator": ServiceDef(
            "orchestrator", "aios_tpu.orchestrator.main",
            ports["orchestrator"],
            deps=["runtime", "memory", "tools", "gateway"],
            env=shared_env,
        ),
    }
    sections = _default_sections()
    sections["system"]["data_dir"] = str(tmp_path / "data")
    sections["boot"]["health_timeout_seconds"] = 120
    sections["boot"]["max_restart_attempts"] = max_restarts
    config = AiosConfig(sections=sections)
    return Supervisor(config=config, services=services), ports


def test_topo_sort_orders_dependencies():
    services = {
        "a": ServiceDef("a", "m", 1, deps=["b"]),
        "b": ServiceDef("b", "m", 2),
        "c": ServiceDef("c", "m", 3, deps=["a", "b"]),
    }
    order = topo_sort(services)
    assert order.index("b") < order.index("a") < order.index("c")
    with pytest.raises(ValueError):
        topo_sort({"x": ServiceDef("x", "m", 1, deps=["y"]),
                   "y": ServiceDef("y", "m", 2, deps=["x"])})


@pytest.mark.slow
def test_boot_health_restart_and_clean_shutdown(tmp_path):
    sup, ports = _build_supervisor(tmp_path, max_restarts=2)
    try:
        started = sup.boot()
        # topo order: all four leaf services before the orchestrator
        assert started[-1] == "orchestrator"
        assert set(started[:4]) == {"runtime", "memory", "tools", "gateway"}
        for name, port in ports.items():
            assert sup.port_open(port), f"{name} not listening on {port}"

        # crash a child -> supervisor restarts it within the cap
        tools = sup.supervised["tools"]
        old_pid = tools.process.pid
        tools.process.kill()
        deadline = time.time() + 60
        while time.time() < deadline:
            p = tools.process
            if p is not None and p.pid != old_pid and sup.port_open(ports["tools"]):
                break
            time.sleep(0.5)
        else:
            pytest.fail("tools was not restarted after a crash")
        assert tools.restarts == 1 and not tools.gave_up

        # exceed the restart cap (2) -> supervisor gives up on the service
        deadline = time.time() + 120
        while not tools.gave_up and time.time() < deadline:
            p = tools.process
            if p is not None and p.poll() is None:
                p.kill()
            time.sleep(0.5)
        assert tools.gave_up, "restart cap was never enforced"
        # the rest of the system is still up
        assert sup.port_open(ports["orchestrator"])
    finally:
        sup.shutdown()

    # clean-shutdown flag written; every child reaped
    assert (tmp_path / "data" / "clean-shutdown").exists()
    for entry in sup.supervised.values():
        if entry.process is not None:
            assert entry.process.poll() is not None


def test_serving_env_from_boot_config(tmp_path):
    """[models] serving knobs translate into AIOS_TPU_* env for every
    child service (one TOML section drives the stack's serving mode)."""
    from aios_tpu.boot.config import load_config, serving_env
    from aios_tpu.boot.supervisor import default_services

    cfg_file = tmp_path / "config.toml"
    cfg_file.write_text(
        "[models]\n"
        "kv_cache = \"int8\"\n"
        "paged_kv_rows = 8192\n"
        "speculative = true\n"
        "json_mode = \"force\"\n"
        "guided_toolcalls = true\n"
        "quantize = \"1\"\n"
        "mesh = \"dp=2,tp=2\"\n"
        "replicas = 2\n"
        "tenant_tokens_per_sec = 500\n"
        "max_queue = 32\n"
    )
    cfg = load_config(str(cfg_file))
    env = serving_env(cfg)
    assert env == {
        "AIOS_TPU_QUANTIZE": "1",
        "AIOS_TPU_KV_CACHE": "int8",
        "AIOS_TPU_PAGED_KV": "8192",
        "AIOS_TPU_SPECULATIVE": "1",
        "AIOS_TPU_JSON_MODE": "force",
        "AIOS_TPU_GUIDED_TOOLCALLS": "1",
        "AIOS_TPU_MESH": "dp=2,tp=2",
        "AIOS_TPU_REPLICAS": "2",
        "AIOS_TPU_TENANT_TOKENS_PER_SEC": "500",
        "AIOS_TPU_MAX_QUEUE": "32",
    }
    defs = default_services(cfg)
    for d in defs.values():
        assert d.env["AIOS_TPU_KV_CACHE"] == "int8"

    # an EXPLICIT max_queue = 0 means unbounded (forwarded as "0"),
    # while leaving it unset injects nothing (serving default of 64)
    zero = tmp_path / "zero.toml"
    zero.write_text("[models]\nmax_queue = 0\n")
    assert serving_env(load_config(str(zero)))["AIOS_TPU_MAX_QUEUE"] == "0"

    # failover knobs forward, and an EXPLICIT retries = 0 means OFF
    # (overriding the serving default of 2); [faults] arms the
    # fault-injection schedule with its seed prepended (docs/FAULTS.md)
    chaos = tmp_path / "chaos.toml"
    chaos.write_text(
        "[models]\n"
        "failover_retries = 0\n"
        "failover_backoff_ms = 25\n"
        "[faults]\n"
        "schedule = \"pool.scheduler_crash=nth:3\"\n"
        "seed = 7\n"
    )
    env = serving_env(load_config(str(chaos)))
    assert env["AIOS_TPU_FAILOVER_RETRIES"] == "0"
    assert env["AIOS_TPU_FAILOVER_BACKOFF_MS"] == "25"
    assert env["AIOS_TPU_FAULTS"] == "seed=7;pool.scheduler_crash=nth:3"

    # defaults: the paged pool + prefix cache default ON ("auto" sizing);
    # no other knob is injected (AiosConfig() directly; load_config(None)
    # would read this HOST's /etc/aios config)
    from aios_tpu.boot.config import AiosConfig

    assert serving_env(AiosConfig()) == {"AIOS_TPU_PAGED_KV": "auto"}
    # configless default_services injects nothing (no boot config at all)
    assert default_services()["runtime"].env == {}
    assert default_services(AiosConfig())["runtime"].env == {
        "AIOS_TPU_PAGED_KV": "auto"
    }

    # explicit 0 turns the pool off
    off = tmp_path / "off.toml"
    off.write_text("[models]\npaged_kv_rows = 0\n")
    assert "AIOS_TPU_PAGED_KV" not in serving_env(load_config(str(off)))

    # env beats config: an operator-exported knob is not clobbered
    import os

    os.environ["AIOS_TPU_KV_CACHE"] = "bf16"
    try:
        assert "AIOS_TPU_KV_CACHE" not in serving_env(cfg)
        assert serving_env(cfg)["AIOS_TPU_JSON_MODE"] == "force"
    finally:
        del os.environ["AIOS_TPU_KV_CACHE"]

    # malformed paged_kv_rows warns and is skipped, not fatal
    bad = tmp_path / "bad.toml"
    bad.write_text('[models]\npaged_kv_rows = "64k"\n')
    env2 = serving_env(load_config(str(bad)))
    assert "AIOS_TPU_PAGED_KV" not in env2
