"""Result aggregation + decision logging.

Reference parity:
  * ResultAggregator (agent-core/src/result_aggregator.rs): per-goal
    TaskResult collection with GoalSummary {total/succeeded/failed/tokens/
    duration/models} (result_aggregator.rs:65-94);
  * DecisionLogger (agent-core/src/decision_logger.rs): bounded ring
    (10k) of {context, options, chosen, reasoning, level, model, outcome}
    with success-rate analytics (decision_logger.rs:33-121).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import instruments as obs


@dataclass
class TaskOutcome:
    task_id: str
    success: bool
    output: Dict = field(default_factory=dict)
    error: str = ""
    duration_ms: int = 0
    tokens_used: int = 0
    model_used: str = ""


@dataclass
class GoalSummary:
    goal_id: str
    total_tasks: int = 0
    succeeded: int = 0
    failed: int = 0
    total_tokens: int = 0
    total_duration_ms: int = 0
    models_used: List[str] = field(default_factory=list)


class ResultAggregator:
    def __init__(self):
        self._by_goal: Dict[str, List[TaskOutcome]] = {}
        self._lock = threading.Lock()

    def record(self, goal_id: str, outcome: TaskOutcome) -> None:
        with self._lock:
            self._by_goal.setdefault(goal_id, []).append(outcome)
        # the same numbers the per-goal summary() aggregates, exported
        # through the process registry (no parallel telemetry path)
        obs.GOAL_TASKS.labels(
            outcome="success" if outcome.success else "failure"
        ).inc()
        if outcome.tokens_used:
            obs.GOAL_TASK_TOKENS.inc(outcome.tokens_used)
        obs.GOAL_TASK_DURATION.observe(outcome.duration_ms / 1000.0)

    def summary(self, goal_id: str) -> GoalSummary:
        with self._lock:
            outcomes = list(self._by_goal.get(goal_id, []))
        s = GoalSummary(goal_id=goal_id, total_tasks=len(outcomes))
        for o in outcomes:
            s.succeeded += int(o.success)
            s.failed += int(not o.success)
            s.total_tokens += o.tokens_used
            s.total_duration_ms += o.duration_ms
            if o.model_used and o.model_used not in s.models_used:
                s.models_used.append(o.model_used)
        return s


@dataclass
class Decision:
    context: str
    options: List[str]
    chosen: str
    reasoning: str
    intelligence_level: str = ""
    model_used: str = ""
    outcome: str = ""  # success | failure | "" (pending)
    timestamp: int = field(default_factory=lambda: int(time.time()))


class DecisionLogger:
    def __init__(self, capacity: int = 10_000):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def log(self, decision: Decision) -> None:
        with self._lock:
            self._ring.append(decision)
        obs.DECISIONS.labels(
            level=decision.intelligence_level or "unknown"
        ).inc()

    def recent(self, limit: int = 50) -> List[Decision]:
        with self._lock:
            return list(self._ring)[-limit:]

    def success_rate(self, context_filter: str = "") -> Optional[float]:
        with self._lock:
            relevant = [
                d
                for d in self._ring
                if d.outcome and (not context_filter or context_filter in d.context)
            ]
        if not relevant:
            return None
        return sum(1 for d in relevant if d.outcome == "success") / len(relevant)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
