#!/usr/bin/env python3
"""Disaggregation smoke: three REAL processes serve one stream across
the fleet data plane (the preflight.sh gate 7; docs/TESTING.md).

One round:

  1. spawn prefill host A (scripts/fleet_worker.py, role=prefill, no
     peers) and issue the reference request — with no decode peer the
     router counts ``no_peer`` and serves locally, so the reference
     text comes from THE SAME weights the disaggregated run will use;
  2. spawn decode hosts B and C seeded with A's metrics endpoint; B
     carries ``AIOS_TPU_FAULTS="...;fleet.host_kill=nth:3,exit=1"`` —
     a scheduled process kill on the 3rd handed-off token;
  3. poll A's ``/fleet/members`` until both decode rows are "up" and
     advertise a ``kvx_addr`` (the transfer endpoint gossip);
  4. issue the SAME request again: A prefills + emits the first token,
     pushes the KV chain, and hands the stream to B (least-loaded,
     lexicographic tie-break -> deterministic). B dies mid-stream with
     exit status 17 (disagg.KILL_EXIT_STATUS — assert the kill we
     scheduled is the death we observed); A re-hands the stream to C
     with every already-relayed token, and the response text must be
     byte-identical to the single-host reference;
  5. assert A's ``/metrics``: ``route_total`` counted exactly one
     ``no_peer``, one ``handoff``, one ``handoff_resume``, zero
     ``fallback_local``; ``kvx_pages_total{direction="push"}`` moved a
     whole chain (> 0, same page count every run);
  6. poll A's membership until C's row gossips a non-empty prefix
     digest for the model — the decode host now ADVERTISES the chain
     it restored, closing the gossiped-prefix-index loop end to end.

The whole round runs TWICE; the port-free verdicts (text, route
counters, pushed pages, B's exit status) must be identical across runs.
Human progress goes to stderr; ONE JSON verdict line goes to stdout.
Exit 0 on pass.

Tuned short via the AIOS_TPU_FLEET_*_SECS knobs; FLEET_SMOKE_TIME_SCALE
stretches every window and timeout on slow containers.
"""

import json
import os
import re
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

SCALE = float(os.environ.get("FLEET_SMOKE_TIME_SCALE", "1") or 1)
INTERVAL = 0.3 * SCALE
SUSPECT = 1.5 * SCALE
DEAD = 3.0 * SCALE
MODEL = "fleet-smoke"
KILL_EXIT_STATUS = 17  # disagg.KILL_EXIT_STATUS, pinned here on purpose
PROMPT = (
    "disaggregate this stream across the fleet: the prefill host "
    "computes the prompt pages once, pushes the chain over the wire, "
    "and a decode host carries the tokens home even when its first "
    "target dies mid-flight"
)
MAX_TOKENS = 16


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def worker_env(host_id: str, fleet_role: str, peers: str = "",
               faults: str = "") -> dict:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
        "AIOS_TPU_FLEET": "1",
        "AIOS_TPU_FLEET_HOST": host_id,
        "AIOS_TPU_FLEET_ROLE": fleet_role,
        "AIOS_TPU_FLEET_PEERS": peers,
        "AIOS_TPU_FLEET_INTERVAL_SECS": str(INTERVAL),
        "AIOS_TPU_FLEET_SUSPECT_SECS": str(SUSPECT),
        "AIOS_TPU_FLEET_DEAD_SECS": str(DEAD),
        # the data plane needs pages to ship: paged KV + a host-RAM
        # spill tier on every member (model_manager env knobs)
        "AIOS_TPU_PAGED_KV": "auto",
        "AIOS_TPU_PREFIX_HOST_BYTES": str(32 << 20),
    }
    env.pop("AIOS_TPU_FAULTS", None)
    if faults:
        env["AIOS_TPU_FAULTS"] = faults
    return env


def spawn_worker(host_id: str, fleet_role: str, peers: str = "",
                 faults: str = "") -> tuple:
    """-> (Popen, grpc_port, metrics_port); waits for the ready line."""
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_worker.py")],
        env=worker_env(host_id, fleet_role, peers, faults), cwd=REPO,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    deadline = time.monotonic() + 180 * SCALE
    while True:
        line = p.stdout.readline()
        if line.startswith("FLEET_WORKER_READY "):
            ports = json.loads(line.split(" ", 1)[1])
            return p, ports["grpc_port"], ports["metrics_port"]
        if not line and p.poll() is not None:
            raise RuntimeError(f"worker {host_id} died before ready")
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError(f"worker {host_id} never became ready")


def fetch_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_text(port: int, path: str) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode("utf-8")


def poll(fn, what: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1 * SCALE)
    raise RuntimeError(f"timed out waiting for {what}")


def infer(grpc_port: int, task_id: str) -> str:
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2

    channel = rpc.insecure_channel(f"127.0.0.1:{grpc_port}")
    try:
        resp = services.AIRuntimeStub(channel).Infer(
            runtime_pb2.InferRequest(
                model=MODEL, prompt=PROMPT, max_tokens=MAX_TOKENS,
                temperature=5e-5, task_id=task_id,
            ),
            timeout=180,
        )
        return resp.text
    finally:
        channel.close()


def counter(metrics_text: str, name: str, **labels) -> float:
    """One sample's value out of the exposition text, 0.0 when the
    child was never touched (pre-registered children render as 0)."""
    want = {k: str(v) for k, v in labels.items()}
    for line in metrics_text.splitlines():
        m = re.match(rf"^{re.escape(name)}\{{([^}}]*)\}} (\S+)$", line)
        if m:
            got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
            if got == want:
                return float(m.group(2))
    return 0.0


def run_round(tag: str) -> dict:
    """One full smoke round -> the port-free verdict dict."""
    pa, grpc_a, metrics_a = spawn_worker("hostA", "prefill")
    pb = pc = None
    try:
        # reference BEFORE any decode peer exists: the router counts
        # no_peer and serves the stream locally on A
        ref = infer(grpc_a, "disagg-smoke-ref")
        log(f"[{tag}] reference from solo prefill host: {len(ref)} chars")

        pb, _, _ = spawn_worker(
            "hostB", "decode", peers=f"127.0.0.1:{metrics_a}",
            faults="seed=7;fleet.host_kill=nth:3,exit=1",
        )
        pc, _, _ = spawn_worker(
            "hostC", "decode", peers=f"127.0.0.1:{metrics_a}",
        )

        def decoders_ready():
            members = fetch_json(metrics_a, "/fleet/members")["members"]
            ready = {
                m["host"] for m in members
                if m["state"] == "up" and m.get("role") == "decode"
                and m.get("kvx_addr")
            }
            return {"hostB", "hostC"} <= ready

        poll(decoders_ready, "decode hosts up with kvx_addr on A",
             30 * SCALE)
        log(f"[{tag}] decode hosts gossiped their transfer endpoints")

        out = infer(grpc_a, "disagg-smoke-kill")
        b_status = pb.wait(timeout=30 * SCALE)
        pb = None
        log(f"[{tag}] disaggregated stream done; hostB exit={b_status}")

        metrics = fetch_text(metrics_a, "/metrics")
        routes = {
            reason: counter(
                metrics, "aios_tpu_fleet_route_total",
                model=MODEL, reason=reason,
            )
            for reason in ("no_peer", "handoff", "handoff_resume",
                           "fallback_local")
        }
        pushed = counter(
            metrics, "aios_tpu_fleet_kvx_pages_total",
            model=MODEL, direction="push",
        )

        def survivor_gossips_chain():
            members = fetch_json(metrics_a, "/fleet/members")["members"]
            for m in members:
                if m["host"] == "hostC":
                    return bool((m.get("gprefix") or {}).get(MODEL))
            return False

        gossip = False
        try:
            poll(survivor_gossips_chain,
                 "hostC advertising a prefix digest for the model",
                 15 * SCALE)
            gossip = True
        except RuntimeError:
            pass
        log(f"[{tag}] routes={routes} pushed_pages={pushed} "
            f"gossip={gossip}")

        verdict = {
            "text_matches": out == ref,
            "text_len": len(ref),
            "killed_exit": b_status,
            "routes": routes,
            "pushed_pages": pushed,
            "gossip": gossip,
        }
        verdict["pass"] = (
            verdict["text_matches"]
            and b_status == KILL_EXIT_STATUS
            and routes["no_peer"] == 1.0
            and routes["handoff"] == 1.0
            and routes["handoff_resume"] == 1.0
            and routes["fallback_local"] == 0.0
            and pushed > 0
            and gossip
        )
        if not verdict["pass"]:
            log(f"[{tag}] FAIL detail: ref={ref!r} out={out!r}")
        return verdict
    finally:
        for p in (pa, pb, pc):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def main() -> int:
    rounds = [run_round("round1"), run_round("round2")]
    identical = rounds[0] == rounds[1]
    verdict = {
        "smoke": "disagg",
        "round": rounds[0],
        "identical": identical,
        "pass": identical and all(r["pass"] for r in rounds),
    }
    print(json.dumps(verdict, sort_keys=True))
    if not identical:
        log("FAIL: verdicts diverged across seeded runs:")
        log(f"  round1: {rounds[0]}")
        log(f"  round2: {rounds[1]}")
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
