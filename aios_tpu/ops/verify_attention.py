"""Ragged MULTI-QUERY decode attention: T in-flight queries per slot.

The speculative verify step scores a slot's pending token plus K draft
tokens in one forward (model.verify_step). Its attention is T queries per
slot over that slot's valid cache rows — without a kernel it falls back to
a full-cache masked read, paying C-row HBM traffic per slot regardless of
how short the slot actually is. This kernel generalizes the single-query
ragged decode kernel (decode_attention.py): same double-buffered
HBM→VMEM DMA over only the blocks that hold valid rows, but each block is
scored against all T queries, with the causal staircase applied per query
(query t sees cols <= base + t·stride).

``stride`` is 1 for active slots and 0 for inactive ones, matching
verify_step's convention that inactive slots expose only the
overwritten-before-read col 0 for every query.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _mq_kernel(
    len_ref,  # SMEM [B] int32 — base: row `len` holds query 0's row
    stride_ref,  # SMEM [B] int32 — 1 active (staircase), 0 inactive
    q_ref,  # VMEM [1, T, H, D]
    k_hbm,  # ANY  [B, C, KH*D]  (bf16, or int8 when quantized)
    v_hbm,  # ANY  [B, C, KH*D]
    *rest,  # quantized: ks_hbm [B, KH, C] f32 (head-major — the lane dim
    #         must be the 128-aligned cache axis), vs_hbm, o_ref; else o_ref
    num_kv_heads: int,
    head_dim: int,
    block_kv: int,
    window: Optional[int],
    sm_scale: float,
    quantized: bool = False,
):
    if quantized:
        ks_hbm, vs_hbm, o_ref = rest
    else:
        (o_ref,) = rest
    b = pl.program_id(0)
    KH, D, bk = num_kv_heads, head_dim, block_kv
    T, H = q_ref.shape[1], q_ref.shape[2]
    G = H // KH

    base = len_ref[b]
    stride = stride_ref[b]
    C = k_hbm.shape[1]
    # rows [0, base + (T-1)*stride] are visible to SOME query; clamp at the
    # cache end — a saturated slot's clamped writes collide there and its
    # outputs are unconsumed by contract, but the DMA must stay in bounds
    total = jnp.minimum(base + (T - 1) * stride + 1, C)
    n_blk = pl.cdiv(total, bk)
    if window is not None:
        # earliest col any query needs is query 0's window start
        start_blk = jnp.maximum(base + 1 - window, 0) // bk
    else:
        start_blk = jnp.int32(0)

    # [T*G, D] per kv head, rows ordered (t, g)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [T, H, D]
    qpos = base + jnp.arange(T) * stride  # [T] each query's own row

    def body(k_buf, v_buf, sems, ks_buf=None, vs_buf=None):
        def dma(buf_hbm, scr, slot, blk, sem_idx):
            return pltpu.make_async_copy(
                buf_hbm.at[b, pl.ds(blk * bk, bk)],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        def dma_scales(buf_hbm, scr, slot, blk, sem_idx):
            # head-major scales: slice the lane (cache) axis, heads full
            return pltpu.make_async_copy(
                buf_hbm.at[b, :, pl.ds(blk * bk, bk)],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        def start_all(slot, blk):
            dma(k_hbm, k_buf, slot, blk, 0).start()
            dma(v_hbm, v_buf, slot, blk, 1).start()
            if quantized:
                dma_scales(ks_hbm, ks_buf, slot, blk, 2).start()
                dma_scales(vs_hbm, vs_buf, slot, blk, 3).start()

        def wait_all(slot, blk):
            dma(k_hbm, k_buf, slot, blk, 0).wait()
            dma(v_hbm, v_buf, slot, blk, 1).wait()
            if quantized:
                dma_scales(ks_hbm, ks_buf, slot, blk, 2).wait()
                dma_scales(vs_hbm, vs_buf, slot, blk, 3).wait()

        start_all(0, start_blk)

        def loop(i, carry):
            m, l, acc = carry  # [KH*T*G, 1], [KH*T*G, 1], [KH*T*G, D]
            slot = jax.lax.rem(i - start_blk, 2)

            @pl.when(i + 1 < n_blk)
            def _prefetch():
                start_all(1 - slot, i + 1)

            wait_all(slot, i)
            kb = k_buf[slot]  # [bk, KH*D]
            vb = v_buf[slot]
            ksb = ks_buf[slot] if quantized else None  # [KH, bk] f32
            vsb = vs_buf[slot] if quantized else None

            cols = i * bk + jax.lax.broadcasted_iota(jnp.int32, (T, bk), 1)
            valid = cols <= qpos[:, None]  # causal staircase per query
            if window is not None:
                valid = jnp.logical_and(valid, cols > qpos[:, None] - window)
            # [T, bk] -> [T*G, bk] (repeat per query's G heads)
            validg = jnp.repeat(valid, G, axis=0)

            parts = []
            for h in range(KH):
                qh = q[:, h * G : (h + 1) * G, :].reshape(T * G, D)
                kh = kb[:, h * D : (h + 1) * D]
                if quantized:
                    kh = kh.astype(jnp.float32)
                s = jax.lax.dot_general(
                    qh, kh, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [T*G, bk]
                if quantized:
                    s = s * ksb[h][None, :]
                parts.append(jnp.where(validg, s, NEG_INF))
            s_all = jnp.concatenate(parts, axis=0)  # [KH*T*G, bk]

            m_cur = jnp.max(s_all, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s_all - m_new)
            p = jnp.where(
                jnp.concatenate([validg] * KH, axis=0), p, 0.0
            )
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)

            outs = []
            for h in range(KH):
                ph = p[h * T * G : (h + 1) * T * G, :]
                if quantized:
                    ph = ph * vsb[h][None, :]
                else:
                    ph = ph.astype(vb.dtype)
                vh = vb[:, h * D : (h + 1) * D]
                if quantized:
                    vh = vh.astype(jnp.float32)
                outs.append(
                    jax.lax.dot_general(
                        ph, vh, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            acc_new = acc * alpha + jnp.concatenate(outs, axis=0)
            return m_new, l_new, acc_new

        init = (
            jnp.full((KH * T * G, 1), NEG_INF, jnp.float32),
            jnp.zeros((KH * T * G, 1), jnp.float32),
            jnp.zeros((KH * T * G, D), jnp.float32),
        )
        m, l, acc = jax.lax.fori_loop(start_blk, n_blk, loop, init)
        safe_l = jnp.where(l <= 0.0, 1.0, l)
        out = acc / safe_l  # [KH*T*G, D]
        out = out.reshape(KH, T, G, D).transpose(1, 0, 2, 3)
        o_ref[0] = out.reshape(T, H, D).astype(o_ref.dtype)

    if quantized:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, bk, KH * D), jnp.int8),
            v_buf=pltpu.VMEM((2, bk, KH * D), jnp.int8),
            sems=pltpu.SemaphoreType.DMA((2, 4)),
            ks_buf=pltpu.VMEM((2, KH, bk), jnp.float32),
            vs_buf=pltpu.VMEM((2, KH, bk), jnp.float32),
        )
    else:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, bk, KH * D), k_hbm.dtype),
            v_buf=pltpu.VMEM((2, bk, KH * D), v_hbm.dtype),
            sems=pltpu.SemaphoreType.DMA((2, 2)),
        )


def _mq_call(q, k_cache, v_cache, lengths, strides, scales, *, window,
             block_kv, interpret):
    """Shared pallas_call plumbing for both cache dtypes."""
    from .decode_attention import pick_block_kv

    B, T, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    bk = pick_block_kv(C) if block_kv is None else min(block_kv, C)
    if C % bk:
        raise ValueError(f"block_kv {bk} must evenly divide cache length {C}")
    quantized = scales is not None
    if quantized and bk % 128 and not interpret:
        raise ValueError(
            f"int8 mq kernel needs 128-aligned kv blocks, got {bk} "
            f"(cache length {C})"
        )
    kernel = functools.partial(
        _mq_kernel,
        num_kv_heads=KH,
        head_dim=D,
        block_kv=bk,
        window=window,
        sm_scale=1.0 / float(np.sqrt(D)),
        quantized=quantized,
    )
    cache_specs = [pl.BlockSpec(memory_space=pltpu.ANY)] * (
        2 + (2 if quantized else 0)
    )
    args = [
        lengths.astype(jnp.int32),
        strides.astype(jnp.int32),
        q,
        k_cache.reshape(B, C, KH * D),
        v_cache.reshape(B, C, KH * D),
    ]
    if quantized:
        # [B, C, KH] -> head-major [B, KH, C] (see decode_attention.py)
        args.extend(s.transpose(0, 2, 1) for s in scales)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, T, H, D), q.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths
            pl.BlockSpec(memory_space=pltpu.SMEM),  # strides
            pl.BlockSpec((1, T, H, D), lambda b: (b, 0, 0, 0)),
            *cache_specs,
        ],
        out_specs=pl.BlockSpec((1, T, H, D), lambda b: (b, 0, 0, 0)),
        interpret=interpret,
    )(*args)


@functools.partial(
    jax.jit, static_argnames=("window", "block_kv", "interpret")
)
def multiquery_decode_attention(
    q: jnp.ndarray,  # [B, T, H, D] — T in-flight queries per slot
    k_cache: jnp.ndarray,  # [B, C, KH, D]
    v_cache: jnp.ndarray,  # [B, C, KH, D]
    lengths: jnp.ndarray,  # [B] int32 — query 0's own (just-written) row
    strides: jnp.ndarray,  # [B] int32 — 1 active, 0 inactive
    *,
    window: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged multi-query decode attention; returns [B, T, H, D]."""
    return _mq_call(
        q, k_cache, v_cache, lengths, strides, None,
        window=window, block_kv=block_kv, interpret=interpret,
    )


@functools.partial(
    jax.jit, static_argnames=("window", "block_kv", "interpret")
)
def multiquery_decode_attention_int8(
    q: jnp.ndarray,  # [B, T, H, D]
    k_cache: jnp.ndarray,  # [B, C, KH, D] int8
    v_cache: jnp.ndarray,  # [B, C, KH, D] int8
    k_scales: jnp.ndarray,  # [B, C, KH] f32
    v_scales: jnp.ndarray,  # [B, C, KH] f32
    lengths: jnp.ndarray,  # [B] int32
    strides: jnp.ndarray,  # [B] int32
    *,
    window: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query ragged attention over an INT8 KV cache: the cache
    streams as int8 with per-(row, kv-head) scales folded into the
    score/value dots — speculative verify at half the cache bandwidth."""
    return _mq_call(
        q, k_cache, v_cache, lengths, strides, (k_scales, v_scales),
        window=window, block_kv=block_kv, interpret=interpret,
    )


def multiquery_decode_attention_int8_reference(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,  # [B, C, KH, D] int8
    v_cache: jnp.ndarray,
    k_scales: jnp.ndarray,  # [B, C, KH] f32
    v_scales: jnp.ndarray,
    lengths: jnp.ndarray,
    strides: jnp.ndarray,
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dequantize-then-attend ground truth for the int8 mq kernel."""
    kf = k_cache.astype(jnp.float32) * k_scales[..., None]
    vf = v_cache.astype(jnp.float32) * v_scales[..., None]
    return multiquery_decode_attention_reference(
        q, kf, vf, lengths, strides, window=window
    )


def multiquery_decode_attention_reference(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    strides: jnp.ndarray,
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Naive jnp multi-query ragged attention (CPU fallback + parity)."""
    B, T, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    G = H // KH
    qpos = lengths[:, None] + jnp.arange(T)[None, :] * strides[:, None]
    cols = jnp.arange(C)[None, None, :]
    mask = cols <= qpos[..., None]  # [B, T, C]
    if window is not None:
        mask = mask & (cols > qpos[..., None] - window)
    qg = q.reshape(B, T, KH, G, D)
    s = jnp.einsum("btkgd,bckd->bkgtc", qg, k_cache).astype(jnp.float32)
    s = s / np.sqrt(D)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgtc,bckd->btkgd", p, v_cache)
    return out.reshape(B, T, H, D)
