"""Stdlib /metrics + /healthz endpoint for every service.

Each service's ``serve()`` can start one next to its gRPC port — either
by passing ``metrics_port`` explicitly or via the per-service env var
``AIOS_<SERVICE>_METRICS_PORT`` (0 = ephemeral port, useful in tests);
``AIOS_METRICS_HOST`` widens the bind beyond the 127.0.0.1 default for
external scrapers.
A Prometheus scrape of ``/metrics`` sees the process-wide default
registry; ``/healthz`` answers a JSON liveness probe (optionally backed
by a service-supplied callable).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("aios.obs")


def start_metrics_server(
    port: int = 0,
    host: str = "127.0.0.1",
    registry: Optional[MetricsRegistry] = None,
    health_fn: Optional[Callable[[], dict]] = None,
) -> Tuple[ThreadingHTTPServer, int]:
    """Start the exposition endpoint on a daemon thread; returns
    (server, bound_port). ``server.shutdown()`` stops it."""
    reg = registry or REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path.split("?")[0] == "/metrics":
                body = reg.render().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/healthz":
                payload = {"status": "ok"}
                if health_fn is not None:
                    try:
                        payload.update(health_fn())
                    except Exception as exc:  # noqa: BLE001
                        payload = {"status": "degraded", "error": repr(exc)[:200]}
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(
        target=server.serve_forever, name="obs-metrics-http", daemon=True
    )
    thread.start()
    bound = server.server_address[1]
    log.info("metrics endpoint on http://%s:%d/metrics", host, bound)
    return server, bound


def maybe_start_metrics_server(
    service_name: str,
    metrics_port: Optional[int] = None,
    health_fn: Optional[Callable[[], dict]] = None,
) -> Tuple[Optional[ThreadingHTTPServer], Optional[int]]:
    """serve()-helper: start the endpoint when asked for explicitly or via
    ``AIOS_<SERVICE>_METRICS_PORT``; (None, None) otherwise."""
    host = os.environ.get("AIOS_METRICS_HOST", "127.0.0.1")
    if metrics_port is None:
        env = os.environ.get(f"AIOS_{service_name.upper()}_METRICS_PORT")
        if env is None or env == "":
            return None, None
        try:
            metrics_port = int(env)
        except ValueError:
            log.warning(
                "AIOS_%s_METRICS_PORT=%r is not an integer; metrics "
                "endpoint disabled", service_name.upper(), env,
            )
            return None, None
    try:
        return start_metrics_server(
            port=metrics_port, host=host, health_fn=health_fn
        )
    except (OSError, OverflowError) as exc:  # taken port / port > 65535
        # the endpoint is optional: a taken/invalid port must not crash a
        # serve() whose gRPC server is already up
        log.warning(
            "%s metrics endpoint on port %s failed (%s); continuing "
            "without it", service_name, metrics_port, exc,
        )
        return None, None
