"""aios.memory.MemoryService — three-tier memory (operational/working/long-term)
plus knowledge base, migration pipeline, and context assembly.

Reference: memory/src/ (SURVEY.md section 2 row 4).
"""
