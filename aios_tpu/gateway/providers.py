"""Provider clients: Claude, OpenAI, Qwen3 (OpenAI-compat), local TPU runtime.

Reference parity (api-gateway/src/{claude,openai}.rs + router.rs):
  * Claude native Messages API, default model claude-sonnet-4-20250514
    (claude.rs:54-67), key from CLAUDE_API_KEY;
  * OpenAI chat completions, default gpt-5, key from OPENAI_API_KEY;
  * Qwen3 = OpenAI-compatible endpoint (default api.viwoapp.net,
    model qwen3:30b-128k), key from QWEN3_API_KEY;
  * local = the reference hits llama-server HTTP on 127.0.0.1:8082; here it
    is the TPU runtime's gRPC Infer — always available, no key.

Base URLs are env-overridable (CLAUDE_BASE_URL etc.) which is also how the
offline test suite stubs them.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Optional, Tuple


class ProviderError(Exception):
    pass




@dataclass
class InferResult:
    text: str
    input_tokens: int
    output_tokens: int
    model: str
    provider: str


def _post_json(url: str, payload: dict, headers: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **headers},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as exc:
        body = exc.read().decode("utf-8", "replace")[:500]
        raise ProviderError(f"HTTP {exc.code} from {url}: {body}") from exc
    except (OSError, ValueError) as exc:
        raise ProviderError(f"request to {url} failed: {exc}") from exc


class ClaudeClient:
    name = "claude"

    def __init__(self):
        self.api_key = os.environ.get("CLAUDE_API_KEY", "")
        self.base_url = os.environ.get("CLAUDE_BASE_URL", "https://api.anthropic.com")
        self.model = os.environ.get("CLAUDE_MODEL", "claude-sonnet-4-20250514")
        self.timeout = float(os.environ.get("CLAUDE_TIMEOUT", "120"))

    def available(self) -> bool:
        return bool(self.api_key)

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        payload = {
            "model": self.model,
            "max_tokens": max_tokens or 1024,
            "messages": [{"role": "user", "content": prompt}],
            "temperature": temperature,
        }
        if system:
            payload["system"] = system
        data = _post_json(
            f"{self.base_url}/v1/messages",
            payload,
            {"x-api-key": self.api_key, "anthropic-version": "2023-06-01"},
            self.timeout,
        )
        try:
            text = "".join(
                b.get("text", "") for b in data["content"] if b.get("type") == "text"
            )
            usage = data.get("usage", {})
            return InferResult(
                text=text,
                input_tokens=usage.get("input_tokens", 0),
                output_tokens=usage.get("output_tokens", 0),
                model=data.get("model", self.model),
                provider=self.name,
            )
        except (KeyError, TypeError) as exc:
            raise ProviderError(f"malformed claude response: {exc}") from exc


class OpenAICompatClient:
    """OpenAI chat-completions protocol (used by both openai and qwen3)."""

    def __init__(self, name: str, key_env: str, base_env: str, default_base: str,
                 model_env: str, default_model: str):
        self.name = name
        self.api_key = os.environ.get(key_env, "")
        self.base_url = os.environ.get(base_env, default_base)
        self.model = os.environ.get(model_env, default_model)
        self.timeout = 120.0

    def available(self) -> bool:
        return bool(self.api_key)

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        messages = []
        if system:
            messages.append({"role": "system", "content": system})
        messages.append({"role": "user", "content": prompt})
        data = _post_json(
            f"{self.base_url}/v1/chat/completions",
            {
                "model": self.model,
                "messages": messages,
                "max_tokens": max_tokens or 1024,
                "temperature": temperature,
            },
            {"Authorization": f"Bearer {self.api_key}"},
            self.timeout,
        )
        try:
            text = data["choices"][0]["message"]["content"]
            usage = data.get("usage", {})
            return InferResult(
                text=text or "",
                input_tokens=usage.get("prompt_tokens", 0),
                output_tokens=usage.get("completion_tokens", 0),
                model=data.get("model", self.model),
                provider=self.name,
            )
        except (KeyError, IndexError, TypeError) as exc:
            raise ProviderError(f"malformed {self.name} response: {exc}") from exc


def openai_client() -> OpenAICompatClient:
    return OpenAICompatClient(
        "openai", "OPENAI_API_KEY", "OPENAI_BASE_URL",
        "https://api.openai.com", "OPENAI_MODEL", "gpt-5",
    )


def qwen3_client() -> OpenAICompatClient:
    return OpenAICompatClient(
        "qwen3", "QWEN3_API_KEY", "QWEN3_BASE_URL",
        "https://api.viwoapp.net", "QWEN3_MODEL", "qwen3:30b-128k",
    )


class LocalRuntimeClient:
    """The TPU runtime as a gateway provider (final fallback, always on)."""

    name = "local"
    supports_json_schema = True  # grammar-guided decoding in the engine

    def __init__(self, address: Optional[str] = None):
        from ..services import service_address

        self.address = address or service_address("runtime")
        self._stub = None

    def available(self) -> bool:
        return True  # router.rs treats local as always-available

    def _get_stub(self):
        if self._stub is None:
            from .. import rpc
            from ..services import AIRuntimeStub

            self._stub = AIRuntimeStub(rpc.insecure_channel(self.address))
        return self._stub

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        import grpc

        from ..proto_gen import runtime_pb2

        try:
            resp = self._get_stub().Infer(
                runtime_pb2.InferRequest(
                    prompt=prompt,
                    system_prompt=system,
                    max_tokens=max_tokens or 512,
                    temperature=temperature,
                    # structured output rides through to the TPU engine's
                    # grammar-guided decoding; cloud providers ignore it
                    json_schema=json_schema,
                ),
                timeout=120,
            )
        except grpc.RpcError as exc:
            self._stub = None
            raise ProviderError(f"local runtime: {exc.details()}") from exc
        return InferResult(
            text=resp.text,
            input_tokens=max(0, resp.tokens_used),
            output_tokens=0,
            model=resp.model_used or "local",
            provider=self.name,
        )

    def stream_infer(self, prompt: str, system: str, max_tokens: int,
                     temperature: float, json_schema: str = "",
                     register_call=None):
        """Yield text deltas live from the runtime's StreamInfer.

        This is the true-streaming path the reference never had: its
        inference.rs:261 buffers the whole completion and re-chunks it, a
        quirk the runtime service here already fixed — so the gateway pipes
        the live token stream instead of replicating the buffer-then-chunk
        behavior (router.route_stream).
        """
        import grpc

        from ..proto_gen import runtime_pb2

        stream = None
        try:
            stream = self._get_stub().StreamInfer(
                runtime_pb2.InferRequest(
                    prompt=prompt,
                    system_prompt=system,
                    max_tokens=max_tokens or 512,
                    temperature=temperature,
                    json_schema=json_schema,
                ),
                timeout=300,
            )
            if register_call is not None:
                # hand the call to the servicer so its RPC-termination
                # callback can cancel it cross-thread while this generator
                # is parked in next() (cancel is thread-safe on gRPC calls)
                register_call(stream)
            for chunk in stream:
                if chunk.text:
                    yield chunk.text
                if chunk.done:
                    return
        except grpc.RpcError as exc:
            # CANCELLED can be our own disconnect-cancel (register_call
            # path) OR a genuine runtime failure (server restart kills
            # in-flight RPCs with CANCELLED) — the router tells them apart
            # via its client_alive probe, not here
            if exc.code() != grpc.StatusCode.CANCELLED:
                self._stub = None
            raise ProviderError(f"local runtime: {exc.details()}") from exc
        finally:
            # our consumer can vanish mid-stream (the gateway's client
            # disconnected -> GeneratorExit lands here): cancel the
            # downstream call so the runtime aborts its decode and frees
            # the slot, instead of streaming to an abandoned iterator
            # until max_tokens. No-op on a completed call.
            if stream is not None:
                stream.cancel()
