"""BaseAgent: lifecycle, service stubs, tool/memory/think helpers.

Reference parity (agent-core/python/aios_agent/base.py, 922 LoC):
  * run() = RegisterAgent + heartbeat loop (10 s) + task poll loop (2 s)
    (base.py:871-901); poll -> execute -> ReportTaskResult (749-802);
  * lazily-created stubs to orchestrator/tools/memory/runtime/gateway
    (147-197) with env-overridable addresses;
  * call_tool / rollback_tool / list_tools (271-324);
  * memory helpers: store/recall/push_event/get_recent_events/
    update_metric/get_metric/store_pattern/find_pattern/store_decision/
    semantic_search/assemble_context (356-566);
  * think(prompt, level) -> runtime Infer (572-616);
  * execute_task bookkeeping wrapper with duration + error capture (808-855).

Deliberate deviation: the reference uses grpc.aio; this build uses sync gRPC
stubs driven by daemon threads — one fewer runtime (no asyncio) in the agent
processes and identical observable behavior through the wire.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional

import grpc

from .. import rpc
from ..proto_gen import (
    api_gateway_pb2,
    common_pb2,
    memory_pb2,
    orchestrator_pb2,
    runtime_pb2,
    tools_pb2,
)
from ..services import (
    AIRuntimeStub,
    ApiGatewayStub,
    MemoryServiceStub,
    OrchestratorStub,
    ToolRegistryStub,
    service_address,
)

HEARTBEAT_INTERVAL = 10.0  # base.py:63
POLL_INTERVAL = 2.0  # base.py:112


class BaseAgent(ABC):
    """Abstract agent; subclasses implement handle_task and metadata."""

    def __init__(self, name: Optional[str] = None):
        agent_type = self.get_agent_type()
        self.agent_id = (
            name
            or os.environ.get("AIOS_AGENT_NAME")
            or f"{agent_type}_agent-{uuid.uuid4().hex[:6]}"
        )
        self.log = logging.getLogger(f"aios.agent.{self.agent_id}")
        self.status = "idle"
        self.current_task_id = ""
        self.tasks_completed = 0
        self.tasks_failed = 0
        self.started_at = time.time()
        self._stubs: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- abstract surface (base.py:121-141) ---------------------------------

    @abstractmethod
    def get_agent_type(self) -> str: ...

    @abstractmethod
    def get_capabilities(self) -> List[str]: ...

    @abstractmethod
    def get_tool_namespaces(self) -> List[str]: ...

    @abstractmethod
    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        """Execute one task dict; return a JSON-able output dict."""

    def periodic(self) -> None:
        """Optional background duty cycle (overridden by agents)."""

    periodic_interval: float = 30.0

    # -- stubs --------------------------------------------------------------

    def _stub(self, name: str, cls):
        stub = self._stubs.get(name)
        if stub is None:
            stub = cls(rpc.insecure_channel(service_address(name)))
            self._stubs[name] = stub
        return stub

    @property
    def orchestrator(self) -> OrchestratorStub:  # type: ignore[valid-type]
        return self._stub("orchestrator", OrchestratorStub)

    @property
    def tools(self) -> ToolRegistryStub:  # type: ignore[valid-type]
        return self._stub("tools", ToolRegistryStub)

    @property
    def memory(self) -> MemoryServiceStub:  # type: ignore[valid-type]
        return self._stub("memory", MemoryServiceStub)

    @property
    def runtime(self) -> AIRuntimeStub:  # type: ignore[valid-type]
        return self._stub("runtime", AIRuntimeStub)

    @property
    def gateway(self) -> ApiGatewayStub:  # type: ignore[valid-type]
        return self._stub("gateway", ApiGatewayStub)

    # -- tools (base.py:271-324) --------------------------------------------

    def call_tool(
        self, tool_name: str, args: Optional[dict] = None, reason: str = ""
    ) -> Dict[str, Any]:
        resp = self.tools.Execute(
            tools_pb2.ExecuteRequest(
                tool_name=tool_name,
                agent_id=self.agent_id,
                task_id=self.current_task_id,
                input_json=json.dumps(args or {}).encode(),
                reason=reason,
            ),
            timeout=120,
        )
        output = {}
        if resp.output_json:
            try:
                output = json.loads(resp.output_json)
            except ValueError:
                pass
        result = {
            "success": resp.success,
            "output": output,
            "error": resp.error,
            "execution_id": resp.execution_id,
        }
        self.store_tool_call(tool_name, args or {}, result)
        return result

    def rollback_tool(self, execution_id: str, reason: str = "") -> bool:
        resp = self.tools.Rollback(
            tools_pb2.RollbackRequest(execution_id=execution_id, reason=reason),
            timeout=60,
        )
        return resp.success

    def list_tools(self, namespace: str = "") -> List[str]:
        resp = self.tools.ListTools(
            tools_pb2.ListToolsRequest(namespace=namespace), timeout=10
        )
        return [t.name for t in resp.tools]

    # -- memory helpers (base.py:356-566) -----------------------------------

    def push_event(
        self, category: str, data: dict, critical: bool = False
    ) -> None:
        self.memory.PushEvent(
            memory_pb2.Event(
                category=category,
                source=self.agent_id,
                data_json=json.dumps(data).encode(),
                critical=critical,
                timestamp=int(time.time()),
            ),
            timeout=5,
        )

    def get_recent_events(self, count: int = 20, category: str = "") -> List[dict]:
        resp = self.memory.GetRecentEvents(
            memory_pb2.RecentEventsRequest(count=count, category=category),
            timeout=5,
        )
        return [
            {
                "category": e.category,
                "source": e.source,
                "data": json.loads(e.data_json or b"{}"),
                "timestamp": e.timestamp,
            }
            for e in resp.events
        ]

    def update_metric(self, key: str, value: float) -> None:
        self.memory.UpdateMetric(
            memory_pb2.MetricUpdate(key=key, value=value,
                                    timestamp=int(time.time())),
            timeout=5,
        )

    def get_metric(self, key: str) -> Optional[float]:
        resp = self.memory.GetMetric(memory_pb2.MetricRequest(key=key),
                                     timeout=5)
        return resp.value if resp.timestamp else None

    def store_pattern(self, trigger: str, action: str,
                      success_rate: float = 1.0) -> None:
        self.memory.StorePattern(
            memory_pb2.Pattern(
                id=str(uuid.uuid4()), trigger=trigger, action=action,
                success_rate=success_rate, uses=1,
                last_used=int(time.time()),
                created_from=self.agent_id,
            ),
            timeout=5,
        )

    def find_pattern(self, trigger: str,
                     min_success_rate: float = 0.5) -> Optional[dict]:
        resp = self.memory.FindPattern(
            memory_pb2.PatternQuery(trigger=trigger,
                                    min_success_rate=min_success_rate),
            timeout=5,
        )
        if not resp.found:
            return None
        return {
            "id": resp.pattern.id,
            "trigger": resp.pattern.trigger,
            "action": resp.pattern.action,
            "success_rate": resp.pattern.success_rate,
        }

    def store_decision(self, context: str, chosen: str, reasoning: str,
                       outcome: str = "") -> None:
        self.memory.StoreDecision(
            memory_pb2.Decision(
                id=str(uuid.uuid4()), context=context, chosen=chosen,
                reasoning=reasoning, outcome=outcome,
                timestamp=int(time.time()),
            ),
            timeout=5,
        )

    def store_tool_call(self, tool: str, args: dict, result: dict) -> None:
        try:
            self.memory.StoreToolCall(
                memory_pb2.ToolCallRecord(
                    id=str(uuid.uuid4()),
                    task_id=self.current_task_id,
                    tool_name=tool,
                    agent=self.agent_id,
                    input_json=json.dumps(args).encode(),
                    output_json=json.dumps(result.get("output", {}))[:4000].encode(),
                    success=bool(result.get("success")),
                    timestamp=int(time.time()),
                ),
                timeout=5,
            )
        except grpc.RpcError:
            pass  # memory being down must not break tool calls

    def semantic_search(self, query: str, n_results: int = 5) -> List[dict]:
        resp = self.memory.SemanticSearch(
            memory_pb2.SemanticSearchRequest(query=query, n_results=n_results),
            timeout=10,
        )
        return [
            {"content": r.content, "relevance": r.relevance,
             "collection": r.collection}
            for r in resp.results
        ]

    def assemble_context(self, description: str, max_tokens: int = 512) -> str:
        resp = self.memory.AssembleContext(
            memory_pb2.ContextRequest(task_description=description,
                                      max_tokens=max_tokens),
            timeout=10,
        )
        return "\n".join(f"[{c.source}] {c.content}" for c in resp.chunks)

    # -- inference (base.py:572-616) ----------------------------------------

    def think(self, prompt: str, level: str = "operational",
              max_tokens: int = 512) -> str:
        resp = self.runtime.Infer(
            runtime_pb2.InferRequest(
                prompt=prompt,
                intelligence_level=level,
                max_tokens=max_tokens,
                requesting_agent=self.agent_id,
                task_id=self.current_task_id,
            ),
            timeout=150,
        )
        return resp.text

    # -- lifecycle (base.py:871-901) ----------------------------------------

    def register(self) -> bool:
        resp = self.orchestrator.RegisterAgent(
            common_pb2.AgentRegistration(
                agent_id=self.agent_id,
                agent_type=self.get_agent_type(),
                capabilities=self.get_capabilities(),
                tool_namespaces=self.get_tool_namespaces(),
                status="idle",
                registered_at=int(time.time()),
            ),
            timeout=10,
        )
        return resp.success

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(HEARTBEAT_INTERVAL):
            try:
                self.orchestrator.Heartbeat(
                    orchestrator_pb2.HeartbeatRequest(
                        agent_id=self.agent_id,
                        status=self.status,
                        current_task_id=self.current_task_id,
                    ),
                    timeout=5,
                )
            except grpc.RpcError:
                self.log.warning("heartbeat failed; orchestrator unreachable")

    def _poll_loop(self) -> None:
        while not self._stop.wait(POLL_INTERVAL):
            try:
                self._poll_and_execute()
            except grpc.RpcError:
                continue
            except Exception:  # noqa: BLE001
                self.log.exception("task execution crashed")

    def _poll_and_execute(self) -> None:
        task = self.orchestrator.GetAssignedTask(
            common_pb2.AgentId(id=self.agent_id), timeout=10
        )
        if not task.id:
            return
        result = self.execute_task(
            {
                "id": task.id,
                "goal_id": task.goal_id,
                "description": task.description,
                "intelligence_level": task.intelligence_level,
                "required_tools": list(task.required_tools),
                "input": json.loads(task.input_json or b"{}"),
            }
        )
        self.orchestrator.ReportTaskResult(
            common_pb2.TaskResult(
                task_id=task.id,
                success=result["success"],
                output_json=json.dumps(result.get("output", {})).encode(),
                error=result.get("error", ""),
                duration_ms=result.get("duration_ms", 0),
                tokens_used=result.get("tokens_used", 0),
                model_used=result.get("model_used", ""),
            ),
            timeout=10,
        )

    def execute_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        """Bookkeeping wrapper around handle_task (base.py:808-855)."""
        self.status = "busy"
        self.current_task_id = task["id"]
        t0 = time.time()
        try:
            output = self.handle_task(task)
            result = {
                "success": True,
                "output": output or {},
                "duration_ms": int((time.time() - t0) * 1000),
            }
            self.tasks_completed += 1
        except Exception as exc:  # noqa: BLE001
            result = {
                "success": False,
                "output": {},
                "error": str(exc),
                "duration_ms": int((time.time() - t0) * 1000),
            }
            self.tasks_failed += 1
            self.log.warning("task %s failed: %s", task["id"], exc)
        finally:
            self.status = "idle"
            self.current_task_id = ""
        return result

    def _periodic_loop(self) -> None:
        while not self._stop.wait(self.periodic_interval):
            try:
                self.periodic()
            except Exception:  # noqa: BLE001
                self.log.exception("periodic duty failed")

    def run(self, block: bool = True) -> None:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if self.register():
                    break
            except grpc.RpcError:
                time.sleep(2)
        else:
            raise RuntimeError("could not register with orchestrator")
        self.log.info("registered as %s", self.agent_id)
        for target in (self._heartbeat_loop, self._poll_loop,
                       self._periodic_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        if block:
            try:
                while not self._stop.wait(3600):
                    pass
            except KeyboardInterrupt:
                self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self.orchestrator.UnregisterAgent(
                common_pb2.AgentId(id=self.agent_id), timeout=5
            )
        except grpc.RpcError:
            pass
        for t in self._threads:
            t.join(timeout=2)
