"""Fleet data plane: disaggregated prefill/decode across processes.

PR 16 built the fleet *telemetry* plane (obs/fleet.py): membership,
federation, trace stitching — role-aware signals ACROSS processes. This
package is the data plane that routes on them (the RTP-LLM lesson:
disaggregated serving stands or falls on cache-aware, failure-aware
cross-host scheduling):

  * :mod:`~aios_tpu.fleet.kvx` — the KV transfer protocol: a gRPC
    service (``aios.fleet.KvTransfer``, aios_tpu/protos/fleet.proto)
    shipping HostPageStore entries between hosts keyed by the same
    sha256 chain hashes the prefix caches use, crc32-verified at BOTH
    ends, chunked and byte-budgeted. Push-on-prefill (the prefill host
    streams pages to its decode target) and pull-on-miss (a decode host
    fetches a chain the router promised).
  * :mod:`~aios_tpu.fleet.gprefix` — the gossiped prefix index: each
    host piggybacks a bounded digest of its cached chain tails on the
    PR 16 ``/fleet/announce`` heartbeat; peers score remote prefix
    overlap without any extra RPC.
  * :mod:`~aios_tpu.fleet.router` — fleet-level routing: extends the
    pool's sticky -> overlap -> least-loaded ladder fleet-wide, with
    transfer-cost-aware tie-breaking (fetch the chain vs recompute it,
    priced off the devprof ledger).
  * :mod:`~aios_tpu.fleet.disagg` — disaggregated roles
    (``AIOS_TPU_FLEET_ROLE=prefill|decode|mixed``): prefill hosts run
    admission + prefill then hand the stream to a decode host over the
    transfer plane, reusing the PR 10 resume-from-emitted contract, so
    greedy streams stay token-identical across the handoff AND across a
    decode-host kill (the ``fleet.host_kill`` chaos point).

Every failure on this plane — unreachable peer, crc mismatch, decode
error, empty chain — degrades to LOCAL prefill, exactly like the PR 10
``restore_fail`` path: slower, never wrong. docs/SERVING.md covers the
routing ladder; docs/RUNBOOK.md §10 the triage.
"""

from . import disagg, gprefix, kvx, router  # noqa: F401

__all__ = ["disagg", "gprefix", "kvx", "router"]
