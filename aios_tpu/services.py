"""Method tables for every aiOS gRPC service.

One `ServiceSpec` per proto service; `aios_tpu.rpc` turns these into stub and
servicer classes at import time. The method lists mirror the proto files in
`aios_tpu/protos/` exactly (which in turn are wire-compatible with the
reference's agent-core/proto).

Default port assignments follow the reference truth table (SURVEY.md section 1):
orchestrator 50051, tools 50052, memory 50053, api-gateway 50054, runtime 50055,
management console HTTP 9090.
"""

from __future__ import annotations

import os

from .proto_gen import (
    agent_pb2,
    api_gateway_pb2,
    common_pb2,
    fleet_pb2,
    memory_pb2,
    orchestrator_pb2,
    runtime_pb2,
    tools_pb2,
)
from .rpc import Method, ServiceSpec, make_servicer, make_stub

# ---------------------------------------------------------------------------
# Default service addresses (env-overridable, same vars as the reference's
# agent-core/src/clients.rs:37-44 / base.py:59-62).
# ---------------------------------------------------------------------------

DEFAULT_PORTS = {
    "orchestrator": 50051,
    "tools": 50052,
    "memory": 50053,
    "gateway": 50054,
    "runtime": 50055,
    "console": 9090,
}


def service_address(name: str) -> str:
    """Resolve a service address, honoring AIOS_<NAME>_ADDR overrides."""
    env = os.environ.get(f"AIOS_{name.upper()}_ADDR")
    if env:
        return env
    return f"127.0.0.1:{DEFAULT_PORTS[name]}"


# ---------------------------------------------------------------------------
# aios.runtime.AIRuntime
# ---------------------------------------------------------------------------

RUNTIME = ServiceSpec(
    "aios.runtime.AIRuntime",
    {
        "LoadModel": Method(runtime_pb2.LoadModelRequest, runtime_pb2.ModelStatus),
        "UnloadModel": Method(runtime_pb2.UnloadModelRequest, common_pb2.Status),
        "ListModels": Method(common_pb2.Empty, runtime_pb2.ModelList),
        "Infer": Method(runtime_pb2.InferRequest, runtime_pb2.InferResponse),
        "StreamInfer": Method(
            runtime_pb2.InferRequest, runtime_pb2.InferChunk, server_streaming=True
        ),
        "HealthCheck": Method(common_pb2.Empty, common_pb2.HealthStatus),
    },
)

# ---------------------------------------------------------------------------
# aios.orchestrator.Orchestrator (19 RPCs)
# ---------------------------------------------------------------------------

ORCHESTRATOR = ServiceSpec(
    "aios.orchestrator.Orchestrator",
    {
        "SubmitGoal": Method(orchestrator_pb2.SubmitGoalRequest, common_pb2.GoalId),
        "GetGoalStatus": Method(
            common_pb2.GoalId, orchestrator_pb2.GoalStatusResponse
        ),
        "CancelGoal": Method(common_pb2.GoalId, common_pb2.Status),
        "ListGoals": Method(
            orchestrator_pb2.ListGoalsRequest, orchestrator_pb2.GoalListResponse
        ),
        "RegisterAgent": Method(common_pb2.AgentRegistration, common_pb2.Status),
        "UnregisterAgent": Method(common_pb2.AgentId, common_pb2.Status),
        "Heartbeat": Method(orchestrator_pb2.HeartbeatRequest, common_pb2.Status),
        "ListAgents": Method(common_pb2.Empty, orchestrator_pb2.AgentListResponse),
        "GetSystemStatus": Method(
            common_pb2.Empty, orchestrator_pb2.SystemStatusResponse
        ),
        "GetAssignedTask": Method(common_pb2.AgentId, common_pb2.Task),
        "ReportTaskResult": Method(common_pb2.TaskResult, common_pb2.Status),
        "RequestCapability": Method(
            orchestrator_pb2.CapabilityRequest, orchestrator_pb2.CapabilityResponse
        ),
        "RevokeCapability": Method(
            orchestrator_pb2.CapabilityRevocation, common_pb2.Status
        ),
        "CreateSchedule": Method(
            orchestrator_pb2.CreateScheduleRequest, orchestrator_pb2.ScheduleResponse
        ),
        "ListSchedules": Method(
            common_pb2.Empty, orchestrator_pb2.ScheduleListResponse
        ),
        "DeleteSchedule": Method(
            orchestrator_pb2.DeleteScheduleRequest, common_pb2.Status
        ),
        "RegisterNode": Method(orchestrator_pb2.NodeRegistration, common_pb2.Status),
        "NodeHeartbeat": Method(orchestrator_pb2.NodeStatus, common_pb2.Status),
        "ListNodes": Method(
            orchestrator_pb2.ListNodesRequest, orchestrator_pb2.NodeListResponse
        ),
    },
)

# ---------------------------------------------------------------------------
# aios.agent.Agent
# ---------------------------------------------------------------------------

AGENT = ServiceSpec(
    "aios.agent.Agent",
    {
        "ExecuteTask": Method(common_pb2.Task, common_pb2.TaskResult),
        "CancelTask": Method(agent_pb2.CancelTaskRequest, common_pb2.Status),
        "GetStatus": Method(common_pb2.Empty, agent_pb2.AgentStatusResponse),
        "Shutdown": Method(common_pb2.Empty, common_pb2.Status),
    },
)

# ---------------------------------------------------------------------------
# aios.tools.ToolRegistry
# ---------------------------------------------------------------------------

TOOLS = ServiceSpec(
    "aios.tools.ToolRegistry",
    {
        "ListTools": Method(tools_pb2.ListToolsRequest, tools_pb2.ListToolsResponse),
        "GetTool": Method(tools_pb2.GetToolRequest, tools_pb2.ToolDefinition),
        "Execute": Method(tools_pb2.ExecuteRequest, tools_pb2.ExecuteResponse),
        "Rollback": Method(tools_pb2.RollbackRequest, tools_pb2.RollbackResponse),
        "Register": Method(
            tools_pb2.RegisterToolRequest, tools_pb2.RegisterToolResponse
        ),
        "Deregister": Method(tools_pb2.DeregisterToolRequest, tools_pb2.Status),
    },
)

# ---------------------------------------------------------------------------
# aios.api_gateway.ApiGateway
# ---------------------------------------------------------------------------

GATEWAY = ServiceSpec(
    "aios.api_gateway.ApiGateway",
    {
        "Infer": Method(
            api_gateway_pb2.ApiInferRequest, common_pb2.InferenceResponse
        ),
        "StreamInfer": Method(
            api_gateway_pb2.ApiInferRequest,
            api_gateway_pb2.StreamChunk,
            server_streaming=True,
        ),
        "GetBudget": Method(common_pb2.Empty, api_gateway_pb2.BudgetStatus),
        "GetUsage": Method(
            api_gateway_pb2.UsageRequest, api_gateway_pb2.UsageResponse
        ),
    },
)

# ---------------------------------------------------------------------------
# aios.memory.MemoryService (23 RPCs)
# ---------------------------------------------------------------------------

_M = memory_pb2
MEMORY = ServiceSpec(
    "aios.memory.MemoryService",
    {
        # operational
        "PushEvent": Method(_M.Event, _M.Empty),
        "GetRecentEvents": Method(_M.RecentEventsRequest, _M.EventList),
        "UpdateMetric": Method(_M.MetricUpdate, _M.Empty),
        "GetMetric": Method(_M.MetricRequest, _M.MetricValue),
        "GetSystemSnapshot": Method(_M.Empty, _M.SystemSnapshot),
        # working
        "StoreGoal": Method(_M.GoalRecord, _M.Empty),
        "UpdateGoal": Method(_M.GoalUpdate, _M.Empty),
        "GetActiveGoals": Method(_M.Empty, _M.GoalList),
        "StoreTask": Method(_M.TaskRecord, _M.Empty),
        "GetTasksForGoal": Method(_M.GoalIdRequest, _M.TaskList),
        "StoreToolCall": Method(_M.ToolCallRecord, _M.Empty),
        "StoreDecision": Method(_M.Decision, _M.Empty),
        "StorePattern": Method(_M.Pattern, _M.Empty),
        "FindPattern": Method(_M.PatternQuery, _M.PatternResult),
        "UpdatePatternStats": Method(_M.PatternStatsUpdate, _M.Empty),
        "StoreAgentState": Method(_M.AgentState, _M.Empty),
        "GetAgentState": Method(_M.AgentStateRequest, _M.AgentState),
        # long-term
        "SemanticSearch": Method(_M.SemanticSearchRequest, _M.SearchResults),
        "StoreProcedure": Method(_M.Procedure, _M.Empty),
        "StoreIncident": Method(_M.Incident, _M.Empty),
        "StoreConfigChange": Method(_M.ConfigChange, _M.Empty),
        # knowledge
        "SearchKnowledge": Method(_M.SemanticSearchRequest, _M.SearchResults),
        "AddKnowledge": Method(_M.KnowledgeEntry, _M.Empty),
        # context
        "AssembleContext": Method(_M.ContextRequest, _M.ContextResponse),
    },
)

# ---------------------------------------------------------------------------
# aios.fleet.KvTransfer — the fleet data plane (aios_tpu/fleet/): cross-host
# HostPageStore transfer (pull-on-miss Fetch, push-on-prefill Push) and the
# disaggregated prefill->decode Handoff stream. No reference counterpart.
# ---------------------------------------------------------------------------

KVTRANSFER = ServiceSpec(
    "aios.fleet.KvTransfer",
    {
        "Fetch": Method(
            fleet_pb2.FetchRequest, fleet_pb2.PageChunk,
            server_streaming=True,
        ),
        "Push": Method(
            fleet_pb2.PageChunk, fleet_pb2.PushAck, client_streaming=True,
        ),
        "Handoff": Method(
            fleet_pb2.HandoffRequest, fleet_pb2.HandoffChunk,
            server_streaming=True,
        ),
    },
)

ALL_SPECS = {
    "runtime": RUNTIME,
    "orchestrator": ORCHESTRATOR,
    "agent": AGENT,
    "tools": TOOLS,
    "gateway": GATEWAY,
    "memory": MEMORY,
    "kvtransfer": KVTRANSFER,
}

# Stub / servicer classes (equivalent surface to grpcio-tools output).
AIRuntimeStub = make_stub(RUNTIME)
AIRuntimeServicer = make_servicer(RUNTIME)
OrchestratorStub = make_stub(ORCHESTRATOR)
OrchestratorServicer = make_servicer(ORCHESTRATOR)
AgentStub = make_stub(AGENT)
AgentServicer = make_servicer(AGENT)
ToolRegistryStub = make_stub(TOOLS)
ToolRegistryServicer = make_servicer(TOOLS)
ApiGatewayStub = make_stub(GATEWAY)
ApiGatewayServicer = make_servicer(GATEWAY)
MemoryServiceStub = make_stub(MEMORY)
MemoryServiceServicer = make_servicer(MEMORY)
KvTransferStub = make_stub(KVTRANSFER)
KvTransferServicer = make_servicer(KVTRANSFER)
