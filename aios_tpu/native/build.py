"""Build the native shared library with g++ (no cmake needed for one TU).

Safe under concurrent callers (the supervisor spawns ~10 agent processes at
boot and each may trigger the lazy build): the compile writes to a private
temp path and is published with an atomic ``os.replace``, serialized by an
``flock`` so only one process pays for the compile.
"""

from __future__ import annotations

import fcntl
import os
import subprocess
from pathlib import Path

HERE = Path(__file__).parent
SRC = HERE / "src" / "aios_native.cpp"
OUT = HERE / "libaios_native.so"
LOCK = HERE / ".build.lock"


def _fresh() -> bool:
    return OUT.exists() and OUT.stat().st_mtime >= SRC.stat().st_mtime


def build(force: bool = False) -> Path:
    if _fresh() and not force:
        return OUT
    with open(LOCK, "w") as lock_fh:
        fcntl.flock(lock_fh, fcntl.LOCK_EX)
        try:
            # someone else may have built while we waited for the lock
            if _fresh() and not force:
                return OUT
            tmp = OUT.with_suffix(f".tmp.{os.getpid()}.so")
            cmd = [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                "-o", str(tmp), str(SRC),
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, OUT)  # atomic publish: readers never see a
                # half-written library
            finally:
                tmp.unlink(missing_ok=True)
            return OUT
        finally:
            fcntl.flock(lock_fh, fcntl.LOCK_UN)


if __name__ == "__main__":
    print(build(force=True))
