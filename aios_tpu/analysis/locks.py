"""Runtime lock verification: named, order-checking debug locks.

``make_lock(<registry name>)`` is what the declared serving-plane locks
call instead of ``threading.Lock()``. Normally it returns a plain
``threading.Lock`` — zero overhead, identical semantics. Under
``AIOS_TPU_LOCK_DEBUG=1`` it returns a :class:`DebugLock` that:

  * tracks the per-thread stack of held lock NAMES (roles, not
    instances: two replicas' batcher locks are one role — an AB/BA
    inversion between roles is a deadlock hazard whichever instances
    are involved);
  * records every acquired-while-holding edge the process observes, with
    the stack that first took it, and RAISES :class:`LockOrderError`
    the moment any thread acquires in an order that closes a cycle —
    the error carries BOTH stacks (the current acquisition and the one
    that established the opposite ordering), which is the whole
    diagnosis;
  * runs a held-too-long watchdog (``AIOS_TPU_LOCK_WATCHDOG_SECS``,
    default 120, 0 disables): a lock held past the threshold logs the
    holder's live stack (via ``sys._current_frames``) and lands in
    :func:`watchdog_trips` for tests to assert on.

The test suite's conftest enables the flag, so every e2e test doubles as
dynamic lock-order verification of the rules the static analyzer
enforces lexically (docs/ANALYSIS.md).

Fast-path cost when enabled: a thread-local list append plus, only on
NESTED acquisitions (rare), one global dict check under a small lock —
cheap enough to leave on for an entire pytest run.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("aios.analysis.locks")

__all__ = [
    "DebugLock", "LockOrderError", "make_lock", "debug_enabled",
    "watchdog_trips", "reset_debug_state",
]


def debug_enabled() -> bool:
    return os.environ.get("AIOS_TPU_LOCK_DEBUG", "").lower() in (
        "1", "true", "on"
    )


def make_lock(name: str):
    """A lock for the declared registry role ``name``: plain
    ``threading.Lock`` normally, order-checking :class:`DebugLock` under
    ``AIOS_TPU_LOCK_DEBUG=1``. The name must match the
    ``analysis.registry`` declaration (test_analysis checks the set)."""
    if debug_enabled():
        return DebugLock(name)
    return threading.Lock()


class LockOrderError(RuntimeError):
    """Two lock roles were acquired in both orders — a latent deadlock.

    The message carries the acquisition stack that closed the cycle AND
    the stack that first established the opposite edge."""


# -- global debug state ------------------------------------------------------

_tls = threading.local()  # .stack: List[Tuple[name, lock_id]]

_state_lock = threading.Lock()
# (held_name, acquired_name) -> formatted stack that first took the edge
_edges: Dict[Tuple[str, str], str] = {}
# lock_id -> (name, thread_id, t_acquired) for the watchdog
_held_now: Dict[int, Tuple[str, int, float]] = {}
_watchdog_trips: List[dict] = []
_watchdog_thread: Optional[threading.Thread] = None


def watchdog_trips() -> List[dict]:
    """Held-too-long events observed so far (name, seconds, holder
    thread's stack at trip time)."""
    return list(_watchdog_trips)


def reset_debug_state() -> None:
    """Forget observed edges/trips — test isolation only."""
    with _state_lock:
        _edges.clear()
        _watchdog_trips.clear()
        _held_now.clear()


def _watchdog_secs() -> float:
    raw = os.environ.get("AIOS_TPU_LOCK_WATCHDOG_SECS", "").strip()
    if not raw:
        return 120.0
    try:
        return float(raw)
    except ValueError:
        return 120.0


def _ensure_watchdog() -> None:
    global _watchdog_thread
    if _watchdog_thread is not None and _watchdog_thread.is_alive():
        return
    with _state_lock:
        if _watchdog_thread is not None and _watchdog_thread.is_alive():
            return
        t = threading.Thread(
            target=_watchdog_loop, name="aios-lock-watchdog", daemon=True
        )
        _watchdog_thread = t
        t.start()


def _watchdog_loop() -> None:
    warned: Dict[Tuple[int, float], bool] = {}
    while True:
        limit = _watchdog_secs()
        time.sleep(min(max(limit / 4.0, 0.01), 1.0))
        if limit <= 0:
            continue
        now = time.monotonic()
        for lock_id, (name, tid, t0) in list(_held_now.items()):
            if now - t0 <= limit or warned.get((lock_id, t0)):
                continue
            warned[(lock_id, t0)] = True
            frames = sys._current_frames()
            holder = frames.get(tid)
            stack = (
                "".join(traceback.format_stack(holder))
                if holder is not None else "<holder thread gone>"
            )
            trip = {
                "lock": name,
                "held_secs": round(now - t0, 3),
                "thread_id": tid,
                "stack": stack,
            }
            _watchdog_trips.append(trip)
            log.warning(
                "DebugLock '%s' held for %.1fs (> %.1fs watchdog) by "
                "thread %d; holder stack:\n%s",
                name, now - t0, limit, tid, stack,
            )
        # drop warn marks for released locks so a re-acquire re-arms
        for key in [k for k in warned if k[0] not in _held_now]:
            del warned[key]


class DebugLock:
    """Drop-in ``threading.Lock`` replacement with a role name, global
    acquisition-order cycle detection, and a held-too-long watchdog."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        if _watchdog_secs() > 0:
            _ensure_watchdog()

    # -- threading.Lock surface ---------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        self._note_released()
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<DebugLock {self.name!r} locked={self.locked()}>"

    # -- ordering ------------------------------------------------------------

    def _check_order(self) -> None:
        held: List[Tuple[str, int]] = getattr(_tls, "stack", None) or []
        if not held:
            return
        held_names = {n for n, _ in held if n != self.name}
        if not held_names:
            return
        me = self.name
        with _state_lock:
            # Would acquiring `me` while holding `h` close a cycle?
            # Follow existing edges OUT of `me`; if any held lock is
            # reachable, the opposite ordering was already observed.
            reachable = {me}
            frontier = [me]
            first_hop: Dict[str, Tuple[str, str]] = {}
            while frontier:
                cur = frontier.pop()
                for (a, b), stk in _edges.items():
                    if a == cur and b not in reachable:
                        reachable.add(b)
                        first_hop[b] = (a, stk)
                        frontier.append(b)
            bad = held_names & (reachable - {me})
            if bad:
                victim = sorted(bad)[0]
                _, opposite_stack = first_hop[victim]
                current = "".join(traceback.format_stack())
                raise LockOrderError(
                    f"lock-order inversion: thread holds "
                    f"'{victim}' and is acquiring '{self.name}', but the "
                    f"order '{self.name}' -> ... -> '{victim}' was "
                    f"already observed.\n"
                    f"--- current acquisition ---\n{current}"
                    f"--- first stack that established the opposite "
                    f"order ---\n{opposite_stack}"
                )
            new_edges = [
                (h, me) for h in held_names if (h, me) not in _edges
            ]
            if new_edges:
                stk = "".join(traceback.format_stack())
                for e in new_edges:
                    _edges[e] = stk

    def _note_acquired(self) -> None:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append((self.name, id(self)))
        _held_now[id(self)] = (
            self.name, threading.get_ident(), time.monotonic()
        )

    def _note_released(self) -> None:
        stack = getattr(_tls, "stack", None)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] == id(self):
                    del stack[i]
                    break
        _held_now.pop(id(self), None)
