"""Serving layer: replica pool, cache-aware routing, quotas, shedding.

Policy units (router / admission) run against plain fakes; the pool and
gRPC tests drive real 2-replica CPU pools over synthetic tiny models
(AIOS_TPU_PAGED_KV=auto so the prefix index — the router's score source —
is live), matching the ISSUE 2 acceptance criteria.
"""

import threading
import time
import urllib.request

import grpc
import numpy as np
import pytest

from aios_tpu import rpc, services
from aios_tpu.engine.batching import Request
from aios_tpu.proto_gen import runtime_pb2
from aios_tpu.runtime.model_manager import ModelManager
from aios_tpu.runtime.service import serve
from aios_tpu.serving import (
    AdmissionController,
    AdmissionError,
    Router,
    ServingConfig,
    TokenBucket,
    tenant_of,
)


# ---------------------------------------------------------------------------
# policy units (no engines)
# ---------------------------------------------------------------------------


class FakeReplica:
    def __init__(self, overlap=0, outstanding=0, queue=0, tps=0.0):
        self._overlap = overlap
        self._outstanding = outstanding
        self._queue = queue
        self._tps = tps

    def overlap_rows(self, ids, hashes=None):
        return self._overlap

    def outstanding_tokens(self):
        return self._outstanding

    def queue_depth(self):
        return self._queue

    def tokens_per_second(self):
        return self._tps


def test_router_picks_prefix_overlapping_replica():
    """The replica holding the prompt's prefix pages wins even when it is
    busier than its siblings (recomputing the prefix costs more than
    queueing behind the cache)."""
    router = Router(overlap_min_ratio=0.25)
    replicas = [
        FakeReplica(overlap=0, outstanding=0),
        FakeReplica(overlap=128, outstanding=500),
    ]
    idx, reason = router.select(replicas, list(range(140)))
    assert (idx, reason) == (1, "prefix")


def test_router_least_loaded_fallback_below_threshold():
    """Overlap under the threshold fraction of the prompt falls back to
    fewest outstanding tokens."""
    router = Router(overlap_min_ratio=0.5)
    replicas = [
        FakeReplica(overlap=16, outstanding=300),  # 16/140 < 0.5
        FakeReplica(overlap=0, outstanding=10),
    ]
    idx, reason = router.select(replicas, list(range(140)))
    assert (idx, reason) == (1, "least_loaded")


def test_router_sticky_task_id_routing():
    """A task_id continuation returns to the replica that served the task
    before, regardless of load or overlap scores."""
    router = Router()
    replicas = [FakeReplica(outstanding=900), FakeReplica(outstanding=0)]
    router.note_routed("task-42", 0)
    idx, reason = router.select(replicas, [1, 2, 3], task_id="task-42")
    assert (idx, reason) == (0, "sticky")
    # unknown task ids route normally; blank ids never stick
    idx, reason = router.select(replicas, [1, 2, 3], task_id="task-other")
    assert reason == "least_loaded"
    router.note_routed("", 1)
    idx, reason = router.select(replicas, [1, 2, 3], task_id="")
    assert reason == "least_loaded"


def test_token_bucket_quota_and_retry_after():
    b = TokenBucket(rate=10.0, burst=100.0)
    assert b.try_take(100.0) == 0.0  # burst drains fine
    wait = b.try_take(50.0)  # empty: 50 tokens at 10/s ≈ 5 s
    assert 4.0 < wait <= 5.1
    cfg = ServingConfig(tenant_tokens_per_sec=10.0, tenant_burst_tokens=100.0)
    adm = AdmissionController(cfg, "unit-quota")
    adm.check_quota("tenant-a", 90)  # fits the burst
    with pytest.raises(AdmissionError) as err:
        adm.check_quota("tenant-a", 90)
    assert err.value.cause == "quota"
    assert err.value.retriable
    assert err.value.retry_after_ms > 0
    # another tenant's bucket is untouched
    adm.check_quota("tenant-b", 90)
    # a cost no refill can ever cover is PERMANENT, not retriable
    with pytest.raises(AdmissionError) as err2:
        adm.check_quota("tenant-c", 150)  # burst is 100
    assert not err2.value.retriable
    # burst defaults to 4 s of refill when constructed directly with a
    # rate but no burst (not just through from_env)
    adm3 = AdmissionController(
        ServingConfig(tenant_tokens_per_sec=100.0), "unit-quota3"
    )
    adm3.check_quota("tenant-d", 300)  # fits the 400-token default burst


def test_deadline_infeasible_sheds_before_queueing():
    cfg = ServingConfig()
    adm = AdmissionController(cfg, "unit-deadline")
    # 400 outstanding + 100 requested at 100 tok/s = 5 s > 1 s deadline
    with pytest.raises(AdmissionError) as err:
        adm.check_deadline(1.0, 400, 100, 100.0)
    assert err.value.cause == "deadline"
    # feasible: fits the deadline
    adm.check_deadline(10.0, 400, 100, 100.0)
    # no observed rate and no assumed rate: never shed (cannot estimate)
    adm.check_deadline(0.001, 10_000, 100, 0.0)
    # the assumed-rate floor enables cold-start feasibility checks
    adm2 = AdmissionController(
        ServingConfig(assumed_tokens_per_sec=10.0), "unit-deadline2"
    )
    with pytest.raises(AdmissionError):
        adm2.check_deadline(1.0, 0, 100, 0.0)


def test_bounded_queue_sheds_with_retry_hint():
    adm = AdmissionController(ServingConfig(max_queue=4), "unit-queue")
    adm.check_queue(3, 100, 50.0)
    with pytest.raises(AdmissionError) as err:
        adm.check_queue(4, 100, 50.0)
    assert err.value.cause == "queue_full"
    assert err.value.retry_after_ms == 2000  # 100 tokens / 50 tok/s
    # 0 disables the bound
    AdmissionController(ServingConfig(max_queue=0), "unit-queue0") \
        .check_queue(10_000, 0, 0.0)


def test_tenant_identity_resolution():
    class R:
        requesting_agent = "coder"
        task_id = "research-77:phase2"

    assert tenant_of(R()) == "coder"
    R.requesting_agent = ""
    assert tenant_of(R()) == "research"
    assert tenant_of(R(), mode="task_prefix") == "research"
    R.task_id = ""
    assert tenant_of(R()) == "anonymous"


# ---------------------------------------------------------------------------
# 2-replica CPU pool (real engines, paged + prefix index)
# ---------------------------------------------------------------------------

CTX = 256  # page_size 128 -> prompts past 129 ids have a cacheable block
PREFIX_A = list(range(1, 131))
PREFIX_B = list(range(131, 261))


@pytest.fixture(scope="module")
def pool_server():
    """2-replica pool behind a live gRPC server + /metrics endpoint."""
    mp = pytest.MonkeyPatch()
    mp.setenv("AIOS_TPU_PAGED_KV", "auto")
    mp.setenv("AIOS_TPU_REPLICAS", "2")
    manager = ModelManager(num_slots=2, warm_compile=False)
    managed = manager.load_model(
        "tinyserve", "synthetic://tiny-test", context_length=CTX
    )
    server, service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False, metrics_port=0
    )
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.AIRuntimeStub(channel), manager, managed, service
    channel.close()
    server.stop(grace=None)
    if service.metrics_server is not None:
        service.metrics_server.shutdown()
    manager.unload_model("tinyserve")
    mp.undo()


def _drain(handle):
    return handle.tokens()


def test_pool_routes_shared_prefix_to_cache_holder(pool_server):
    """ISSUE 2 acceptance: two tenants issuing shared-prefix prompts on a
    2-replica pool — ≥80% of same-prefix requests land on the replica
    already holding the prefix pages."""
    _, _, managed, _ = pool_server
    pool = managed.pool
    assert len(pool.replicas) == 2
    before = dict(pool._routed)
    # warm both prefixes CONCURRENTLY so least-loaded spreads them: A
    # occupies its replica while B routes
    ha = pool.submit(Request(prompt_ids=PREFIX_A + [300], max_tokens=4,
                             temperature=0.0), tenant="tenant-a")
    hb = pool.submit(Request(prompt_ids=PREFIX_B + [300], max_tokens=4,
                             temperature=0.0), tenant="tenant-b")
    _drain(ha), _drain(hb)
    # each prefix is now resident on exactly the replica that served it
    holder_a = [i for i, r in enumerate(pool.replicas)
                if r.overlap_rows(PREFIX_A + [301]) > 0]
    holder_b = [i for i, r in enumerate(pool.replicas)
                if r.overlap_rows(PREFIX_B + [301]) > 0]
    assert holder_a and holder_b
    # 20 same-prefix continuations, two tenants interleaved
    n = 20
    for i in range(n // 2):
        h1 = pool.submit(Request(prompt_ids=PREFIX_A + [301 + i],
                                 max_tokens=3, temperature=0.0),
                         tenant="tenant-a")
        h2 = pool.submit(Request(prompt_ids=PREFIX_B + [301 + i],
                                 max_tokens=3, temperature=0.0),
                         tenant="tenant-b")
        _drain(h1), _drain(h2)
    prefix_routed = pool._routed["prefix"] - before.get("prefix", 0)
    assert prefix_routed >= 0.8 * n, (prefix_routed, dict(pool._routed))


def test_sticky_task_routing_through_pool(pool_server):
    _, _, managed, _ = pool_server
    pool = managed.pool
    before = pool._routed["sticky"]
    first = pool.submit(Request(prompt_ids=[7, 8, 9], max_tokens=2,
                                temperature=0.0, request_id="conv-1"))
    _drain(first)
    cont = pool.submit(Request(prompt_ids=[7, 8, 9, 10], max_tokens=2,
                               temperature=0.0, request_id="conv-1"))
    _drain(cont)
    assert pool._routed["sticky"] == before + 1


def test_stream_infer_e2e_and_serving_metrics(pool_server):
    """StreamInfer through the 2-replica pool over gRPC, then the
    aios_tpu_serving_* family shows up on /metrics."""
    stub, _, managed, service = pool_server
    chunks = list(stub.StreamInfer(runtime_pb2.InferRequest(
        prompt="hello serving", max_tokens=6, temperature=0.0,
        requesting_agent="metrics-agent", task_id="metrics-1",
    )))
    assert chunks[-1].done
    assert service.metrics_port is not None
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{service.metrics_port}/metrics", timeout=10
    ).read().decode()
    assert 'aios_tpu_serving_replicas_total{model="tinyserve"} 2' in body
    assert 'aios_tpu_serving_routing_decisions_total{' in body
    assert 'aios_tpu_serving_replica_occupancy_ratio{model="tinyserve"' in body
    assert "aios_tpu_serving_queue_wait_seconds_bucket" in body
    assert "aios_tpu_serving_shed_total" in body


def test_health_reports_pool_stats(pool_server):
    stub, _, _, _ = pool_server
    from aios_tpu.proto_gen import common_pb2

    h = stub.HealthCheck(common_pb2.Empty())
    serving = h.details["tinyserve.serving"]
    assert "replicas=2" in serving
    assert "routed_prefix=" in serving
    assert "shed_quota=" in serving
    assert "completed=" in serving  # the pre-pool keys survive


def test_admission_gate_order_quota_debits_last(pool_server):
    """Quota must be the LAST gate: debiting the bucket is a side effect,
    and a request the queue/deadline gates shed must not burn the
    tenant's tokens (shed->retry loops would starve feasible traffic)."""
    _, _, managed, _ = pool_server
    pool = managed.pool
    adm = pool.admission
    calls = []
    originals = {}
    for gate in ("check_queue", "check_deadline", "check_quota"):
        originals[gate] = getattr(adm, gate)

        def spy(*a, _g=gate, **kw):
            calls.append(_g)
            return originals[_g](*a, **kw)

        setattr(adm, gate, spy)
    try:
        h = pool.submit(Request(prompt_ids=[1, 2], max_tokens=2,
                                temperature=0.0))
        _drain(h)
    finally:
        for gate, fn in originals.items():
            setattr(adm, gate, fn)
    assert calls == ["check_queue", "check_deadline", "check_quota"]


def test_deadline_cost_capped_by_cache_room(pool_server):
    """A giant max_tokens is not a giant deadline requirement: the decode
    budget is capped at the cache room left after the prompt, so a
    request that can only decode a handful of tokens admits under a
    short deadline."""
    _, _, managed, _ = pool_server
    pool = managed.pool
    orig = pool.admission
    pool.admission = AdmissionController(
        ServingConfig(assumed_tokens_per_sec=10.0), "cap-test"
    )
    try:
        # ctx 256, prompt 250 -> <=6 decodable tokens (~0.6 s at 10
        # tok/s), feasible inside 5 s despite max_tokens=50k (raw
        # 50k/10 — or even ctx/10 — would have shed)
        h = pool.submit(
            Request(prompt_ids=list(range(1, 251)), max_tokens=50_000,
                    temperature=0.0),
            deadline_s=5.0,
        )
        assert len(_drain(h)) > 0
    finally:
        pool.admission = orig


def test_replica_crash_restart_counted(pool_server):
    """A replica whose scheduler recorded a fatal error gets a fresh
    batcher on the next submit — surfaced through the spawner-style
    restart counter."""
    _, _, managed, _ = pool_server
    pool = managed.pool
    victim = pool.replicas[0]
    old_batcher = victim.batcher
    old_batcher.last_error = RuntimeError("synthetic scheduler crash")
    before = pool.restarts
    h = pool.submit(Request(prompt_ids=[5, 6], max_tokens=2,
                            temperature=0.0))
    assert _drain(h) is not None
    assert pool.restarts == before + 1
    assert victim.batcher is not old_batcher
    assert victim.batcher.last_error is None


# ---------------------------------------------------------------------------
# quota + deadline shedding over gRPC
# ---------------------------------------------------------------------------


def _serve_tiny(mp, env, **mgr_kw):
    # the module fixture's 2-replica env may still be live; these servers
    # pin their own serving policy
    mp.delenv("AIOS_TPU_REPLICAS", raising=False)
    for k, v in env.items():
        mp.setenv(k, v)
    manager = ModelManager(num_slots=2, warm_compile=False, **mgr_kw)
    manager.load_model("quotatiny", "synthetic://tiny-test",
                       context_length=128)
    server, service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False
    )
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    return manager, server, channel, services.AIRuntimeStub(channel)


def test_quota_rejection_resource_exhausted_with_retry_after(monkeypatch):
    """ISSUE 2 acceptance: the over-quota tenant gets RESOURCE_EXHAUSTED
    plus a retry-after-ms trailing-metadata hint while the other tenant's
    requests still complete."""
    manager, server, channel, stub = _serve_tiny(monkeypatch, {
        "AIOS_TPU_TENANT_TOKENS_PER_SEC": "1",
        "AIOS_TPU_TENANT_BURST_TOKENS": "100",
    })
    try:
        err = None
        for i in range(10):  # drain tenant-a's bucket
            try:
                stub.Infer(runtime_pb2.InferRequest(
                    prompt="hi", max_tokens=8, temperature=0.0,
                    requesting_agent="tenant-a",
                ))
            except grpc.RpcError as e:
                err = e
                break
        assert err is not None, "tenant-a was never shed"
        assert err.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        md = dict(err.trailing_metadata() or ())
        assert int(md["retry-after-ms"]) > 0
        # the OTHER tenant still completes
        resp = stub.Infer(runtime_pb2.InferRequest(
            prompt="hi", max_tokens=8, temperature=0.0,
            requesting_agent="tenant-b",
        ))
        assert resp.tokens_used > 0
        pool = manager.get("quotatiny").pool
        assert pool._shed["quota"] >= 1
    finally:
        channel.close()
        server.stop(grace=None)
        manager.unload_model("quotatiny")


def test_deadline_infeasible_shed_without_consuming_a_slot(monkeypatch):
    """ISSUE 2 acceptance: a request whose gRPC deadline cannot cover the
    estimated queue+decode time is rejected immediately — no slot, no
    queue position."""
    manager, server, channel, stub = _serve_tiny(monkeypatch, {
        "AIOS_TPU_ASSUMED_TPS": "5",  # 64 tokens -> ~12.8 s estimated
    })
    try:
        pool = manager.get("quotatiny").pool
        with pytest.raises(grpc.RpcError) as err:
            stub.Infer(
                runtime_pb2.InferRequest(
                    prompt="hi", max_tokens=64, temperature=0.0
                ),
                timeout=2.0,
            )
        assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert pool._shed["deadline"] == 1
        # nothing was consumed: no queue entry, no live slot, no retire
        for r in pool.replicas:
            assert r.queue_depth() == 0
            assert r.batcher.active_count == 0
            assert r.batcher.completed == 0
        # a no-deadline request on the same pool still serves
        resp = stub.Infer(runtime_pb2.InferRequest(
            prompt="hi", max_tokens=4, temperature=0.0
        ))
        assert resp.tokens_used > 0
    finally:
        channel.close()
        server.stop(grace=None)
        manager.unload_model("quotatiny")


def test_tenant_by_task_prefix_wired_through_service(monkeypatch):
    """AIOS_TPU_TENANT_BY=task_prefix reaches the service's tenant
    resolution: two callers sharing one agent id but distinct task
    prefixes get SEPARATE buckets (with agent-mode identity they would
    share one and both shed)."""
    manager, server, channel, stub = _serve_tiny(monkeypatch, {
        "AIOS_TPU_TENANT_TOKENS_PER_SEC": "1",
        "AIOS_TPU_TENANT_BURST_TOKENS": "100",
        "AIOS_TPU_TENANT_BY": "task_prefix",
    })
    try:
        err = None
        for i in range(10):
            try:
                stub.Infer(runtime_pb2.InferRequest(
                    prompt="hi", max_tokens=8, temperature=0.0,
                    requesting_agent="shared-agent", task_id=f"ta-{i}",
                ))
            except grpc.RpcError as e:
                err = e
                break
        assert err is not None and \
            err.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        # same agent id, different task prefix: its own fresh bucket
        resp = stub.Infer(runtime_pb2.InferRequest(
            prompt="hi", max_tokens=8, temperature=0.0,
            requesting_agent="shared-agent", task_id="tb-0",
        ))
        assert resp.tokens_used > 0
    finally:
        channel.close()
        server.stop(grace=None)
        manager.unload_model("quotatiny")


def test_failed_reload_keeps_serving_model(monkeypatch):
    """A hot-swap reload that FAILS must not clobber the still-working
    model: the READY pool keeps serving and the caller sees the load
    error."""
    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)
    manager = ModelManager(num_slots=2, warm_compile=False)
    m = manager.load_model("keep", "synthetic://tiny-test",
                           context_length=128)
    try:
        with pytest.raises(Exception):
            manager.load_model("keep", "/nonexistent/model.gguf")
        cur = manager.get("keep")
        assert cur is m and cur.state == "ready"
        h = cur.submit(Request(prompt_ids=[1, 2], max_tokens=2,
                               temperature=0.0))
        assert len(h.tokens()) == 2
    finally:
        manager.unload_model("keep")


# ---------------------------------------------------------------------------
# drain + hot-swap
# ---------------------------------------------------------------------------


def test_drain_completes_inflight_streams_then_swaps(monkeypatch):
    """A LoadModel with a changed geometry hot-swaps the pool: the NEW
    pool serves immediately while the old one drains — the in-flight
    stream finishes untruncated on the engine it started on."""
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)
    manager = ModelManager(num_slots=2, warm_compile=False)
    first = manager.load_model("swap", "synthetic://tiny-test",
                               context_length=128)
    old_pool = first.pool
    handle = first.submit(Request(prompt_ids=[1, 2, 3], max_tokens=24,
                                  temperature=0.0))
    got = []
    it = iter(handle)
    got.append(next(it))  # stream genuinely in flight
    try:
        second = manager.load_model("swap", "synthetic://tiny-test",
                                    context_length=256)
        assert second is not first
        assert second.pool is not old_pool
        assert manager.get("swap") is second
        assert second.engine.max_context == 256
        # the in-flight stream completes fully (not aborted, not cut)
        got.extend(it)
        assert len(got) == 24
        assert not handle.aborted
        # the old pool refuses new work while/after draining
        with pytest.raises(AdmissionError):
            old_pool.submit(Request(prompt_ids=[4], max_tokens=2))
        # and eventually closes in the background
        deadline = time.time() + 30
        while not old_pool._closed and time.time() < deadline:
            time.sleep(0.05)
        assert old_pool._closed
        # the swapped-in pool serves
        h2 = second.submit(Request(prompt_ids=[9, 9], max_tokens=2,
                                   temperature=0.0))
        assert len(_drain(h2)) == 2
        # an identical reload is a no-op, not another swap
        assert manager.load_model(
            "swap", "synthetic://tiny-test", context_length=256
        ) is second
    finally:
        manager.unload_model("swap")


def test_drain_waits_for_inflight(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)
    manager = ModelManager(num_slots=2, warm_compile=False)
    m = manager.load_model("draintiny", "synthetic://tiny-test",
                           context_length=128)
    try:
        pool = m.pool
        handle = pool.submit(Request(prompt_ids=[1, 2], max_tokens=12,
                                     temperature=0.0))
        out = {}

        def consume():
            out["tokens"] = handle.tokens()

        t = threading.Thread(target=consume)
        t.start()
        assert pool.drain(timeout=60.0)
        t.join(timeout=10)
        assert len(out["tokens"]) == 12
        with pytest.raises(AdmissionError) as err:
            pool.submit(Request(prompt_ids=[3], max_tokens=2))
        assert err.value.cause == "draining"
    finally:
        manager.unload_model("draintiny")


# ---------------------------------------------------------------------------
# satellites riding this PR
# ---------------------------------------------------------------------------


def test_pool_eviction_marks_victim_aborted():
    """A pool-exhaustion eviction sets the victim's abort_reason so the
    serving layer returns an error instead of a silently truncated
    completion (ADVICE r5)."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.engine.batching import ContinuousBatcher
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0),
                           dtype=jnp.float32)
    eng = TPUEngine(TINY_TEST, params, num_slots=3, max_context=128,
                    cache_dtype=jnp.float32, paged_pool_rows=96,
                    page_size=32, prefix_cache=False)
    b = ContinuousBatcher(eng)
    try:
        hs = [
            b.submit(Request(prompt_ids=[s + 1, 2, 3], max_tokens=80,
                             temperature=0.0))
            for s in range(3)
        ]
        outs = [h.tokens() for h in hs]
        assert b.pool_evictions >= 1
        evicted = [h for h in hs if h.aborted]
        assert evicted, "no victim carried an abort_reason"
        assert all("evicted" in h.abort_reason for h in evicted)
        # survivors stay normal completions
        assert any(
            not h.aborted and len(o) == 80 for h, o in zip(hs, outs)
        )
    finally:
        b.shutdown()
        eng.close()


def test_validate_prequantized_tp_checks_int8_leaves():
    """A prepared int8 tree with tp-indivisible dims fails load with the
    re-prepare recipe instead of an opaque GSPMD shape error (ADVICE r5):
    N % tp for column-parallel leaves, K % tp for the row-parallel ones."""
    from aios_tpu.engine.engine import _validate_prequantized_tp

    def leaf(K, N):
        return {"q": np.zeros((K, N), np.int8),
                "s": np.zeros((1, N), np.float32)}

    good = {"layers": {"wq": leaf(64, 64), "wo": leaf(64, 64)}}
    _validate_prequantized_tp(good, 2)  # divisible: fine

    bad_col = {"layers": {"wq": leaf(64, 63)}}  # N % 2 != 0
    with pytest.raises(ValueError, match="int8.*wq"):
        _validate_prequantized_tp(bad_col, 2)

    bad_row = {"layers": {"wo": leaf(63, 64)}}  # K % 2 != 0 (row-parallel)
    with pytest.raises(ValueError, match="int8.*wo"):
        _validate_prequantized_tp(bad_row, 2)
    # the column-parallel K need not divide, nor the row-parallel N
    mixed = {"layers": {"wq": leaf(63, 64), "wo": leaf(64, 63)}}
    _validate_prequantized_tp(mixed, 2)


def test_seq_shard_degrade_uses_dense_estimate(monkeypatch):
    """The HBM auto-degrade records the SEQ-SHARDED (dense num_slots x ctx
    over dp*tp*sp) KV estimate, not the paged pool's rows divided by sp
    (ADVICE r5): the footprint gap between a paged model and a degraded
    one matches the recomputed formula exactly."""
    monkeypatch.setenv("AIOS_TPU_MESH", "sp=2")
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)

    monkeypatch.setenv("AIOS_TPU_HBM_GB", "16")
    mgr = ModelManager(num_slots=2, warm_compile=False)
    paged = mgr.load_model("a", "synthetic://tiny-test", context_length=128)
    assert paged.engine.paged
    hbm_paged = paged.hbm_chip_bytes
    cfg = paged.config
    mgr.unload_model("a")

    monkeypatch.setenv("AIOS_TPU_HBM_GB", "0.000001")
    mgr2 = ModelManager(num_slots=2, warm_compile=False)
    degraded = mgr2.load_model("a", "synthetic://tiny-test",
                               context_length=128)
    try:
        assert degraded.engine.seq_sharded
        import jax.numpy as jnp

        row = mgr2._kv_row_bytes(cfg, jnp.bfloat16)
        paged_rows = (2 + 1) * 128       # auto pool: (slots+1) x ctx
        seq_rows_per_chip = 2 * 128 / 2  # slots x ctx / sp
        want_gap = row * (paged_rows - seq_rows_per_chip)
        assert hbm_paged - degraded.hbm_chip_bytes == pytest.approx(want_gap)
    finally:
        mgr2.unload_model("a")
