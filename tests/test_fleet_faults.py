"""Fleet fault domains (ISSUE 18): per-edge network faults, the
gray-host quarantine breaker, and the graceful-drain protocol.

Fast CPU tier only — every test runs on injected clocks and in-process
fakes. The slow acceptance (two real workers partitioned, severed,
quarantined, drained) lives in scripts/partition_smoke.py (preflight
gate 8), not here.
"""

import pytest

from aios_tpu import faults
from aios_tpu.faults import net
from aios_tpu.fleet import breaker as breaker_mod
from aios_tpu.fleet.breaker import BreakerBoard, BreakerConfig


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Every test runs as fleet host "hostA" with no schedule armed, a
    fresh addr map, and the default process-wide board restored — a
    leaked plan or edge map would inject faults into unrelated tests."""
    monkeypatch.setenv("AIOS_TPU_FLEET_HOST", "hostA")
    faults.deactivate()
    net._reset()
    yield
    faults.deactivate()
    net._reset()
    breaker_mod.reset()


def _cfg(**over):
    cfg = BreakerConfig()
    cfg.threshold = over.get("threshold", 2.0)
    cfg.cooldown_secs = over.get("cooldown_secs", 5.0)
    cfg.max_cooldown_secs = over.get("max_cooldown_secs", 60.0)
    cfg.probes = over.get("probes", 2)
    cfg.lat_floor_secs = over.get("lat_floor_secs", 0.0)
    return cfg


# ---------------------------------------------------------------------------
# per-edge determinism (faults/net.py over faults/inject.py)
# ---------------------------------------------------------------------------


def test_edge_hit_counters_are_independent():
    """Hits count PER (src, dst) edge: traffic to one peer never shifts
    another edge's hit index — the determinism anchor of the per-edge
    contract."""
    faults.activate("net.partition=nth:2,dst=hostB")
    net.check_send("hostB", "rpc")            # hostB hit 1: clean
    net.check_send("hostC", "rpc")            # other edge, other counter
    net.check_send("hostC", "rpc")
    with pytest.raises(net.NetFaultRefused) as err:
        net.check_send("hostB", "rpc")        # hostB hit 2: fires
    assert err.value.edge == ("hostA", "hostB")
    assert err.value.hit == 2
    net.check_send("hostC", "rpc")            # hostC still never fires


def test_until_widens_nth_to_a_held_window():
    """``until=M`` holds the fault from hit N through hit M — the
    sustained-partition grammar the membership arc needs."""
    faults.activate("net.partition=nth:2,until=4,dst=hostB")
    net.check_send("hostB", "rpc")            # hit 1: before the window
    for _ in range(3):                        # hits 2..4: held
        with pytest.raises(net.NetFaultRefused):
            net.check_send("hostB", "rpc")
    net.check_send("hostB", "rpc")            # hit 5: healed


def test_surface_mismatch_neither_fires_nor_consumes():
    """A spec scoped surface=rpc must ignore http traffic WITHOUT
    consuming a hit — otherwise unrelated-surface traffic would shift
    the k-th-send determinism the schedule anchors on."""
    faults.activate("net.drop_after=nth:1,dst=hostB,surface=rpc,"
                    "after_msgs=2")
    for _ in range(3):
        net.check_drop_response("hostB", "http")  # wrong surface: no-op
    severed = net.sever_stream("hostB", iter(range(10)))  # rpc hit 1
    assert next(severed) == 0
    assert next(severed) == 1                 # after_msgs=2 delivered
    with pytest.raises(net.NetFaultSevered):
        next(severed)


def test_delay_point_sleeps_instead_of_raising():
    faults.activate("net.delay=prob:1.0,delay_ms=1,dst=hostB")
    net.check_send("hostB", "rpc")            # delayed, not refused


def test_netfault_doubles_as_unavailable_rpc_error():
    """Every existing ``except grpc.RpcError`` recovery path must catch
    an injected edge fault exactly as it catches a real dead peer."""
    import grpc

    err = net.NetFaultRefused("net.partition", ("hostA", "hostB"), 1)
    assert isinstance(err, ConnectionError)
    assert isinstance(err, grpc.RpcError)
    assert err.code() == grpc.StatusCode.UNAVAILABLE
    assert "hostA->hostB" in err.details()


def test_addr_to_host_mapping_survives_urls():
    """Edges are named by fleet host ids: gossip teaches the namer each
    peer's addresses; an unseen address resolves to itself."""
    net.map_addr("10.0.0.7:9100", "hostB")
    assert net.host_of("10.0.0.7:9100") == "hostB"
    assert net.host_of("http://10.0.0.7:9100/metrics") == "hostB"
    assert net.host_of("127.0.0.1:1234") == "127.0.0.1:1234"


def test_solo_invariance_without_a_schedule():
    """Faults off (the solo serving path): every net gate is a strict
    no-op — same iterator object back, announces unconditionally
    accepted, no points scheduled."""
    net.check_send("hostB", "rpc")
    net.check_drop_response("hostB")
    stream = iter(range(3))
    assert net.sever_stream("hostB", stream) is stream
    assert net.gate_announce("hostB") == (True, True)
    assert net.active_points() == ()


# ---------------------------------------------------------------------------
# asymmetric partition (gate_announce + the membership state machine)
# ---------------------------------------------------------------------------


def test_gate_announce_models_both_partition_flavors():
    """The announce REPLY travels the self->announcer edge: a one-way
    partition folds the peer's descriptor (their data reached us) but
    withholds the reply; a full partition refuses both directions."""
    faults.activate("net.partition_oneway=nth:1,until=100,dst=hostB,"
                    "surface=http")
    assert net.gate_announce("hostB") == (True, False)
    assert net.gate_announce("hostC") == (True, True)
    faults.activate("net.partition=nth:1,until=100,dst=hostB,"
                    "surface=http")
    assert net.gate_announce("hostB") == (False, False)


def test_asymmetric_partition_membership_divergence():
    """The up/suspect/dead machine under asymmetry: A keeps hearing B
    (B stays up on A) while B hears nothing from A — so B walks A
    through suspect to dead. Divergent views are correct here; the
    gossip reply, once the edge heals, reconverges them."""
    from aios_tpu.obs.fleet import FleetConfig, FleetRegistry

    def _registry(self_host, now):
        cfg = FleetConfig()
        cfg.suspect_secs = 5.0
        cfg.dead_secs = 10.0
        cfg.peers = ()
        return FleetRegistry(
            {"host": self_host, "role": "runtime", "rank": "0",
             "version": "t"},
            "127.0.0.1:9100", cfg=cfg, clock=lambda: now[0],
        )

    now = [100.0]
    reg_a = _registry("hostA", now)
    reg_b = _registry("hostB", now)
    desc_b = {"host": "hostB", "role": "runtime", "rank": "1",
              "version": "t", "metrics_addr": "127.0.0.1:9101"}
    desc_a = {"host": "hostA", "role": "runtime", "rank": "0",
              "version": "t", "metrics_addr": "127.0.0.1:9100"}
    reg_a.receive(desc_b)
    reg_b.receive(desc_a)
    # the partition: B's announces still reach A; A's never reach B
    for t in (103.0, 106.0, 109.0, 112.0):
        now[0] = t
        reg_a.receive(desc_b)
        reg_a.tick(now=t)
        reg_b.tick(now=t)
    a_view = {m["host"]: m["state"] for m in reg_a.members()}
    b_view = {m["host"]: m["state"] for m in reg_b.members()}
    assert a_view["hostB"] == "up"
    assert b_view["hostA"] == "dead"


# ---------------------------------------------------------------------------
# the gray-host quarantine breaker (fleet/breaker.py, injected clock)
# ---------------------------------------------------------------------------


def test_breaker_trips_cools_down_and_probes_closed():
    now = [0.0]
    b = BreakerBoard(cfg=_cfg(), clock=lambda: now[0])
    assert b.allow("hostB")
    b.record_failure("hostB", "unavailable")
    assert b.state("hostB") == "closed"       # score 1 < threshold 2
    b.record_failure("hostB", "timeout")
    assert b.state("hostB") == "open"
    assert b.quarantined("hostB")
    assert not b.allow("hostB")               # cooldown not elapsed
    now[0] = 5.1
    assert b.allow("hostB")                   # half-open, probe 1 of 2
    assert b.state("hostB") == "half_open"
    assert b.quarantined("hostB")             # overlay until CLOSED
    b.record_ok("hostB")
    b.record_ok("hostB")                      # 2 consecutive: closed
    assert b.state("hostB") == "closed"
    assert not b.quarantined("hostB")
    assert b.snapshot()["hostB"]["score"] == 0.0


def test_half_open_failure_reopens_with_doubled_cooldown():
    now = [0.0]
    b = BreakerBoard(cfg=_cfg(cooldown_secs=5.0, max_cooldown_secs=8.0),
                     clock=lambda: now[0])
    b.record_failure("hostB")
    b.record_failure("hostB")
    assert b.snapshot()["hostB"]["cooldown"] == 5.0
    now[0] = 5.1
    assert b.allow("hostB")                   # half-open probe
    b.record_failure("hostB")                 # failed probe: re-open
    assert b.state("hostB") == "open"
    assert b.snapshot()["hostB"]["cooldown"] == 8.0  # doubled, capped


def test_probe_budget_bounds_half_open_calls():
    now = [0.0]
    b = BreakerBoard(cfg=_cfg(probes=2), clock=lambda: now[0])
    b.record_failure("hostB")
    b.record_failure("hostB")
    now[0] = 5.1
    assert b.allow("hostB")
    assert b.allow("hostB")
    assert not b.allow("hostB")               # budget of 2 spent


def test_corruption_outweighs_slowness():
    """crc_mismatch carries weight 2.0: a peer shipping bad bytes trips
    the breaker in ONE failure at the default-ish threshold."""
    b = BreakerBoard(cfg=_cfg(threshold=2.0))
    b.record_failure("hostB", "crc_mismatch")
    assert b.state("hostB") == "open"


def test_success_decays_the_failure_score():
    """Occasional blips on a busy edge never accumulate to a trip."""
    b = BreakerBoard(cfg=_cfg(threshold=2.0))
    for _ in range(4):
        b.record_failure("hostB", "timeout")  # score +1
        b.record_ok("hostB")                  # score halved
    assert b.state("hostB") == "closed"


def test_gray_latency_floor_counts_successes_as_failures():
    """The gray-host case proper: calls that 'succeed' above the
    latency floor quarantine the peer anyway."""
    b = BreakerBoard(cfg=_cfg(threshold=2.0, lat_floor_secs=0.01))
    b.record_ok("hostB", latency_s=5.0)
    b.record_ok("hostB", latency_s=5.0)
    assert b.state("hostB") == "open"


def test_unknown_peer_is_closed_and_allowed():
    b = BreakerBoard(cfg=_cfg())
    assert b.allow("never-seen")
    assert b.state("never-seen") == "closed"
    assert not b.quarantined("never-seen")
    assert b.allow("")                        # addressless: always allowed


# ---------------------------------------------------------------------------
# graceful drain (fleet/drain.py, injected exit_fn; no real exit)
# ---------------------------------------------------------------------------


class _FakeManager:
    def ready_models(self):
        return []


def _run_drain(timeout_s=0.1):
    from aios_tpu.fleet import drain

    exits = []
    coord = drain.DrainCoordinator(_FakeManager(), exit_fn=exits.append)
    phase = coord.request_drain(timeout_s)
    t = coord._thread
    assert t is not None
    t.join(timeout=10.0)
    assert not t.is_alive()
    return coord, phase, exits


def test_drain_walks_the_phase_ladder_and_exits_zero():
    from aios_tpu.serving import admission

    try:
        coord, phase, exits = _run_drain()
        assert phase == "draining"
        assert coord.phase() == "leaving"
        assert exits == [0]
        # the front door closed while the protocol ran
        assert admission.host_draining()
    finally:
        admission.set_host_draining(False)


def test_drain_is_idempotent():
    from aios_tpu.serving import admission

    try:
        coord, _, exits = _run_drain()
        # a second POST reports the terminal phase, starts nothing new
        t1 = coord._thread
        assert coord.request_drain() == "leaving"
        assert coord._thread is t1
        assert exits == [0]
    finally:
        admission.set_host_draining(False)


def test_unarmed_module_surface_stays_serving():
    from aios_tpu.fleet import drain

    drain.disarm()
    assert drain.phase() == "serving"
    assert not drain.draining()
    assert drain.request_drain() == "serving"


def test_arm_and_module_phase_follow_coordinator():
    from aios_tpu.fleet import drain
    from aios_tpu.serving import admission

    try:
        exits = []
        drain.arm(_FakeManager(), exit_fn=exits.append)
        assert drain.phase() == "serving"
        assert drain.request_drain(0.05) == "draining"
        assert drain.draining()
        t = drain.COORD._thread
        t.join(timeout=10.0)
        assert drain.phase() == "leaving"
        assert exits == [0]
    finally:
        drain.disarm()
        admission.set_host_draining(False)


def test_admission_sheds_with_the_draining_host_cause():
    from aios_tpu.serving import admission
    from aios_tpu.serving.admission import AdmissionController, AdmissionError
    from aios_tpu.serving.config import ServingConfig

    adm = AdmissionController(ServingConfig(), "drainmodel")
    adm.check_host_drain()                    # healthy: no-op
    admission.set_host_draining(True)
    try:
        with pytest.raises(AdmissionError) as err:
            adm.check_host_drain()
        assert err.value.cause == "draining_host"
        assert err.value.retriable
    finally:
        admission.set_host_draining(False)
    adm.check_host_drain()
