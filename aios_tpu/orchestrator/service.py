"""aios.orchestrator.Orchestrator gRPC service — 19 RPCs.

Reference parity (agent-core/src/main.rs:142-553): goal submission triggers
decomposition; agents register/heartbeat/poll via GetAssignedTask/
ReportTaskResult; capability requests are auto-granted (a reference quirk,
main.rs:395-411, preserved consciously); node RPCs back the cluster plane.

Conscious fix vs the reference: the schedule RPCs actually create/list/
delete entries in the GoalScheduler — in the reference they are stubs that
never touch it (main.rs:426-468; SURVEY.md "known quirks").
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

import psutil

from .. import rpc
from ..obs.http import maybe_start_metrics_server
from ..proto_gen import common_pb2, orchestrator_pb2
from ..services import ORCHESTRATOR, OrchestratorServicer, service_address
from .agent_router import AgentRouter, TrackedAgent
from .autonomy import AutonomyLoop
from .cluster import ClusterManager, ClusterNode
from .goal_engine import GoalEngine, Task
from .scheduler import GoalScheduler
from .task_planner import TaskPlanner
from .telemetry import ResultAggregator, TaskOutcome

log = logging.getLogger("aios.orchestrator")


class OrchestratorService(OrchestratorServicer):
    def __init__(
        self,
        engine: Optional[GoalEngine] = None,
        planner: Optional[TaskPlanner] = None,
        router: Optional[AgentRouter] = None,
        autonomy: Optional[AutonomyLoop] = None,
        scheduler: Optional[GoalScheduler] = None,
        cluster: Optional[ClusterManager] = None,
        aggregator: Optional[ResultAggregator] = None,
        loaded_models: Optional[callable] = None,
    ):
        self.engine = engine or GoalEngine()
        self.planner = planner or TaskPlanner()
        self.router = router or AgentRouter()
        self.autonomy = autonomy
        self.scheduler = scheduler or GoalScheduler(
            lambda d, p: self.engine.submit_goal(d, p, source="scheduler")
        )
        self.cluster = cluster or ClusterManager()
        self.aggregator = aggregator or ResultAggregator()
        self.loaded_models = loaded_models or (lambda: [])
        self.started_at = time.time()

    # -- goals --------------------------------------------------------------

    def SubmitGoal(self, request, context):
        metadata = {}
        if request.metadata_json:
            try:
                metadata = json.loads(request.metadata_json)
            except ValueError:
                pass
        goal = self.engine.submit_goal(
            request.description,
            priority=request.priority or 5,
            source=request.source or "user",
            tags=list(request.tags),
            metadata=metadata,
        )
        self.engine.add_message(goal.id, "user", request.description)
        return common_pb2.GoalId(id=goal.id)

    def GetGoalStatus(self, request, context):
        goal = self.engine.goals.get(request.id)
        if goal is None:
            import grpc

            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(f"goal {request.id} not found")
            return orchestrator_pb2.GoalStatusResponse()
        tasks = self.engine.tasks_for_goal(goal.id)
        return orchestrator_pb2.GoalStatusResponse(
            goal=self._goal_proto(goal),
            tasks=[self._task_proto(t) for t in tasks],
            current_phase=goal.status,
            progress_percent=self.engine.progress(goal.id),
        )

    def cancel_goal_by_id(self, goal_id: str) -> bool:
        """Shared by the CancelGoal RPC and the console's cancel route:
        cancel the goal AND abort any in-flight AI inference for it (the
        loop's between-rounds check only stops future rounds)."""
        ok = self.engine.cancel_goal(goal_id)
        if ok and self.autonomy is not None:
            self.autonomy.notify_goal_cancelled(goal_id)
        return ok

    def CancelGoal(self, request, context):
        ok = self.cancel_goal_by_id(request.id)
        return common_pb2.Status(
            success=ok, message="cancelled" if ok else "not cancellable"
        )

    def ListGoals(self, request, context):
        goals = self.engine.list_goals(
            status_filter=request.status_filter,
            limit=request.limit or 100,
            offset=request.offset,
        )
        return orchestrator_pb2.GoalListResponse(
            goals=[self._goal_proto(g) for g in goals],
            total=len(self.engine.goals),
        )

    # -- agents -------------------------------------------------------------

    def RegisterAgent(self, request, context):
        self.router.register(
            TrackedAgent(
                agent_id=request.agent_id,
                agent_type=request.agent_type,
                capabilities=list(request.capabilities),
                tool_namespaces=list(request.tool_namespaces),
            )
        )
        log.info("agent %s (%s) registered", request.agent_id,
                 request.agent_type)
        return common_pb2.Status(success=True, message="registered")

    def UnregisterAgent(self, request, context):
        ok = self.router.unregister(request.id)
        return common_pb2.Status(success=ok)

    def Heartbeat(self, request, context):
        ok = self.router.heartbeat(
            request.agent_id,
            status=request.status,
            current_task_id=request.current_task_id,
        )
        return common_pb2.Status(
            success=ok, message="" if ok else "agent not registered"
        )

    def ListAgents(self, request, context):
        return orchestrator_pb2.AgentListResponse(
            agents=[
                common_pb2.AgentRegistration(
                    agent_id=a.agent_id,
                    agent_type=a.agent_type,
                    capabilities=a.capabilities,
                    tool_namespaces=a.tool_namespaces,
                    status=a.status if a.alive else "dead",
                    registered_at=a.registered_at,
                )
                for a in self.router.agents()
            ]
        )

    # -- system -------------------------------------------------------------

    def GetSystemStatus(self, request, context):
        vm = psutil.virtual_memory()
        active = self.engine.active_goals()
        pending = self.engine.unblocked_pending_tasks(limit=1000)
        return orchestrator_pb2.SystemStatusResponse(
            active_goals=len(active),
            pending_tasks=len(pending),
            active_agents=sum(1 for a in self.router.agents() if a.alive),
            loaded_models=list(self.loaded_models()),
            cpu_percent=psutil.cpu_percent(interval=None),
            memory_used_mb=vm.used / 1e6,
            memory_total_mb=vm.total / 1e6,
            autonomy_level="full",
            uptime_seconds=int(time.time() - self.started_at),
        )

    # -- task dispatch (polling pair, main.rs:299-383) ----------------------

    def GetAssignedTask(self, request, context):
        task = self.router.next_task_for(request.id)
        if task is None:
            return common_pb2.Task()  # empty = nothing assigned
        self.engine.set_task_status(task.id, "in_progress", agent=request.id)
        return self._task_proto(task)

    def ReportTaskResult(self, request, context):
        task = self.engine.tasks.get(request.task_id)
        if task is None:
            return common_pb2.Status(success=False, message="unknown task")
        output = {}
        if request.output_json:
            try:
                output = json.loads(request.output_json)
            except ValueError:
                output = {"raw": request.output_json.decode("utf-8", "replace")}
        if request.success:
            self.engine.complete_task(request.task_id, output=output)
        else:
            self.engine.set_task_status(
                request.task_id, "failed", error=request.error
            )
        if task.assigned_agent:
            self.router.task_finished(task.assigned_agent, request.success)
        self.aggregator.record(
            task.goal_id,
            TaskOutcome(
                task_id=task.id,
                success=request.success,
                output=output,
                error=request.error,
                duration_ms=request.duration_ms,
                tokens_used=request.tokens_used,
                model_used=request.model_used,
            ),
        )
        self.engine.check_goal_completion(task.goal_id)
        return common_pb2.Status(success=True)

    # -- capabilities (auto-grant quirk preserved, main.rs:395-411) ---------

    def RequestCapability(self, request, context):
        return orchestrator_pb2.CapabilityResponse(
            granted=True,
            capabilities=list(request.capabilities),
            expires_at="",
        )

    def RevokeCapability(self, request, context):
        return common_pb2.Status(success=True, message="revoked")

    # -- schedules (wired for real, unlike the reference stubs) -------------

    def CreateSchedule(self, request, context):
        try:
            sid = self.scheduler.create(
                request.cron_expr, request.goal_template,
                priority=request.priority or 5,
            )
        except ValueError as exc:
            return orchestrator_pb2.ScheduleResponse(success=False,
                                                     schedule_id=str(exc))
        return orchestrator_pb2.ScheduleResponse(schedule_id=sid, success=True)

    def ListSchedules(self, request, context):
        return orchestrator_pb2.ScheduleListResponse(
            schedules=[
                orchestrator_pb2.ScheduleEntry(
                    id=s.id,
                    cron_expr=s.cron_expr,
                    goal_template=s.goal_template,
                    priority=s.priority,
                    enabled=s.enabled,
                    last_run=s.last_run,
                )
                for s in self.scheduler.list()
            ]
        )

    def DeleteSchedule(self, request, context):
        ok = self.scheduler.delete(request.schedule_id)
        return common_pb2.Status(success=ok)

    # -- cluster (main.rs:470-553) ------------------------------------------

    def RegisterNode(self, request, context):
        self.cluster.register(
            ClusterNode(
                node_id=request.node_id,
                hostname=request.hostname,
                address=request.address,
                agents=list(request.agents),
                metadata=dict(request.metadata),
                max_tasks=request.max_tasks or 10,
            )
        )
        return common_pb2.Status(success=True)

    def NodeHeartbeat(self, request, context):
        ok = self.cluster.heartbeat(
            request.node_id,
            cpu=request.cpu_usage,
            memory=request.memory_usage,
            active_tasks=request.active_tasks,
        )
        return common_pb2.Status(success=ok)

    def ListNodes(self, request, context):
        return orchestrator_pb2.NodeListResponse(
            nodes=[
                orchestrator_pb2.NodeInfo(
                    node_id=n.node_id,
                    hostname=n.hostname,
                    address=n.address,
                    agents=n.agents,
                    cpu_usage=n.cpu_usage,
                    memory_usage=n.memory_usage,
                    active_tasks=n.active_tasks,
                    healthy=n.alive,
                )
                for n in self.cluster.nodes(include_dead=request.include_dead)
            ]
        )

    # -- proto adapters -----------------------------------------------------

    @staticmethod
    def _goal_proto(g) -> common_pb2.Goal:
        return common_pb2.Goal(
            id=g.id,
            description=g.description,
            priority=g.priority,
            source=g.source,
            status=g.status,
            created_at=g.created_at,
            updated_at=g.updated_at,
            tags=g.tags,
            metadata_json=json.dumps(g.metadata).encode(),
        )

    @staticmethod
    def _task_proto(t: Task) -> common_pb2.Task:
        return common_pb2.Task(
            id=t.id,
            goal_id=t.goal_id,
            description=t.description,
            assigned_agent=t.assigned_agent,
            status=t.status,
            intelligence_level=t.intelligence_level,
            required_tools=t.required_tools,
            depends_on=t.depends_on,
            input_json=json.dumps(t.input).encode(),
            output_json=json.dumps(t.output).encode(),
            created_at=t.created_at,
            started_at=t.started_at,
            completed_at=t.completed_at,
            error=t.error,
        )


def serve(
    address: Optional[str] = None,
    service: Optional[OrchestratorService] = None,
    block: bool = True,
    metrics_port: Optional[int] = None,
):
    """Start the orchestrator server (reference binds 0.0.0.0:50051,
    main.rs:791). ``metrics_port`` (or AIOS_ORCHESTRATOR_METRICS_PORT)
    also starts the /metrics + /healthz endpoint."""
    address = address or service_address("orchestrator")
    server = rpc.create_server(max_workers=32)
    service = service or OrchestratorService()
    rpc.add_to_server(ORCHESTRATOR, service, server)
    port = server.add_insecure_port(address)
    server.start()
    service.metrics_server, service.metrics_port = maybe_start_metrics_server(
        "orchestrator",
        metrics_port,
        health_fn=lambda: {"service": "orchestrator"},
    )
    log.info("Orchestrator listening on %s", address)
    if block:
        server.wait_for_termination()
    return server, service, port
