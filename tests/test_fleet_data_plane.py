"""Fleet data plane acceptance (aios_tpu/fleet/, ISSUE 17).

Fast CPU tier, tiny engines. Three layers:

  1. Wire-format / store units: ``pack_entry``/``unpack_entry`` round
     trips (int8-scale pages byte-exact), receiving-end crc32 tamper
     detection, and ``HostPageStore.export_chain``'s sender-side
     recheck.
  2. Gossip + routing units: prefix-chain scoring off an advertised
     digest, peer filtering, and the fleet router's gain/cost gates.
  3. THE two-host acceptance: "hosts" are separate ReplicaPools with
     identically-seeded weights behind real gRPC KvTransfer servers in
     one process — a prompt prefilled on host A decodes on host B
     token-identically to a single-host run, including across a seeded
     decode-host kill (re-handoff to the survivor) and across
     failed/corrupt transfers (local-prefill fallback, failure
     counted).
"""

import threading

import numpy as np
import pytest

from aios_tpu.engine import paged
from aios_tpu.fleet import disagg, gprefix, kvx
from aios_tpu.obs import instruments as obs


MODEL = "fleet-dp-test"
PAGE = 32


# ---------------------------------------------------------------------------
# 1. wire format + store export (no engines)
# ---------------------------------------------------------------------------


def _entry(seed=0, dtype=np.float32, scales=False):
    rng = np.random.default_rng(seed)
    if dtype == np.int8:
        e = {
            "k": rng.integers(-128, 127, (2, 4, PAGE, 8), dtype=np.int8),
            "v": rng.integers(-128, 127, (2, 4, PAGE, 8), dtype=np.int8),
        }
    else:
        e = {
            "k": rng.standard_normal((2, 4, PAGE, 8)).astype(dtype),
            "v": rng.standard_normal((2, 4, PAGE, 8)).astype(dtype),
        }
    if scales:
        e["k_s"] = rng.standard_normal((2, 4, PAGE)).astype(np.float32)
        e["v_s"] = rng.standard_normal((2, 4, PAGE)).astype(np.float32)
    return e


def _h(i):
    return bytes([i]) * 32


def test_pack_unpack_round_trip_byte_exact():
    e = _entry(1)
    out = paged.unpack_entry(paged.pack_entry(e))
    assert sorted(out) == sorted(e)
    for k in e:
        assert out[k].dtype == e[k].dtype
        assert out[k].shape == e[k].shape
        assert np.array_equal(out[k], e[k])
        assert out[k].flags["WRITEABLE"]  # host_store.corrupt needs this
    # the crc (the transfer plane's integrity token) survives the trip
    assert (paged.HostPageStore._entry_crc(out)
            == paged.HostPageStore._entry_crc(e))


def test_int8_scale_pages_survive_byte_exact():
    """Quantized-cache entries (int8 KV + float32 scales) must cross
    the wire byte-exact: a single flipped scale byte rescales a whole
    page of keys."""
    e = _entry(2, dtype=np.int8, scales=True)
    out = paged.unpack_entry(paged.pack_entry(e))
    assert out["k"].dtype == np.int8 and out["v"].dtype == np.int8
    for k in ("k", "v", "k_s", "v_s"):
        assert out[k].tobytes() == e[k].tobytes()


def test_unpack_rejects_damaged_framing():
    payload = paged.pack_entry(_entry(3))
    with pytest.raises(ValueError):
        paged.unpack_entry(b"XXXX" + payload[4:])  # bad magic
    with pytest.raises(ValueError):
        paged.unpack_entry(payload[:-7])  # truncated payload
    with pytest.raises(ValueError):
        paged.unpack_entry(payload + b"\x00")  # trailing bytes


def test_verify_entry_detects_tamper_at_receiving_end():
    """The RECEIVING end re-derives the crc from the unpacked arrays —
    a bit flipped anywhere in transit (or in the sender's host RAM
    after the crc was stamped) fails verification."""
    from aios_tpu.proto_gen import fleet_pb2

    e = _entry(4)
    payload = paged.pack_entry(e)
    crc = paged.HostPageStore._entry_crc(e)
    good = fleet_pb2.PageEntry(hash=_h(1), crc32=crc, payload=payload)
    assert sorted(kvx.verify_entry(good)) == sorted(e)
    # flip the LAST byte: lands inside array data, so framing still
    # parses and only the checksum can catch it
    tampered = payload[:-1] + bytes([payload[-1] ^ 0xFF])
    bad = fleet_pb2.PageEntry(hash=_h(1), crc32=crc, payload=tampered)
    with pytest.raises(kvx.CrcMismatch):
        kvx.verify_entry(bad)


def test_export_chain_round_trip_and_budget():
    store = paged.HostPageStore(32 << 20)
    entries = {_h(i): _entry(i) for i in (1, 2, 3)}
    for h, e in entries.items():
        store.put(h, e)
    chain = [_h(1), _h(2), _h(3)]
    out = store.export_chain(chain)
    assert [h for h, _, _ in out] == chain
    for h, crc, e in out:
        assert crc == paged.HostPageStore._entry_crc(e)
        # entry -> wire bytes -> entry, byte-exact
        rt = paged.unpack_entry(paged.pack_entry(e))
        for k in e:
            assert np.array_equal(rt[k], entries[h][k])
    one = paged.HostPageStore._entry_bytes(entries[_h(1)])
    assert len(store.export_chain(chain, budget_bytes=one)) == 1
    # a hole truncates the chain (transfer past a gap restores nothing)
    assert [h for h, _, _ in store.export_chain([_h(1), _h(9), _h(3)])] \
        == [_h(1)]


def test_export_chain_drops_rotten_entry():
    """Sender-side half of verified-at-both-ends: host-RAM rot since
    the spill is caught BEFORE the page ships."""
    store = paged.HostPageStore(32 << 20)
    for i in (1, 2, 3):
        store.put(_h(i), _entry(i))
    with store._lock:
        store._entries[_h(2)]["k"].flat[0] += 1.0  # the rot
    out = store.export_chain([_h(1), _h(2), _h(3)])
    assert [h for h, _, _ in out] == [_h(1)]
    assert store.corruptions == 1
    assert store.peek_chain([_h(1), _h(2)]) == 1  # rotten entry evicted


# ---------------------------------------------------------------------------
# 2. gossip + scoring units
# ---------------------------------------------------------------------------


def _digest_for(hashes, page=PAGE, depth_from=1):
    return {
        "page": page,
        "tails": {gprefix.tail(h): depth_from + i
                  for i, h in enumerate(hashes)},
    }


def test_score_tails_is_prefix_not_membership():
    chain = [_h(1), _h(2), _h(3), _h(4)]
    assert gprefix.score_tails(_digest_for(chain), chain) == 4 * PAGE
    # a hole at block 2 makes the advertised blocks 3/4 unreachable
    holed = _digest_for([chain[0], chain[2], chain[3]])
    assert gprefix.score_tails(holed, chain) == 1 * PAGE
    assert gprefix.score_tails({}, chain) == 0
    assert gprefix.score_tails(_digest_for(chain), []) == 0
    assert gprefix.score_tails({"page": 0, "tails": {"ab": 1}}, chain) == 0


def test_best_peer_filters_dead_self_and_addressless():
    chain = [_h(1), _h(2), _h(3)]
    full = {MODEL: _digest_for(chain)}
    shallow = {MODEL: _digest_for(chain[:1])}
    peers = [
        {"host": "dead", "state": "dead", "kvx_addr": "a:1",
         "gprefix": full},
        {"host": "me", "state": "up", "self": True, "kvx_addr": "a:2",
         "gprefix": full},
        {"host": "mute", "state": "up", "kvx_addr": "",
         "gprefix": full},
        {"host": "shallow", "state": "up", "kvx_addr": "a:3",
         "gprefix": shallow},
        {"host": "deep", "state": "up", "kvx_addr": "a:4",
         "gprefix": full},
    ]
    peer, rows = gprefix.best_peer(peers, MODEL, chain)
    assert peer["host"] == "deep" and rows == 3 * PAGE
    assert gprefix.best_peer(peers[:3], MODEL, chain) == (None, 0)


# ---------------------------------------------------------------------------
# 3. two-host acceptance rig: real engines, real gRPC, one process
# ---------------------------------------------------------------------------


class _MM:
    """ManagedModel stand-in: exactly the surface the fleet plane uses."""

    def __init__(self, name, engine, pool):
        self.name, self.engine, self.pool = name, engine, pool

    def submit(self, req, tenant="anonymous", deadline_s=None):
        return self.pool.submit(req, tenant=tenant, deadline_s=deadline_s)


class _Mgr:
    def __init__(self, models):
        self._models = models

    def get(self, name):
        return self._models.get(name)

    def ready_models(self):
        return list(self._models.values())


class _Rig:
    """One 'fleet' in one process: prefill host A plus decode hosts B
    and C, each a 1-replica pool over identically-seeded weights (greedy
    streams are therefore comparable across hosts), B and C behind real
    KvTransfer gRPC servers on ephemeral ports."""

    def __init__(self):
        import jax
        import jax.numpy as jnp

        from aios_tpu import rpc, services
        from aios_tpu.engine import model as model_mod
        from aios_tpu.engine.batching import ContinuousBatcher
        from aios_tpu.engine.config import TINY_TEST
        from aios_tpu.engine.engine import TPUEngine
        from aios_tpu.serving import ReplicaPool, ServingConfig

        cfg = TINY_TEST.scaled(name=MODEL, max_context=256)
        params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                       dtype=jnp.float32)
        self.mms = {}
        self.servers = []
        self.addrs = {}
        for host in ("hostA", "hostB", "hostC"):
            engine = TPUEngine(
                cfg, params, num_slots=2, max_context=256,
                cache_dtype=jnp.float32, paged_pool_rows=256,
                page_size=PAGE, prefix_host_bytes=32 << 20,
            )
            pool = ReplicaPool(
                MODEL, [engine],
                lambda e: ContinuousBatcher(e, chunk_steps=2,
                                            admit_chunk_steps=2),
                ServingConfig(replicas=1, failover_retries=2),
            )
            mm = _MM(MODEL, engine, pool)
            self.mms[host] = mm
            if host != "hostA":
                server = rpc.create_server(max_workers=8)
                rpc.add_to_server(
                    services.KVTRANSFER,
                    disagg.DisaggService(_Mgr({MODEL: mm})), server,
                )
                port = server.add_insecure_port("127.0.0.1:0")
                server.start()
                self.servers.append(server)
                self.addrs[host] = f"127.0.0.1:{port}"
        kvx.register_kvx_metrics(MODEL)
        from aios_tpu.fleet.router import register_route_metrics

        register_route_metrics(MODEL)
        self.plane = disagg.DisaggPlane(_Mgr({MODEL: self.mms["hostA"]}))
        self.plane._members = self.members  # instance attr shadows method

    def members(self, hosts=("hostB", "hostC")):
        return [
            {"host": h, "role": "decode", "state": "up", "self": False,
             "kvx_addr": self.addrs[h], "pools": {}, "gprefix": {}}
            for h in hosts
        ]

    def shutdown(self):
        kvx.reset_channels()
        for s in self.servers:
            s.stop(grace=0.2)
        for mm in self.mms.values():
            mm.pool.shutdown()


@pytest.fixture(scope="module")
def rig():
    r = _Rig()
    yield r
    r.shutdown()


def _prompt(seed, n=100):
    return [(seed * 131 + i * 7) % 500 + 1 for i in range(n)]


def _req(seed, rid, max_tokens=24):
    from aios_tpu.engine.batching import Request

    return Request(prompt_ids=_prompt(seed), max_tokens=max_tokens,
                   temperature=0.0, request_id=rid)


def _counter(metric, **labels):
    return metric.labels(**labels).value


def test_router_gain_and_peer_gates(rig):
    """decide_pull walks local -> no_peer -> remote_pull off the
    gossiped digests: a peer promising the full chain wins an uncached
    prompt; no advertising peer on a cold prompt is ``no_peer``."""
    mm = rig.mms["hostA"]
    route_ids = _prompt(90)
    chain = mm.engine.prefix_hashes(route_ids)
    assert len(chain) == (len(route_ids) - 1) // PAGE
    router = rig.plane.router
    rows = rig.members(("hostB",))
    rows[0]["gprefix"] = {MODEL: _digest_for(chain)}
    router._peers = lambda: rows
    reason, detail = router.decide_pull(mm, route_ids)
    assert reason == "remote_pull"
    assert detail["addr"] == rig.addrs["hostB"]
    assert detail["hashes"] == chain[: max(detail["rows"] // PAGE, 1)]
    # nobody advertises overlap and the local cache is cold: no_peer
    router._peers = lambda: rig.members(("hostB",))
    assert router.decide_pull(mm, route_ids)[0] == "no_peer"
    del router._peers


def test_kvx_push_then_fetch_round_trip_over_grpc(rig):
    """Pages pushed into host B's spill tier come back byte-exact
    through a Fetch — the full wire round trip, both ends verifying."""
    addr = rig.addrs["hostB"]
    store_b = rig.mms["hostB"].engine.host_store
    chain = [_h(0x21), _h(0x22), _h(0x23)]
    pairs = [(h, _entry(i + 10)) for i, h in enumerate(chain)]
    before = _counter(obs.FLEET_KVX_PAGES, model=MODEL, direction="push")
    assert kvx.push_chain(addr, MODEL, pairs) == 3
    assert _counter(obs.FLEET_KVX_PAGES, model=MODEL,
                    direction="push") == before + 3
    assert store_b.peek_chain(chain) == 3
    got = kvx.fetch_chain(addr, MODEL, chain)
    assert [h for h, _ in got] == chain
    for (h, e), (_, sent) in zip(got, pairs):
        for k in sent:
            assert np.array_equal(e[k], sent[k])
    store_b.discard(chain)


def test_kvx_push_tamper_rejected_at_receiver(rig):
    """A payload corrupted in transit is rejected by the RECEIVER's crc
    re-derivation: counted on the closed cause enum, never stored."""
    addr = rig.addrs["hostB"]
    store_b = rig.mms["hostB"].engine.host_store
    e = _entry(30)
    payload = paged.pack_entry(e)
    crc = paged.HostPageStore._entry_crc(e)
    tampered = payload[:-1] + bytes([payload[-1] ^ 0xFF])
    before = _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                      cause="crc_mismatch")
    ack = kvx._stub(addr).Push(
        kvx.entries_to_chunks(MODEL, [(_h(0x31), crc, tampered)])
    )
    assert (ack.accepted, ack.rejected) == (0, 1)
    assert _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                    cause="crc_mismatch") == before + 1
    assert store_b.peek_chain([_h(0x31)]) == 0


def test_kvx_fetch_failure_causes(rig):
    """An unfulfilled promise counts ``empty``; an unreachable peer
    counts the transport cause — both return [] (the caller falls back
    to local prefill), never raise."""
    before = _counter(obs.FLEET_KVX_FAILURES, model=MODEL, cause="empty")
    assert kvx.fetch_chain(rig.addrs["hostB"], MODEL, [_h(0x41)]) == []
    assert _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                    cause="empty") == before + 1
    before = _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                      cause="unavailable")
    assert kvx.fetch_chain("127.0.0.1:1", MODEL, [_h(0x42)]) == []
    assert _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                    cause="unavailable") == before + 1


# -- THE acceptance: disaggregated streams are token-identical ---------------


def _ref_tokens(rig, seed, rid, max_tokens=24):
    h = rig.mms["hostA"].submit(_req(seed, rid, max_tokens))
    toks = h.tokens()
    assert not h.aborted and len(toks) == max_tokens
    return toks


def test_handoff_stream_token_identical_across_hosts(rig):
    """ISSUE 17 acceptance: prompt prefilled on host A decodes on host
    B with a token stream identical to a single-host run — first token
    from A's prefill, the rest relayed over the Handoff stream, KV
    pushed ahead over the kvx plane."""
    ref = _ref_tokens(rig, 50, "dp-ref-0")
    pushed_before = _counter(obs.FLEET_KVX_PAGES, model=MODEL,
                             direction="push")
    handoffs_before = _counter(obs.FLEET_ROUTE, model=MODEL,
                               reason="handoff")
    handle = disagg.HandoffHandle(
        rig.plane, rig.mms["hostA"], _req(50, "dp-handoff-0"), "t", None,
    )
    out = handle.tokens()
    assert out == ref, "disaggregated stream must be token-identical"
    assert not handle.aborted
    assert handle.ttft_ms > 0.0
    assert _counter(obs.FLEET_ROUTE, model=MODEL,
                    reason="handoff") == handoffs_before + 1
    # the prefix chain actually crossed hosts ((prompt-1)//page pages)
    assert _counter(obs.FLEET_KVX_PAGES, model=MODEL, direction="push") \
        >= pushed_before + (len(_prompt(50)) - 1) // PAGE


def test_handoff_survives_decode_host_kill(rig):
    """A seeded ``fleet.host_kill`` aborts decode host B mid-stream;
    host A re-hands prompt + ALL emitted tokens to survivor C and the
    client stream is still token-identical — tokens already relayed are
    never re-sent."""
    from aios_tpu.faults import inject as faults

    ref = _ref_tokens(rig, 51, "dp-ref-kill")
    resumed_before = _counter(obs.FLEET_ROUTE, model=MODEL,
                              reason="handoff_resume")
    faults.activate("seed=1;fleet.host_kill=nth:3")
    try:
        handle = disagg.HandoffHandle(
            rig.plane, rig.mms["hostA"], _req(51, "dp-kill-0"), "t", None,
        )
        out = handle.tokens()
    finally:
        faults.deactivate()
    assert out == ref, "kill-and-resume stream must be token-identical"
    assert not handle.aborted
    assert _counter(obs.FLEET_ROUTE, model=MODEL,
                    reason="handoff_resume") == resumed_before + 1


def test_failed_push_and_pull_fall_back_to_local_prefill(rig,
                                                         monkeypatch):
    """Corrupt/failed-transfer contract: the push 'fails' (0 accepted)
    and the decode host's pull-on-miss hits a dead source — it simply
    recomputes the prefill locally (PR 10 restore_fail, one hop out).
    The stream is still token-identical and the failure is counted."""
    from aios_tpu.obs import fleet as obs_fleet

    ref = _ref_tokens(rig, 52, "dp-ref-fb")
    monkeypatch.setattr(kvx, "push_chain", lambda *a, **k: 0)
    obs_fleet.set_transfer_addr("127.0.0.1:1")  # dead source for the pull
    fail_before = _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                           cause="unavailable")
    try:
        handle = disagg.HandoffHandle(
            rig.plane, rig.mms["hostA"], _req(52, "dp-fb-0"), "t", None,
        )
        out = handle.tokens()
    finally:
        obs_fleet.set_transfer_addr("")
    assert out == ref, "failed transfer must not change the stream"
    assert not handle.aborted
    assert _counter(obs.FLEET_KVX_FAILURES, model=MODEL,
                    cause="unavailable") > fail_before


def test_no_decode_peer_falls_back_to_local_decode(rig, monkeypatch):
    """The whole decode tier gone: the prefill host finishes the stream
    itself off the resume-from-emitted contract, counted
    ``fallback_local``."""
    ref = _ref_tokens(rig, 53, "dp-ref-solo")
    monkeypatch.setattr(rig.plane, "_members", lambda: [])
    fb_before = _counter(obs.FLEET_ROUTE, model=MODEL,
                         reason="fallback_local")
    handle = disagg.HandoffHandle(
        rig.plane, rig.mms["hostA"], _req(53, "dp-solo-0"), "t", None,
    )
    out = handle.tokens()
    assert out == ref
    assert not handle.aborted
    assert _counter(obs.FLEET_ROUTE, model=MODEL,
                    reason="fallback_local") == fb_before + 1


def test_route_submit_degrades_to_plain_submit_when_disarmed(rig):
    """Solo hosts keep the exact pre-fleet path: with the plane
    disarmed, route_submit IS m.submit."""
    assert disagg.PLANE is None
    handle = disagg.route_submit(rig.mms["hostA"], _req(54, "dp-plain-0"))
    assert handle.tokens() == _ref_tokens(rig, 54, "dp-plain-ref")


def test_pick_decode_prefers_least_loaded_and_excludes(rig):
    rows = rig.members()
    rows[0]["pools"] = {MODEL: {"occupancy": 0.9, "waiting": 3}}
    plane = rig.plane
    orig = plane._members
    plane._members = lambda: rows
    try:
        host, addr = plane.pick_decode(MODEL)
        assert (host, addr) == ("hostC", rig.addrs["hostC"])
        host, _ = plane.pick_decode(MODEL, exclude=["hostC"])
        assert host == "hostB"
        assert plane.pick_decode(MODEL,
                                 exclude=["hostB", "hostC"]) is None
    finally:
        plane._members = orig


def test_handoff_concurrent_streams(rig):
    """Several disaggregated streams in flight at once (the decode host
    batches them) all stay token-identical."""
    seeds = (60, 61, 62)
    refs = [_ref_tokens(rig, s, f"dp-ref-c{s}", max_tokens=16)
            for s in seeds]
    handles = [
        disagg.HandoffHandle(
            rig.plane, rig.mms["hostA"],
            _req(s, f"dp-conc-{s}", max_tokens=16), "t", None,
        )
        for s in seeds
    ]
    out = {}
    threads = [
        threading.Thread(target=lambda i=i, h=h: out.__setitem__(
            i, h.tokens()), daemon=True)
        for i, h in enumerate(handles)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "a disaggregated stream leaked"
    assert [out[i] for i in range(len(seeds))] == refs
