"""The JAX/XLA TPU inference + training engine.

This package is the replacement for the reference's llama.cpp backend
(runtime/src/, SURVEY.md section 2.3): weights land as HBM-resident sharded
bf16 params and the decode loop is a single jitted graph.
"""
