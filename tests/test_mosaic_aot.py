"""Chipless Mosaic compilation tests for every Pallas kernel.

Interpret-mode parity (test_ops.py etc.) validates kernel MATH but not what
the real Mosaic compiler accepts — r3 proof: the int8-KV ragged kernel
family passed interpret mode yet failed on hardware, because Mosaic rejects
DMA-slicing a <128 lane extent (the per-(row, kv-head) scale arrays had the
tiny head count on lanes). These tests close that gap without needing a
chip: libtpu's AOT compiler builds each kernel against a v5e topology
description, so a Mosaic-invalid layout fails in CI the way it would fail
in serving.

Skips cleanly when no libtpu is importable (non-TPU dev machines).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def rep_sharding():
    # skip ONLY when libtpu itself is absent (non-TPU dev machine); any
    # other failure to build the topology is a real regression of this
    # module's CI gate and must fail loudly
    try:
        import libtpu  # noqa: F401
    except ImportError:
        pytest.skip("libtpu not installed — no Mosaic AOT compiler here")

    # libtpu wants these before its first init. Set here (not at module
    # import) so collecting this file can't leak a fake 4-chip topology
    # into a process that will talk to real TPU hardware.
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-4")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:2x2x1"
    )
    mesh = Mesh(np.array(topo.devices[:1]).reshape(1), ("x",))
    return NamedSharding(mesh, PartitionSpec())


def aot_compile(rep, fn, *args, **static):
    f = jax.jit(
        functools.partial(fn, **static) if static else fn,
        in_shardings=(rep,) * len(args),
        out_shardings=rep,
    )
    f.trace(*args).lower().compile()  # raises on Mosaic rejection


# TinyLlama-shaped decode geometry (the shapes that caught the r3 bug)
B, H, KH, D, C = 8, 32, 4, 64, 4096


def test_aot_flash_attention(rep_sharding):
    from aios_tpu import ops

    T = 512
    q = jnp.ones((2, T, H, D), jnp.bfloat16)
    kv = jnp.ones((2, T, KH, D), jnp.bfloat16)
    aot_compile(rep_sharding, ops.flash_attention, q, kv, kv, causal=True)


def test_aot_quantized_matmul(rep_sharding):
    from aios_tpu import ops

    x = jnp.ones((8, 2048), jnp.bfloat16)
    w = jnp.ones((2048, 5632), jnp.int8)
    s = jnp.ones((1, 5632), jnp.float32)
    aot_compile(rep_sharding, ops.quantized_matmul, x, w, s)


@pytest.mark.parametrize("K,N", [(4096, 6144), (14336, 4096), (4096, 32000)])
def test_aot_int4_matmul(rep_sharding, K, N):
    from aios_tpu.ops.int4_matmul import GROUP, int4_matmul

    x = jnp.ones((8, K), jnp.bfloat16)
    p = jnp.ones((K // 2, N), jnp.uint8)
    s = jnp.ones((K // GROUP, 1, N), jnp.float32)
    aot_compile(rep_sharding, int4_matmul, x, p, s)


def test_aot_ragged_decode_bf16(rep_sharding):
    from aios_tpu import ops

    q = jnp.ones((B, H, D), jnp.bfloat16)
    kc = jnp.ones((B, C, KH, D), jnp.bfloat16)
    lens = jnp.ones((B,), jnp.int32)
    aot_compile(rep_sharding, ops.decode_attention, q, kc, kc, lens)


def test_aot_ragged_decode_int8(rep_sharding):
    """The kernel that failed real Mosaic in r3 (scale lane layout)."""
    from aios_tpu import ops

    q = jnp.ones((B, H, D), jnp.bfloat16)
    kq = jnp.ones((B, C, KH, D), jnp.int8)
    ks = jnp.ones((B, C, KH), jnp.float32)
    lens = jnp.ones((B,), jnp.int32)
    aot_compile(
        rep_sharding, ops.decode_attention_int8, q, kq, kq, ks, ks, lens
    )


def test_aot_paged_decode_both_dtypes(rep_sharding):
    from aios_tpu import ops

    N_, P = 64, 128
    q = jnp.ones((B, H, D), jnp.bfloat16)
    tbl = jnp.zeros((B, 32), jnp.int32)
    lens = jnp.ones((B,), jnp.int32)
    kp = jnp.ones((N_, P, KH, D), jnp.bfloat16)
    aot_compile(rep_sharding, ops.paged_decode_attention, q, kp, kp, tbl, lens)
    kq = jnp.ones((N_, P, KH, D), jnp.int8)
    ps = jnp.ones((N_, P, KH), jnp.float32)
    aot_compile(
        rep_sharding, ops.paged_decode_attention_int8,
        q, kq, kq, ps, ps, tbl, lens,
    )


def test_aot_multiquery_verify_both_dtypes(rep_sharding):
    from aios_tpu import ops

    T = 4
    qt = jnp.ones((B, T, H, D), jnp.bfloat16)
    lens = jnp.ones((B,), jnp.int32)
    strides = jnp.ones((B,), jnp.int32)
    kc = jnp.ones((B, C, KH, D), jnp.bfloat16)
    aot_compile(
        rep_sharding, ops.multiquery_decode_attention,
        qt, kc, kc, lens, strides,
    )
    kq = jnp.ones((B, C, KH, D), jnp.int8)
    ks = jnp.ones((B, C, KH), jnp.float32)
    aot_compile(
        rep_sharding, ops.multiquery_decode_attention_int8,
        qt, kq, kq, ks, ks, lens, strides,
    )
