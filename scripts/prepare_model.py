#!/usr/bin/env python
"""Prepare a model for TPU serving: GGUF/HF source -> aios-tpu checkpoint.

TPU analog of the reference's model pipeline (scripts/download-models.sh
fetches GGUF files; scripts/build-llamacpp.sh builds the engine that parses
them on every load). Here the expensive work — GGUF parse, Q4_K/Q6_K
dequantization, HF safetensors mapping — happens ONCE, producing a
checkpoint directory {params/ (orbax), aios_model.json (config+tokenizer)}
that `AIRuntime.LoadModel` restores straight to device.

Usage:
  python scripts/prepare_model.py /path/model.gguf  /var/lib/aios/models/name
  python scripts/prepare_model.py /path/hf_dir      /var/lib/aios/models/name
  python scripts/prepare_model.py synthetic://tinyllama-1.1b out_dir  # tests

Options:
  --dtype bf16|f32     serving dtype for dense weights (default bf16)
  --quantize int8|int4 save the SERVING-QUANTIZED layout instead of dense —
                       LoadModel then restores {"q","s"}/{"q4","s4"} leaves
                       straight to device with no quantization pass (and no
                       dense-weights HBM transient) on the serving path
  --tp N               prepare the quantized artifact for an N-way
                       tensor-parallel plan: stores the UNFUSED per-
                       projection layout with int4 eligibility and scale
                       groups computed for the shard-local dims, so a TP
                       deployment (BASELINE config 4) restores straight to
                       the mesh instead of re-quantizing dense weights at
                       every boot. Default 1 = fused single-chip layout.
  --context N          override max_context recorded in the config
  --verify             run a short greedy generation after writing
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("source", help="GGUF file, HF dir, or synthetic://preset")
    ap.add_argument("out", help="output checkpoint directory")
    ap.add_argument("--dtype", default="bf16", choices=("bf16", "f32"))
    ap.add_argument("--quantize", default="", choices=("", "int8", "int4"))
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--context", type=int, default=0)
    ap.add_argument("--verify", action="store_true")
    args = ap.parse_args()
    if args.tp > 1 and not args.quantize:
        # dense checkpoints already load under any plan (the engine shards
        # and optionally quantizes at load); --tp only changes the stored
        # QUANTIZED layout
        ap.error("--tp requires --quantize (dense artifacts are plan-"
                 "agnostic already)")

    import jax.numpy as jnp

    from aios_tpu.engine import checkpoint as ckpt
    from aios_tpu.runtime.model_manager import ModelManager

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    t0 = time.time()
    mgr = ModelManager(warm_compile=False)
    name = Path(args.out).name
    try:
        cfg, params, tokenizer = mgr._load_weights(
            name, args.source, args.context
        )
    except (FileNotFoundError, KeyError) as exc:
        print(f"error: cannot load {args.source!r}: {exc}", file=sys.stderr)
        return 2
    if dtype != jnp.bfloat16:
        from aios_tpu.engine import weights as weights_mod

        params = weights_mod.map_params(params, lambda a: a.astype(dtype))
    print(
        f"loaded {cfg.name}: {cfg.num_params() / 1e9:.2f}B params, "
        f"vocab {cfg.vocab_size}, ctx {cfg.max_context} "
        f"({time.time() - t0:.1f}s)",
        file=sys.stderr,
    )

    if args.quantize:
        from aios_tpu.engine import model as model_mod

        t0 = time.time()
        # target="tpu": strict kernel eligibility, so preparing on a CPU
        # build box never bakes in int4 leaves a TPU can't kernel-serve.
        # tp>1 keeps the projections unfused with shard-local eligibility
        # (the fused concat has no TP sharding rule).
        params = model_mod.quantize_params(
            params, mode=args.quantize, target="tpu",
            fuse=args.tp == 1, tp=args.tp,
        )
        layout = "fused single-chip" if args.tp == 1 else f"unfused tp={args.tp}"
        print(f"quantized to {args.quantize} serving layout ({layout}) "
              f"({time.time() - t0:.1f}s)", file=sys.stderr)

    t0 = time.time()
    ckpt.save_model_checkpoint(args.out, cfg, params, tokenizer, tp=args.tp)
    print(f"checkpoint written to {args.out} ({time.time() - t0:.1f}s)",
          file=sys.stderr)

    if args.verify:
        from aios_tpu.engine.engine import TPUEngine

        cfg2, params2, tok2 = ckpt.load_model_checkpoint(args.out)
        eng = TPUEngine(
            cfg2, params2, num_slots=1,
            max_context=min(256, cfg2.max_context),
        )
        ids = tok2.encode("The quick brown fox")
        out = eng.generate(ids, max_new_tokens=8, temperature=0.0)
        print(f"verify: generated {len(out)} tokens: {tok2.decode(out)!r}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
