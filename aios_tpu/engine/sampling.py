"""On-device token sampling: temperature, top-k, top-p, greedy.

Runs inside the jitted decode step (no host round-trip per token), vectorized
over slots with *per-slot* sampling parameters — different agents' requests in
the same continuous batch can use different temperatures (the reference's
per-request `temperature` field, runtime.proto InferRequest).

Replaces llama-server's sampler chain for the parameters the reference
actually exposes (temperature; plus top-k/top-p which llama-server applies
with its defaults — inference.rs:103-112 sends temperature only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

GREEDY_EPS = 1e-4  # temperatures below this mean argmax


def top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the nucleus. logits [B, V], top_p [B] in (0, 1]."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens while the cumulative mass *before* them is < top_p
    keep_sorted = (cumulative - sorted_probs) < top_p[:, None]
    # threshold = smallest logit still kept
    kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
    threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def top_k_filter(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask logits below the k-th largest. top_k [B] int32 (0 = disabled)."""
    V = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    k = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))
    threshold = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


# Candidate pool for the decode-loop sampler. A full-vocab sort per step is
# the naive approach and measurably slow on TPU; restricting top-p to the 64
# highest logits matches llama.cpp's own sampler chain, which applies
# top-k 40 *before* top-p by default (the reference sends temperature only,
# inference.rs:103-112, so llama-server uses those defaults).
TOPK_CAP = 64


def sample(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B], 1.0 disables
    top_k: jnp.ndarray | None = None,  # [B] int32; 0 => the TOPK_CAP pool
) -> jnp.ndarray:
    """Sample one token per row; temperature < GREEDY_EPS rows take argmax.

    Nucleus + top-k filtering run on the TOPK_CAP highest logits via
    ``lax.top_k`` — no full-vocab sort in the decode graph. Consequently the
    candidate pool is capped at TOPK_CAP: top_k values above it (or 0,
    "disabled") sample from the best TOPK_CAP tokens, and top-p mass beyond
    them is truncated — matching llama-server, whose default chain applies
    top-k 40 before top-p.
    """
    B, V = logits.shape
    K = min(TOPK_CAP, V)
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, GREEDY_EPS)[:, None]
    vals, idx = jax.lax.top_k(logits / temp, K)  # [B, K] sorted desc
    if top_k is not None:
        kk = jnp.where(top_k <= 0, K, jnp.minimum(top_k, K))
        pos = jnp.arange(K)[None, :]
        vals = jnp.where(pos < kk[:, None], vals, -jnp.inf)
    probs = jax.nn.softmax(vals, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    keep = (cumulative - probs) < top_p[:, None]
    vals = jnp.where(keep, vals, -jnp.inf)
    choice = jax.random.categorical(key, vals, axis=-1)  # [B] in [0, K)
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]

    return jnp.where(temperature < GREEDY_EPS, greedy, sampled).astype(jnp.int32)
