"""Tier migration pipeline.

Reference parity (memory/src/migration.rs:1-50):
  * finished working-tier goals migrate to long-term after 1 hour;
  * operational events migrate to long-term after 24 hours;
  * successful goals with their tasks are distilled into procedures;
  * patterns pruned at 1000; long-term capped at 365 days.

Runs as a background thread with a configurable period (the reference runs
it inside the memory service process the same way).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from .tiers import LongTermMemory, OperationalMemory, WorkingMemory

WORKING_TO_LONGTERM_AGE = 3600  # 1 h
OPERATIONAL_TO_LONGTERM_AGE = 86400  # 24 h


class MigrationPipeline:
    def __init__(
        self,
        operational: OperationalMemory,
        working: WorkingMemory,
        longterm: LongTermMemory,
        period_seconds: float = 300.0,
    ):
        self.operational = operational
        self.working = working
        self.longterm = longterm
        self.period = period_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> dict:
        """One migration pass; returns counters (also used by tests)."""
        stats = {"goals": 0, "events": 0, "procedures": 0, "patterns_pruned": 0}

        # finished goals -> long-term memories (+ procedure extraction)
        for goal in self.working.finished_goals_older_than(WORKING_TO_LONGTERM_AGE):
            tasks = self.working.tasks_for_goal(goal["id"])
            summary = (
                f"goal '{goal['description']}' {goal['status']}"
                f" with {len(tasks)} task(s); result: {goal.get('result','')}"
            )
            self.longterm.store_memory(
                summary,
                collection="goal_history",
                metadata={"goal_id": goal["id"], "status": goal["status"]},
            )
            stats["goals"] += 1
            if goal["status"] == "completed" and tasks:
                steps = [
                    {"description": t["description"], "agent": t["agent"]}
                    for t in tasks
                ]
                self.longterm.store_procedure(
                    {
                        "name": goal["description"][:80],
                        "description": f"extracted from goal {goal['id']}",
                        "steps_json": json.dumps(steps),
                        "success_count": 1,
                    }
                )
                stats["procedures"] += 1
            self.working.delete_goal(goal["id"])

        # old operational events -> long-term
        for ev in self.operational.drain_older_than(OPERATIONAL_TO_LONGTERM_AGE):
            self.longterm.store_memory(
                f"event {ev.get('category','')}/{ev.get('source','')}: "
                f"{ev.get('data_json','')}",
                collection="events",
                metadata={"critical": ev.get("critical", False)},
            )
            stats["events"] += 1

        stats["patterns_pruned"] = self.working.prune_patterns()
        self.working.retention_sweep()
        self.longterm.retention_sweep()
        return stats

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.period):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — keep the pipeline alive
                    pass

        self._thread = threading.Thread(target=loop, name="memory-migration", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
