"""Agent registry + task routing.

Reference parity (agent-core/src/agent_router.rs):
  * TrackedAgent registry with heartbeat timestamps, status, counters;
  * route_task: agents whose tool_namespaces cover the task's required
    tools AND heartbeat < 15 s AND idle; fallback to busy-but-capable;
    idle-first then most-experienced ordering (agent_router.rs:73-141);
  * tasks with empty required_tools are deliberately unroutable -> they go
    to the AI reasoning path instead (agent_router.rs:91-95);
  * dead_agents() for timeout-based task requeue (192-198);
  * cluster spillover via route_task_to_node (202-226).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import instruments as obs
from .goal_engine import Task

HEARTBEAT_TIMEOUT = 15.0


def _now() -> float:
    return time.monotonic()


@dataclass
class TrackedAgent:
    agent_id: str
    agent_type: str
    capabilities: List[str] = field(default_factory=list)
    tool_namespaces: List[str] = field(default_factory=list)
    status: str = "idle"  # idle | busy
    current_task_id: str = ""
    last_heartbeat: float = field(default_factory=_now)
    tasks_completed: int = 0
    tasks_failed: int = 0
    registered_at: int = field(default_factory=lambda: int(time.time()))

    @property
    def alive(self) -> bool:
        return _now() - self.last_heartbeat < HEARTBEAT_TIMEOUT

    @property
    def idle(self) -> bool:
        return self.status == "idle" and not self.current_task_id


class AgentRouter:
    def __init__(self):
        self._agents: Dict[str, TrackedAgent] = {}
        self._assigned: Dict[str, List[Task]] = {}  # agent_id -> task queue
        self._lock = threading.RLock()

    # -- registry -----------------------------------------------------------

    def register(self, agent: TrackedAgent) -> None:
        with self._lock:
            self._agents[agent.agent_id] = agent
            self._assigned.setdefault(agent.agent_id, [])

    def unregister(self, agent_id: str) -> bool:
        with self._lock:
            self._assigned.pop(agent_id, None)
            return self._agents.pop(agent_id, None) is not None

    def heartbeat(
        self, agent_id: str, status: str = "", current_task_id: str = ""
    ) -> bool:
        with self._lock:
            a = self._agents.get(agent_id)
            if a is None:
                return False
            a.last_heartbeat = _now()
            if status:
                a.status = status
            a.current_task_id = current_task_id
            return True

    def agents(self) -> List[TrackedAgent]:
        with self._lock:
            return list(self._agents.values())

    def get(self, agent_id: str) -> Optional[TrackedAgent]:
        with self._lock:
            return self._agents.get(agent_id)

    def dead_agents(self) -> List[TrackedAgent]:
        with self._lock:
            return [a for a in self._agents.values() if not a.alive]

    def prune_dead(self) -> List[str]:
        with self._lock:
            dead = [aid for aid, a in self._agents.items() if not a.alive]
            for aid in dead:
                del self._agents[aid]
                self._assigned.pop(aid, None)
            return dead

    # -- routing ------------------------------------------------------------

    def route_task(self, task: Task) -> Optional[str]:
        """Pick an agent for the task; None -> AI path.

        Empty required_tools is deliberately unroutable (the AI reasoning
        loop handles those, agent_router.rs:91-95).
        """
        if not task.required_tools:
            obs.ROUTER_TASKS.labels(outcome="ai_path").inc()
            return None
        with self._lock:
            capable = [
                a
                for a in self._agents.values()
                if a.alive
                and all(ns in a.tool_namespaces for ns in task.required_tools)
            ]
            if not capable:
                obs.ROUTER_TASKS.labels(outcome="no_capable_agent").inc()
                return None
            # idle first, then most experienced (agent_router.rs:120-141)
            capable.sort(
                key=lambda a: (0 if a.idle else 1, -a.tasks_completed)
            )
            chosen = capable[0]
            self._assigned.setdefault(chosen.agent_id, []).append(task)
            chosen.status = "busy"
            chosen.current_task_id = task.id
            obs.ROUTER_TASKS.labels(outcome="routed").inc()
            return chosen.agent_id

    def next_task_for(self, agent_id: str) -> Optional[Task]:
        """Polling endpoint backing GetAssignedTask."""
        with self._lock:
            queue = self._assigned.get(agent_id)
            if queue:
                return queue.pop(0)
            return None

    def task_finished(self, agent_id: str, success: bool) -> None:
        with self._lock:
            a = self._agents.get(agent_id)
            if a is None:
                return
            a.status = "idle"
            a.current_task_id = ""
            if success:
                a.tasks_completed += 1
            else:
                a.tasks_failed += 1

    def requeue_from(self, agent_id: str) -> List[Task]:
        """Pull undelivered tasks back from a dead agent's queue."""
        with self._lock:
            queue = self._assigned.get(agent_id, [])
            tasks, queue[:] = list(queue), []
            return tasks
