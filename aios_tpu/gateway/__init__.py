"""aios.api_gateway.ApiGateway — cloud + local inference routing.

Reference: api-gateway/src/ (SURVEY.md section 2 row 5). The `local`
provider differs by design: instead of llama-server HTTP on 127.0.0.1:8082 it
calls the TPU runtime's gRPC Infer — the always-available final fallback is
the TPU chip itself.
"""
