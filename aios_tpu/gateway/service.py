"""aios.api_gateway.ApiGateway gRPC service.

Reference parity: api-gateway/src/main.rs (binds 0.0.0.0:50054) — Infer/
StreamInfer/GetBudget/GetUsage over the router + budget manager.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import grpc

from .. import rpc
from ..obs.http import maybe_start_metrics_server
from ..proto_gen import api_gateway_pb2 as pb
from ..proto_gen import common_pb2
from ..services import GATEWAY, ApiGatewayServicer, service_address
from .budget import BudgetManager
from .providers import ProviderError
from .router import RequestRouter

log = logging.getLogger("aios.gateway")


class GatewayService(ApiGatewayServicer):
    def __init__(self, router: Optional[RequestRouter] = None):
        self.router = router or RequestRouter()

    def Infer(self, request, context):
        t0 = time.time()
        try:
            result = self.router.route(
                prompt=request.prompt,
                system=request.system_prompt,
                max_tokens=request.max_tokens or 1024,
                temperature=request.temperature or 0.7,
                preferred=request.preferred_provider,
                allow_fallback=request.allow_fallback,
                json_schema=getattr(request, "json_schema", ""),
                agent=request.requesting_agent,
                task_id=request.task_id,
            )
        except ProviderError as exc:
            context.set_code(grpc.StatusCode.UNAVAILABLE)
            context.set_details(str(exc))
            return common_pb2.InferenceResponse()
        return common_pb2.InferenceResponse(
            text=result.text,
            tokens_used=result.input_tokens + result.output_tokens,
            latency_ms=int((time.time() - t0) * 1000),
            model_used=f"{result.provider}/{result.model}",
        )

    def StreamInfer(self, request, context):
        """True streaming: deltas are relayed as the serving provider emits
        them (live token stream for the local TPU runtime; 64-char rechunk
        only for providers without a streaming client — router.route_stream)."""
        provider = ""
        emitted = False
        # Disconnect propagation while NO delta is flowing: this generator
        # parks in the provider's next() then, so GeneratorExit can't reach
        # it — the termination callback cancels the registered downstream
        # call(s) cross-thread instead, which unblocks the provider loop
        # and aborts the runtime's decode. Registration after the RPC died
        # cancels immediately (add_callback no longer fires).
        downstream = []

        def register_call(call):
            downstream.append(call)
            if not context.is_active():
                call.cancel()

        context.add_callback(lambda: [c.cancel() for c in downstream])
        try:
            for delta, provider in self.router.route_stream(
                prompt=request.prompt,
                system=request.system_prompt,
                max_tokens=request.max_tokens or 1024,
                temperature=request.temperature or 0.7,
                preferred=request.preferred_provider,
                allow_fallback=request.allow_fallback,
                json_schema=getattr(request, "json_schema", ""),
                agent=request.requesting_agent,
                task_id=request.task_id,
                register_call=register_call,
                client_alive=context.is_active,
            ):
                emitted = True
                yield pb.StreamChunk(text=delta, done=False, provider=provider)
        except ProviderError as exc:
            if not context.is_active():
                # our client is gone (its disconnect tore the downstream
                # call); nothing to report to nobody
                return
            if not emitted:
                context.set_code(grpc.StatusCode.UNAVAILABLE)
                context.set_details(str(exc))
                return
            context.set_code(grpc.StatusCode.ABORTED)
            context.set_details(f"stream interrupted: {exc}")
            return
        yield pb.StreamChunk(text="", done=True, provider=provider)

    def GetBudget(self, request, context):
        s = self.router.budget.status()
        return pb.BudgetStatus(
            claude_monthly_budget_usd=s["claude_monthly_budget_usd"],
            claude_used_usd=s["claude_used_usd"],
            openai_monthly_budget_usd=s["openai_monthly_budget_usd"],
            openai_used_usd=s["openai_used_usd"],
            days_remaining=s["days_remaining"],
            daily_rate_usd=s["daily_rate_usd"],
            budget_exceeded=s["budget_exceeded"],
        )

    def GetUsage(self, request, context):
        records = self.router.budget.usage(
            provider=request.provider, days=request.days or 30
        )
        return pb.UsageResponse(
            records=[
                pb.UsageRecord(
                    provider=r.provider,
                    model=r.model,
                    input_tokens=r.input_tokens,
                    output_tokens=r.output_tokens,
                    cost_usd=r.cost_usd,
                    timestamp=r.timestamp,
                    requesting_agent=r.requesting_agent,
                    task_id=r.task_id,
                )
                for r in records
            ],
            total_cost_usd=sum(r.cost_usd for r in records),
            total_requests=len(records),
            total_tokens=sum(r.input_tokens + r.output_tokens for r in records),
        )


def serve(
    address: Optional[str] = None,
    router: Optional[RequestRouter] = None,
    block: bool = True,
    metrics_port: Optional[int] = None,
):
    address = address or service_address("gateway")
    server = rpc.create_server()
    service = GatewayService(router)
    rpc.add_to_server(GATEWAY, service, server)
    port = server.add_insecure_port(address)
    server.start()
    service.metrics_server, service.metrics_port = maybe_start_metrics_server(
        "gateway", metrics_port, health_fn=lambda: {"service": "gateway"}
    )
    log.info("ApiGateway listening on %s", address)
    if block:
        server.wait_for_termination()
    return server, service, port


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    serve()
