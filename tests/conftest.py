"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so every sharding path (TP/DP/SP)
is exercised without TPU hardware; the driver separately compile-checks the
real-chip path. Env vars must be set before the first `import jax` anywhere
in the test process, which is why this lives at the top of conftest.
"""

import os

# Force CPU: the session env may point JAX at the real TPU chip (and a site
# hook can force jax_platforms after import), but the test suite runs on a
# virtual 8-device CPU mesh — the driver benches on TPU separately. Both the
# env var and the config override are needed, before backends initialize.
os.environ["JAX_PLATFORMS"] = "cpu"

# Dynamic lock-order verification: every declared serving-plane lock
# (aios_tpu/analysis/registry.py) becomes a named, order-checking
# DebugLock, so the e2e tests double as deadlock detection — an AB/BA
# acquisition inversion raises LockOrderError with both stacks instead
# of hanging a run someday. setdefault: AIOS_TPU_LOCK_DEBUG=0 in the
# environment turns it off for A/B timing comparisons.
os.environ.setdefault("AIOS_TPU_LOCK_DEBUG", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture()
def tmp_db_path(tmp_path):
    return str(tmp_path / "test.db")


def pytest_collection_modifyitems(config, items):
    """Everything not marked slow is the fast commit-gate tier
    (`pytest -m fast` — service plane + runtime surface, <2 min on CPU)."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)
