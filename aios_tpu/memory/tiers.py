"""The three memory tiers + knowledge base.

Reference parity (memory/src/):
  * operational (operational.rs): in-process ring buffer of events + metric
    map; target <1 ms access — pure python structures under a lock.
  * working (working.rs): SQLite WAL, tables goals/tasks/tool_calls/
    decisions/patterns/agent_state; 30-day retention.
  * long-term (longterm.rs): SQLite + hash embeddings (embeddings.py) with
    hybrid keyword/vector search; stores memories/procedures/incidents/
    config changes; collections are search-filterable.
  * knowledge (knowledge.rs): same embedding scheme, separate table.

All SQLite handles are per-tier connections with WAL enabled, guarded by a
lock (sqlite connections are not thread-safe under the default isolation;
the reference wraps its !Send connection in a Mutex the same way,
goal_engine.rs:30-31).
"""

from __future__ import annotations

import collections
import json
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import instruments as obs
from .embeddings import embed, rank


def _lookup(tier: str, hit: bool) -> None:
    """Per-tier hit/miss accounting: a lookup that returns something is a
    hit, an empty result a miss (the tier-efficiency signal the migration
    policy and dashboards watch)."""
    obs.MEMORY_TIER_LOOKUPS.labels(
        tier=tier, result="hit" if hit else "miss"
    ).inc()

RING_CAPACITY = 10_000
WORKING_RETENTION_DAYS = 30
LONGTERM_RETENTION_DAYS = 365
PATTERN_CAP = 1_000


def _now() -> int:
    return int(time.time())


# ---------------------------------------------------------------------------
# Operational tier
# ---------------------------------------------------------------------------


@dataclass
class OperationalMemory:
    """Hot tier: bounded event ring + last-value metric map."""

    capacity: int = RING_CAPACITY
    _events: collections.deque = field(default_factory=collections.deque)
    _metrics: Dict[str, Tuple[float, int]] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def push_event(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if not event.get("id"):
                event["id"] = str(uuid.uuid4())
            if not event.get("timestamp"):
                event["timestamp"] = _now()
            self._events.append(event)
            while len(self._events) > self.capacity:
                self._events.popleft()

    def recent_events(
        self, count: int = 50, category: str = "", source: str = ""
    ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for ev in reversed(self._events):
                if category and ev.get("category") != category:
                    continue
                if source and ev.get("source") != source:
                    continue
                out.append(ev)
                if len(out) >= count:
                    break
            return out

    def drain_older_than(self, age_seconds: int) -> List[Dict[str, Any]]:
        """Remove and return events older than ``age_seconds`` (migration)."""
        cutoff = _now() - age_seconds
        with self._lock:
            old, keep = [], collections.deque()
            for ev in self._events:
                (old if ev.get("timestamp", 0) < cutoff else keep).append(ev)
            self._events = keep
            return old

    def update_metric(self, key: str, value: float, timestamp: int = 0) -> None:
        with self._lock:
            self._metrics[key] = (value, timestamp or _now())

    def get_metric(self, key: str) -> Optional[Tuple[float, int]]:
        with self._lock:
            value = self._metrics.get(key)
        _lookup("operational", value is not None)
        return value

    def all_metrics(self) -> Dict[str, Tuple[float, int]]:
        with self._lock:
            return dict(self._metrics)


# ---------------------------------------------------------------------------
# Working tier
# ---------------------------------------------------------------------------

_WORKING_SCHEMA = """
CREATE TABLE IF NOT EXISTS goals (
    id TEXT PRIMARY KEY, description TEXT, status TEXT, priority INTEGER,
    created_at INTEGER, completed_at INTEGER, result TEXT, metadata_json TEXT
);
CREATE TABLE IF NOT EXISTS tasks (
    id TEXT PRIMARY KEY, goal_id TEXT, description TEXT, agent TEXT,
    status TEXT, input_json TEXT, output_json TEXT,
    started_at INTEGER, completed_at INTEGER, duration_ms INTEGER, error TEXT
);
CREATE TABLE IF NOT EXISTS tool_calls (
    id TEXT PRIMARY KEY, task_id TEXT, tool_name TEXT, agent TEXT,
    input_json TEXT, output_json TEXT, success INTEGER,
    duration_ms INTEGER, reason TEXT, timestamp INTEGER
);
CREATE TABLE IF NOT EXISTS decisions (
    id TEXT PRIMARY KEY, context TEXT, options_json TEXT, chosen TEXT,
    reasoning TEXT, intelligence_level TEXT, model_used TEXT,
    outcome TEXT, timestamp INTEGER
);
CREATE TABLE IF NOT EXISTS patterns (
    id TEXT PRIMARY KEY, trigger TEXT, action TEXT, success_rate REAL,
    uses INTEGER, last_used INTEGER, created_from TEXT
);
CREATE TABLE IF NOT EXISTS agent_state (
    agent_name TEXT PRIMARY KEY, state_json TEXT, updated_at INTEGER
);
CREATE INDEX IF NOT EXISTS idx_tasks_goal ON tasks(goal_id);
CREATE INDEX IF NOT EXISTS idx_patterns_trigger ON patterns(trigger);
"""


class WorkingMemory:
    """Warm tier: SQLite WAL; goal/task/tool-call/decision/pattern records."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.executescript(_WORKING_SCHEMA)
        self._lock = threading.Lock()

    def _exec(self, sql: str, args: tuple = ()):
        with self._lock:
            cur = self._conn.execute(sql, args)
            self._conn.commit()
            return cur

    def _query(self, sql: str, args: tuple = ()) -> List[tuple]:
        with self._lock:
            return self._conn.execute(sql, args).fetchall()

    # goals
    def store_goal(self, g: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO goals VALUES (?,?,?,?,?,?,?,?)",
            (
                g["id"],
                g.get("description", ""),
                g.get("status", "pending"),
                g.get("priority", 5),
                g.get("created_at") or _now(),
                g.get("completed_at", 0),
                g.get("result", ""),
                g.get("metadata_json", ""),
            ),
        )

    def update_goal(self, goal_id: str, status: str, result: str = "") -> None:
        completed = _now() if status in ("completed", "failed", "cancelled") else 0
        self._exec(
            "UPDATE goals SET status=?, result=?, "
            "completed_at=CASE WHEN ?>0 THEN ? ELSE completed_at END WHERE id=?",
            (status, result, completed, completed, goal_id),
        )

    def active_goals(self) -> List[Dict[str, Any]]:
        rows = self._query(
            "SELECT id, description, status, priority, created_at, completed_at,"
            " result, metadata_json FROM goals"
            " WHERE status IN ('pending','planning','in_progress')"
            " ORDER BY priority DESC, created_at"
        )
        keys = [
            "id", "description", "status", "priority",
            "created_at", "completed_at", "result", "metadata_json",
        ]
        return [dict(zip(keys, r)) for r in rows]

    def finished_goals_older_than(self, age_seconds: int) -> List[Dict[str, Any]]:
        cutoff = _now() - age_seconds
        rows = self._query(
            "SELECT id, description, status, result, completed_at FROM goals"
            " WHERE status IN ('completed','failed')"
            " AND completed_at > 0 AND completed_at < ?",
            (cutoff,),
        )
        return [
            dict(zip(["id", "description", "status", "result", "completed_at"], r))
            for r in rows
        ]

    def delete_goal(self, goal_id: str) -> None:
        self._exec("DELETE FROM goals WHERE id=?", (goal_id,))
        self._exec("DELETE FROM tasks WHERE goal_id=?", (goal_id,))

    # tasks
    def store_task(self, t: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO tasks VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            (
                t["id"],
                t.get("goal_id", ""),
                t.get("description", ""),
                t.get("agent", ""),
                t.get("status", "pending"),
                t.get("input_json", ""),
                t.get("output_json", ""),
                t.get("started_at", 0),
                t.get("completed_at", 0),
                t.get("duration_ms", 0),
                t.get("error", ""),
            ),
        )

    def tasks_for_goal(self, goal_id: str) -> List[Dict[str, Any]]:
        rows = self._query(
            "SELECT id, goal_id, description, agent, status, input_json,"
            " output_json, started_at, completed_at, duration_ms, error"
            " FROM tasks WHERE goal_id=?",
            (goal_id,),
        )
        keys = [
            "id", "goal_id", "description", "agent", "status", "input_json",
            "output_json", "started_at", "completed_at", "duration_ms", "error",
        ]
        return [dict(zip(keys, r)) for r in rows]

    # tool calls / decisions
    def store_tool_call(self, c: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO tool_calls VALUES (?,?,?,?,?,?,?,?,?,?)",
            (
                c.get("id") or str(uuid.uuid4()),
                c.get("task_id", ""),
                c.get("tool_name", ""),
                c.get("agent", ""),
                c.get("input_json", ""),
                c.get("output_json", ""),
                1 if c.get("success") else 0,
                c.get("duration_ms", 0),
                c.get("reason", ""),
                c.get("timestamp") or _now(),
            ),
        )

    def store_decision(self, d: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO decisions VALUES (?,?,?,?,?,?,?,?,?)",
            (
                d.get("id") or str(uuid.uuid4()),
                d.get("context", ""),
                d.get("options_json", ""),
                d.get("chosen", ""),
                d.get("reasoning", ""),
                d.get("intelligence_level", ""),
                d.get("model_used", ""),
                d.get("outcome", ""),
                d.get("timestamp") or _now(),
            ),
        )

    def recent_decisions(self, limit: int = 20) -> List[Dict[str, Any]]:
        rows = self._query(
            "SELECT context, chosen, reasoning, outcome, timestamp FROM decisions"
            " ORDER BY timestamp DESC LIMIT ?",
            (limit,),
        )
        keys = ["context", "chosen", "reasoning", "outcome", "timestamp"]
        return [dict(zip(keys, r)) for r in rows]

    # patterns
    def store_pattern(self, p: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO patterns VALUES (?,?,?,?,?,?,?)",
            (
                p.get("id") or str(uuid.uuid4()),
                p.get("trigger", ""),
                p.get("action", ""),
                p.get("success_rate", 0.0),
                p.get("uses", 0),
                p.get("last_used", 0),
                p.get("created_from", ""),
            ),
        )

    def find_pattern(
        self, trigger: str, min_success_rate: float = 0.0
    ) -> Optional[Dict[str, Any]]:
        rows = self._query(
            "SELECT id, trigger, action, success_rate, uses, last_used,"
            " created_from FROM patterns"
            " WHERE trigger LIKE ? AND success_rate >= ?"
            " ORDER BY success_rate DESC, uses DESC LIMIT 1",
            (f"%{trigger}%", min_success_rate),
        )
        _lookup("working", bool(rows))
        if not rows:
            return None
        keys = ["id", "trigger", "action", "success_rate", "uses", "last_used",
                "created_from"]
        return dict(zip(keys, rows[0]))

    def update_pattern_stats(self, pattern_id: str, success: bool) -> None:
        row = self._query(
            "SELECT success_rate, uses FROM patterns WHERE id=?", (pattern_id,)
        )
        if not row:
            return
        rate, uses = row[0]
        new_rate = (rate * uses + (1.0 if success else 0.0)) / (uses + 1)
        self._exec(
            "UPDATE patterns SET success_rate=?, uses=?, last_used=? WHERE id=?",
            (new_rate, uses + 1, _now(), pattern_id),
        )

    def prune_patterns(self, cap: int = PATTERN_CAP) -> int:
        """Keep the best `cap` patterns (migration.rs pattern pruning)."""
        n = self._query("SELECT COUNT(*) FROM patterns")[0][0]
        if n <= cap:
            return 0
        self._exec(
            "DELETE FROM patterns WHERE id NOT IN ("
            " SELECT id FROM patterns ORDER BY success_rate DESC, uses DESC"
            " LIMIT ?)",
            (cap,),
        )
        return n - cap

    # agent state
    def store_agent_state(self, name: str, state_json: str) -> None:
        self._exec(
            "INSERT OR REPLACE INTO agent_state VALUES (?,?,?)",
            (name, state_json, _now()),
        )

    def get_agent_state(self, name: str) -> Optional[Tuple[str, int]]:
        rows = self._query(
            "SELECT state_json, updated_at FROM agent_state WHERE agent_name=?",
            (name,),
        )
        _lookup("working", bool(rows))
        return (rows[0][0], rows[0][1]) if rows else None

    def retention_sweep(self, days: int = WORKING_RETENTION_DAYS) -> None:
        cutoff = _now() - days * 86400
        self._exec(
            "DELETE FROM tool_calls WHERE timestamp < ?", (cutoff,)
        )
        self._exec("DELETE FROM decisions WHERE timestamp < ?", (cutoff,))


# ---------------------------------------------------------------------------
# Long-term tier + knowledge base
# ---------------------------------------------------------------------------

_LONGTERM_SCHEMA = """
CREATE TABLE IF NOT EXISTS memories (
    id TEXT PRIMARY KEY, collection TEXT, content TEXT,
    metadata_json TEXT, embedding BLOB, created_at INTEGER
);
CREATE TABLE IF NOT EXISTS procedures (
    id TEXT PRIMARY KEY, name TEXT, description TEXT, steps_json TEXT,
    success_count INTEGER, fail_count INTEGER, avg_duration_ms INTEGER,
    tags TEXT, created_at INTEGER, last_used INTEGER, embedding BLOB
);
CREATE TABLE IF NOT EXISTS incidents (
    id TEXT PRIMARY KEY, description TEXT, symptoms_json TEXT,
    root_cause TEXT, resolution TEXT, resolved_by TEXT, prevention TEXT,
    timestamp INTEGER, embedding BLOB
);
CREATE TABLE IF NOT EXISTS config_changes (
    id TEXT PRIMARY KEY, file_path TEXT, content TEXT, changed_by TEXT,
    reason TEXT, timestamp INTEGER
);
CREATE TABLE IF NOT EXISTS knowledge (
    id TEXT PRIMARY KEY, title TEXT, content TEXT, source TEXT,
    tags TEXT, embedding BLOB, created_at INTEGER
);
CREATE INDEX IF NOT EXISTS idx_memories_coll ON memories(collection);
"""


class LongTermMemory:
    """Cold tier: SQLite + hash-embedding vectors, hybrid search."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.executescript(_LONGTERM_SCHEMA)
        self._lock = threading.Lock()

    def _exec(self, sql: str, args: tuple = ()):
        with self._lock:
            cur = self._conn.execute(sql, args)
            self._conn.commit()
            return cur

    def _query(self, sql: str, args: tuple = ()) -> List[tuple]:
        with self._lock:
            return self._conn.execute(sql, args).fetchall()

    def store_memory(
        self,
        content: str,
        collection: str = "general",
        metadata: Optional[Dict[str, Any]] = None,
        memory_id: str = "",
    ) -> str:
        memory_id = memory_id or str(uuid.uuid4())
        vec = embed(content)
        self._exec(
            "INSERT OR REPLACE INTO memories VALUES (?,?,?,?,?,?)",
            (
                memory_id,
                collection,
                content,
                json.dumps(metadata or {}),
                vec.tobytes(),
                _now(),
            ),
        )
        return memory_id

    def search(
        self,
        query: str,
        collections: Optional[List[str]] = None,
        n_results: int = 5,
        min_relevance: float = 0.0,
    ) -> List[Dict[str, Any]]:
        if collections:
            marks = ",".join("?" * len(collections))
            rows = self._query(
                f"SELECT id, collection, content, metadata_json, embedding"
                f" FROM memories WHERE collection IN ({marks})",
                tuple(collections),
            )
        else:
            rows = self._query(
                "SELECT id, collection, content, metadata_json, embedding"
                " FROM memories"
            )
        texts = [r[2] for r in rows]
        vecs = [np.frombuffer(r[4], dtype=np.float32) for r in rows]
        out = []
        for idx, score in rank(query, texts, vecs)[:n_results]:
            if score < min_relevance:
                continue
            r = rows[idx]
            out.append(
                {
                    "id": r[0],
                    "collection": r[1],
                    "content": r[2],
                    "metadata_json": r[3],
                    "relevance": score,
                }
            )
        _lookup("longterm", bool(out))
        return out

    def store_procedure(self, p: Dict[str, Any]) -> None:
        text = f"{p.get('name','')} {p.get('description','')}"
        self._exec(
            "INSERT OR REPLACE INTO procedures VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            (
                p.get("id") or str(uuid.uuid4()),
                p.get("name", ""),
                p.get("description", ""),
                p.get("steps_json", ""),
                p.get("success_count", 0),
                p.get("fail_count", 0),
                p.get("avg_duration_ms", 0),
                json.dumps(p.get("tags", [])),
                p.get("created_at") or _now(),
                p.get("last_used", 0),
                embed(text).tobytes(),
            ),
        )

    def store_incident(self, inc: Dict[str, Any]) -> None:
        text = f"{inc.get('description','')} {inc.get('root_cause','')}"
        self._exec(
            "INSERT OR REPLACE INTO incidents VALUES (?,?,?,?,?,?,?,?,?)",
            (
                inc.get("id") or str(uuid.uuid4()),
                inc.get("description", ""),
                inc.get("symptoms_json", ""),
                inc.get("root_cause", ""),
                inc.get("resolution", ""),
                inc.get("resolved_by", ""),
                inc.get("prevention", ""),
                inc.get("timestamp") or _now(),
                embed(text).tobytes(),
            ),
        )

    def store_config_change(self, c: Dict[str, Any]) -> None:
        self._exec(
            "INSERT OR REPLACE INTO config_changes VALUES (?,?,?,?,?,?)",
            (
                c.get("id") or str(uuid.uuid4()),
                c.get("file_path", ""),
                c.get("content", ""),
                c.get("changed_by", ""),
                c.get("reason", ""),
                c.get("timestamp") or _now(),
            ),
        )

    def retention_sweep(self, days: int = LONGTERM_RETENTION_DAYS) -> None:
        cutoff = _now() - days * 86400
        self._exec("DELETE FROM memories WHERE created_at < ?", (cutoff,))

    # knowledge base (knowledge.rs — same embedding scheme, own table)
    def add_knowledge(
        self, title: str, content: str, source: str = "", tags: Optional[List[str]] = None
    ) -> str:
        kid = str(uuid.uuid4())
        self._exec(
            "INSERT INTO knowledge VALUES (?,?,?,?,?,?,?)",
            (
                kid,
                title,
                content,
                source,
                json.dumps(tags or []),
                embed(f"{title} {content}").tobytes(),
                _now(),
            ),
        )
        return kid

    def search_knowledge(
        self, query: str, n_results: int = 5, min_relevance: float = 0.0
    ) -> List[Dict[str, Any]]:
        rows = self._query(
            "SELECT id, title, content, source, tags, embedding FROM knowledge"
        )
        texts = [f"{r[1]} {r[2]}" for r in rows]
        vecs = [np.frombuffer(r[5], dtype=np.float32) for r in rows]
        out = []
        for idx, score in rank(query, texts, vecs)[:n_results]:
            if score < min_relevance:
                continue
            r = rows[idx]
            out.append(
                {
                    "id": r[0],
                    "collection": "knowledge",
                    "content": r[2],
                    "metadata_json": json.dumps({"title": r[1], "source": r[3]}),
                    "relevance": score,
                }
            )
        _lookup("knowledge", bool(out))
        return out
