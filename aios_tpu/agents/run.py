"""Agent process entry point: `python -m aios_tpu.agents.run`.

Reads AIOS_AGENT_TYPE / AIOS_AGENT_NAME from the environment (set by the
spawner, agent_spawner.rs:183-190) or from --type/--name args.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--type", default=os.environ.get("AIOS_AGENT_TYPE", ""))
    parser.add_argument("--name", default=os.environ.get("AIOS_AGENT_NAME", ""))
    args = parser.parse_args()
    if not args.type:
        parser.error("agent type required (--type or AIOS_AGENT_TYPE)")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from . import agent_class

    cls = agent_class(args.type)
    agent = cls(name=args.name or None)
    agent.run(block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
