"""Blockwise (flash) causal attention for prefill and training.

Replaces the naive score-materializing attention on the prefill path: the
[T, S] score matrix never exists in HBM — each (query-block, kv-block) tile
is produced in VMEM, folded into a running online softmax (max / sum / value
accumulator), and discarded. This is the standard flash recurrence:

    m'   = max(m, rowmax(S))
    l'   = l * exp(m - m') + rowsum(exp(S - m'))
    acc' = acc * exp(m - m') + exp(S - m') @ V

Grid layout: (batch, q_head, q_block, kv_block) with kv_block innermost —
on TPU the grid is executed sequentially per core, so VMEM scratch
accumulators persist across the kv_block sweep for one query block.

GQA is handled by index-mapping kv blocks through head // group_size; causal
and sliding-window structure is exploited at block granularity (fully-masked
tiles skip their compute entirely via pl.when).

Reference behavior being replaced: llama.cpp's fused attention inside
llama-server (SURVEY.md section 2.3) — here it is a first-class Mosaic
kernel instead of an external binary.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128  # stat scratch is kept lane-replicated for layout friendliness


def _flash_kernel(
    q_ref,  # [1, 1, bq, D]
    k_ref,  # [1, 1, bk, D]
    v_ref,  # [1, 1, bk, D]
    o_ref,  # [1, 1, bq, D]
    m_scr,  # VMEM [bq, LANES] f32
    l_scr,  # VMEM [bq, LANES] f32
    acc_scr,  # VMEM [bq, D] f32
    *,
    causal: bool,
    window: Optional[int],
    block_q: int,
    block_kv: int,
    sm_scale: float,
):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = i * block_q
    kv_start = j * block_kv

    # Block-level structure: skip tiles that the causal / window masks kill
    # entirely. Per-element masking inside _compute handles partial tiles.
    # Causal kills tiles newer than the *newest* row; the window kills tiles
    # older than what the *oldest* row (q_start) can still see.
    run = jnp.bool_(True)
    if causal:
        run = kv_start <= q_start + block_q - 1
    if window is not None:
        run = jnp.logical_and(run, kv_start + block_kv - 1 > q_start - window)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :]  # [bq, D]
        k = k_ref[0, 0, :, :]  # [bk, D]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        s = s * sm_scale

        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0
        )
        cols = kv_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1
        )
        mask = None
        if causal:
            mask = cols <= rows
        if window is not None:
            win = cols > rows - window
            mask = win if mask is None else jnp.logical_and(mask, win)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [bq, bk] f32
        if mask is not None:
            # rows fully masked in this tile have m_new = NEG_INF and would
            # otherwise get p = exp(0) = 1 across the board
            p = jnp.where(mask, p, 0.0)
        l_cur = jnp.sum(p, axis=-1, keepdims=True)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_prev * alpha + l_cur

        acc = acc_scr[:] * alpha  # [bq, D]
        acc_scr[:] = acc + jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l <= 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal",
        "window",
        "block_q",
        "block_kv",
        "interpret",
    ),
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, KH, D]
    v: jnp.ndarray,  # [B, S, KH, D]
    *,
    causal: bool = True,
    window: Optional[int] = None,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash GQA attention; drop-in for the naive reference (model layout)."""
    B, T, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    G = H // KH
    bq = min(block_q, T)
    bk = min(block_kv, S)
    if T % bq or S % bk:
        raise ValueError(f"T={T} / S={S} must divide blocks ({bq}, {bk})")

    # kernel layout: heads as a grid axis
    qt = q.transpose(0, 2, 1, 3)  # [B, H, T, D]
    kt = k.transpose(0, 2, 1, 3)  # [B, KH, S, D]
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, T // bq, S // bk)
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        window=window,
        block_q=bq,
        block_kv=bk,
        sm_scale=1.0 / float(np.sqrt(D)),
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, D), lambda b, h, i, j, G=G: (b, h // G, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, D), lambda b, h, i, j, G=G: (b, h // G, j, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)  # back to [B, T, H, D]


def flash_attention_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Naive jnp GQA attention (CPU fallback + parity ground truth)."""
    B, T, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    G = H // KH
    qg = q.reshape(B, T, KH, G, D)
    s = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32)
    s = s / np.sqrt(D)
    rows = jnp.arange(T)[:, None]
    cols = jnp.arange(S)[None, :]
    mask = jnp.ones((T, S), bool)
    if causal:
        mask = cols <= rows
    if window is not None:
        mask = mask & (cols > rows - window)
    s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", p, v)
    return out.reshape(B, T, H, D)
