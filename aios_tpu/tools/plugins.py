"""Self-evolution: AI-authored Python plugins.

Reference parity (tools/src/plugin/, SURVEY.md row 3i): `plugin.create`
accepts {name, description, code (must define `main(input_data) -> dict`),
capabilities, requirements, next_plugins, output_mode}; plugins are stored in
the plugin dir with a `.meta.json` sidecar, auto-registered as callable tools
on create, executed inside the sandbox (network allowed, /tmp writable,
main.rs:129-167), and chainable via pipe (output feeds the next plugin's
input) or merge (outputs are merged into one dict) (main.rs:177-244).
"""

from __future__ import annotations

import json
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional

from .handlers import ToolError
from .sandbox import ResourceLimits, Sandbox

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{1,48}$")

# stdin JSON -> plugin.main -> stdout JSON, run inside the sandbox
_RUNNER = """\
import json, sys, importlib.util
spec = importlib.util.spec_from_file_location("aios_plugin", sys.argv[1])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
payload = json.loads(sys.stdin.read() or "{}")
result = mod.main(payload)
if not isinstance(result, dict):
    result = {"result": result}
print(json.dumps(result))
"""

TEMPLATES = {
    "basic": (
        "def main(input_data):\n"
        "    return {'echo': input_data}\n"
    ),
    "http_check": (
        "import urllib.request\n\n"
        "def main(input_data):\n"
        "    url = input_data.get('url', 'http://127.0.0.1:9090/api/health')\n"
        "    try:\n"
        "        with urllib.request.urlopen(url, timeout=5) as r:\n"
        "            return {'status': r.status, 'ok': r.status == 200}\n"
        "    except OSError as e:\n"
        "        return {'ok': False, 'error': str(e)}\n"
    ),
    "file_summary": (
        "def main(input_data):\n"
        "    path = input_data['path']\n"
        "    text = open(path, errors='replace').read()\n"
        "    lines = text.splitlines()\n"
        "    return {'path': path, 'lines': len(lines), 'chars': len(text)}\n"
    ),
}


class PluginManager:
    def __init__(self, plugin_dir: str = "/tmp/aios/plugins"):
        self.plugin_dir = Path(plugin_dir)
        self.plugin_dir.mkdir(parents=True, exist_ok=True)
        self._runner = self.plugin_dir / "_runner.py"
        self._runner.write_text(_RUNNER)
        self.sandbox = Sandbox(
            limits=ResourceLimits(wall_timeout=30.0),
            writable_paths=["/tmp"],
            allow_network=True,
        )

    # -- lifecycle ----------------------------------------------------------

    def validate(self, name: str, code: str) -> None:
        if not _NAME_RE.match(name):
            raise ToolError(
                f"invalid plugin name {name!r} (lowercase, digits, underscore)"
            )
        if "def main(" not in code:
            raise ToolError("plugin code must define main(input_data) -> dict")
        try:
            compile(code, f"<plugin:{name}>", "exec")
        except SyntaxError as exc:
            raise ToolError(f"plugin syntax error: {exc}") from exc

    def create(
        self,
        name: str,
        code: str,
        description: str = "",
        capabilities: Optional[List[str]] = None,
        requirements: Optional[List[str]] = None,
        next_plugins: Optional[List[str]] = None,
        output_mode: str = "pipe",
    ) -> Dict[str, Any]:
        self.validate(name, code)
        if output_mode not in ("pipe", "merge"):
            raise ToolError(f"output_mode must be pipe|merge, got {output_mode}")
        (self.plugin_dir / f"{name}.py").write_text(code)
        meta = {
            "name": name,
            "description": description,
            "capabilities": capabilities or [],
            "requirements": requirements or [],
            "next_plugins": next_plugins or [],
            "output_mode": output_mode,
        }
        (self.plugin_dir / f"{name}.meta.json").write_text(json.dumps(meta))
        return meta

    def from_template(self, name: str, template: str, **kw) -> Dict[str, Any]:
        code = TEMPLATES.get(template)
        if code is None:
            raise ToolError(f"unknown template {template}; have {list(TEMPLATES)}")
        return self.create(name, code, description=f"from template {template}", **kw)

    def delete(self, name: str) -> bool:
        removed = False
        for suffix in (".py", ".meta.json"):
            p = self.plugin_dir / f"{name}{suffix}"
            if p.exists():
                p.unlink()
                removed = True
        return removed

    def list(self) -> List[Dict[str, Any]]:
        out = []
        for meta_file in sorted(self.plugin_dir.glob("*.meta.json")):
            try:
                out.append(json.loads(meta_file.read_text()))
            except ValueError:
                continue
        return out

    def get_meta(self, name: str) -> Optional[Dict[str, Any]]:
        p = self.plugin_dir / f"{name}.meta.json"
        if not p.exists():
            return None
        return json.loads(p.read_text())

    def install_deps(self, name: str) -> Dict[str, Any]:
        meta = self.get_meta(name)
        if meta is None:
            raise ToolError(f"plugin {name} not found")
        reqs = meta.get("requirements", [])
        if not reqs:
            return {"installed": [], "note": "no requirements"}
        if shutil.which("pip") is None:
            raise ToolError("pip unavailable; cannot install plugin deps")
        import subprocess

        proc = subprocess.run(
            ["pip", "install", "--quiet", *reqs],
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != 0:
            raise ToolError(f"pip install failed: {proc.stderr[-500:]}")
        return {"installed": reqs}

    # -- execution ----------------------------------------------------------

    def execute(
        self, name: str, input_data: Dict[str, Any], _depth: int = 0
    ) -> Dict[str, Any]:
        """Run a plugin in the sandbox; follow its chain (pipe/merge)."""
        if _depth > 5:
            raise ToolError("plugin chain too deep (max 5)")
        path = self.plugin_dir / f"{name}.py"
        if not path.exists():
            raise ToolError(f"plugin {name} not found")
        meta = self.get_meta(name) or {}
        try:
            proc = self.sandbox.run(
                ["python3", str(self._runner), str(path)],
                stdin_data=json.dumps(input_data).encode(),
            )
        except Exception as exc:  # TimeoutExpired etc.
            raise ToolError(f"plugin {name} failed to run: {exc}") from exc
        if proc.returncode != 0:
            raise ToolError(
                f"plugin {name} exited {proc.returncode}: "
                f"{proc.stderr.decode('utf-8', 'replace')[-500:]}"
            )
        try:
            result = json.loads(proc.stdout.decode("utf-8", "replace"))
        except ValueError as exc:
            raise ToolError(f"plugin {name} printed non-JSON output") from exc

        chain = meta.get("next_plugins") or []
        mode = meta.get("output_mode", "pipe")
        for nxt in chain:
            nxt_result = self.execute(nxt, result, _depth=_depth + 1)
            if mode == "merge":
                result = {**result, **nxt_result}
            else:  # pipe
                result = nxt_result
        return result
