"""Cron-scheduled goals.

Reference parity (agent-core/src/scheduler.rs): SQLite-persisted schedule
entries, a 60 s tick, and a 5-field cron matcher supporting `*`, `*/n` and
comma lists (scheduler.rs:186-226); last_run persisted so restarts don't
double-fire (scheduler.rs:123-134).
"""

from __future__ import annotations

import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..obs import instruments as obs


def _field_matches(spec: str, value: int) -> bool:
    if spec == "*":
        return True
    for part in spec.split(","):
        part = part.strip()
        if part.startswith("*/"):
            try:
                step = int(part[2:])
            except ValueError:
                return False
            if step > 0 and value % step == 0:
                return True
        elif "-" in part:
            try:
                lo, hi = part.split("-", 1)
                if int(lo) <= value <= int(hi):
                    return True
            except ValueError:
                return False
        else:
            try:
                if int(part) == value:
                    return True
            except ValueError:
                return False
    return False


def matches_cron(expr: str, t: Optional[time.struct_time] = None) -> bool:
    """5-field cron: minute hour day-of-month month day-of-week."""
    fields = expr.split()
    if len(fields) != 5:
        return False
    t = t or time.localtime()
    minute, hour, dom, month, dow = fields
    return (
        _field_matches(minute, t.tm_min)
        and _field_matches(hour, t.tm_hour)
        and _field_matches(dom, t.tm_mday)
        and _field_matches(month, t.tm_mon)
        and _field_matches(dow, t.tm_wday)  # 0 = Monday (python convention)
    )


@dataclass
class ScheduleEntry:
    id: str
    cron_expr: str
    goal_template: str
    priority: int = 5
    enabled: bool = True
    last_run: int = 0


class GoalScheduler:
    def __init__(
        self,
        submit_goal: Callable[[str, int], object],
        db_path: str = ":memory:",
        tick_seconds: float = 60.0,
    ):
        self.submit_goal = submit_goal
        self.tick_seconds = tick_seconds
        self._conn = sqlite3.connect(db_path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS schedules ("
            " id TEXT PRIMARY KEY, cron_expr TEXT, goal_template TEXT,"
            " priority INTEGER, enabled INTEGER, last_run INTEGER)"
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def create(self, cron_expr: str, goal_template: str, priority: int = 5) -> str:
        if len(cron_expr.split()) != 5:
            raise ValueError(f"bad cron expression {cron_expr!r}")
        sid = str(uuid.uuid4())
        with self._lock:
            self._conn.execute(
                "INSERT INTO schedules VALUES (?,?,?,?,1,0)",
                (sid, cron_expr, goal_template, priority),
            )
            self._conn.commit()
        return sid

    def delete(self, schedule_id: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM schedules WHERE id=?", (schedule_id,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    def list(self) -> List[ScheduleEntry]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, cron_expr, goal_template, priority, enabled,"
                " last_run FROM schedules"
            ).fetchall()
        return [
            ScheduleEntry(r[0], r[1], r[2], r[3], bool(r[4]), r[5]) for r in rows
        ]

    def tick(self, now: Optional[float] = None) -> int:
        """Fire matching schedules at most once per minute; returns count."""
        now = now or time.time()
        t = time.localtime(now)
        fired = 0
        for entry in self.list():
            if not entry.enabled:
                continue
            # don't double-fire within the same minute (scheduler.rs:123-134)
            if entry.last_run and int(now) - entry.last_run < 60:
                continue
            if matches_cron(entry.cron_expr, t):
                self.submit_goal(entry.goal_template, entry.priority)
                obs.SCHEDULER_FIRED.inc()
                with self._lock:
                    self._conn.execute(
                        "UPDATE schedules SET last_run=? WHERE id=?",
                        (int(now), entry.id),
                    )
                    self._conn.commit()
                fired += 1
        return fired

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.tick_seconds):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001
                    pass

        self._thread = threading.Thread(target=loop, name="goal-scheduler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
