"""Thread-safe Prometheus-style metrics: Counter, Gauge, Histogram.

Self-contained (the image has no prometheus_client); the exposition
format follows the Prometheus text format 0.0.4 so any scraper or human
reading ``/metrics`` sees the standard shape:

    # HELP aios_tpu_engine_ttft_seconds Time to first token
    # TYPE aios_tpu_engine_ttft_seconds histogram
    aios_tpu_engine_ttft_seconds_bucket{le="0.1",model="m"} 3
    ...

Design points:
  * one process-wide default ``REGISTRY``; tests build private registries;
  * label children are created on demand via ``labels(**kv)`` and cached —
    hot paths resolve the child ONCE and call ``inc()``/``observe()`` on
    it, which is a single locked float add;
  * a Gauge child can be backed by a callback (``set_function``), so slot
    occupancy / queue depth / KV-page gauges read live state at scrape
    time instead of requiring the hot loop to push updates;
  * per-metric child caps guard label-cardinality blowups (a runaway
    label turns into a capped, counted overflow series, not an OOM).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# Bounds the children one metric may hold: labels are sometimes derived
# from external input (tool names, model names) and an unbounded child
# map is a slow memory leak. The 1024th distinct label set collapses
# into a single overflow child.
MAX_CHILDREN = 1024
_OVERFLOW_KEY = ("__overflow__",)

# Latency-shaped default buckets (seconds): decode dispatches are
# O(10 ms), RPC fan-outs O(100 ms), XLA compiles O(10 s).
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                   extra: str = "") -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    ]
    if extra:
        pairs = [extra] + pairs
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # scrape must never take the service down
                return float("nan")
        with self._lock:
            return self._value


class CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount


class GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Pull the gauge's value from ``fn`` at scrape time (live state —
        occupancy, queue depth — without hot-path pushes). Re-registering
        replaces the previous callback (model reload)."""
        with self._lock:
            self._fn = fn


class HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "_sum", "_count")

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    @property
    def sample_count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sample_sum(self) -> float:
        with self._lock:
            return self._sum


class Metric:
    """Base: a named family of children keyed by label values."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Iterable[str] = (),
        registry: Optional["MetricsRegistry"] = None,
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        self.overflows = 0
        if not self.labelnames:
            # the unlabeled series exists from registration (renders 0)
            self._children[()] = self._new_child()
        if registry is None:
            registry = REGISTRY
        registry.register(self)

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labelvalues: str):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= MAX_CHILDREN:
                    # cardinality guard: collapse the runaway label set
                    self.overflows += 1
                    child = self._children.get(_OVERFLOW_KEY)
                    if child is None:
                        child = self._new_child()
                        self._children[_OVERFLOW_KEY] = child
                    return child
                child = self._new_child()
                self._children[key] = child
            return child

    def remove(self, **labelvalues: str) -> None:
        """Drop one child series. For pull-gauges whose owner is going
        away for good (e.g. a replica pool shrinking on hot-swap) —
        without this the dead series scrapes as a misleading constant
        forever. No-op when the series does not exist."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    def _iter_children(self):
        with self._lock:
            return list(self._children.items())

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()"
            )
        return self._children[()]


class Counter(Metric):
    kind = "counter"

    def _new_child(self) -> CounterChild:
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    @property
    def value(self) -> float:
        return sum(c.value for _, c in self._iter_children())


class Gauge(Metric):
    kind = "gauge"

    def _new_child(self) -> GaugeChild:
        return GaugeChild()

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._unlabeled().set_function(fn)

    @property
    def value(self) -> float:
        return sum(c.value for _, c in self._iter_children())


class Histogram(Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Iterable[str] = (),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
        registry: Optional["MetricsRegistry"] = None,
    ) -> None:
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        super().__init__(name, help, labelnames, registry=registry)

    def _new_child(self) -> HistogramChild:
        return HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._unlabeled().observe(value)


class MetricsRegistry:
    """Collection of metrics with text exposition.

    ``REGISTRY`` is the process-wide default every instrument in
    ``obs.instruments`` registers into; tests pass private registries.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(f"duplicate metric name {metric.name!r}")
            self._metrics[metric.name] = metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    # -- test/inspection helpers -------------------------------------------

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def sample(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        """Current value of one series (counters/gauges) — test helper."""
        m = self.get(name)
        if m is None:
            raise KeyError(name)
        key = tuple(str((labels or {})[n]) for n in m.labelnames)
        child = m._children.get(key)
        if child is None:
            return 0.0
        return child.value

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for m in self.collect():
            out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for key, child in sorted(m._iter_children()):
                if key == _OVERFLOW_KEY:
                    names, values = ("overflow",), ("true",)
                else:
                    names, values = m.labelnames, key
                if isinstance(child, HistogramChild):
                    with child._lock:
                        counts = list(child.counts)
                        s, n = child._sum, child._count
                    cum = 0
                    for b, c in zip(
                        list(m.buckets) + [math.inf], counts
                    ):
                        cum += c
                        le = _format_value(b)
                        lbl = _format_labels(names, values, f'le="{le}"')
                        out.append(f"{m.name}_bucket{lbl} {cum}")
                    lbl = _format_labels(names, values)
                    out.append(f"{m.name}_sum{lbl} {_format_value(s)}")
                    out.append(f"{m.name}_count{lbl} {n}")
                else:
                    lbl = _format_labels(names, values)
                    out.append(f"{m.name}{lbl} {_format_value(child.value)}")
        return "\n".join(out) + "\n"


REGISTRY = MetricsRegistry()
