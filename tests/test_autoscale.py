"""SLO-burn autoscaler: control law, ladder mechanics, token identity.

The controller (serving/autoscale.py) is driven through tick() with
injected clocks and a private SLOEngine, over REAL tiny pools — module-
scoped params keep the engine builds cheap. The satellites ride along:
the degraded-admission priority floor, the devprof-seeded assumed-TPS
cold-start rate, and the /livez-vs-/healthz split under controller
action.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from aios_tpu.engine import model as model_mod
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.obs import flightrec
from aios_tpu.obs.http import start_metrics_server
from aios_tpu.obs.slo import SLOConfig, SLOEngine, annotate_health
from aios_tpu.serving import (
    AdmissionController,
    AdmissionError,
    AutoscaleConfig,
    AutoscaleController,
    ReplicaPool,
    ServingConfig,
)
from aios_tpu.serving.autoscale import ACTIONS, CAUSES, LADDER

CFG = TINY_TEST.scaled(name="autoscale-test", max_context=128)


@pytest.fixture(scope="module")
def params():
    return model_mod.init_params(CFG, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)


def make_engine(params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_context", 128)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(CFG, params, **kw)


def make_pool(params, name="autoscale-test", speculative=False, **ekw):
    return ReplicaPool(
        name, [make_engine(params, **ekw)],
        lambda e: ContinuousBatcher(e, chunk_steps=2, admit_chunk_steps=2,
                                    speculative=speculative),
        ServingConfig(replicas=1),
    )


def tight_slo():
    """Targets real CPU latencies always miss -> burn far above 1."""
    return SLOEngine(SLOConfig(ttft_ms=0.001, tpot_ms=0.001, target=0.99,
                               window_secs=30.0, min_samples=4))


def calm_slo():
    return SLOEngine(SLOConfig(ttft_ms=60_000, tpot_ms=60_000, target=0.9,
                               window_secs=30.0, min_samples=4))


def feed(slo, model, *, bad: bool, n=8, now=None):
    ms = 100.0 if bad else 0.0001
    for _ in range(n):
        slo.record(model, ttft_ms=ms, tpot_ms=ms, ok=True, now=now)


def controller(pool, slo, factory=None, **over):
    kw = dict(max_replicas=2, hold_ticks=1, cooldown_secs=0.0)
    kw.update(over)
    return AutoscaleController(pool, AutoscaleConfig(**kw),
                               engine_factory=factory, slo_engine=slo)


# ---------------------------------------------------------------------------
# control law
# ---------------------------------------------------------------------------


def test_scale_up_then_full_ladder_then_recovery(params):
    """The acceptance arc in one run: burn scales to the ceiling, then
    walks the ladder in declared order; recovery walks it back BEFORE
    giving the replica up, and the journal records every action with a
    closed-enum (action, cause)."""
    pool = make_pool(params)
    slo = tight_slo()
    ctl = controller(pool, slo, factory=lambda: make_engine(params))
    try:
        t0 = time.monotonic()
        feed(slo, pool.name, bad=True, now=t0)
        assert ctl.tick(now=t0) == "scale_up"
        assert len(pool.replicas) == 2
        # replica added mid-degrade inherits level (none yet) and serves
        for i, expect in enumerate(("degrade",) * 3):
            assert ctl.tick(now=t0 + 0.1 * (i + 1)) == expect
        assert pool.degrade_level == 3
        assert ctl.tick(now=t0 + 1.0) == "saturated"
        rungs = [a["rung"] for a in ctl.actions()
                 if a["action"] == "degrade"]
        assert rungs == list(LADDER)
        # every batcher carries the switches; admission carries the floor
        for r in pool.replicas:
            assert r.batcher.degrade_spec and r.batcher.degrade_jump
        assert pool.admission.min_priority == 1
        # recovery: bad samples age out of the window, good ones land
        t1 = t0 + 120.0
        feed(slo, pool.name, bad=False, now=t1)
        seq = [ctl.tick(now=t1 + 0.1 * i) for i in range(5)]
        assert seq == ["restore", "restore", "restore", "scale_down",
                       "steady"]
        assert pool.degrade_level == 0 and len(pool.replicas) == 1
        assert pool.admission.min_priority == 0
        for a in ctl.actions():
            assert a["action"] in ACTIONS and a["cause"] in CAUSES
    finally:
        pool.shutdown()


def test_no_factory_degrades_without_scaling(params):
    pool = make_pool(params)
    slo = tight_slo()
    ctl = controller(pool, slo, factory=None)
    try:
        t0 = time.monotonic()
        feed(slo, pool.name, bad=True, now=t0)
        assert ctl.tick(now=t0) == "degrade"
        assert len(pool.replicas) == 1 and pool.degrade_level == 1
        assert ctl.actions()[0]["cause"] == "burn"  # not at a ceiling
    finally:
        pool.shutdown()


def test_hysteresis_hold_ticks_and_cooldown(params):
    pool = make_pool(params)
    slo = tight_slo()
    ctl = controller(pool, slo, hold_ticks=2, cooldown_secs=5.0)
    try:
        t0 = time.monotonic()
        feed(slo, pool.name, bad=True, now=t0)
        assert ctl.tick(now=t0) == "hold"  # 1 of 2
        assert ctl.tick(now=t0 + 0.1) == "degrade"  # 2 of 2
        # next escalation wants 2 fresh holds AND the cooldown
        assert ctl.tick(now=t0 + 0.2) == "hold"
        assert ctl.tick(now=t0 + 0.3) == "cooldown"
        assert pool.degrade_level == 1  # no flap
        # past the cooldown (samples still in the window): acts again
        assert ctl.tick(now=t0 + 6.0) == "degrade"
    finally:
        pool.shutdown()


def test_quiescent_on_healthy_and_on_empty_window(params):
    """Zero actions on a healthy run — the acceptance's quiescence
    line — and zero on a cold pool (no evaluable window)."""
    pool = make_pool(params)
    slo = calm_slo()
    ctl = controller(pool, slo, factory=lambda: make_engine(params))
    try:
        assert ctl.tick() == "idle"  # no samples at all
        t0 = time.monotonic()
        slo.record(pool.name, ttft_ms=5.0, tpot_ms=5.0, ok=True, now=t0)
        assert ctl.tick(now=t0) == "idle"  # below min_samples
        feed(slo, pool.name, bad=False, now=t0)
        for i in range(6):
            assert ctl.tick(now=t0 + 0.1 * i) in ("hold", "steady")
        assert ctl.actions() == []
    finally:
        pool.shutdown()


def test_kill_switch_restores_and_freezes(params, monkeypatch):
    pool = make_pool(params)
    slo = tight_slo()
    ctl = controller(pool, slo)
    try:
        t0 = time.monotonic()
        feed(slo, pool.name, bad=True, now=t0)
        ctl.tick(now=t0)
        ctl.tick(now=t0 + 0.1)
        assert pool.degrade_level == 2
        monkeypatch.setenv("AIOS_TPU_AUTOSCALE_KILL", "1")
        assert ctl.tick(now=t0 + 0.2) == "kill"
        assert pool.degrade_level == 0  # restored
        assert ctl.actions()[-1]["cause"] == "kill_switch"
        n = len(ctl.actions())
        assert ctl.tick(now=t0 + 0.3) == "kill"  # frozen, no new action
        assert len(ctl.actions()) == n
        monkeypatch.delenv("AIOS_TPU_AUTOSCALE_KILL")
        assert ctl.tick(now=t0 + 0.4) in ("hold", "degrade")  # live again
    finally:
        pool.shutdown()


def test_autoscale_metric_children_and_model_event(params):
    from aios_tpu.obs import instruments as obs

    pool = make_pool(params)
    slo = tight_slo()
    ctl = controller(pool, slo)
    try:
        t0 = time.monotonic()
        feed(slo, pool.name, bad=True, now=t0)
        before = obs.AUTOSCALE_ACTIONS.labels(
            model=pool.name, action="degrade", cause="burn"
        ).value
        ctl.tick(now=t0)
        after = obs.AUTOSCALE_ACTIONS.labels(
            model=pool.name, action="degrade", cause="burn"
        ).value
        assert after == before + 1
        kinds = [k for _, m, k, _ in flightrec.RECORDER.model_events(
            pool.name)]
        assert "autoscale" in kinds
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# pool mechanics: elastic replicas + degraded admission
# ---------------------------------------------------------------------------


def test_add_remove_replica_serve_and_drain(params):
    pool = make_pool(params)
    try:
        idx = pool.add_replica(make_engine(params))
        assert idx == 1 and len(pool.replicas) == 2
        # both replicas serve; new batcher inherits pool-level hooks
        hs = [pool.submit(Request(prompt_ids=[5 + i, 3], max_tokens=4,
                                  temperature=0.0,
                                  request_id=f"ar-{i}"))
              for i in range(4)]
        assert all(len(h.tokens()) == 4 for h in hs)
        victim = pool.remove_replica()
        assert victim is not None and len(pool.replicas) == 1
        assert victim.batcher._closed  # drained + shut down
        victim.engine.close()
        # pool still serves after the shrink
        h = pool.submit(Request(prompt_ids=[9, 3], max_tokens=3,
                                temperature=0.0))
        assert len(h.tokens()) == 3
        assert pool.remove_replica() is None  # never below one
    finally:
        pool.shutdown()


def test_degraded_admission_sheds_best_effort_protects_reactive(params):
    pool = make_pool(params)
    try:
        pool.set_degrade_level(3)
        with pytest.raises(AdmissionError) as err:
            pool.submit(Request(prompt_ids=[1, 2], max_tokens=2,
                                temperature=0.0, priority=0))
        assert err.value.cause == "degraded"
        assert err.value.retry_after_ms > 0
        # the reactive/operational tier (priority >= 1) keeps admitting
        h = pool.submit(Request(prompt_ids=[1, 2], max_tokens=2,
                                temperature=0.0, priority=1))
        assert len(h.tokens()) == 2
        assert pool.stats()["shed_degraded"] == 1
        pool.set_degrade_level(0)
        h = pool.submit(Request(prompt_ids=[1, 3], max_tokens=2,
                                temperature=0.0, priority=0))
        assert len(h.tokens()) == 2
    finally:
        pool.shutdown()


def test_spawned_batcher_inherits_degrade_level(params):
    pool = make_pool(params)
    try:
        pool.set_degrade_level(2)
        idx = pool.add_replica(make_engine(params))
        b = pool.replicas[idx].batcher
        assert b.degrade_spec and b.degrade_jump
    finally:
        pool.shutdown()


def test_streams_token_identical_across_ladder_transitions(params):
    """The acceptance's stream-identity line: a greedy wave decoded
    while the pool walks 0 -> 1 -> 2 -> 3 mid-stream matches a wave on
    an untouched control pool (speculative batchers, so rung 1 flips a
    real mechanism)."""
    prompts = [[3 + i, 7, 11, 13] for i in range(4)]

    def wave(pool, degrade=False):
        hs = [pool.submit(Request(prompt_ids=p, max_tokens=24,
                                  temperature=0.0, priority=1,
                                  request_id=f"ladder-{i}"))
              for i, p in enumerate(prompts)]
        if degrade:
            for lvl in (1, 2, 3):
                time.sleep(0.02)  # transitions land mid-decode
                pool.set_degrade_level(lvl)
        return [h.tokens() for h in hs]

    control = make_pool(params, speculative=True)
    try:
        expect = wave(control)
    finally:
        control.shutdown()
    pool = make_pool(params, speculative=True)
    try:
        got = wave(pool, degrade=True)
        assert got == expect
        assert pool.degrade_level == 3
        # and back down, still identical
        pool.set_degrade_level(0)
        assert wave(pool) == expect
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# satellite: devprof-seeded assumed-TPS cold-start floor
# ---------------------------------------------------------------------------


def test_assumed_rate_env_knob_wins_over_devprof_seed():
    adm = AdmissionController(
        ServingConfig(assumed_tokens_per_sec=50.0), "ats-env"
    )
    adm.devprof_rate_fn = lambda: 999.0
    assert adm.assumed_rate() == 50.0  # the knob wins when set
    adm2 = AdmissionController(ServingConfig(), "ats-seed")
    adm2.devprof_rate_fn = lambda: 200.0
    assert adm2.assumed_rate() == 200.0  # devprof seeds the cold floor
    adm3 = AdmissionController(ServingConfig(), "ats-cold")
    assert adm3.assumed_rate() == 0.0  # nothing: gate stays cold-off


def test_devprof_seed_drives_cold_deadline_gate():
    """With a devprof-seeded rate, the feasibility gate sheds an
    infeasible request even before any rate was observed (the stale
    hardcoded floor this satellite replaces would have mis-judged)."""
    adm = AdmissionController(ServingConfig(), "ats-gate")
    adm.devprof_rate_fn = lambda: 10.0
    with pytest.raises(AdmissionError) as err:
        adm.check_deadline(1.0, 400, 100, 0.0)  # 500 tok at 10/s >> 1 s
    assert err.value.cause == "deadline"
    adm.check_deadline(120.0, 400, 100, 0.0)  # feasible at the seed


def test_pool_devprof_rate_reads_step_ledger(params):
    from aios_tpu.obs.devprof import DevprofLedger

    pool = make_pool(params, name="ats-pool")
    try:
        assert pool._devprof_rate() == 0.0  # unarmed: no ledgers
        led = DevprofLedger("ats-pool", device_kind="", sample_n=1)
        led.note("step", None)
        led.sample("step", None, 0.05)  # 50 ms per step-dispatch
        steps = pool.replicas[0].batcher.chunk_steps
        assert pool._devprof_rate() == pytest.approx(steps / 0.05)
        # and it is wired as the admission fallback
        assert pool.admission.assumed_rate() == pytest.approx(
            steps / 0.05
        )
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# satellite: /livez vs /healthz under controller action
# ---------------------------------------------------------------------------


def test_livez_stays_200_while_controller_degrades_and_healthz_503s(
    params,
):
    """Restart probes must never kill a warmed process because the
    autoscaler is mid-mitigation: /livez answers 200 through breach +
    every ladder transition while /healthz flips to 503 (LB rotation),
    and the pool keeps serving protected traffic throughout."""
    model = "livez-ctl"
    slo = SLOEngine(SLOConfig(ttft_ms=0.001, tpot_ms=0.001, target=0.99,
                              window_secs=30.0, min_samples=4))
    pool = make_pool(params, name=model)
    ctl = controller(pool, slo)
    def health_fn():
        breached = [
            m for m in slo.models()
            if any(o["breached"] for o in slo.evaluate(m).values())
        ]
        payload = {"status": "ok", "service": "runtime"}
        if breached:
            payload["status"] = "degraded"
            payload["slo_breached"] = breached
        return payload

    server, port = start_metrics_server(port=0, health_fn=health_fn)

    def get(path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    try:
        assert get("/livez")[0] == 200
        assert get("/healthz")[0] == 200
        t0 = time.monotonic()
        feed(slo, model, bad=True, now=t0)
        for i in range(3):  # breach -> controller walks the ladder
            ctl.tick(now=t0 + 0.1 * i)
        assert pool.degrade_level == 3
        code, body = get("/healthz")
        assert code == 503 and model in body["slo_breached"]
        # liveness is UNTOUCHED by breach or degrade — and the warmed
        # process demonstrably survives: it still serves protected work
        assert get("/livez")[0] == 200
        h = pool.submit(Request(prompt_ids=[2, 4], max_tokens=3,
                                temperature=0.0, priority=1))
        assert len(h.tokens()) == 3
    finally:
        server.shutdown()
        pool.shutdown()
