#!/usr/bin/env python3
"""Fleet telemetry smoke: two REAL processes federate, stitch, and die
deterministically (the preflight.sh gate 6; docs/TESTING.md).

One round:

  1. spawn worker A (scripts/fleet_worker.py, fleet armed, no peers)
     and worker B seeded with A's bound metrics endpoint — membership
     converges through announce gossip;
  2. poll A's ``/fleet/members`` until BOTH members are "up", and
     assert A's ``/healthz`` carries the actual bound ``metrics_port``
     (the ephemeral-port discoverability contract);
  3. assert ``/metrics/fleet`` on A carries
     ``aios_tpu_fleet_member_up_total`` samples for both host labels;
  4. issue one traced request to EACH worker under a single client span
     (the interceptors carry the traceparent across the gRPC boundary)
     and assert ``/debug/trace/fleet?trace=<id>`` renders ONE stitched
     Chrome trace with a lane group per host;
  5. ``fleetctl status`` against A exits 0 showing both members;
  6. kill B and poll A's journal until the ``up -> suspect -> dead``
     edges land; assert ``/metrics/fleet`` dropped hostB's samples.

The whole round runs TWICE; the membership-transition journals —
normalized to (host, role, from, to) — must be identical across runs
(the failure detector is deterministic given the same death). Human
progress goes to stderr; ONE JSON verdict line goes to stdout. Exit 0
on pass.

Tuned short via the AIOS_TPU_FLEET_*_SECS knobs; FLEET_SMOKE_TIME_SCALE
stretches every window and timeout on slow containers.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

SCALE = float(os.environ.get("FLEET_SMOKE_TIME_SCALE", "1") or 1)
INTERVAL = 0.3 * SCALE
SUSPECT = 1.5 * SCALE
DEAD = 3.0 * SCALE
MODEL = "fleet-smoke"


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def worker_env(host_id: str, peers: str = "") -> dict:
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
        "AIOS_TPU_FLEET": "1",
        # explicit host ids: the default includes the pid, which would
        # make the cross-run journal comparison vacuously fail
        "AIOS_TPU_FLEET_HOST": host_id,
        "AIOS_TPU_FLEET_PEERS": peers,
        "AIOS_TPU_FLEET_INTERVAL_SECS": str(INTERVAL),
        "AIOS_TPU_FLEET_SUSPECT_SECS": str(SUSPECT),
        "AIOS_TPU_FLEET_DEAD_SECS": str(DEAD),
    }


def spawn_worker(host_id: str, peers: str = "") -> tuple:
    """-> (Popen, grpc_port, metrics_port); waits for the ready line."""
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_worker.py")],
        env=worker_env(host_id, peers), cwd=REPO,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    deadline = time.monotonic() + 180 * SCALE
    while True:
        line = p.stdout.readline()
        if line.startswith("FLEET_WORKER_READY "):
            ports = json.loads(line.split(" ", 1)[1])
            return p, ports["grpc_port"], ports["metrics_port"]
        if not line and p.poll() is not None:
            raise RuntimeError(f"worker {host_id} died before ready")
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError(f"worker {host_id} never became ready")


def fetch_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_text(port: int, path: str) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode("utf-8")


def poll(fn, what: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1 * SCALE)
    raise RuntimeError(f"timed out waiting for {what}")


def traced_requests(ports: list) -> str:
    """One client span wrapping one Infer per worker -> the trace id
    both processes' flight recorders now share."""
    from aios_tpu import rpc, services
    from aios_tpu.obs import tracing
    from aios_tpu.proto_gen import runtime_pb2

    with tracing.start_span("fleet-smoke") as span:
        for i, port in enumerate(ports):
            channel = rpc.insecure_channel(f"127.0.0.1:{port}")
            try:
                services.AIRuntimeStub(channel).Infer(
                    runtime_pb2.InferRequest(
                        model=MODEL, prompt="stitch me across the fleet",
                        max_tokens=4, temperature=5e-5,
                        task_id=f"fleet-smoke-{i}",
                    ),
                    timeout=120,
                )
            finally:
                channel.close()
        return span.trace_id


def norm_journal(journal: list) -> list:
    return [(e["host"], e["role"], e["from"], e["to"]) for e in journal]


def run_round(tag: str) -> list:
    """One full smoke round -> the normalized journal from worker A."""
    pa, grpc_a, metrics_a = spawn_worker("hostA")
    pb = None
    try:
        pb, grpc_b, metrics_b = spawn_worker(
            "hostB", peers=f"127.0.0.1:{metrics_a}"
        )
        log(f"[{tag}] workers up: A grpc={grpc_a} metrics={metrics_a}, "
            f"B grpc={grpc_b} metrics={metrics_b}")

        # ephemeral-port discoverability: /healthz names the bound port
        hz = fetch_json(metrics_a, "/healthz")
        assert hz.get("metrics_port") == metrics_a, hz

        def both_up():
            members = fetch_json(metrics_a, "/fleet/members")["members"]
            ups = {m["host"] for m in members if m["state"] == "up"}
            return ups == {"hostA", "hostB"}

        poll(both_up, "both members up on A", 30 * SCALE)
        log(f"[{tag}] membership converged")

        def federated():
            text = fetch_text(metrics_a, "/metrics/fleet")
            # process_info is a series only its OWN process exports
            # (identity in labels) — seeing hostB's proves the scrape,
            # not just A's bookkeeping about B
            return ('aios_tpu_fleet_member_up_total{host="hostA"' in text
                    and 'aios_tpu_process_info{host="hostB"' in text)

        poll(federated, "both hosts in /metrics/fleet", 15 * SCALE)
        log(f"[{tag}] federation carries both host labels")

        trace = traced_requests([grpc_a, grpc_b])

        def stitched():
            got = fetch_json(
                metrics_a, f"/debug/trace/fleet?trace={trace}"
            )
            hosts = {
                ev["args"]["name"].split(" ", 1)[0]
                for ev in got.get("traceEvents", [])
                if ev.get("name") == "process_name"
            }
            return {"host:hostA", "host:hostB"} <= hosts
        poll(stitched, "two host lanes in the stitched trace", 15 * SCALE)
        log(f"[{tag}] stitched trace {trace} has both host lanes")

        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleetctl.py"),
             "status", "--target", f"127.0.0.1:{metrics_a}"],
            cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode
        assert rc == 0, f"fleetctl status exited {rc} with both members up"
        log(f"[{tag}] fleetctl status: 0")

        pb.kill()
        pb.wait()
        pb = None

        def b_dead():
            members = fetch_json(metrics_a, "/fleet/members")["members"]
            return any(m["host"] == "hostB" and m["state"] == "dead"
                       for m in members)

        poll(b_dead, "hostB aging to dead", (DEAD + 10) * SCALE)
        # the dead host's SCRAPED series are gone; A's own membership
        # gauge about hostB legitimately stays (member_up=0 + absence of
        # hostB's self-exported series IS the death signal)
        text = fetch_text(metrics_a, "/metrics/fleet")
        assert 'aios_tpu_process_info{host="hostB"' not in text, \
            "/metrics/fleet still carries the dead host's scraped series"
        assert ('aios_tpu_fleet_member_up_total{host="hostB"'
                ',role="runtime"} 0' in text), \
            "member_up gauge for the dead host should read 0"
        journal = norm_journal(
            fetch_json(metrics_a, "/fleet/members")["journal"]
        )
        log(f"[{tag}] hostB suspect->dead observed; journal: {journal}")
        return journal
    finally:
        for p in (pa, pb):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def main() -> int:
    journals = [run_round("round1"), run_round("round2")]
    identical = journals[0] == journals[1]
    expected_edges = [
        ("hostB", "runtime", "", "up"),
        ("hostB", "runtime", "up", "suspect"),
        ("hostB", "runtime", "suspect", "dead"),
    ]
    has_lifecycle = all(e in journals[0] for e in expected_edges)
    verdict = {
        "smoke": "fleet",
        "journal": [list(e) for e in journals[0]],
        "identical": identical,
        "lifecycle": has_lifecycle,
        "pass": identical and has_lifecycle,
    }
    print(json.dumps(verdict, sort_keys=True))
    if not identical:
        log("FAIL: membership journals diverged across seeded runs:")
        log(f"  round1: {journals[0]}")
        log(f"  round2: {journals[1]}")
    if not has_lifecycle:
        log(f"FAIL: lifecycle edges missing from {journals[0]}")
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
