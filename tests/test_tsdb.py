"""Black-box time-series ring units (aios_tpu/obs/tsdb.py, ISSUE 20).

Deterministic tier: config/arming, the sampler's delta/gauge/histogram
flattening on an injected clock, ring -> wheel downsample math, counter
resets, the cardinality-cap drop accounting, the closed-verb query form,
the window snapshot incidents freeze, and the HTTP surface (including
the /debug route index). One engine-backed test pins the ON/OFF
invariant: a pipelined batcher's token stream is identical with the
sampler thread running hot.
"""

import json
import urllib.error
import urllib.request

import pytest

from aios_tpu.obs import tsdb
from aios_tpu.obs.metrics import Counter, Gauge, Histogram, MetricsRegistry
from aios_tpu.obs.tsdb import Tsdb, TsdbConfig


def _cfg(**kw) -> TsdbConfig:
    cfg = TsdbConfig()
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _ring(now, registry, **kw) -> Tsdb:
    return Tsdb(cfg=_cfg(**kw), registry=registry,
                clock=lambda: now[0])


# -- config / arming --------------------------------------------------------


def test_config_defaults_off(monkeypatch):
    for var in ("AIOS_TPU_TSDB", "AIOS_TPU_TSDB_STEP_SECS",
                "AIOS_TPU_TSDB_MAX_SERIES"):
        monkeypatch.delenv(var, raising=False)
    cfg = TsdbConfig()
    assert not cfg.enabled
    assert cfg.step_secs == 1.0
    assert cfg.raw_secs == 300.0
    assert cfg.wheel_step_secs == 10.0
    assert cfg.wheel_secs == 3600.0
    assert cfg.max_series == 4096
    assert cfg.raw_slots == 300
    assert cfg.wheel_slots == 360


def test_config_env_parsing_and_clamps(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_TSDB", "1")
    monkeypatch.setenv("AIOS_TPU_TSDB_STEP_SECS", "0.5")
    monkeypatch.setenv("AIOS_TPU_TSDB_RAW_SECS", "60")
    monkeypatch.setenv("AIOS_TPU_TSDB_WHEEL_STEP_SECS", "5")
    monkeypatch.setenv("AIOS_TPU_TSDB_WHEEL_SECS", "600")
    monkeypatch.setenv("AIOS_TPU_TSDB_MAX_SERIES", "128")
    cfg = TsdbConfig()
    assert cfg.enabled
    assert (cfg.step_secs, cfg.raw_secs) == (0.5, 60.0)
    assert (cfg.wheel_step_secs, cfg.wheel_secs) == (5.0, 600.0)
    assert cfg.max_series == 128
    assert cfg.raw_slots == 120
    monkeypatch.setenv("AIOS_TPU_TSDB_STEP_SECS", "0.0001")  # clamps
    assert TsdbConfig().step_secs == 0.05
    monkeypatch.setenv("AIOS_TPU_TSDB_STEP_SECS", "oops")  # default
    assert TsdbConfig().step_secs == 1.0


def test_maybe_start_noop_when_unarmed(monkeypatch):
    monkeypatch.delenv("AIOS_TPU_TSDB", raising=False)
    prev = tsdb.install(None)
    try:
        assert tsdb.maybe_start() is None
        assert tsdb.TSDB is None and not tsdb.enabled()
        assert tsdb.trend("aios_tpu_whatever_total") is None
    finally:
        tsdb.install(prev)


def test_maybe_start_arms_and_is_idempotent(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_TSDB", "1")
    monkeypatch.setenv("AIOS_TPU_TSDB_STEP_SECS", "30")
    prev = tsdb.install(None)
    try:
        ring = tsdb.maybe_start()
        assert ring is not None and tsdb.enabled()
        assert tsdb.maybe_start() is ring  # second call: the same ring
        ring.stop()
    finally:
        tsdb.install(prev)


# -- sampler semantics ------------------------------------------------------


def test_counters_sample_as_deltas_gauges_raw():
    reg = MetricsRegistry()
    c = Counter("aios_tpu_t_flow_total", "h", registry=reg)
    g = Gauge("aios_tpu_t_level_ratio", "h", registry=reg)
    now = [100.0]
    ring = _ring(now, reg)
    g.set(7.0)
    ring.sample_once()  # counter pass 1: prev only, no point yet
    for _ in range(3):
        now[0] += 1.0
        c.inc(5.0)
        g.set(now[0])
        ring.sample_once()
    got = ring.query("aios_tpu_t_flow_total", verb="raw", window=60)
    (s,) = got["series"]
    assert s["kind"] == "delta"
    assert [v for _, v in s["points"]] == [5.0, 5.0, 5.0]
    got = ring.query("aios_tpu_t_level_ratio", verb="raw", window=60)
    (s,) = got["series"]
    assert s["kind"] == "gauge"
    assert [v for _, v in s["points"]] == [7.0, 101.0, 102.0, 103.0]


def test_labeled_children_become_distinct_series():
    reg = MetricsRegistry()
    c = Counter("aios_tpu_t_req_total", "h", ("model",), registry=reg)
    c.labels(model="a").inc(2.0)
    c.labels(model="b").inc(3.0)
    now = [0.0]
    ring = _ring(now, reg)
    ring.sample_once()
    now[0] += 1.0
    c.labels(model="a").inc(4.0)
    ring.sample_once()
    got = ring.query("aios_tpu_t_req_total", verb="rate", window=1)
    by_label = {s["labels"]["model"]: s["value"] for s in got["series"]}
    assert by_label == {"a": 4.0, "b": 0.0}
    # matchers narrow the selection
    got = ring.query("aios_tpu_t_req_total", {"model": "a"}, verb="rate",
                     window=1)
    assert len(got["series"]) == 1


def test_counter_reset_respawn_becomes_delta_not_negative_spike():
    """A respawned process's counter restarts from zero: the sampled
    total DROPS. rate() must fold the new total in as the delta since
    the reset — never a negative rate (the Prometheus reset rule)."""
    reg = MetricsRegistry()
    c = Counter("aios_tpu_t_reset_total", "h", registry=reg)
    now = [0.0]
    ring = _ring(now, reg)
    c.inc(100.0)
    ring.sample_once()          # prev = 100
    now[0] += 1.0
    c.inc(10.0)
    ring.sample_once()          # delta 10
    # simulate the respawn: a FRESH registry child starting over
    with c._lock:
        c._children[()].__init__()
    c.inc(3.0)
    now[0] += 1.0
    ring.sample_once()          # 3 < 110 -> delta = 3 (the new total)
    got = ring.query("aios_tpu_t_reset_total", verb="raw", window=60)
    assert [v for _, v in got["series"][0]["points"]] == [10.0, 3.0]
    got = ring.query("aios_tpu_t_reset_total", verb="rate", window=2)
    assert got["series"][0]["value"] == pytest.approx(13.0 / 2)


def test_nan_fn_gauge_skipped():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_sick_ratio", "h", registry=reg)
    g.set_function(lambda: 1 / 0)  # value property -> nan
    ring = _ring([0.0], reg)
    assert ring.sample_once() == 0
    assert ring.series_count() == 0


# -- ring -> wheel downsample math ------------------------------------------


def test_wheel_downsample_math_vs_injected_clock():
    """Raw ring 10 x 1s, wheel 10s buckets: after 40 passes the query
    window is served by raw points for the recent 10s and flushed wheel
    buckets (gauge: bucket average; delta: bucket sum) for the rest —
    and the numbers are EXACT."""
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_wave_ratio", "h", registry=reg)
    c = Counter("aios_tpu_t_tick_total", "h", registry=reg)
    now = [0.0]
    ring = _ring(now, reg, step_secs=1.0, raw_secs=10.0,
                 wheel_step_secs=10.0, wheel_secs=100.0)
    for t in range(40):
        now[0] = float(t)
        g.set(float(t))
        c.inc(1.0)
        ring.sample_once()
    got = ring.query("aios_tpu_t_wave_ratio", verb="raw", window=40)
    pts = got["series"][0]["points"]
    # raw covers t=30..39; flushed buckets 0/10/20 render their average
    assert pts[:3] == [[0.0, 4.5], [10.0, 14.5], [20.0, 24.5]]
    assert [v for _, v in pts[3:]] == [float(t) for t in range(30, 40)]
    assert got["series"][0]["kind"] == "gauge"
    avg = ring.query("aios_tpu_t_wave_ratio", verb="avg", window=40)
    expect = (4.5 + 14.5 + 24.5 + sum(range(30, 40))) / 13
    assert avg["series"][0]["value"] == pytest.approx(expect)
    assert ring.query("aios_tpu_t_wave_ratio", verb="min",
                      window=40)["series"][0]["value"] == 4.5
    assert ring.query("aios_tpu_t_wave_ratio", verb="max",
                      window=40)["series"][0]["value"] == 39.0
    # delta series: wheel buckets render the SUM (counts, not averages)
    got = ring.query("aios_tpu_t_tick_total", verb="raw", window=40)
    pts = got["series"][0]["points"]
    # pass 0 only set prev; bucket 0 holds 9 deltas, 10/20 hold 10
    assert pts[:3] == [[0.0, 9.0], [10.0, 10.0], [20.0, 20.0 - 10.0]]
    rate = ring.query("aios_tpu_t_tick_total", verb="rate", window=40)
    assert rate["series"][0]["value"] == pytest.approx(39.0 / 40)


def test_raw_ring_is_bounded():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_b_ratio", "h", registry=reg)
    now = [0.0]
    ring = _ring(now, reg, step_secs=1.0, raw_secs=5.0)
    for t in range(50):
        now[0] = float(t)
        g.set(1.0)
        ring.sample_once()
    with ring._lock:
        (s,) = [x for x in ring._series.values()
                if x.name == "aios_tpu_t_b_ratio"]
        assert len(s.raw) == 5


def test_histogram_buckets_count_sum_and_quantile():
    reg = MetricsRegistry()
    h = Histogram("aios_tpu_t_lat_seconds", "h",
                  buckets=(0.1, 1.0, 10.0), registry=reg)
    now = [0.0]
    ring = _ring(now, reg)
    ring.sample_once()  # zero baseline (prev)
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    now[0] += 1.0
    ring.sample_once()
    names = {s.name for s in ring._series.values()}
    assert {"aios_tpu_t_lat_seconds_bucket",
            "aios_tpu_t_lat_seconds_count",
            "aios_tpu_t_lat_seconds_sum"} <= names
    got = ring.query("aios_tpu_t_lat_seconds_sum", verb="rate", window=1)
    assert got["series"][0]["value"] == pytest.approx(6.05)
    # p50: rank 2 of 4 lands in the (0.1, 1.0] bucket, interpolated
    got = ring.query("aios_tpu_t_lat_seconds", verb="p50", window=60)
    (s,) = got["series"]
    assert s["samples"] == 4.0
    assert s["value"] == pytest.approx(0.1 + (1.0 - 0.1) * 0.5)
    # p99: rank 3.96 of 4 interpolates inside the (1.0, 10.0] bucket
    got = ring.query("aios_tpu_t_lat_seconds", verb="p99", window=60)
    assert got["series"][0]["value"] == pytest.approx(
        1.0 + (10.0 - 1.0) * 0.96
    )


# -- cardinality cap --------------------------------------------------------


def test_cardinality_cap_counts_each_dropped_series_once():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_many_ratio", "h", ("k",), registry=reg)
    for i in range(24):
        g.labels(k=str(i)).set(float(i))
    now = [0.0]
    ring = _ring(now, reg, max_series=16)
    ring.sample_once()
    assert ring.series_count() == 16
    assert ring.dropped_series() == 8
    # the SAME series dropping again on later passes is not re-counted
    now[0] += 1.0
    ring.sample_once()
    assert ring.dropped_series() == 8
    # a genuinely new series past the cap adds exactly one more
    g.labels(k="late").set(1.0)
    now[0] += 1.0
    ring.sample_once()
    assert ring.dropped_series() == 9
    assert ring.stats()["dropped_series"] == 9


# -- queries ----------------------------------------------------------------


def test_unknown_verb_raises_listing_the_enum():
    ring = _ring([0.0], MetricsRegistry())
    with pytest.raises(ValueError, match="raw, rate, avg"):
        ring.query("aios_tpu_x_total", verb="sum")


def test_rate_on_gauge_is_none():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_g_ratio", "h", registry=reg)
    g.set(5.0)
    now = [0.0]
    ring = _ring(now, reg)
    ring.sample_once()
    got = ring.query("aios_tpu_t_g_ratio", verb="rate", window=10)
    assert got["series"][0]["value"] is None


def test_window_snapshot_bounded_with_explicit_truncation():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_snap_ratio", "h", ("k",), registry=reg)
    for i in range(8):
        g.labels(k=str(i)).set(float(i))
    now = [10.0]
    ring = _ring(now, reg)
    ring.sample_once()
    snap = ring.window_snapshot(0.0, 20.0, max_series=5)
    assert len(snap["series"]) == 5
    assert snap["truncated"] == 3
    assert snap["start"] == 0.0 and snap["end"] == 20.0
    assert all(s["points"] for s in snap["series"])


def test_trend_reads_worst_series():
    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_burn_ratio", "h", ("model",), registry=reg)
    now = [0.0]
    ring = _ring(now, reg)
    prev = tsdb.install(ring)
    try:
        for t in range(3):
            now[0] = float(t)
            g.labels(model="cool").set(0.1)
            g.labels(model="hot").set(float(t))
            ring.sample_once()
        got = tsdb.trend("aios_tpu_t_burn_ratio", window=60)
        assert got["last"] == 2.0 and got["first"] == 0.0
        assert got["points"] == 3
        assert tsdb.trend("aios_tpu_no_such_total") is None
    finally:
        tsdb.install(prev)


def test_handle_query_form():
    reg = MetricsRegistry()
    c = Counter("aios_tpu_t_hq_total", "h", ("model",), registry=reg)
    c.labels(model="m").inc(1.0)
    now = [0.0]
    ring = _ring(now, reg)
    ring.sample_once()
    now[0] += 1.0
    c.labels(model="m").inc(1.0)
    ring.sample_once()
    prev = tsdb.install(ring)
    try:
        payload, status = tsdb.handle_query({})
        assert status == 200 and payload["stats"]["series"] == 1
        payload, status = tsdb.handle_query({
            "name": ["aios_tpu_t_hq_total"], "verb": ["rate"],
            "window": ["1"], "match": ["model:m"],
        })
        assert status == 200
        assert payload["series"][0]["value"] == pytest.approx(1.0)
        _, status = tsdb.handle_query({"name": ["x"], "verb": ["nope"]})
        assert status == 400
        _, status = tsdb.handle_query({"name": ["x"], "match": ["bad"]})
        assert status == 400
        _, status = tsdb.handle_query({"name": ["x"], "window": ["z"]})
        assert status == 400
    finally:
        tsdb.install(prev)
    payload, status = tsdb.handle_query({}) if tsdb.TSDB is None else ({}, 0)
    if status:  # unarmed process: the 404 names the arming knob
        assert status == 404 and "AIOS_TPU_TSDB" in payload["error"]


# -- HTTP surface -----------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.status, r.read().decode()


def test_debug_tsdb_http_and_route_index():
    from aios_tpu.obs import http as obs_http
    from aios_tpu.obs.http import start_metrics_server

    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_http_ratio", "h", registry=reg)
    g.set(3.0)
    ring = _ring([0.0], reg)
    ring.sample_once()
    prev = tsdb.install(ring)
    server, port = start_metrics_server(port=0)
    try:
        status, body = _get(
            port, "/debug/tsdb?name=aios_tpu_t_http_ratio&verb=max"
        )
        assert status == 200
        assert json.loads(body)["series"][0]["value"] == 3.0
        status, body = _get(port, "/debug/tsdb")
        assert status == 200 and json.loads(body)["stats"]["series"] == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/debug/tsdb?name=x&verb=nope")
        assert ei.value.code == 400
        # the /debug index lists every registered route
        status, body = _get(port, "/debug")
        assert status == 200
        listed = {(r["method"], r["route"])
                  for r in json.loads(body)["routes"]}
        assert listed == {(m, r) for m, r, _ in obs_http.ROUTES}
        assert all(h for _, _, h in obs_http.ROUTES)
    finally:
        tsdb.install(prev)
        server.shutdown()


def test_debug_tsdb_404_when_unarmed():
    from aios_tpu.obs.http import start_metrics_server

    prev = tsdb.install(None)
    server, port = start_metrics_server(port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/debug/tsdb")
        assert ei.value.code == 404
    finally:
        tsdb.install(prev)
        server.shutdown()


# -- the ON/OFF invariant (engine tier) -------------------------------------


def test_sampler_on_off_token_streams_identical():
    """The acceptance invariant at unit scale: a pipelined batcher's
    greedy token streams are bit-identical with the sampler thread
    running hot against the process registry — the ring only READS
    instruments, it never perturbs scheduling."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher, Request
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST.scaled(name="tsdb-onoff", max_context=128)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    engine = TPUEngine(cfg, params, num_slots=2, max_context=128,
                       cache_dtype=jnp.float32)
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2,
                          pipeline=True)

    def wave(tag):
        handles = [
            b.submit(Request(prompt_ids=[3 + i, 7, 11], max_tokens=12,
                             temperature=0.0,
                             request_id=f"{tag}-{i}"))
            for i in range(4)
        ]
        return [h.tokens() for h in handles]

    try:
        off = wave("off")
        ring = Tsdb(cfg=_cfg(step_secs=0.01))  # global registry, hot
        prev = tsdb.install(ring)
        ring.start()
        try:
            on = wave("on")
        finally:
            ring.stop()
            tsdb.install(prev)
        assert on == off, "tsdb sampling must not perturb decode"
        assert ring.stats()["passes"] > 0, "the sampler never ran"
        assert ring.series_count() > 0
    finally:
        b.shutdown()
