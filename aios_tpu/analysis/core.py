"""Shared AST substrate for the static rules in :mod:`aios_tpu.analysis`.

Every rule (and the migrated source checks in ``tests/test_obs_lint.py``)
works from the same three primitives so there is ONE way to read the
tree:

  * :class:`ModuleInfo` — a parsed module: AST with parent links, raw
    source lines, class/function tables, and the per-line waiver map;
  * :class:`Finding` — one diagnostic, ``rule`` id + ``path:line`` +
    message, with the waiver resolution already applied;
  * the call helpers (:func:`callee_chain`, :func:`string_call_args`,
    :func:`assigned_string_literals`, :func:`names_used_in`) — the
    AST-shaped replacements for the regex greps the lint tests used to
    carry.

Waiver pragma grammar (inline, same line as the finding or the governing
``with`` statement)::

    # aios: waive(<rule-id>): <mandatory justification>

A waiver with no justification text does not waive anything — it instead
raises its own ``waiver-reason`` finding, so the rationale lives at the
call site or the pragma goes.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

WAIVE_RE = re.compile(
    r"#\s*aios:\s*waive\(\s*([a-z0-9_-]+)\s*\)\s*(?::\s*(\S.*))?"
)


@dataclass
class Finding:
    """One diagnostic. ``path`` is repo-relative, ``line`` 1-indexed."""

    rule: str
    path: str
    line: int
    message: str
    waived: bool = False
    waive_reason: str = ""

    def render(self) -> str:
        tag = " [waived]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "waived": self.waived,
            "waive_reason": self.waive_reason,
        }


@dataclass
class FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str  # "Class.method" or "func"
    class_name: Optional[str]


@dataclass
class ClassInfo:
    node: ast.ClassDef
    bases: Tuple[str, ...]  # base names as written (dotted tails kept)


class ModuleInfo:
    """A parsed module plus the lookup tables every rule needs."""

    def __init__(self, name: str, path: str, source: str) -> None:
        self.name = name  # dotted module name, e.g. "aios_tpu.engine.paged"
        self.path = path  # repo-relative path used in findings
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        _link_parents(self.tree)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self._index_defs()
        # line -> [(rule, reason)] waivers; empty reason kept (and flagged)
        self.waivers: Dict[int, List[Tuple[str, str]]] = {}
        self._index_waivers()

    # -- construction -------------------------------------------------------

    @classmethod
    def from_path(cls, name: str, path: Path, rel: str) -> "ModuleInfo":
        return cls(name, rel, path.read_text())

    @classmethod
    def from_source(cls, source: str, name: str = "fixture",
                    path: str = "<fixture>") -> "ModuleInfo":
        """Inline-snippet constructor for the rule tests."""
        return cls(name, path, source)

    # -- indexing -----------------------------------------------------------

    def _index_defs(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                bases = tuple(
                    _dotted_tail(b) for b in node.bases if _dotted_tail(b)
                )
                self.classes[node.name] = ClassInfo(node, bases)
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        q = f"{node.name}.{sub.name}"
                        self.functions[q] = FuncInfo(sub, q, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = FuncInfo(node, node.name, None)

    def _index_waivers(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = WAIVE_RE.search(text)
            if not m:
                continue
            rule, reason = m.group(1), (m.group(2) or "").strip()
            line = i
            if text.lstrip().startswith("#"):
                # a standalone pragma governs the next code line (stacked
                # pragmas skip over each other and other comments)
                j = i
                while j < len(self.lines) and (
                    not self.lines[j].strip()
                    or self.lines[j].lstrip().startswith("#")
                ):
                    j += 1
                line = j + 1 if j < len(self.lines) else i
            self.waivers.setdefault(line, []).append((rule, reason))

    # -- waiver resolution --------------------------------------------------

    def waiver_for(self, rule: str, *lines: int) -> Optional[str]:
        """The justification text if any of ``lines`` carries a waiver
        for ``rule`` (or the catch-all id ``all``); None otherwise.
        Empty-reason waivers never match — they are findings themselves."""
        for ln in lines:
            for r, reason in self.waivers.get(ln, ()):  # usually empty
                if r in (rule, "all") and reason:
                    return reason
        return None

    def finding(self, rule: str, line: int, message: str,
                *extra_lines: int) -> Finding:
        """Build a finding, resolving waivers at ``line`` plus any
        ``extra_lines`` (e.g. the governing ``with`` statement)."""
        reason = self.waiver_for(rule, line, *extra_lines)
        return Finding(rule, self.path, line, message,
                       waived=reason is not None,
                       waive_reason=reason or "")

    # -- structure helpers --------------------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[FuncInfo]:
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = getattr(cur, "_aios_parent", None)
                if isinstance(cls, ast.ClassDef):
                    return self.functions.get(f"{cls.name}.{cur.name}")
                if isinstance(cls, ast.Module):
                    return self.functions.get(cur.name)
                # nested function: attribute to the outer def
                cur = cls
                continue
            cur = getattr(cur, "_aios_parent", None)
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        cur = node
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = getattr(cur, "_aios_parent", None)
        return None

    def ancestry(self, class_name: str) -> List[str]:
        """``class_name`` plus its in-module base chain (names only)."""
        out, seen = [], set()
        stack = [class_name]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            out.append(c)
            info = self.classes.get(c)
            if info:
                stack.extend(info.bases)
        return out

    def subclasses_of(self, class_name: str) -> List[str]:
        return [
            name for name in self.classes
            if class_name in self.ancestry(name)
        ]


def _link_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._aios_parent = node  # type: ignore[attr-defined]


def _dotted_tail(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


# -- call-shape helpers ------------------------------------------------------


def callee_chain(call: ast.Call) -> List[str]:
    """The dotted name chain of a call's callee, outermost first.

    ``jax.block_until_ready(x)`` -> ``["jax", "block_until_ready"]``;
    ``self._step_fn(n)(args)`` (outer call) -> ``["()", "_step_fn"]`` —
    a leading ``"()"`` marks calling the RESULT of an inner call, whose
    own chain is reported at its own Call node."""
    out: List[str] = []
    cur: ast.AST = call.func
    while True:
        if isinstance(cur, ast.Attribute):
            out.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Name):
            out.append(cur.id)
            break
        elif isinstance(cur, ast.Call):
            out.append("()")
            break
        else:
            break
    out.reverse()
    return out


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def string_call_args(
    root: ast.AST,
    method_names: Sequence[str],
    arg_index: int = 0,
) -> List[Tuple[str, int]]:
    """``(literal, line)`` for every call whose terminal callee name is
    in ``method_names`` and whose ``arg_index``-th positional argument is
    a string literal. The AST replacement for the lint tests' call-site
    regexes — immune to line wrapping and argument whitespace."""
    out: List[Tuple[str, int]] = []
    for call in iter_calls(root):
        chain = callee_chain(call)
        if not chain or chain[-1] not in method_names:
            continue
        if len(call.args) > arg_index:
            arg = call.args[arg_index]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((arg.value, arg.lineno))
    return out


def assigned_string_literals(
    root: ast.AST, attr_name: str
) -> List[Tuple[str, int]]:
    """String literals bound to ``<attr_name>`` anywhere a value can be
    handed to it: attribute assignments (``live.abort_reason = "..."``)
    AND keyword arguments (``self._finish(x, abort_reason="...")``) —
    the old regex lint covered both shapes, so the AST walker must too.
    F-string literal heads count."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(root):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if any(
                isinstance(t, ast.Attribute) and t.attr == attr_name
                for t in targets
            ):
                lit = _string_head(node.value)
                if lit is not None:
                    out.append((lit, node.lineno))
        elif isinstance(node, ast.keyword) and node.arg == attr_name:
            lit = _string_head(node.value)
            if lit is not None:
                out.append((lit, node.value.lineno))
    return out


def _string_head(val: Optional[ast.AST]) -> Optional[str]:
    """A plain str literal, or the leading literal text of an f-string."""
    if isinstance(val, ast.Constant) and isinstance(val.value, str):
        return val.value
    if isinstance(val, ast.JoinedStr) and val.values:
        head = val.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def call_string_heads(
    root: ast.AST, callee: str, arg_index: int = 0
) -> List[Tuple[str, int]]:
    """Like :func:`string_call_args` but also accepts f-string arguments,
    returning their literal head (``_terminate_outstanding(f"evicted: {x}")``
    -> ``"evicted: "``)."""
    out: List[Tuple[str, int]] = []
    for call in iter_calls(root):
        chain = callee_chain(call)
        if not chain or chain[-1] != callee:
            continue
        if len(call.args) > arg_index:
            lit = _string_head(call.args[arg_index])
            if lit is not None:
                out.append((lit, call.lineno))
    return out


def names_used_in(func_node: ast.AST) -> set:
    """Every bare identifier and attribute name referenced in a function
    body — the AST replacement for ``"X" in inspect.getsource(fn)``."""
    out = set()
    for node in ast.walk(func_node):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def string_constants(
    root: ast.AST, pattern: "re.Pattern[str]"
) -> List[Tuple[str, int]]:
    """All string constants fully matching ``pattern`` with their lines."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(root):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if pattern.fullmatch(node.value):
                out.append((node.value, node.lineno))
    return out


# -- module loading ----------------------------------------------------------


def load_package(pkg_root: Path, repo_root: Path,
                 package: str = "aios_tpu") -> List[ModuleInfo]:
    """Parse every ``*.py`` under ``pkg_root`` into ModuleInfos (sorted by
    module name; ``proto_gen`` generated stubs are skipped — machine
    output, not ours to lint)."""
    mods: List[ModuleInfo] = []
    for path in sorted(pkg_root.rglob("*.py")):
        rel = path.relative_to(repo_root).as_posix()
        if "/proto_gen/" in f"/{rel}":
            continue
        parts = list(path.relative_to(pkg_root).with_suffix("").parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        name = ".".join([package] + parts)
        mods.append(ModuleInfo.from_path(name, path, rel))
    return mods


def module_info_for(module) -> ModuleInfo:
    """ModuleInfo for an already-imported module object — the entry point
    the migrated lint tests use (``inspect.getsource`` equivalent)."""
    import inspect

    path = inspect.getsourcefile(module)
    assert path, f"no source for {module!r}"
    return ModuleInfo(module.__name__, path, Path(path).read_text())
