"""fs.* — filesystem tools (reference: tools/src/fs/, 13 handlers)."""

from __future__ import annotations

import os
import shutil
import stat as stat_mod
from pathlib import Path

from . import ToolError, ToolSpec

MAX_READ = 256 * 1024


def _path(args: dict, key: str = "path") -> Path:
    raw = args.get(key)
    if not raw:
        raise ToolError(f"missing required arg '{key}'")
    return Path(raw)


def fs_read(args: dict) -> dict:
    p = _path(args)
    if not p.is_file():
        raise ToolError(f"{p} is not a file")
    data = p.read_bytes()[: int(args.get("max_bytes", MAX_READ))]
    return {"path": str(p), "content": data.decode("utf-8", "replace"),
            "bytes": len(data)}


def fs_write(args: dict) -> dict:
    p = _path(args)
    content = args.get("content", "")
    append = bool(args.get("append", False))
    p.parent.mkdir(parents=True, exist_ok=True)
    mode = "a" if append else "w"
    with open(p, mode, encoding="utf-8") as f:
        f.write(content)
    return {"path": str(p), "bytes_written": len(content.encode()), "append": append}


def fs_delete(args: dict) -> dict:
    p = _path(args)
    if not p.exists():
        raise ToolError(f"{p} does not exist")
    if p.is_dir():
        if not args.get("recursive", False):
            raise ToolError(f"{p} is a directory; pass recursive=true")
        shutil.rmtree(p)
    else:
        p.unlink()
    return {"deleted": str(p)}


def fs_list(args: dict) -> dict:
    p = _path(args)
    if not p.is_dir():
        raise ToolError(f"{p} is not a directory")
    entries = []
    for child in sorted(p.iterdir())[: int(args.get("limit", 500))]:
        try:
            st = child.stat()
            entries.append(
                {
                    "name": child.name,
                    "type": "dir" if child.is_dir() else "file",
                    "size": st.st_size,
                    "mtime": int(st.st_mtime),
                }
            )
        except OSError:
            continue
    return {"path": str(p), "entries": entries, "count": len(entries)}


def fs_stat(args: dict) -> dict:
    p = _path(args)
    if not p.exists():
        raise ToolError(f"{p} does not exist")
    st = p.stat()
    return {
        "path": str(p),
        "size": st.st_size,
        "mode": oct(st.st_mode),
        "uid": st.st_uid,
        "gid": st.st_gid,
        "mtime": int(st.st_mtime),
        "is_dir": p.is_dir(),
        "is_symlink": p.is_symlink(),
    }


def fs_mkdir(args: dict) -> dict:
    p = _path(args)
    p.mkdir(parents=bool(args.get("parents", True)), exist_ok=True)
    return {"created": str(p)}


def fs_move(args: dict) -> dict:
    src, dst = _path(args, "src"), _path(args, "dst")
    if not src.exists():
        raise ToolError(f"{src} does not exist")
    shutil.move(str(src), str(dst))
    return {"moved": str(src), "to": str(dst)}


def fs_copy(args: dict) -> dict:
    src, dst = _path(args, "src"), _path(args, "dst")
    if not src.exists():
        raise ToolError(f"{src} does not exist")
    if src.is_dir():
        shutil.copytree(src, dst, dirs_exist_ok=True)
    else:
        shutil.copy2(src, dst)
    return {"copied": str(src), "to": str(dst)}


def fs_chmod(args: dict) -> dict:
    p = _path(args)
    mode = args.get("mode")
    if mode is None:
        raise ToolError("missing required arg 'mode' (octal string)")
    p.chmod(int(str(mode), 8))
    return {"path": str(p), "mode": str(mode)}


def fs_chown(args: dict) -> dict:
    p = _path(args)
    uid = int(args.get("uid", -1))
    gid = int(args.get("gid", -1))
    try:
        os.chown(p, uid, gid)
    except PermissionError as exc:
        raise ToolError(f"chown {p}: {exc}") from exc
    return {"path": str(p), "uid": uid, "gid": gid}


def fs_symlink(args: dict) -> dict:
    target, link = _path(args, "target"), _path(args, "link")
    if link.exists():
        raise ToolError(f"{link} already exists")
    link.symlink_to(target)
    return {"link": str(link), "target": str(target)}


def fs_search(args: dict) -> dict:
    p = _path(args)
    pattern = args.get("pattern", "*")
    content = args.get("content", "")
    limit = int(args.get("limit", 100))
    hits = []
    for f in p.rglob(pattern):
        if len(hits) >= limit:
            break
        if content:
            try:
                if f.is_file() and content in f.read_text(errors="ignore"):
                    hits.append(str(f))
            except OSError:
                continue
        else:
            hits.append(str(f))
    return {"matches": hits, "count": len(hits)}


def fs_disk_usage(args: dict) -> dict:
    p = Path(args.get("path", "/"))
    usage = shutil.disk_usage(p)
    return {
        "path": str(p),
        "total_gb": round(usage.total / 1e9, 2),
        "used_gb": round(usage.used / 1e9, 2),
        "free_gb": round(usage.free / 1e9, 2),
        "percent_used": round(usage.used / usage.total * 100, 1),
    }


TOOLS = {
    "fs.read": ToolSpec(fs_read, "Read a file's contents", idempotent=True),
    "fs.write": ToolSpec(
        fs_write, "Write/append content to a file",
        reversible=True, target_arg="path",
    ),
    "fs.delete": ToolSpec(
        fs_delete, "Delete a file or directory",
        reversible=True, target_arg="path", requires_confirmation=True,
    ),
    "fs.list": ToolSpec(fs_list, "List directory entries", idempotent=True),
    "fs.stat": ToolSpec(fs_stat, "Stat a path", idempotent=True),
    "fs.mkdir": ToolSpec(fs_mkdir, "Create a directory", idempotent=True),
    "fs.move": ToolSpec(fs_move, "Move/rename a path", reversible=True,
                        target_arg="src"),
    "fs.copy": ToolSpec(fs_copy, "Copy a file or tree", reversible=True,
                        target_arg="dst"),
    "fs.chmod": ToolSpec(fs_chmod, "Change file mode", reversible=True,
                         target_arg="path"),
    "fs.chown": ToolSpec(fs_chown, "Change file ownership", reversible=True,
                         target_arg="path"),
    "fs.symlink": ToolSpec(fs_symlink, "Create a symlink"),
    "fs.search": ToolSpec(fs_search, "Search files by glob and content",
                          idempotent=True),
    "fs.disk_usage": ToolSpec(fs_disk_usage, "Filesystem usage summary",
                              idempotent=True),
}
