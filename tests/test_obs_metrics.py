"""Unit tests for the obs metrics registry: counter/gauge/histogram
semantics, label handling and cardinality, concurrent increments, and the
Prometheus text exposition format."""

import math
import threading

import pytest

from aios_tpu.obs import metrics as M


@pytest.fixture()
def reg():
    return M.MetricsRegistry()


def test_counter_semantics(reg):
    c = M.Counter("aios_tpu_x_total", "h", registry=reg)
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_labeled_counter_children_are_independent(reg):
    c = M.Counter("aios_tpu_x_total", "h", ("model",), registry=reg)
    c.labels(model="a").inc()
    c.labels(model="b").inc(4)
    assert reg.sample("aios_tpu_x_total", {"model": "a"}) == 1
    assert reg.sample("aios_tpu_x_total", {"model": "b"}) == 4
    assert c.value == 5  # family total sums children


def test_label_names_must_match(reg):
    c = M.Counter("aios_tpu_x_total", "h", ("model",), registry=reg)
    with pytest.raises(ValueError):
        c.labels(wrong="a")
    with pytest.raises(ValueError):
        c.labels()  # missing
    with pytest.raises(ValueError):
        c.labels(model="a", extra="b")
    with pytest.raises(ValueError):
        c.inc()  # labeled family has no unlabeled series


def test_gauge_set_inc_dec_and_callback(reg):
    g = M.Gauge("aios_tpu_g_total", "h", registry=reg)
    g.set(10)
    g.inc(2)
    g.dec(0.5)
    assert g.value == 11.5
    state = {"v": 3}
    g.set_function(lambda: state["v"])
    assert g.value == 3
    state["v"] = 9
    assert g.value == 9  # read at scrape time, not registration time
    g.set(1)  # an explicit set clears the callback
    assert g.value == 1


def test_gauge_callback_exception_degrades_to_nan(reg):
    g = M.Gauge("aios_tpu_g_total", "h", registry=reg)
    g.set_function(lambda: 1 / 0)
    assert math.isnan(g.value)  # a broken callback must not kill a scrape
    assert "aios_tpu_g_total" in reg.render()


def test_histogram_buckets_cumulative_sum_count(reg):
    h = M.Histogram(
        "aios_tpu_h_seconds", "h", buckets=(0.1, 1.0, 10.0), registry=reg
    )
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = reg.render()
    assert 'aios_tpu_h_seconds_bucket{le="0.1"} 1' in text
    assert 'aios_tpu_h_seconds_bucket{le="1"} 3' in text
    assert 'aios_tpu_h_seconds_bucket{le="10"} 4' in text
    assert 'aios_tpu_h_seconds_bucket{le="+Inf"} 5' in text
    assert "aios_tpu_h_seconds_count 5" in text
    assert "aios_tpu_h_seconds_sum 56.05" in text


def test_histogram_labeled_child(reg):
    h = M.Histogram(
        "aios_tpu_h_seconds", "h", ("m",), buckets=(1.0,), registry=reg
    )
    h.labels(m="x").observe(0.5)
    assert h.labels(m="x").sample_count == 1
    with pytest.raises(ValueError):
        h.observe(0.5)  # labeled family needs .labels()


def test_metric_name_validation(reg):
    with pytest.raises(ValueError):
        M.Counter("Bad-Name", "h", registry=reg)
    with pytest.raises(ValueError):
        M.Counter("aios_tpu_ok_total", "h", ("Bad-Label",), registry=reg)


def test_duplicate_registration_rejected(reg):
    M.Counter("aios_tpu_x_total", "h", registry=reg)
    with pytest.raises(ValueError):
        M.Counter("aios_tpu_x_total", "h", registry=reg)


def test_concurrent_increments_are_exact(reg):
    c = M.Counter("aios_tpu_x_total", "h", ("t",), registry=reg)
    child = c.labels(t="shared")
    n_threads, per_thread = 8, 5000

    def worker():
        for _ in range(per_thread):
            child.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert child.value == n_threads * per_thread


def test_label_cardinality_capped(reg):
    c = M.Counter("aios_tpu_x_total", "h", ("k",), registry=reg)
    for i in range(M.MAX_CHILDREN + 10):
        c.labels(k=f"v{i}").inc()
    assert len(c._children) <= M.MAX_CHILDREN + 1  # + the overflow child
    assert c.overflows == 10
    assert 'overflow="true"' in reg.render()
    assert c.value == M.MAX_CHILDREN + 10  # nothing dropped, just collapsed


def test_exposition_escapes_label_values(reg):
    c = M.Counter("aios_tpu_x_total", "h", ("p",), registry=reg)
    c.labels(p='a"b\\c\nd').inc()
    text = reg.render()
    assert r'p="a\"b\\c\nd"' in text


def test_exposition_help_and_type_lines(reg):
    M.Counter("aios_tpu_c_total", "counts things", registry=reg)
    M.Gauge("aios_tpu_g_ratio", "gauges things", registry=reg)
    text = reg.render()
    assert "# HELP aios_tpu_c_total counts things" in text
    assert "# TYPE aios_tpu_c_total counter" in text
    assert "# TYPE aios_tpu_g_ratio gauge" in text
    assert "aios_tpu_c_total 0" in text  # unlabeled series exists at 0


def test_unlabeled_metrics_render_before_any_activity(reg):
    M.Histogram("aios_tpu_h_seconds", "h", buckets=(1.0,), registry=reg)
    text = reg.render()
    assert "aios_tpu_h_seconds_count 0" in text
