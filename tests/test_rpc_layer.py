"""RPC layer: programmatic stubs/servicers round-trip over a real socket.

Mirrors the reference's approach of exercising gRPC handlers directly
(SURVEY.md section 4) but additionally goes through a live localhost server to
prove the hand-built method tables are wire-correct.
"""

import threading

import grpc
import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import common_pb2, runtime_pb2


class _EchoRuntime(services.AIRuntimeServicer):
    def Infer(self, request, context):
        return runtime_pb2.InferResponse(
            text=f"echo:{request.prompt}",
            tokens_used=7,
            latency_ms=1,
            model_used=request.model or "default",
        )

    def StreamInfer(self, request, context):
        for tok in request.prompt.split():
            yield runtime_pb2.InferChunk(text=tok, done=False)
        yield runtime_pb2.InferChunk(text="", done=True)

    def HealthCheck(self, request, context):
        return common_pb2.HealthStatus(
            healthy=True, service="runtime", details={"backend": "jax-tpu"}
        )


@pytest.fixture(scope="module")
def echo_server():
    server = rpc.create_server()
    rpc.add_to_server(services.RUNTIME, _EchoRuntime(), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_unary_roundtrip(echo_server):
    with rpc.insecure_channel(echo_server) as channel:
        stub = services.AIRuntimeStub(channel)
        resp = stub.Infer(runtime_pb2.InferRequest(prompt="hello", model="m1"))
    assert resp.text == "echo:hello"
    assert resp.tokens_used == 7
    assert resp.model_used == "m1"


def test_server_streaming(echo_server):
    with rpc.insecure_channel(echo_server) as channel:
        stub = services.AIRuntimeStub(channel)
        chunks = list(stub.StreamInfer(runtime_pb2.InferRequest(prompt="a b c")))
    assert [c.text for c in chunks] == ["a", "b", "c", ""]
    assert [c.done for c in chunks] == [False, False, False, True]


def test_health_map_field(echo_server):
    with rpc.insecure_channel(echo_server) as channel:
        stub = services.AIRuntimeStub(channel)
        h = stub.HealthCheck(common_pb2.Empty())
    assert h.healthy and h.details["backend"] == "jax-tpu"


def test_unimplemented_method_returns_grpc_error(echo_server):
    with rpc.insecure_channel(echo_server) as channel:
        stub = services.AIRuntimeStub(channel)
        with pytest.raises(grpc.RpcError) as err:
            stub.LoadModel(runtime_pb2.LoadModelRequest(model_name="x"))
    assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_all_specs_have_stub_and_servicer():
    for name, spec in services.ALL_SPECS.items():
        stub_cls = rpc.make_stub(spec)
        servicer_cls = rpc.make_servicer(spec)
        assert stub_cls and servicer_cls, name
        assert len(spec.methods) > 0


def test_spec_counts_match_reference_surface():
    # RPC counts from the reference protos (SURVEY.md sections 1-2).
    assert len(services.ORCHESTRATOR.methods) == 19
    # 23 tier RPCs + AssembleContext (memory.proto)
    assert len(services.MEMORY.methods) == 24
    assert len(services.RUNTIME.methods) == 6
    assert len(services.TOOLS.methods) == 6
    assert len(services.GATEWAY.methods) == 4
    assert len(services.AGENT.methods) == 4


def test_concurrent_unary_calls(echo_server):
    results = []

    def call(i):
        with rpc.insecure_channel(echo_server) as channel:
            stub = services.AIRuntimeStub(channel)
            results.append(stub.Infer(runtime_pb2.InferRequest(prompt=str(i))).text)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == sorted(f"echo:{i}" for i in range(8))
