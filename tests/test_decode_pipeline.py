"""Pipelined decode loop + dispatch-free AOT warmup (ISSUE 6).

Three guarantees under test:
  * token identity: the depth-2 pipelined batcher (AIOS_TPU_DECODE_PIPELINE)
    emits byte-for-byte the streams the sync loop emits — greedy AND
    sampled with a fixed seed — including across retirement boundaries,
    ``force_pending_token`` (grammar-constrained admission), and
    chunked-prefill interleaving, where the pipeline must flush;
  * no compile after warmup: ``engine.warmup()`` AOT-compiles every graph
    the serving path can hit, so a post-warmup sweep across every prefill
    bucket, both chunked-admission paths, every decode chunk size, the
    masked step, and the prefix-hit path moves ``engine.stats()``'s
    compile counters by exactly zero;
  * the unified dynamic-step graph (AIOS_TPU_UNIFIED_STEP) is greedy-
    identical to the per-size scan graphs and serves unwarmed chunk sizes
    without compiling.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def params():
    return M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)


def make_engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_context", 128)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(TINY_TEST, params, **kw)


def run_batch(params, pipeline, reqs, *, engine_kw=None, batcher_kw=None,
              warm=True):
    """One engine+batcher lifecycle: submit ``reqs`` (dicts) up front,
    drain every stream, return (per-request token lists, batcher, engine
    stats)."""
    eng = make_engine(params, **(engine_kw or {}))
    if warm:
        eng.warmup(step_sizes=(2, 4), prefill_chunk=32)
    kw = dict(chunk_steps=4, admit_chunk_steps=2, pipeline=pipeline)
    kw.update(batcher_kw or {})
    b = ContinuousBatcher(eng, **kw)
    try:
        handles = [b.submit(Request(**r)) for r in reqs]
        outs = [h.tokens() for h in handles]
        stats = dict(eng.stats())
        stats["flushes"] = b.flushes
        stats["dispatches"] = b.decode_dispatches
        stats["evictions"] = b.pool_evictions
        stats["aborted"] = [h.abort_reason for h in handles]
        return outs, stats
    finally:
        b.shutdown()
        eng.close()


def test_pipeline_token_identical_greedy(params):
    """Same streams pipeline-on vs -off at temperature 0, with staggered
    max_tokens so requests retire at different dispatch boundaries and a
    stop token that fires mid-dispatch."""
    reqs = [
        dict(prompt_ids=[3 + i, 17, 91, 4 + i], max_tokens=18 + 5 * i,
             temperature=0.0)
        for i in range(4)
    ]
    off, s_off = run_batch(params, False, reqs)
    # make one request stop early on a token the free run actually emits
    reqs[1]["stop_ids"] = (off[1][4],)
    off, s_off = run_batch(params, False, reqs)
    on, s_on = run_batch(params, True, reqs)
    assert on == off
    assert len(off[1]) <= 5 + 1  # the stop actually fired
    # the pipelined run really pipelined: dispatches were issued ahead
    assert s_on["dispatches"] > 0


def test_pipeline_token_identical_sampled(params):
    """Fixed engine seed, temperature > 0: the pipelined dispatch chain
    consumes the SAME per-dispatch key splits, so sampled streams match
    token-for-token."""
    reqs = [
        dict(prompt_ids=[7 + i, 2, 55], max_tokens=21 + 4 * i,
             temperature=0.85, top_p=0.9)
        for i in range(4)
    ]
    off, _ = run_batch(params, False, reqs)
    on, _ = run_batch(params, True, reqs)
    assert on == off
    assert any(len(set(t)) > 1 for t in on)  # actually sampled something


def test_pipeline_flushes_idle_after_retirement(params):
    """When the whole batch retires, the next tick flushes (or shutdown
    drops) the speculatively-issued dispatch; the stream itself is exactly
    max_tokens long."""
    reqs = [dict(prompt_ids=[5, 6, 7], max_tokens=13, temperature=0.0)]
    off, _ = run_batch(params, False, reqs)
    on, stats = run_batch(params, True, reqs)
    assert on == off and len(on[0]) == 13


def test_pipeline_constrained_flush_and_force_pending_token(params):
    """A json_mode request admitted mid-stream forces its pending opener
    (force_pending_token) and rides 1-step masked dispatches — the
    pipeline must drain first (cause=constrained), and both the
    constrained and the co-resident unconstrained stream stay correct."""
    tok = ByteTokenizer()
    eng = make_engine(params)
    eng.warmup(step_sizes=(2, 4), prefill_chunk=32, masked_step=True)
    b = ContinuousBatcher(eng, chunk_steps=4, admit_chunk_steps=2,
                          pipeline=True, tokenizer=tok)
    try:
        plain = b.submit(Request(prompt_ids=tok.encode("plain"),
                                 max_tokens=60, temperature=0.0))
        # consume a few tokens FIRST: after >= 1 plain decode tick the
        # pipeline holds an in-flight dispatch (and keeps holding one,
        # tick over tick) — so the constrained admission below MUST
        # drain it, deterministically
        it = iter(plain)
        t_plain = [next(it) for _ in range(4)]
        constrained = b.submit(Request(
            prompt_ids=tok.encode("emit json"), max_tokens=40,
            temperature=0.9, stop_ids=(tok.eos_id,), json_mode=True,
        ))
        t_plain += list(it)
        t_json = constrained.tokens()
        parsed = json.loads(tok.decode(t_json))
        assert isinstance(parsed, dict)
        assert len(t_plain) == 60
        # the constrained tick drained the pipeline at least once while
        # the plain stream was mid-flight
        assert b.flushes >= 1
    finally:
        b.shutdown()
        eng.close()


def test_pipeline_chunked_prefill_interleave_identical(params):
    """A long prompt admitting chunk-by-chunk between pipelined decode
    dispatches: streams match the sync loop exactly (the chunk writes and
    the in-flight decode order through the donated state chain)."""
    long_prompt = (np.arange(1, 90) % 250 + 1).tolist()  # > prefill_chunk 32
    reqs = [
        dict(prompt_ids=[9, 8, 7], max_tokens=24, temperature=0.0),
        dict(prompt_ids=long_prompt, max_tokens=12, temperature=0.0),
        dict(prompt_ids=[41, 2], max_tokens=16, temperature=0.0),
    ]
    kw = dict(batcher_kw=dict(prefill_chunk=32))
    off, _ = run_batch(params, False, reqs, **kw)
    on, _ = run_batch(params, True, reqs, **kw)
    assert on == off
    assert len(on[1]) == 12


def test_pipeline_pool_eviction_flush(params):
    """Pool exhaustion mid-decode with a dispatch in flight: the eviction
    path flushes first (the victim keeps every token it produced before
    the abort), the survivor completes, and the engine state stays
    coherent."""
    # 4 usable pages (128 rows): both streams fit at admission (1 page
    # each) but cross their 3rd-page boundary together mid-decode — 6
    # pages wanted, 4 exist — so the dispatch path must evict the
    # priority-0 stream while the priority-1 survivor (80 rows = 3 pages
    # peak) still completes
    reqs = [
        dict(prompt_ids=list(range(1, 31)), max_tokens=50, temperature=0.0,
             priority=1),
        dict(prompt_ids=list(range(40, 70)), max_tokens=80, temperature=0.0),
    ]
    outs, stats = run_batch(
        params, True, reqs,
        engine_kw=dict(num_slots=2, paged_pool_rows=128, page_size=32,
                       prefix_cache=False),
    )
    assert stats["evictions"] >= 1
    aborted = [r for r in stats["aborted"] if r]
    assert aborted and "evicted" in aborted[0]
    # the survivor (higher priority) ran to completion
    survivor = [o for o, r in zip(outs, stats["aborted"]) if not r]
    assert survivor and len(survivor[0]) > 0


def test_no_compile_after_warmup_serving_sweep(params):
    """The AOT readiness gate covers the WHOLE serving surface: walking
    every prefill bucket, the chunked-admission path, the prefix-hit
    path, every warmed decode chunk size, and the grammar-masked step
    moves the engine's compile counters by exactly zero."""
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32, prefix_host_bytes=32 << 20,
    )
    try:
        eng.warmup(step_sizes=(1, 2, 8, 16), masked_step=True)
        before = eng.stats()["xla_compiles"]
        rng = np.random.default_rng(7)
        # every monolithic prefill bucket the pool can back
        for b in eng.buckets:
            n = b // 2 + 1
            if eng.allocator.blocks_for(n) > eng.allocator.capacity_blocks():
                continue
            prompt = [int(t) for t in rng.integers(1, 500, n)]
            eng.prefill(0, prompt, temperature=0.0)
            eng.step(1)
            eng.release(0)
        # chunked admission (mid + final chunk graphs)
        long_prompt = [int(t) for t in rng.integers(1, 500, 420)]
        pc = eng.start_chunked_prefill(0, long_prompt, chunk=eng._prefix_chunk)
        while pc.step() is None:
            pass
        # both batcher chunk sizes + the masked step + a forced token
        for n in (1, 2, 8, 16):
            eng.step(n)
        eng.force_pending_token(0, 3)
        eng.step_masked(np.zeros((2, TINY_TEST.vocab_size), np.float32))
        eng.release(0)
        # prefix-HIT path: resubmit -> history backfill + tail chunks
        eng.prefill(0, long_prompt + [5], temperature=0.0)
        eng.release(0)
        assert eng.stats()["xla_compiles"] == before, (
            "serving sweep compiled a graph warmup should have covered"
        )
    finally:
        eng.close()


def test_warmup_covers_host_tier_restore(params):
    """Spill -> restore after warmup compiles nothing: the bucketed
    restore scatters were AOT-built behind the readiness gate."""
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32, prefix_host_bytes=32 << 20,
    )
    try:
        eng.warmup(step_sizes=(1,))
        before = eng.stats()["xla_compiles"]
        rng = np.random.default_rng(11)
        preamble = [int(t) for t in rng.integers(1, 500, 321)]
        eng.prefill(0, preamble, temperature=0.0)
        eng.release(0)
        pressure = [int(t) for t in rng.integers(1, 500, 480)]
        eng.prefill(0, pressure, temperature=0.0)  # reclaim -> spill
        eng.release(0)
        deadline = __import__("time").time() + 10
        while eng.host_store.spills < 2 and __import__("time").time() < deadline:
            __import__("time").sleep(0.02)
        eng.prefill(0, preamble, temperature=0.0)  # host-tier restore
        eng.release(0)
        assert eng.stats().get("host_tier_restores", 0) >= 1
        assert eng.stats()["xla_compiles"] == before
    finally:
        eng.close()


def test_unified_step_greedy_identical_one_graph(params):
    """AIOS_TPU_UNIFIED_STEP mode: one dynamic-n graph serves every chunk
    size (warmed or not) with zero extra compiles, and greedy output
    matches the per-size scan graphs token-for-token."""
    uni = make_engine(params, unified_step=True)
    ref = make_engine(params)
    try:
        uni.warmup(step_sizes=(1, 2, 8, 16), prefill_chunk=0)
        step_graphs = [k for k in uni._step_fns if isinstance(k, tuple)]
        assert step_graphs == [("uni", 16)]
        before = uni.stats()["xla_compiles"]
        prompt = [3, 17, 91, 4, 55, 8]
        g_uni = [uni.prefill(0, prompt, temperature=0.0)]
        g_ref = [ref.prefill(0, prompt, temperature=0.0)]
        for n in (1, 2, 8, 5, 16, 3):  # 5 and 3 were never warmed
            g_uni += [int(t) for t in uni.step(n)[:, 0]]
            g_ref += [int(t) for t in ref.step(n)[:, 0]]
        assert g_uni == g_ref
        assert uni.stats()["xla_compiles"] == before
    finally:
        uni.close()
        ref.close()


def test_batcher_attach_compiles_missing_sizes_without_dispatch(params):
    """A batcher with non-default chunk sizes attaching to a warmed
    engine AOT-compiles its sizes — engine state must not move (the old
    path dispatched real steps to compile them)."""
    eng = make_engine(params)
    eng.warmup(step_sizes=(16,), prefill_chunk=0)
    try:
        b = ContinuousBatcher(eng, chunk_steps=5, admit_chunk_steps=3)
        try:
            assert {3, 5} <= set(eng._step_fns)
            assert eng.decode_steps == 0
        finally:
            b.shutdown()
    finally:
        eng.close()


def test_pending_decode_lengths_snapshot(params):
    """step_async dispatches run FIFO on the engine's dispatch worker,
    and each pending handle carries the post-dispatch lengths of ITS
    dispatch — later dispatches must not leak into the snapshot (the
    out-of-cache retirement anchor)."""
    eng = make_engine(params)
    try:
        eng.prefill(0, [1, 2, 3], temperature=0.0)
        p1 = eng.step_async(2)
        p2 = eng.step_async(4)
        assert p1.wait().shape == (2, 4)
        assert p2.wait().shape == (4, 4)
        assert p1.lengths[0] == 5 and p2.lengths[0] == 9
        assert eng.slot_length(0) == 9
        # the fence used by the batcher's tick ordering
        p2.wait_started()
    finally:
        eng.close()
