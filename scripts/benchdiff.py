#!/usr/bin/env python3
"""Diff two bench.py JSONL captures' devprof ledgers — the per-graph
device-time regression sentinel (docs/OBSERVABILITY.md "Device-time
attribution").

Usage:
    scripts/benchdiff.py BASELINE.json NEW.json [--threshold 0.15]

Reads both files as bench.py output (one JSON object per line), finds
each side's ``bench_devprof`` line (the one carrying a ``devprof``
ledger), and compares per graph kind:

  * ``device_seconds_per_dispatch`` — sampled mean device time; a NEW
    value more than ``--threshold`` above baseline is a regression;
  * ``dispatches`` — the ledger phase's workload is fixed and
    deterministic, so a graph kind dispatching more than ``--threshold``
    above baseline is a regression too (a graph doing extra work for
    the same tokens);
  * a kind present in the baseline but missing from NEW is reported as
    lost coverage (warning, not failure — e.g. a CPU capture diffed
    against a TPU one legitimately drops kinds).

Refuses cross-schema comparisons: both lines must carry the same
``schema_version`` (bench.py stamps every line; a missing stamp reads
as version 0). Exit codes: 0 clean, 1 regression past the threshold,
2 unusable inputs (missing ledger, schema mismatch).

The human-readable table goes to stderr; ONE machine-readable JSON
verdict line goes to stdout, so CI can archive it beside the captures.
scripts/preflight.sh runs this against the committed BASELINE_DEVPROF
capture with a loosened threshold (cross-run CPU timing noise); same-
machine A/Bs use the default.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def load_lines(path: str) -> List[dict]:
    out = []
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    obj = json.loads(raw)
                except ValueError:
                    continue  # interleaved log noise: skip, keep JSON
                if isinstance(obj, dict):
                    out.append(obj)
    except OSError as exc:
        log(f"benchdiff: cannot read {path}: {exc}")
    return out


def pick_devprof(lines: List[dict]) -> Optional[dict]:
    """The LAST line carrying a devprof ledger (a capture may emit
    several runs; last wins, like bench re-runs overwrite)."""
    for obj in reversed(lines):
        dp = obj.get("devprof")
        if isinstance(dp, dict) and dp.get("graphs"):
            return obj
    return None


def diff(base: dict, new: dict, threshold: float) -> Tuple[list, list]:
    """-> (regressions, warnings); each entry is a dict."""
    regressions, warnings = [], []
    bg = base["devprof"]["graphs"]
    ng = new["devprof"]["graphs"]
    for kind in sorted(bg):
        b = bg[kind]
        n = ng.get(kind)
        if n is None:
            if b.get("dispatches"):
                warnings.append({
                    "graph": kind, "what": "coverage_lost",
                    "detail": f"{b['dispatches']} baseline dispatches, "
                              f"absent from new capture",
                })
            continue
        b_disp, n_disp = b.get("dispatches", 0), n.get("dispatches", 0)
        if b_disp and n_disp > b_disp * (1.0 + threshold):
            regressions.append({
                "graph": kind, "what": "dispatches",
                "base": b_disp, "new": n_disp,
                "ratio": round(n_disp / b_disp, 3),
            })
        b_s = b.get("device_seconds_per_dispatch")
        n_s = n.get("device_seconds_per_dispatch")
        if b_s and n_s:
            ratio = n_s / b_s
            row = {
                "graph": kind, "what": "device_seconds_per_dispatch",
                "base": b_s, "new": n_s, "ratio": round(ratio, 3),
            }
            if ratio > 1.0 + threshold:
                regressions.append(row)
            else:
                warnings.append({**row, "what": "timing_ok"})
    for kind in sorted(set(ng) - set(bg)):
        warnings.append({
            "graph": kind, "what": "new_coverage",
            "detail": f"{ng[kind].get('dispatches', 0)} dispatches with "
                      f"no baseline entry",
        })
    return regressions, warnings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-graph devprof regression diff of two bench.py "
                    "JSONL captures",
    )
    ap.add_argument("baseline", help="baseline capture (JSONL)")
    ap.add_argument("new", help="new capture (JSONL)")
    ap.add_argument("--threshold", type=float, default=0.15, metavar="R",
                    help="relative regression budget per graph kind "
                         "(default 0.15 = +15%%; preflight loosens it "
                         "for cross-run CPU noise)")
    args = ap.parse_args(argv)

    base = pick_devprof(load_lines(args.baseline))
    new = pick_devprof(load_lines(args.new))
    if base is None or new is None:
        which = args.baseline if base is None else args.new
        log(f"benchdiff: no devprof ledger line in {which} "
            f"(run `python bench.py --devprof`)")
        print(json.dumps({"verdict": "unusable", "missing": which}))
        return 2

    b_schema = base.get("schema_version", 0)
    n_schema = new.get("schema_version", 0)
    if b_schema != n_schema:
        log(f"benchdiff: REFUSING cross-schema comparison "
            f"(baseline schema_version={b_schema}, new={n_schema}); "
            f"re-capture the baseline with this bench.py")
        print(json.dumps({
            "verdict": "schema_mismatch",
            "baseline_schema": b_schema, "new_schema": n_schema,
        }))
        return 2

    regressions, warnings = diff(base, new, args.threshold)
    for w in warnings:
        if w["what"] == "timing_ok":
            log(f"  ok   {w['graph']:<13} {w['base']:.6f}s -> "
                f"{w['new']:.6f}s/dispatch (x{w['ratio']})")
        else:
            log(f"  note {w['graph']:<13} {w['what']}: "
                f"{w.get('detail', '')}")
    for r in regressions:
        log(f"  FAIL {r['graph']:<13} {r['what']} {r['base']} -> "
            f"{r['new']} (x{r['ratio']}, budget +{args.threshold:.0%})")
    verdict = "regression" if regressions else "ok"
    log(f"benchdiff: {verdict} "
        f"({len(regressions)} regression(s), threshold "
        f"+{args.threshold:.0%})")
    print(json.dumps({
        "verdict": verdict,
        "threshold": args.threshold,
        "schema_version": n_schema,
        "regressions": regressions,
        "warnings": [w for w in warnings if w["what"] != "timing_ok"],
    }))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
