"""Speculative decoding (engine/spec.py, model.verify_step).

The acceptance rule is exact for greedy requests, so the key contract is
sequence *identity* with plain greedy decoding — speculation may only change
how many dispatches the sequence takes, never the tokens. Reference
equivalence: llama.cpp's lookup/draft decoding behind llama-server
(SURVEY.md section 2.3), rebuilt as a device-resident scan loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model, spec
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def params():
    return model.init_params(TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32)


def make_engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_context", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(TINY_TEST, params, **kw)


# ---------------------------------------------------------------------------
# proposer
# ---------------------------------------------------------------------------


def test_propose_ngram_finds_most_recent_match():
    C = 64
    hist = np.zeros((1, C + spec.HISTORY_PAD), np.int32)
    # sequence: 7 8 9 1 2 3 4 5 6 7 8 9 1 2 3   (last token: 3 at col 14)
    seq = [7, 8, 9, 1, 2, 3, 4, 5, 6, 7, 8, 9, 1, 2, 3]
    hist[0, : len(seq)] = seq
    lengths = jnp.asarray([len(seq) - 1], jnp.int32)  # last known col = 14
    drafts, num = spec.propose_ngram(jnp.asarray(hist), lengths, 4, 3, C)
    # trailing 3-gram [1, 2, 3] occurred at cols 3-5; continuation 4 5 6 7
    assert int(num[0]) == 4
    assert drafts[0].tolist() == [4, 5, 6, 7]


def test_propose_ngram_no_match_and_short_history():
    C = 64
    hist = np.zeros((2, C + spec.HISTORY_PAD), np.int32)
    hist[0, :6] = [1, 2, 3, 4, 5, 6]  # no repeated trigram
    hist[1, :2] = [9, 9]  # shorter than the n-gram itself
    lengths = jnp.asarray([5, 1], jnp.int32)
    drafts, num = spec.propose_ngram(jnp.asarray(hist), lengths, 4, 3, C)
    assert num.tolist() == [0, 0]
    assert (np.asarray(drafts) == -1).all()


def test_propose_ngram_clamps_to_cache_room():
    C = 16  # tiny cache: lengths near the end must cap the draft
    hist = np.zeros((1, C + spec.HISTORY_PAD), np.int32)
    seq = [1, 2, 3, 4, 5, 1, 2, 3, 4, 5, 1, 2, 3]
    hist[0, : len(seq)] = seq
    lengths = jnp.asarray([len(seq) - 1], jnp.int32)  # 12; room = 16-2-12 = 2
    drafts, num = spec.propose_ngram(jnp.asarray(hist), lengths, 8, 3, C)
    assert int(num[0]) == 2
    assert drafts[0, :2].tolist() == [4, 5]
    assert (np.asarray(drafts[0, 2:]) == -1).all()


def test_accept_counts_prefix_rule():
    drafts = jnp.asarray([[5, 6, 7], [5, 9, 7], [-1, -1, -1]], jnp.int32)
    g = jnp.asarray(
        [[5, 6, 7, 1], [5, 6, 7, 1], [5, 6, 7, 1]], jnp.int32
    )
    assert spec.accept_counts(drafts, g).tolist() == [3, 1, 0]


# ---------------------------------------------------------------------------
# verify_step vs decode_step
# ---------------------------------------------------------------------------


def test_verify_step_t1_matches_decode_step(params):
    cfg = TINY_TEST
    S, C = 3, 64
    k, v = model.init_kv_cache(cfg, S, C, jnp.float32)
    tokens = jnp.asarray([3, 7, 11], jnp.int32)
    lengths = jnp.zeros((S,), jnp.int32)
    active = jnp.ones((S,), bool)
    d_logits, dk, dv = model.decode_step(
        params, cfg, tokens, lengths, k, v, kernels=False, active=active
    )
    v_logits, vk, vv = model.verify_step(
        params, cfg, tokens[:, None], lengths, k, v, active=active
    )
    np.testing.assert_allclose(
        np.asarray(d_logits), np.asarray(v_logits[:, 0]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(np.asarray(dk), np.asarray(vk), rtol=1e-5, atol=1e-5)


def test_verify_step_rows_match_sequential_decode(params):
    """Feeding [t0, t1, t2] at once gives the same logits as three
    sequential decode steps that feed t0, t1, t2."""
    cfg = TINY_TEST
    S, C, T = 2, 64, 3
    feed = jnp.asarray([[3, 9, 4], [8, 1, 6]], jnp.int32)
    k, v = model.init_kv_cache(cfg, S, C, jnp.float32)
    lengths = jnp.zeros((S,), jnp.int32)
    v_logits, _, _ = model.verify_step(params, cfg, feed, lengths, k, v)

    k, v = model.init_kv_cache(cfg, S, C, jnp.float32)
    for t in range(T):
        d_logits, k, v = model.decode_step(
            params, cfg, feed[:, t], lengths + t, k, v, kernels=False
        )
        np.testing.assert_allclose(
            np.asarray(d_logits),
            np.asarray(v_logits[:, t]),
            rtol=2e-4,
            atol=2e-4,
        )


# ---------------------------------------------------------------------------
# engine spec_step
# ---------------------------------------------------------------------------


def test_spec_generate_matches_plain_greedy(params):
    eng = make_engine(params)
    ref = eng.generate([1, 2, 3], max_new_tokens=96, temperature=0.0)
    eng.close()
    eng = make_engine(params)
    got = eng.generate(
        [1, 2, 3], max_new_tokens=96, temperature=0.0, speculative=True
    )
    rounds = eng.decode_steps
    eng.close()
    assert got == ref
    # greedy decode from a tiny random model falls into a cycle; the n-gram
    # proposer must exploit it — fewer verify rounds than tokens
    assert rounds < len(ref) - 1, f"no drafts accepted in {rounds} rounds"


def test_spec_generate_int8_kv_cache(params):
    eng = make_engine(params, cache_dtype=jnp.int8)
    ref = eng.generate([4, 5, 6], max_new_tokens=64, temperature=0.0)
    eng.close()
    eng = make_engine(params, cache_dtype=jnp.int8)
    got = eng.generate(
        [4, 5, 6], max_new_tokens=64, temperature=0.0, speculative=True
    )
    eng.close()
    assert got == ref


def test_spec_step_host_lengths_track_device(params):
    eng = make_engine(params, max_context=32)
    eng.prefill(0, [1, 2, 3], temperature=0.0)
    total = 3
    for _ in range(12):
        _, counts = eng.spec_step(1, draft_len=4)
        total = min(total + int(counts[0, 0]), eng.max_context - 1)
    assert eng.slot_length(0) == total
    dev = int(np.asarray(eng.state["lengths"])[0])
    assert dev == total  # host mirror never diverges, even at the clamp
    eng.close()


def test_spec_sampling_slots_one_token_per_round(params):
    """temp>0 slots never speculate: one token per round, sequence valid."""
    eng = make_engine(params)
    eng.prefill(0, [1, 2, 3, 1, 2, 3, 1, 2], temperature=0.9, top_p=0.9)
    toks, counts = eng.spec_step(6, draft_len=7)
    assert (counts[:, 0] == 1).all()
    assert ((toks[:, 0, 0] >= 0) & (toks[:, 0, 0] < TINY_TEST.vocab_size)).all()
    eng.close()


# ---------------------------------------------------------------------------
# continuous batcher
# ---------------------------------------------------------------------------


def _batch_outputs(params, speculative, prompts, **bkw):
    eng = make_engine(params, num_slots=4, max_context=256)
    b = ContinuousBatcher(eng, speculative=speculative, **bkw)
    handles = [
        b.submit(Request(prompt_ids=p, max_tokens=40, temperature=0.0))
        for p in prompts
    ]
    outs = [h.tokens() for h in handles]
    b.shutdown()
    eng.close()
    return outs


def test_batcher_speculative_greedy_identical(params):
    prompts = [[1, 2, 3], [7, 8, 9, 7, 8, 9, 7, 8], [11, 12]]
    ref = _batch_outputs(params, False, prompts)
    got = _batch_outputs(params, True, prompts)
    assert got == ref


def test_batcher_speculative_mixed_sampling_completes(params):
    eng = make_engine(params, num_slots=4)
    b = ContinuousBatcher(eng, speculative=True)
    hs = [
        b.submit(Request(prompt_ids=[1, 2, 3], max_tokens=24, temperature=0.0)),
        b.submit(
            Request(prompt_ids=[5, 6], max_tokens=24, temperature=0.8, top_p=0.9)
        ),
    ]
    outs = [h.tokens() for h in hs]
    b.shutdown()
    eng.close()
    assert all(len(o) > 0 for o in outs)
    assert b.last_error is None


def test_history_preserved_during_chunked_prefill(params):
    """Interleaved decode/spec dispatches must not scribble over the prompt
    tokens a mid-chunked-prefill slot has already written to its history
    (inactive slots write the sacrificial pad column) — otherwise the
    n-gram proposer silently loses the quoted-context workload."""
    eng = make_engine(params, num_slots=2, max_context=256)
    eng.prefill(0, [1, 2, 3], temperature=0.0)
    prompt = [int(t) for t in np.random.default_rng(3).integers(1, 500, 150)]
    pc = eng.start_chunked_prefill(1, prompt, chunk=64)
    while pc.step() is None:
        eng.spec_step(2, draft_len=7)  # speculative decode for slot 0
        eng.step(2)  # and plain decode
    hist = np.asarray(eng.state["history"])[1]
    assert hist[: len(prompt)].tolist() == prompt
    eng.close()


def test_batcher_speculative_with_chunked_prefill(params):
    """A long admission chunk-prefills while spec dispatches decode the
    other slots — active-gating must keep both correct."""
    long_prompt = list(np.random.default_rng(0).integers(1, 500, 150))
    prompts = [[1, 2, 3], [int(t) for t in long_prompt]]
    ref = _batch_outputs(params, False, prompts, prefill_chunk=64)
    got = _batch_outputs(params, True, prompts, prefill_chunk=64)
    assert got == ref


def test_spec_generate_saturating_cache_matches_plain(params):
    """Generation that runs into the cache end: tokens from rounds after a
    slot saturates are indeterminate (verify_step scatter contract) and
    must never be consumed — output must equal the plain path's."""
    prompt = [1, 2, 3, 1, 2, 3, 1, 2]
    eng = make_engine(params, max_context=32)
    ref = eng.generate(prompt, max_new_tokens=64, temperature=0.0)
    eng.close()
    eng = make_engine(params, max_context=32)
    got = eng.generate(
        prompt, max_new_tokens=64, temperature=0.0, speculative=True
    )
    eng.close()
    assert got == ref


def test_spec_max_tokens_respected(params):
    eng = make_engine(params)
    b = ContinuousBatcher(eng, speculative=True)
    out = b.generate([1, 2, 3], max_tokens=17, temperature=0.0)
    b.shutdown()
    eng.close()
    assert len(out) == 17


def test_spec_generate_int4_weights(params):
    """Speculative decoding over int4 serving weights: bit-identical to the
    SAME int4 engine's plain greedy decode (draft/verify/accept is
    weight-format-agnostic), and drafts actually accept."""
    eng = make_engine(params, quantize="int4")
    assert eng.quant_mode == "int4"
    ref = eng.generate([1, 2, 3], max_new_tokens=64, temperature=0.0)
    eng.close()
    eng = make_engine(params, quantize="int4")
    got = eng.generate(
        [1, 2, 3], max_new_tokens=64, temperature=0.0, speculative=True
    )
    rounds = eng.decode_steps
    eng.close()
    assert got == ref
    assert rounds < len(ref) - 1, f"no drafts accepted in {rounds} rounds"
