// aios_native — C++ runtime primitives for the aiOS-TPU service plane.
//
// The reference implements its service plane in Rust (tools/src, memory/src);
// this library provides the equivalent native hot-path primitives for the
// Python services, exported over a C ABI for ctypes:
//
//   * a fixed-capacity MPMC event ring buffer (operational memory tier,
//     reference memory/src/operational.rs — <1 ms access target),
//   * monotonic token buckets (tool rate limiting, tools/src/executor.rs
//     52-104),
//   * a self-contained SHA-256 + hash-chain step (audit ledger,
//     tools/src/audit.rs:54-104).
//
// Build: scripts in ../build.py invoke g++ -O2 -shared -fPIC.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), self-contained
// ---------------------------------------------------------------------------

namespace sha256 {

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

struct Ctx {
  uint32_t h[8];
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Ctx() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t len) {
    total += len;
    while (len > 0) {
      size_t take = 64 - buflen;
      if (take > len) take = len;
      memcpy(buf + buflen, data, take);
      buflen += take;
      data += take;
      len -= take;
      if (buflen == 64) {
        block(buf);
        buflen = 0;
      }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenbuf[8];
    for (int i = 0; i < 8; i++) lenbuf[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenbuf, 8);
    for (int i = 0; i < 8; i++) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

static void hex(const uint8_t* digest, char* out) {
  static const char* digits = "0123456789abcdef";
  for (int i = 0; i < 32; i++) {
    out[i * 2] = digits[digest[i] >> 4];
    out[i * 2 + 1] = digits[digest[i] & 0xf];
  }
  out[64] = '\0';
}

}  // namespace sha256

extern "C" {

// out must hold >= 65 bytes
void aios_sha256_hex(const uint8_t* data, uint64_t len, char* out) {
  sha256::Ctx ctx;
  ctx.update(data, len);
  uint8_t digest[32];
  ctx.final(digest);
  sha256::hex(digest, out);
}

// One audit-chain step: hash(prev_hex || payload) -> hex.
void aios_chain_hash(const char* prev_hex, const uint8_t* payload,
                     uint64_t payload_len, char* out) {
  sha256::Ctx ctx;
  ctx.update(reinterpret_cast<const uint8_t*>(prev_hex), strlen(prev_hex));
  ctx.update(payload, payload_len);
  uint8_t digest[32];
  ctx.final(digest);
  sha256::hex(digest, out);
}

// ---------------------------------------------------------------------------
// Event ring buffer (operational memory tier)
// ---------------------------------------------------------------------------

struct Ring {
  std::mutex mu;
  std::deque<std::vector<uint8_t>> items;
  size_t capacity;
  uint64_t total_pushed = 0;
};

void* aios_ring_create(uint64_t capacity) {
  Ring* r = new Ring();
  r->capacity = capacity ? capacity : 1;
  return r;
}

void aios_ring_destroy(void* handle) { delete static_cast<Ring*>(handle); }

void aios_ring_push(void* handle, const uint8_t* data, uint64_t len) {
  Ring* r = static_cast<Ring*>(handle);
  std::lock_guard<std::mutex> lock(r->mu);
  r->items.emplace_back(data, data + len);
  r->total_pushed++;
  while (r->items.size() > r->capacity) r->items.pop_front();
}

uint64_t aios_ring_size(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  std::lock_guard<std::mutex> lock(r->mu);
  return r->items.size();
}

uint64_t aios_ring_total(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  std::lock_guard<std::mutex> lock(r->mu);
  return r->total_pushed;
}

// Copy the i-th most recent item (0 = newest) into out; returns its length,
// 0 if absent, or the required size if out_cap is too small.
// Returns the item's size (0 is a valid empty item; copy happens only when
// it fits out_cap) or -1 when `index` is beyond the ring — a distinct
// sentinel so empty events are not mistaken for end-of-ring.
int64_t aios_ring_get_recent(void* handle, uint64_t index, uint8_t* out,
                             uint64_t out_cap) {
  Ring* r = static_cast<Ring*>(handle);
  std::lock_guard<std::mutex> lock(r->mu);
  if (index >= r->items.size()) return -1;
  const auto& item = r->items[r->items.size() - 1 - index];
  if (item.size() > out_cap) return static_cast<int64_t>(item.size());
  memcpy(out, item.data(), item.size());
  return static_cast<int64_t>(item.size());
}

// ---------------------------------------------------------------------------
// Token bucket (rate limiting)
// ---------------------------------------------------------------------------

struct Bucket {
  std::mutex mu;
  double rate;
  double capacity;
  double tokens;
  std::chrono::steady_clock::time_point updated;
};

void* aios_bucket_create(double rate, double capacity) {
  Bucket* b = new Bucket();
  b->rate = rate;
  b->capacity = capacity > 0 ? capacity : rate;
  b->tokens = b->capacity;
  b->updated = std::chrono::steady_clock::now();
  return b;
}

void aios_bucket_destroy(void* handle) { delete static_cast<Bucket*>(handle); }

int aios_bucket_try_acquire(void* handle, double n) {
  Bucket* b = static_cast<Bucket*>(handle);
  std::lock_guard<std::mutex> lock(b->mu);
  auto now = std::chrono::steady_clock::now();
  double elapsed = std::chrono::duration<double>(now - b->updated).count();
  b->updated = now;
  b->tokens = std::min(b->capacity, b->tokens + elapsed * b->rate);
  if (b->tokens >= n) {
    b->tokens -= n;
    return 1;
  }
  return 0;
}

double aios_bucket_tokens(void* handle) {
  Bucket* b = static_cast<Bucket*>(handle);
  std::lock_guard<std::mutex> lock(b->mu);
  return b->tokens;
}

}  // extern "C"
