"""Incident-bundle units (aios_tpu/obs/incidents.py, ISSUE 20).

Deterministic tier: arming matrix, the notify funnel's cooldown/
suppression accounting on an injected clock, bundle sections (armed and
unarmed tsdb), the trigger hooks (flightrec snapshot, breaker open,
fired fault), the bounded store + HTTP surface + disk dump, and THE
acceptance determinism check: a seeded ``pool.scheduler_crash`` wave run
twice produces identical bundles modulo timestamps.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from aios_tpu.obs import flightrec, incidents, tsdb
from aios_tpu.obs.incidents import (
    IncidentConfig,
    IncidentStore,
    MAX_INCIDENTS,
    TRIGGER_CAUSES,
)


def _store(clock=None, **kw) -> IncidentStore:
    cfg = IncidentConfig()
    cfg.window_secs = kw.pop("window_secs", 0.0)
    cfg.cooldown_secs = kw.pop("cooldown_secs", 0.0)
    for k, v in kw.items():
        setattr(cfg, k, v)
    return IncidentStore(cfg, clock=clock or time.time)


def _wait_for(store, n, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        incs = store.incidents()
        if len(incs) >= n:
            return incs
        time.sleep(0.02)
    raise AssertionError(
        f"only {len(store.incidents())} of {n} bundles built in time"
    )


# -- config / arming --------------------------------------------------------


def test_arming_matrix(monkeypatch):
    monkeypatch.delenv("AIOS_TPU_INCIDENTS", raising=False)
    monkeypatch.delenv("AIOS_TPU_TSDB", raising=False)
    assert not IncidentConfig().enabled
    monkeypatch.setenv("AIOS_TPU_TSDB", "1")  # rides the tsdb arming
    assert IncidentConfig().enabled
    monkeypatch.setenv("AIOS_TPU_INCIDENTS", "0")  # explicit off wins
    assert not IncidentConfig().enabled
    monkeypatch.delenv("AIOS_TPU_TSDB", raising=False)
    monkeypatch.setenv("AIOS_TPU_INCIDENTS", "1")  # explicit on alone
    assert IncidentConfig().enabled
    monkeypatch.setenv("AIOS_TPU_INCIDENT_WINDOW_SECS", "5")
    monkeypatch.setenv("AIOS_TPU_INCIDENT_COOLDOWN_SECS", "7")
    cfg = IncidentConfig()
    assert (cfg.window_secs, cfg.cooldown_secs) == (5.0, 7.0)


def test_maybe_start_noop_when_unarmed(monkeypatch):
    monkeypatch.delenv("AIOS_TPU_INCIDENTS", raising=False)
    monkeypatch.delenv("AIOS_TPU_TSDB", raising=False)
    prev = incidents.install(None)
    try:
        assert incidents.maybe_start() is None
        assert not incidents.enabled()
        incidents.notify("m", "manual")  # the funnel is a pure no-op
        assert incidents.STORE is None
    finally:
        incidents.install(prev)


# -- the notify funnel ------------------------------------------------------


def test_cooldown_suppresses_and_counts():
    now = [0.0]
    store = _store(clock=lambda: now[0], cooldown_secs=30.0)
    assert store.notify("m", "manual", sync=True) is not None
    now[0] += 10.0
    assert store.notify("m", "manual", sync=True) is None  # suppressed
    # a different (model, cause) pair has its own stamp
    assert store.notify("m2", "manual", sync=True) is not None
    now[0] += 25.0  # 35s since the first -> cooldown elapsed
    assert store.notify("m", "manual", sync=True) is not None
    ids = [b["id"] for b in store.incidents()]
    assert ids == [1, 2, 3]


def test_unknown_cause_normalizes_to_manual():
    store = _store()
    b = store.notify("m", "definitely_not_a_cause", sync=True)
    assert b["cause"] == "manual"
    assert set(TRIGGER_CAUSES) == {
        "abort", "autoscale", "breaker_open", "crash_respawn", "fault",
        "manual", "shed_spike", "slo_breach",
    }


def test_bundle_sections_unarmed_tsdb():
    prev = tsdb.install(None)
    try:
        store = _store()
        b = store.notify("m", "manual", sync=True, note="x")
        assert b["tsdb"] == {"armed": False, "series": [], "truncated": 0}
        assert b["fields"] == {"note": "x"}
        assert b["window"]["start"] <= b["at"] <= b["window"]["end"]
        assert isinstance(b["faults"], list)
        assert isinstance(b["devprof"], dict)
        assert isinstance(b["lock_trips"], list)
        assert b["flightrec"]["snapshot_id"] is None
    finally:
        tsdb.install(prev)


def test_bundle_freezes_tsdb_window_and_marks_model_lane():
    from aios_tpu.obs.metrics import Gauge, MetricsRegistry
    from aios_tpu.obs.tsdb import Tsdb, TsdbConfig

    reg = MetricsRegistry()
    g = Gauge("aios_tpu_t_inc_ratio", "h", registry=reg)
    g.set(1.0)
    ring = Tsdb(cfg=TsdbConfig(), registry=reg)
    ring.sample_once()
    prev = tsdb.install(ring)
    try:
        store = _store(window_secs=60.0)
        b = store.notify("inc-model", "manual", sync=True)
        assert b["tsdb"]["armed"] is True
        assert any(s["name"] == "aios_tpu_t_inc_ratio"
                   for s in b["tsdb"]["series"])
        # the bundle itself lands on the model lane as an event the
        # closed EVENT_KINDS enum covers
        lane = flightrec.RECORDER.model_events("inc-model")
        assert any(
            k == "incident" and f.get("incident_id") == b["id"]
            for _, _, k, f in lane
        )
    finally:
        tsdb.install(prev)


def test_store_is_bounded():
    now = [0.0]
    store = _store(clock=lambda: now[0])
    for i in range(MAX_INCIDENTS + 5):
        now[0] += 1.0
        store.notify(f"m{i}", "manual", sync=True)
    incs = store.incidents()
    assert len(incs) == MAX_INCIDENTS
    assert incs[-1]["id"] == MAX_INCIDENTS + 5


def test_dump_dir_writes_bundle_json(tmp_path):
    store = _store(dump_dir=str(tmp_path))
    b = store.notify("m", "manual", sync=True)
    path = tmp_path / f"incident-m-manual-{b['id']}.json"
    assert path.exists()
    assert json.loads(path.read_text())["cause"] == "manual"


# -- trigger hooks ----------------------------------------------------------


def test_flightrec_snapshot_triggers_incident():
    store = _store()
    prev = incidents.install(store)
    try:
        snap = flightrec.RECORDER.snapshot("snaptrig-model", "abort")
        assert snap is not None
        incs = _wait_for(store, 1)
        assert incs[0]["cause"] == "abort"
        assert incs[0]["model"] == "snaptrig-model"
        # the matching snapshot is folded into the bundle
        assert incs[0]["flightrec"]["snapshot_id"] == snap["id"]
    finally:
        incidents.install(prev)


def test_breaker_open_edge_triggers_incident():
    from aios_tpu.fleet import breaker

    store = _store()
    prev = incidents.install(store)
    try:
        board = breaker.BreakerBoard(clock=lambda: 0.0)
        for _ in range(4):  # past the default threshold -> open edge
            board.record_failure("sickhost", "unavailable")
        incs = _wait_for(store, 1)
        assert incs[0]["cause"] == "breaker_open"
        assert incs[0]["model"] == "fleet"
        assert incs[0]["fields"]["peer"] == "sickhost"
    finally:
        incidents.install(prev)


def test_fired_fault_triggers_incident():
    from aios_tpu import faults

    store = _store()
    prev = incidents.install(store)
    faults.activate("seed=1;pool.scheduler_crash=nth:1")
    try:
        act = faults.point("pool.scheduler_crash", "faulted-model")
        assert act is not None
        incs = _wait_for(store, 1)
        assert incs[0]["cause"] == "fault"
        assert incs[0]["model"] == "faulted-model"
        assert incs[0]["fields"]["point"] == "pool.scheduler_crash"
    finally:
        faults.deactivate()
        incidents.install(prev)


# -- HTTP surface -----------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.status, r.read().decode()


def test_debug_incidents_http():
    from aios_tpu.obs.http import start_metrics_server

    store = _store()
    store.notify("m", "manual", sync=True, note="hi")
    prev = incidents.install(store)
    server, port = start_metrics_server(port=0)
    try:
        status, body = _get(port, "/debug/incidents")
        data = json.loads(body)
        assert status == 200 and len(data["incidents"]) == 1
        meta = data["incidents"][0]
        assert meta["cause"] == "manual" and meta["fields"] == {"note": "hi"}
        assert "tsdb" not in meta  # the list is metadata, not bundles
        status, body = _get(port, f"/debug/incidents?id={meta['id']}")
        assert status == 200 and "tsdb" in json.loads(body)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/debug/incidents?id=999")
        assert ei.value.code == 404
    finally:
        incidents.install(prev)
        server.shutdown()


def test_debug_incidents_404_when_unarmed():
    from aios_tpu.obs.http import start_metrics_server

    prev = incidents.install(None)
    server, port = start_metrics_server(port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/debug/incidents")
        assert ei.value.code == 404
    finally:
        incidents.install(prev)
        server.shutdown()


# -- THE determinism acceptance (engine tier) -------------------------------


MODEL = "incident-crash"


@pytest.fixture(scope="module")
def crash_pool():
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.serving import ReplicaPool, ServingConfig

    cfg = TINY_TEST.scaled(name=MODEL, max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    engines = [
        TPUEngine(cfg, params, num_slots=2, max_context=256,
                  cache_dtype=jnp.float32)
        for _ in range(2)
    ]
    pool = ReplicaPool(
        MODEL, engines,
        lambda e: ContinuousBatcher(e, chunk_steps=2, admit_chunk_steps=2),
        ServingConfig(replicas=2, failover_retries=2),
    )
    yield pool
    pool.shutdown()


def _crash_wave(pool, tag, n=4, max_tokens=24):
    from aios_tpu.engine.batching import Request

    handles = [
        pool.submit(
            Request(prompt_ids=[3 + i, 7, 11], max_tokens=max_tokens,
                    temperature=0.0, request_id=f"{tag}-{i}"),
            tenant="chaos-tenant",
        )
        for i in range(n)
    ]
    streams = {}
    threads = []
    for i, h in enumerate(handles):
        t = threading.Thread(
            target=lambda i=i, h=h: streams.__setitem__(i, h.tokens()),
            daemon=True,
        )
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=120)
    return [streams.get(i) for i in range(n)]


def _normalize(bundle):
    """A bundle modulo timestamps, ids, and the cross-layer state that
    legitimately accumulates across runs (devprof counters, lane
    history): the trigger identity, its fields, and the fired-fault
    evidence must reproduce exactly."""
    return {
        "model": bundle["model"],
        "cause": bundle["cause"],
        "fields": bundle["fields"],
        "faults": [
            {k: e.get(k) for k in ("point", "mode", "hit", "model")}
            for e in bundle["faults"]
        ],
    }


def test_seeded_crash_incident_bundles_identical_across_runs(crash_pool):
    """ISSUE 20 acceptance: the same seeded ``pool.scheduler_crash``
    wave run twice produces incident bundles identical modulo
    timestamps — the chaos pipeline's replayable-verdict rule extended
    to the incident layer."""
    from aios_tpu import faults

    def run(tag):
        store = _store()
        prev = incidents.install(store)
        faults.activate("seed=2;pool.scheduler_crash=nth:6")
        try:
            streams = _crash_wave(crash_pool, tag)
            assert all(s for s in streams), "a request died in the wave"
            incs = _wait_for(store, 1)
        finally:
            faults.deactivate()
            incidents.install(prev)
        fault_incs = [b for b in incs if b["cause"] == "fault"]
        assert fault_incs, "the fired fault never produced an incident"
        return [_normalize(b) for b in fault_incs]

    first = run("inc-a")
    second = run("inc-b")
    assert first == second
    assert first[0]["model"] == MODEL
    assert first[0]["fields"]["point"] == "pool.scheduler_crash"
    assert first[0]["faults"][-1]["hit"] == 6
