"""Provider clients: Claude, OpenAI, Qwen3 (OpenAI-compat), local TPU runtime.

Reference parity (api-gateway/src/{claude,openai}.rs + router.rs):
  * Claude native Messages API, default model claude-sonnet-4-20250514
    (claude.rs:54-67), key from CLAUDE_API_KEY;
  * OpenAI chat completions, default gpt-5, key from OPENAI_API_KEY;
  * Qwen3 = OpenAI-compatible endpoint (default api.viwoapp.net,
    model qwen3:30b-128k), key from QWEN3_API_KEY;
  * local = the reference hits llama-server HTTP on 127.0.0.1:8082; here it
    is the TPU runtime's gRPC Infer — always available, no key.

Base URLs are env-overridable (CLAUDE_BASE_URL etc.) which is also how the
offline test suite stubs them.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Optional, Tuple


class ProviderError(Exception):
    pass




@dataclass
class InferResult:
    text: str
    input_tokens: int
    output_tokens: int
    model: str
    provider: str


def _post_json(url: str, payload: dict, headers: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **headers},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as exc:
        body = exc.read().decode("utf-8", "replace")[:500]
        raise ProviderError(f"HTTP {exc.code} from {url}: {body}") from exc
    except (OSError, ValueError) as exc:
        raise ProviderError(f"request to {url} failed: {exc}") from exc


class ClaudeClient:
    name = "claude"

    def __init__(self):
        self.api_key = os.environ.get("CLAUDE_API_KEY", "")
        self.base_url = os.environ.get("CLAUDE_BASE_URL", "https://api.anthropic.com")
        self.model = os.environ.get("CLAUDE_MODEL", "claude-sonnet-4-20250514")
        self.timeout = float(os.environ.get("CLAUDE_TIMEOUT", "120"))

    def available(self) -> bool:
        return bool(self.api_key)

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        payload = {
            "model": self.model,
            "max_tokens": max_tokens or 1024,
            "messages": [{"role": "user", "content": prompt}],
            "temperature": temperature,
        }
        if system:
            payload["system"] = system
        data = _post_json(
            f"{self.base_url}/v1/messages",
            payload,
            {"x-api-key": self.api_key, "anthropic-version": "2023-06-01"},
            self.timeout,
        )
        try:
            text = "".join(
                b.get("text", "") for b in data["content"] if b.get("type") == "text"
            )
            usage = data.get("usage", {})
            return InferResult(
                text=text,
                input_tokens=usage.get("input_tokens", 0),
                output_tokens=usage.get("output_tokens", 0),
                model=data.get("model", self.model),
                provider=self.name,
            )
        except (KeyError, TypeError) as exc:
            raise ProviderError(f"malformed claude response: {exc}") from exc


class OpenAICompatClient:
    """OpenAI chat-completions protocol (used by both openai and qwen3)."""

    def __init__(self, name: str, key_env: str, base_env: str, default_base: str,
                 model_env: str, default_model: str):
        self.name = name
        self.api_key = os.environ.get(key_env, "")
        self.base_url = os.environ.get(base_env, default_base)
        self.model = os.environ.get(model_env, default_model)
        self.timeout = 120.0

    def available(self) -> bool:
        return bool(self.api_key)

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        messages = []
        if system:
            messages.append({"role": "system", "content": system})
        messages.append({"role": "user", "content": prompt})
        data = _post_json(
            f"{self.base_url}/v1/chat/completions",
            {
                "model": self.model,
                "messages": messages,
                "max_tokens": max_tokens or 1024,
                "temperature": temperature,
            },
            {"Authorization": f"Bearer {self.api_key}"},
            self.timeout,
        )
        try:
            text = data["choices"][0]["message"]["content"]
            usage = data.get("usage", {})
            return InferResult(
                text=text or "",
                input_tokens=usage.get("prompt_tokens", 0),
                output_tokens=usage.get("completion_tokens", 0),
                model=data.get("model", self.model),
                provider=self.name,
            )
        except (KeyError, IndexError, TypeError) as exc:
            raise ProviderError(f"malformed {self.name} response: {exc}") from exc


def openai_client() -> OpenAICompatClient:
    return OpenAICompatClient(
        "openai", "OPENAI_API_KEY", "OPENAI_BASE_URL",
        "https://api.openai.com", "OPENAI_MODEL", "gpt-5",
    )


def qwen3_client() -> OpenAICompatClient:
    return OpenAICompatClient(
        "qwen3", "QWEN3_API_KEY", "QWEN3_BASE_URL",
        "https://api.viwoapp.net", "QWEN3_MODEL", "qwen3:30b-128k",
    )


class LocalRuntimeClient:
    """The TPU runtime as a gateway provider (final fallback, always on).

    Honors the runtime's backoff convention: a shed or crash-abort comes
    back as RESOURCE_EXHAUSTED / UNAVAILABLE with ``retry-after-ms``
    trailing metadata, and this client sleeps the hinted backoff (with
    jitter — a fleet of gateways must not resubmit in lockstep) and
    retries up to ``AIOS_TPU_RUNTIME_RETRY_ATTEMPTS`` times (default 2).
    Errors WITHOUT the hint (wrong model name, invalid schema, a genuine
    outage) propagate immediately — only the runtime's explicit
    "try again later" is worth waiting on."""

    name = "local"
    supports_json_schema = True  # grammar-guided decoding in the engine

    def __init__(self, address: Optional[str] = None):
        from ..services import service_address

        self.address = address or service_address("runtime")
        self._stub = None
        self._channel = None

    def available(self) -> bool:
        return True  # router.rs treats local as always-available

    def _get_stub(self):
        if self._stub is None:
            from .. import rpc
            from ..services import AIRuntimeStub

            # ONE persistent channel, reused across stub rebuilds and
            # retries: gRPC channels reconnect on their own after an
            # UNAVAILABLE, so rebuilding the channel per failure would
            # either leak sockets (dereference) or — worse — close() a
            # channel the gateway's OTHER worker threads have healthy
            # in-flight RPCs on (close cancels every call in progress)
            if self._channel is None:
                self._channel = rpc.insecure_channel(self.address)
            self._stub = AIRuntimeStub(self._channel)
        return self._stub

    @staticmethod
    def _retry_attempts() -> int:
        import os

        raw = os.environ.get("AIOS_TPU_RUNTIME_RETRY_ATTEMPTS", "").strip()
        try:
            return max(int(raw), 0) if raw else 2
        except ValueError:
            return 2

    @staticmethod
    def _retry_after_ms(exc) -> Optional[int]:
        """The runtime's retry-after-ms trailing metadata, or None when
        the error carries no backoff hint (not retryable)."""
        import grpc

        if exc.code() not in (
            grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.RESOURCE_EXHAUSTED
        ):
            return None
        try:
            md = exc.trailing_metadata() or ()
        except Exception:  # noqa: BLE001 - metadata is advisory
            return None
        for k, v in md:
            if k == "retry-after-ms":
                try:
                    return max(int(v), 1)
                except (TypeError, ValueError):
                    return None
        return None

    @staticmethod
    def _backoff(hint_ms: int) -> None:
        import random as _random
        import time as _time

        # jittered: 0.5x..1.5x the hint, capped — the hint is already
        # the runtime's own drain estimate
        _time.sleep(min(hint_ms, 30_000) / 1e3 * (0.5 + _random.random()))

    def infer(self, prompt: str, system: str, max_tokens: int,
              temperature: float, json_schema: str = "") -> InferResult:
        import grpc

        from ..proto_gen import runtime_pb2

        attempts = self._retry_attempts()
        request = runtime_pb2.InferRequest(
            prompt=prompt,
            system_prompt=system,
            max_tokens=max_tokens or 512,
            temperature=temperature,
            # structured output rides through to the TPU engine's
            # grammar-guided decoding; cloud providers ignore it
            json_schema=json_schema,
        )
        for attempt in range(attempts + 1):
            try:
                resp = self._get_stub().Infer(request, timeout=120)
                break
            except grpc.RpcError as exc:
                self._stub = None
                hint = self._retry_after_ms(exc)
                if hint is None or attempt >= attempts:
                    raise ProviderError(
                        f"local runtime: {exc.details()}"
                    ) from exc
                self._backoff(hint)
        return InferResult(
            text=resp.text,
            input_tokens=max(0, resp.tokens_used),
            output_tokens=0,
            model=resp.model_used or "local",
            provider=self.name,
        )

    def stream_infer(self, prompt: str, system: str, max_tokens: int,
                     temperature: float, json_schema: str = "",
                     register_call=None):
        """Yield text deltas live from the runtime's StreamInfer.

        This is the true-streaming path the reference never had: its
        inference.rs:261 buffers the whole completion and re-chunks it, a
        quirk the runtime service here already fixed — so the gateway pipes
        the live token stream instead of replicating the buffer-then-chunk
        behavior (router.route_stream).
        """
        import grpc

        from ..proto_gen import runtime_pb2

        request = runtime_pb2.InferRequest(
            prompt=prompt,
            system_prompt=system,
            max_tokens=max_tokens or 512,
            temperature=temperature,
            json_schema=json_schema,
        )
        attempts = self._retry_attempts()
        stream = None
        emitted = False
        try:
            for attempt in range(attempts + 1):
                try:
                    stream = self._get_stub().StreamInfer(
                        request, timeout=300
                    )
                    if register_call is not None:
                        # hand the call to the servicer so its
                        # RPC-termination callback can cancel it
                        # cross-thread while this generator is parked in
                        # next() (cancel is thread-safe on gRPC calls)
                        register_call(stream)
                    for chunk in stream:
                        if chunk.text:
                            emitted = True
                            yield chunk.text
                        if chunk.done:
                            return
                    return
                except grpc.RpcError as exc:
                    # CANCELLED can be our own disconnect-cancel
                    # (register_call path) OR a genuine runtime failure
                    # (server restart kills in-flight RPCs with
                    # CANCELLED) — the router tells them apart via its
                    # client_alive probe, not here
                    if exc.code() != grpc.StatusCode.CANCELLED:
                        self._stub = None
                    hint = self._retry_after_ms(exc)
                    if emitted or hint is None or attempt >= attempts:
                        # once a delta reached the consumer a blind
                        # resubmit would replay text — the runtime's own
                        # in-pool failover already covers mid-stream
                        # crashes transparently; only a shed/crash BEFORE
                        # the first delta retries here
                        raise ProviderError(
                            f"local runtime: {exc.details()}"
                        ) from exc
                    self._backoff(hint)
        finally:
            # our consumer can vanish mid-stream (the gateway's client
            # disconnected -> GeneratorExit lands here): cancel the
            # downstream call so the runtime aborts its decode and frees
            # the slot, instead of streaming to an abandoned iterator
            # until max_tokens. No-op on a completed call.
            if stream is not None:
                stream.cancel()
