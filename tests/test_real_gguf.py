"""End-to-end proof over REAL llama.cpp-produced GGUF files.

The synthetic spec fixture (test_gguf_spec_fixture.py) validates the
reader against an independent encoder, but only a genuine llama.cpp
artifact proves the Q4_K/Q6_K block layout and the real SentencePiece/BPE
vocab end-to-end (VERDICT r3 missing #2; the reference's entire job is
serving such files, model_manager.rs:187-263).

This build environment has zero network egress, so no real file can be
vendored from here. These tests therefore AUTO-SKIP unless a real model
file exists, and run the full proof the moment one does:

    scripts/download-models.sh --dest /var/lib/aios/models --tier tiny
    AIOS_MODEL_DIR=/var/lib/aios/models python -m pytest tests/test_real_gguf.py

(also picked up from tests/fixtures/real/*.gguf for a vendored tiny file)
"""

import os
import struct
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

_SEARCH_DIRS = [
    os.environ.get("AIOS_MODEL_DIR", "/var/lib/aios/models"),
    str(Path(__file__).parent / "fixtures" / "real"),
]


def _looks_real(path: Path) -> bool:
    """Signature check, not a size floor (VERDICT r4 item 2): a REAL
    llama.cpp artifact — even a tiny one vendored for CI — carries a full
    production vocabulary, which no in-repo synthetic/spec fixture does
    (they top out at a few hundred tokens). A reader/import regression
    must NOT read as "no real file" (that would silently skip the proof),
    so only parse-of-garbage errors are caught."""
    from aios_tpu.engine.gguf import GGUFFile

    try:
        g = GGUFFile(path)
    except (ValueError, OSError, KeyError, EOFError, struct.error):
        return False  # not a GGUF file at all (e.g. a corrupt download)
    tokens = g.metadata.get("tokenizer.ggml.tokens") or []
    return bool(g.metadata.get("general.architecture")) and len(tokens) >= 16000


def _real_files():
    # called from the module-scoped fixture, NOT at collection time: the
    # signature probe parses each candidate's metadata (the full vocab
    # array), too heavy to run on every pytest collection of this module
    out = []
    for d in _SEARCH_DIRS:
        p = Path(d)
        if p.is_dir():
            out.extend(f for f in sorted(p.glob("*.gguf")) if _looks_real(f))
    return out


@pytest.fixture(scope="module")
def managed_model():
    real = _real_files()
    if not real:
        pytest.skip(
            "no real GGUF on this machine (zero-egress build env); run "
            "scripts/download-models.sh and re-run to complete the proof"
        )
    from aios_tpu.runtime.model_manager import ModelManager

    path = real[0]
    mgr = ModelManager(num_slots=2, warm_compile=False)
    # exactly the reference's autoload contract: file-size-derived context
    # (runtime/src/main.rs:65-132) via the manager's scan of the file
    m = mgr.load_model(path.stem, str(path))
    m.real_path = path  # for tests that reload the same file themselves
    yield m
    mgr.unload_model(path.stem)


def test_real_vocab_round_trips(managed_model):
    """The REAL vocab (SentencePiece or byte-level BPE) must round-trip
    text exactly — the property no synthetic vocab can attest."""
    tok = managed_model.tokenizer
    for text in (
        "Hello, world!",
        "The quick brown fox jumps over the lazy dog.",
        "  leading spaces and\nnewlines\tand tabs",
        "unicode: café — über 中文",
    ):
        ids = tok.encode(text, add_bos=False)
        assert ids, text
        assert tok.decode(ids) == text


def test_real_weights_decode_coherently(managed_model):
    """Greedy continuation from real weights must be structured text, not
    the garbage a block-layout misread produces: printable, repetition-
    bounded, and re-encodable to the same ids."""
    eng, tok = managed_model.engine, managed_model.tokenizer
    prompt = tok.encode("The capital of France is", add_bos=True)
    out = eng.generate(prompt, max_new_tokens=12, temperature=0.0)
    text = tok.decode(out)
    assert text.strip(), "empty continuation"
    printable = sum(c.isprintable() or c.isspace() for c in text)
    assert printable / len(text) > 0.95, f"garbage continuation: {text!r}"
    # a Q4_K scale/min misread degenerates into one repeated token
    assert len(set(out)) > 1, f"degenerate repetition: {out}"


def test_real_model_serves_through_runtime_service(managed_model):
    """The same file behind the AIRuntime gRPC surface (the reference's
    serving contract, grpc_service.rs:86-108)."""
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    mgr = ModelManager(num_slots=2, warm_compile=False)
    server, service, port = serve(
        address="127.0.0.1:0", manager=mgr, block=False
    )
    try:
        stub = services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{port}")
        )
        st = stub.LoadModel(runtime_pb2.LoadModelRequest(
            model_name="real", model_path=str(managed_model.real_path)
        ))
        assert st.status == "ready"
        r = stub.Infer(runtime_pb2.InferRequest(
            model="real", prompt="Say hello.", max_tokens=8
        ))
        assert r.tokens_used > 0
        assert r.text.strip()
    finally:
        server.stop(0)
