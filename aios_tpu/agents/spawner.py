"""Agent process spawner + supervisor.

Reference parity (agent-core/src/agent_spawner.rs): loads per-agent TOML
configs from the config dir (defaults to system/network/security when none
exist, agent_spawner.rs:140-175), spawns `python3 -m aios_tpu.agents.run`
child processes with AIOS_AGENT_NAME/AIOS_AGENT_TYPE/AIOS_ORCHESTRATOR_ADDR
in the environment (179-218), and monitors/restarts them with a cap of 5
restarts at 5 s delay (agent_spawner.rs:118-119).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from . import AGENT_TYPES
from .._compat import tomllib
from ..obs import instruments as obs

log = logging.getLogger("aios.spawner")

MAX_RESTARTS = 5
RESTART_DELAY = 5.0
DEFAULT_AGENTS = ["system", "network", "security"]


@dataclass
class AgentConfig:
    name: str
    agent_type: str
    enabled: bool = True
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class SpawnedAgent:
    config: AgentConfig
    process: Optional[subprocess.Popen] = None
    restarts: int = 0
    gave_up: bool = False


def load_agent_configs(config_dir: Optional[str] = None) -> List[AgentConfig]:
    config_dir = config_dir or os.environ.get(
        "AIOS_AGENT_CONFIG_DIR", "/etc/aios/agents"
    )
    d = Path(config_dir)
    configs: List[AgentConfig] = []
    if d.is_dir():
        for f in sorted(d.glob("*.toml")):
            try:
                data = tomllib.loads(f.read_text())
            except (OSError, ValueError):
                continue
            section = data.get("agent", data)
            atype = section.get("type", f.stem)
            if atype not in AGENT_TYPES:
                continue
            configs.append(
                AgentConfig(
                    name=section.get("name", f"{atype}_agent"),
                    agent_type=atype,
                    enabled=section.get("enabled", True),
                    env={k: str(v) for k, v in data.get("env", {}).items()},
                )
            )
    if not configs:  # defaults (agent_spawner.rs:140-175)
        configs = [
            AgentConfig(name=f"{t}_agent", agent_type=t) for t in DEFAULT_AGENTS
        ]
    return [c for c in configs if c.enabled]


class AgentSpawner:
    def __init__(self, config_dir: Optional[str] = None,
                 orchestrator_addr: Optional[str] = None):
        from ..services import service_address

        self.configs = load_agent_configs(config_dir)
        self.orchestrator_addr = orchestrator_addr or service_address(
            "orchestrator"
        )
        self.spawned: Dict[str, SpawnedAgent] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _spawn(self, entry: SpawnedAgent) -> None:
        cfg = entry.config
        env = {
            **os.environ,
            "AIOS_AGENT_NAME": cfg.name,
            "AIOS_AGENT_TYPE": cfg.agent_type,
            "AIOS_ORCHESTRATOR_ADDR": self.orchestrator_addr,
            **cfg.env,
        }
        entry.process = subprocess.Popen(
            [sys.executable, "-m", "aios_tpu.agents.run"],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        log.info("spawned %s (pid %d)", cfg.name, entry.process.pid)

    def start(self) -> None:
        for cfg in self.configs:
            entry = SpawnedAgent(config=cfg)
            self.spawned[cfg.name] = entry
            try:
                self._spawn(entry)
            except OSError as exc:
                log.error("spawn %s failed: %s", cfg.name, exc)
        self._thread = threading.Thread(target=self._monitor_loop,
                                        name="agent-spawner", daemon=True)
        self._thread.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(RESTART_DELAY):
            for entry in self.spawned.values():
                p = entry.process
                if p is None or entry.gave_up:
                    continue
                if p.poll() is None:
                    continue  # still running
                if entry.restarts >= MAX_RESTARTS:
                    entry.gave_up = True
                    log.error("agent %s exceeded %d restarts; giving up",
                              entry.config.name, MAX_RESTARTS)
                    continue
                entry.restarts += 1
                obs.AGENT_RESTARTS.labels(agent=entry.config.name).inc()
                log.warning("agent %s exited (%s); restart %d/%d",
                            entry.config.name, p.returncode,
                            entry.restarts, MAX_RESTARTS)
                try:
                    self._spawn(entry)
                except OSError as exc:
                    log.error("respawn failed: %s", exc)

    def failed_agents(self) -> List[str]:
        return [name for name, e in self.spawned.items() if e.gave_up]

    def stop(self) -> None:
        self._stop.set()
        for entry in self.spawned.values():
            if entry.process and entry.process.poll() is None:
                entry.process.terminate()
        deadline = time.time() + 5
        for entry in self.spawned.values():
            if entry.process:
                try:
                    entry.process.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    entry.process.kill()
        if self._thread:
            self._thread.join(timeout=5)
